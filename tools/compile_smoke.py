#!/usr/bin/env python
"""CI smoke: prove the fused train step jit-compiles without silicon.

Runs ``python bench.py --compile-only --model <m>`` on the CPU backend and
asserts the compile-marker row lands. This is the tier-1 guard for the
step-fusion layer: the chunked fused cross-entropy (custom VJP), the
scan-over-layers + remat encoders, and the fused add+LN path all have to
lower and compile inside one jitted train step — a regression in any of
them trips here, not in the next silicon bench window.

The sharded mode (--mesh dp2,tp2) additionally compiles the dp x tp GSPMD
train step on fake CPU devices and evaluates the model's CONTRACTS row
(paddle_tpu/analysis/contracts.py) against the compiled (post-SPMD,
per-device shapes) HLO: no [rows, V]-scale temporary, no all-gather of
the vocab-sharded projection weight, no f64, no host callback.
`sharded_vocab_check` wraps the full contract — the fused run must be
clean, a PT_FUSED_XENT=0 positive-control run must trip the detector
(proving the judge actually detects full-vocab logits). This tool
compiles; the contract engine judges.

Usage:
  python tools/compile_smoke.py                  # gpt, full-size config
  python tools/compile_smoke.py --tiny           # tiny config (CI budget)
  python tools/compile_smoke.py --model bert --tiny
  python tools/compile_smoke.py --model gpt --tiny --mesh dp2,tp2 --hlo-check
"""

import argparse
import json
import math
import os
import re
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _mesh_devices(mesh):
    """Device count a '--mesh dp2,tp2' spec needs (explicit sizes only)."""
    n = 1
    for part in mesh.split(","):
        m = re.fullmatch(r"([a-z]+)(\d+)", part.strip())
        if not m:
            raise SystemExit(f"--mesh {mesh!r}: compile_smoke needs "
                             "explicit sizes (e.g. dp2,tp2)")
        n *= int(m.group(2))
    return n


def run(model="gpt", tiny=False, timeout=600, extra_env=None, mesh=None,
        batch=None, seq=None, dump_hlo=None, devices=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never touch the tunnel
    env["JAX_PLATFORMS"] = "cpu"
    if mesh:
        # '--mesh auto' has no explicit sizes; the caller must say how
        # many fake devices to fabricate (devices=)
        n = devices if devices is not None else _mesh_devices(mesh)
        flags = " ".join(
            f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count"))
        env["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_"
                            f"count={n}").strip()
    env.update(extra_env or {})
    args = [sys.executable, os.path.join(REPO, "bench.py"),
            "--compile-only", "--model", model]
    if tiny:
        args.append("--tiny")
    if mesh:
        args += ["--mesh", mesh]
    if batch:
        args += ["--batch", str(batch)]
    if seq:
        args += ["--seq", str(seq)]
    if dump_hlo:
        args += ["--dump-hlo", dump_hlo]
    proc = subprocess.run(args, stdout=subprocess.PIPE, text=True,
                          timeout=timeout, env=env, cwd=REPO)
    lines = proc.stdout.strip().splitlines()
    if not lines:
        raise SystemExit(f"no bench output (rc={proc.returncode})")
    row = json.loads(lines[-1])
    if not str(row.get("metric", "")).endswith("_compile_only"):
        raise SystemExit(f"fused step failed to compile: {row}")
    return row


# The HLO judgments live in paddle_tpu/analysis/contracts.py now; this
# tool keeps thin same-signature wrappers (and the compile plumbing).
# The engine is loaded straight from its file so the subprocess-only
# paths never pay the jax import in paddle_tpu/__init__.
_contracts_mod = None


def _contracts():
    global _contracts_mod
    if _contracts_mod is None:
        mod = sys.modules.get("paddle_tpu.analysis.contracts")
        if mod is None:
            import importlib.util
            path = os.path.join(REPO, "paddle_tpu", "analysis",
                                "contracts.py")
            spec = importlib.util.spec_from_file_location(
                "paddle_tpu.analysis.contracts", path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        _contracts_mod = mod
    return _contracts_mod


def vocab_temporaries(hlo_text, vocab, tp, min_rows):
    """Materialized [rows, vocab]-scale logits temporaries (global V or
    the V/tp shard) — thin caller of the NoTemporary contract; min_rows
    sits ABOVE the model width so the [V/tp, H] weight shard (a
    legitimate vocab-axis resident) never trips it."""
    c = _contracts()
    return c.NoTemporary({vocab, vocab // tp}, min_rows).temporaries(
        hlo_text)


def weight_all_gathers(hlo_text, vocab, hidden):
    """all-gather ops whose result carries the full global-vocab dim at
    weight scale (GSPMD re-assembled the vocab-sharded projection
    weight) — thin caller of the NoOpMatching contract."""
    c = _contracts()
    return c.NoOpMatching(
        "all-gather",
        shape_test=lambda shp: (vocab in shp
                                and math.prod(shp) >= vocab * hidden),
    ).matches(hlo_text)


def dense_score_temporaries(hlo_text, tmax, min_rows):
    """f32/bf16 temporaries spanning the PADDED slot capacity Tmax —
    the gathered-dense K/V or score tensor the paged Pallas decode path
    must never materialize. Thin caller of the NoTemporary contract."""
    c = _contracts()
    return c.NoTemporary({tmax}, min_rows).temporaries(hlo_text)


def sharded_vocab_check(model="gpt", mesh="dp2,tp2", timeout=600,
                        positive_control=True, update_snapshots=False):
    """Compile the dp x tp fused train step and evaluate the model's
    full CONTRACTS row (no [rows, V] temporary, no vocab-weight
    all-gather, no f64, no host callback, and — where the row carries
    budget contracts — the XLA cost_analysis flops/bytes priced against
    the autoplan cost model) against its per-device HLO; optionally also
    compile the PT_FUSED_XENT=0 reference step and require the
    NoTemporary detector to TRIP on it (positive control). The budget
    detectors get their own positive control: at tolerance=0 every real
    compile must exceed a zero budget. When the model has a registered
    HloSnapshot the compiled op histogram is judged against the blessed
    record too (``update_snapshots=True`` re-blesses instead)."""
    c = _contracts()
    case = c.SHARDED_TRAIN_CASES[model]
    vocab, hidden = case.vocab, case.hidden
    min_rows = case.min_rows(dp=2)
    row_contracts = c.CONTRACTS[f"train.{model}@dp2,tp2"]
    chunk_env = {"PT_FLAGS_xent_chunk": "64"}
    out = {"model": model, "mesh": mesh}
    with tempfile.TemporaryDirectory() as td:
        fused_hlo = os.path.join(td, "fused.hlo")
        row = run(model=model, tiny=True, timeout=timeout, mesh=mesh,
                  batch=case.batch, seq=case.seq, dump_hlo=fused_hlo,
                  extra_env=chunk_env)
        text = open(fused_hlo).read()
        cost = None
        try:
            with open(fused_hlo + ".cost.json") as f:
                cost = c.normalize_cost(json.load(f))
        except (OSError, ValueError):
            pass
        ctx = c.ContractContext(hlo_text=text, cost=cost)
        violations = c.evaluate(row_contracts, ctx)
        snap = c.CONTRACT_SNAPSHOTS.get(f"train.{model}@{mesh}")
        if snap is not None:
            if update_snapshots:
                out["snapshot_blessed"] = snap.bless(text)["hash"]
            else:
                violations += snap.violations(ctx)
        out.update(row=row, cost=cost,
                   vocab_temporaries=vocab_temporaries(
                       text, vocab, 2, min_rows),
                   weight_all_gathers=weight_all_gathers(
                       text, vocab, hidden),
                   violations=[v.format() for v in violations],
                   clean=not violations)
        budgets = [b for b in row_contracts
                   if isinstance(b, c.MaxHloCost)]
        if positive_control:
            ref_hlo = os.path.join(td, "reference.hlo")
            run(model=model, tiny=True, timeout=timeout, mesh=mesh,
                batch=case.batch, seq=case.seq, dump_hlo=ref_hlo,
                extra_env={**chunk_env, "PT_FUSED_XENT": "0"})
            ref_temps = vocab_temporaries(open(ref_hlo).read(), vocab, 2,
                                          min_rows)
            out["positive_control_trips"] = bool(ref_temps)
            if budgets and cost is not None:
                out["budget_control_trips"] = all(
                    b.with_tolerance(0).check(ctx) for b in budgets)
    return out


def autoplan_check(model="gpt", topology="cpu4", timeout=600):
    """Compile ``bench.py --mesh auto`` — the autoplan search resolves
    the mesh from the named topology on fake CPU devices — and evaluate
    the model's ``train.<model>@auto`` CONTRACTS row against the
    compiled per-device HLO. The acceptance gate for the planner: its
    winning mesh must not just compile, it must compile CLEAN under the
    same NoTemporary/no-vocab-all-gather judgments as the hand-picked
    dp2,tp2 row."""
    c = _contracts()
    case = c.SHARDED_TRAIN_CASES[model]
    m = re.fullmatch(r"(?:\d+x)?[a-z0-9]+?-?(\d+)", topology)
    if not m:
        raise SystemExit(f"unparseable topology {topology!r}")
    devices = int(m.group(1))
    env = {"PT_FLAGS_autoplan_topology": topology,
           "PT_FLAGS_xent_chunk": "64"}
    out = {"model": model, "topology": topology, "devices": devices}
    with tempfile.TemporaryDirectory() as td:
        hlo = os.path.join(td, "auto.hlo")
        row = run(model=model, tiny=True, timeout=timeout, mesh="auto",
                  batch=case.batch, seq=case.seq, dump_hlo=hlo,
                  extra_env=env, devices=devices)
        text = open(hlo).read()
        violations = c.evaluate(c.CONTRACTS[f"train.{model}@auto"],
                                c.ContractContext(hlo_text=text))
        out.update(row=row, plan=row.get("autoplan"),
                   violations=[v.format() for v in violations],
                   clean=not violations)
    return out


# serve-probe shapes: every dim distinct from TMAX=48 (vocab 512, hidden
# 64, ffn 128, heads 4, hd 16, page 8, pages 13, slots 2, prefill 16) so
# the detector can key on the padded slot capacity alone. min_rows=8
# catches even the [S, H, 1, Tmax] score row of the dense fallback.
# Canonical values live with the contract table.
def _serve_dims():
    c = _contracts()
    return c.SERVE_TMAX, c.SERVE_MIN_ROWS


def _serve_engine(num_pages=13, num_slots=2, **cfg_kw):
    import jax
    from paddle_tpu.models.gpt import GPTConfig, GPTDecoder
    from paddle_tpu.serving import ServeConfig, ServingEngine
    cfg = GPTConfig.tiny()
    cfg.dropout = 0.0
    cfg.use_flash = False
    model = GPTDecoder(cfg)
    variables = model.init(jax.random.key(0))
    tmax, _ = _serve_dims()
    sc = ServeConfig(num_slots=num_slots, page_size=8, max_len=tmax,
                     prefill_len=16, num_pages=num_pages, **cfg_kw)
    return model, variables, ServingEngine(model, variables, sc)


def serve_smoke(positive_control=True, update_snapshots=False):
    """Tier-1 contract for the serving fast path, in-process on CPU:

    1. Trace-count probe: mixed-length admission waves through a
       2-slot engine must leave the jitted serve step traced exactly
       ONCE (continuous batching never retraces — the shapes are
       slot-fixed, only values change).
    2. HLO contract: with paging on and the Pallas decode kernel
       engaged (interpret mode off-TPU), the compiled serve step holds
       no [rows, Tmax]-dense gathered-K/V or score temporary; the XLA
       gather-and-mask fallback (use_pallas_decode=0) must TRIP the
       detector (positive control — proves the grep sees dense decode
       attention).
    3. Budget + snapshot gates: the decode step's cost_analysis flops
       and bytes stay under the costmodel.predict_decode budgets (with
       a tolerance=0 positive control), and its op histogram matches
       the blessed serve.decode snapshot (``update_snapshots=True``
       re-blesses instead).
    4. Quantized-KV leg: the same waves through a serve_kv_dtype=int8
       engine must stay traced-once and clean against the
       serve.decode@int8 row — no f32 tensor at page-pool scale (the
       dequant lives inside the kernel's tiles), byte budget re-derived
       from predict_decode(kv_dtype=int8), its own snapshot — while the
       f32 engine's compile TRIPS the KV detector (positive control:
       its pool is exactly the wide-KV tensor the row forbids).
    5. Speculative leg: the waves through a 16-slot self-draft engine
       (spec_k=7) with one injected spec.verify degrade must leave all
       FIVE entry points traced exactly once, emit > 1 token per
       target step, and compile a verify module clean against the
       serve.verify row — budgets from predict_decode(spec_k=...), no
       dense [slots, window, vocab] logits lattice (per-position head),
       its own snapshot. Positive controls: a literal dense-lattice
       einsum trips the detector, and the speculation-off engine trips
       the row's TracedOnce.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    if REPO not in sys.path:       # CLI use; in-suite runs already see it
        sys.path.insert(0, REPO)
    import numpy as np
    from paddle_tpu.core.flags import all_flags, set_flags

    c = _contracts()
    tmax, min_rows = _serve_dims()
    out = {}
    saved = all_flags()
    try:
        set_flags({"pallas_interpret": True, "use_pallas_decode": True})
        _, _, engine = _serve_engine()
        # admission waves of ragged prompts through 2 slots: every
        # admission lands in a freed slot mid-run. The 40-token prompt
        # exceeds prefill_len=16 — chunked prefill admits it as three
        # calls of the SAME prefill trace (the traced-once assertion
        # below covers it). Sampling knobs are deliberately MIXED —
        # greedy, temperature, top-k, top-p, and a pinned seed in one
        # batch — because they ride as traced [slots] values, not
        # retrace axes
        waves = [
            (3, 7, {}), (9, 5, dict(temperature=0.8)),
            (16, 6, dict(temperature=0.9, top_k=5)),
            (40, 6, {}), (5, 9, dict(temperature=0.7, top_p=0.9)),
            (12, 4, dict(temperature=1.0, top_k=8, top_p=0.95)),
            (2, 8, dict(temperature=0.6, seed=123))]

        def _drive(eng):
            rng = np.random.RandomState(0)
            for plen, mn, kw in waves:
                eng.submit(rng.randint(0, 512, (plen,), dtype=np.int32),
                           max_new=mn, **kw)
            return eng.drain()

        done = _drive(engine)
        out["finished"] = len(done)
        out["decode_traces"] = engine.decode_traces
        out["prefill_traces"] = engine.prefill_traces
        out["traced_once"] = (engine.decode_traces == 1
                              and engine.prefill_traces == 1)

        compiled = engine.compiled_decode()
        hlo = compiled.as_text()
        try:
            cost = c.normalize_cost(compiled.cost_analysis())
        except Exception:
            cost = None
        ctx = c.ContractContext(
            hlo_text=hlo, cost=cost,
            trace_counts={"serve.decode": engine.decode_traces,
                          "serve.prefill": engine.prefill_traces})
        violations = c.evaluate(c.CONTRACTS["serve.decode"]
                                + c.CONTRACTS["serve.prefill"], ctx)
        snap = c.CONTRACT_SNAPSHOTS["serve.decode"]
        if update_snapshots:
            out["snapshot_blessed"] = snap.bless(hlo)["hash"]
        else:
            violations += snap.violations(ctx)
        out["dense_temporaries"] = dense_score_temporaries(
            hlo, tmax, min_rows)
        out["cost"] = cost
        out["violations"] = [v.format() for v in violations]
        out["clean"] = not violations

        # --- quantized-KV leg: the same waves through an int8 pool ----
        # (run before the positive controls flip the pallas flags off)
        _, _, qeng = _serve_engine(kv_dtype="int8")
        _drive(qeng)
        q_compiled = qeng.compiled_decode()
        q_hlo = q_compiled.as_text()
        try:
            q_cost = c.normalize_cost(q_compiled.cost_analysis())
        except Exception:
            q_cost = None
        q_ctx = c.ContractContext(
            hlo_text=q_hlo, cost=q_cost,
            trace_counts={"serve.decode": qeng.decode_traces,
                          "serve.prefill": qeng.prefill_traces})
        q_viol = c.evaluate(c.CONTRACTS["serve.decode@int8"], q_ctx)
        q_snap = c.CONTRACT_SNAPSHOTS["serve.decode@int8"]
        if update_snapshots:
            out["int8_snapshot_blessed"] = q_snap.bless(q_hlo)["hash"]
        else:
            q_viol += q_snap.violations(q_ctx)
        out["int8_kv_pool_bytes"] = qeng.kv_pool_bytes()
        out["f32_kv_pool_bytes"] = engine.kv_pool_bytes()
        out["int8_cost"] = q_cost
        out["int8_violations"] = [v.format() for v in q_viol]
        out["int8_clean"] = not q_viol
        # positive control for the KV detector: the f32 engine's page
        # pool IS the KV-layout-scale f32 tensor the int8 row forbids,
        # so judging the f32 compile with it must trip
        kvdet = next(r for r in c.CONTRACTS["serve.decode@int8"]
                     if isinstance(r, c.NoKvDequantTemporary))
        out["kv_control_trips"] = bool(kvdet.temporaries(hlo))

        # --- speculative leg: the same waves through a self-draft ------
        # engine wide enough that slots x window = 128 rows clears the
        # verify row's MIN_ROWS=96 — a dense [slots, window, vocab]
        # logits lattice cannot hide under the weight allowance. One
        # injected spec.verify fault degrades one round to plain decode,
        # so all five entry points (decode, prefill, draft,
        # draft-prefill, verify) earn their traced-once counts in a
        # single drive.
        import jax
        import jax.numpy as jnp
        from paddle_tpu.testing import chaos as _chaos
        vs, vk = c.SERVE_VERIFY_SLOTS, c.SERVE_VERIFY_SPEC_K
        _, _, veng = _serve_engine(num_pages=c.SERVE_VERIFY_PAGES,
                                   num_slots=vs, draft=True, spec_k=vk)
        plan = _chaos.FaultPlan().fail("fault_point",
                                       path=r"spec\.verify")
        with _chaos.active(plan):
            _drive(veng)
        out["spec_fault_degrades"] = plan.fired()
        st = veng.spec_stats()
        out["spec_stats"] = st
        out["spec_traced_once"] = (
            veng.decode_traces == 1 and veng.prefill_traces == 1
            and veng.draft_traces == 1
            and veng.draft_prefill_traces == 1
            and veng.verify_traces == 1)
        out["spec_wins"] = bool(
            st["tokens_per_target_step"] is not None
            and st["tokens_per_target_step"] > 1.0)
        v_compiled = veng.compiled_verify()
        v_hlo = v_compiled.as_text()
        try:
            v_cost = c.normalize_cost(v_compiled.cost_analysis())
        except Exception:
            v_cost = None
        v_ctx = c.ContractContext(
            hlo_text=v_hlo, cost=v_cost,
            trace_counts={"serve.decode": veng.decode_traces,
                          "serve.draft": veng.draft_traces,
                          "serve.verify": veng.verify_traces})
        v_viol = c.evaluate(c.CONTRACTS["serve.verify"], v_ctx)
        v_snap = c.CONTRACT_SNAPSHOTS["serve.verify"]
        if update_snapshots:
            out["verify_snapshot_blessed"] = v_snap.bless(v_hlo)["hash"]
        else:
            v_viol += v_snap.violations(v_ctx)
        out["verify_cost"] = v_cost
        out["verify_violations"] = [v.format() for v in v_viol]
        out["verify_clean"] = not v_viol
        # lattice positive control: compile the dense [slots, window,
        # vocab] logits stack the per-position head avoids — the
        # detector must trip on it
        latdet = next(r for r in c.CONTRACTS["serve.verify"]
                      if isinstance(r, c.NoTemporary))
        lat_hlo = jax.jit(
            lambda h, e: jnp.einsum("swh,vh->swv", h, e)).lower(
                np.zeros((vs, vk + 1, 64), np.float32),
                np.zeros((512, 64), np.float32)).compile().as_text()
        out["lattice_control_trips"] = bool(latdet.temporaries(lat_hlo))
        # speculation-off positive control: judging the plain engine
        # against the verify row must trip TracedOnce (no draft/verify
        # counts exist there — proves the probe is not vacuous)
        off_trips = c.evaluate(
            [r for r in c.CONTRACTS["serve.verify"]
             if isinstance(r, c.TracedOnce)],
            c.ContractContext(
                hlo_text=hlo, cost=cost,
                trace_counts={"serve.decode": engine.decode_traces,
                              "serve.prefill": engine.prefill_traces}))
        out["spec_off_control_trips"] = bool(off_trips)

        if positive_control:
            budgets = [b for b in c.CONTRACTS["serve.decode"]
                       if isinstance(b, c.MaxHloCost)]
            if budgets and cost is not None:
                out["budget_control_trips"] = all(
                    b.with_tolerance(0).check(ctx) for b in budgets)
            v_budgets = [b for b in c.CONTRACTS["serve.verify"]
                         if isinstance(b, c.MaxHloCost)]
            if v_budgets and v_cost is not None:
                out["verify_budget_control_trips"] = all(
                    b.with_tolerance(0).check(v_ctx) for b in v_budgets)
            set_flags({"use_pallas_decode": False})
            _, _, ref_engine = _serve_engine()
            ref_hlo = ref_engine.compiled_decode().as_text()
            ref_temps = dense_score_temporaries(ref_hlo, tmax, min_rows)
            out["positive_control_trips"] = bool(ref_temps)
            # retrace positive control: widening the page table by one
            # column IS a shape leak, so calling the decode jit with it
            # must register as a retrace and trip the TracedOnce row
            # (proves the probe sees real retraces, including any the
            # per-request sampling args could have introduced)
            s = engine.cfg.num_slots
            wide = np.concatenate(
                [engine._page_table,
                 np.zeros((s, 1), engine._page_table.dtype)], axis=1)
            _, engine._caches = engine._decode_jit(
                engine._params, engine._caches, np.zeros(s, np.int32),
                wide, np.zeros(s, np.int32), np.zeros(s, bool),
                np.zeros(s, np.float32), np.zeros(s, np.int32),
                np.zeros(s, np.float32), np.zeros(s, np.uint32),
                np.zeros(s, np.int32))
            ctx_re = c.ContractContext(
                hlo_text=hlo, cost=cost,
                trace_counts={"serve.decode": engine.decode_traces,
                              "serve.prefill": engine.prefill_traces})
            tripped = c.evaluate(
                [r for r in c.CONTRACTS["serve.decode"]
                 if isinstance(r, c.TracedOnce)], ctx_re)
            out["retrace_control_trips"] = bool(tripped)
    finally:
        set_flags(saved)
    out["ok"] = bool(out.get("traced_once") and out.get("clean")
                     and out.get("int8_clean")
                     and out.get("kv_control_trips")
                     and out.get("spec_traced_once")
                     and out.get("spec_wins")
                     and out.get("verify_clean")
                     and out.get("lattice_control_trips")
                     and out.get("spec_off_control_trips")
                     and out.get("spec_fault_degrades") == 1
                     and out.get("positive_control_trips",
                                 not positive_control)
                     and out.get("retrace_control_trips",
                                 not positive_control))
    return out


def mlp_smoke(positive_control=True):
    """Tier-1 contract for the fused GLU/MLP kernel, in-process on CPU:

    with the Pallas path engaged (interpret mode off-TPU), the compiled
    forward holds no [rows, 4H] activation temporary — the kernel
    streams I-axis tiles through a [block_rows, H] accumulator. The
    unfused composition (use_pallas_mlp=0) must TRIP the detector
    (positive control — proves the grep sees the materialized
    activation). Both the plain MLP and the gated (GLU) variant run
    under the same judgment.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    if REPO not in sys.path:       # CLI use; in-suite runs already see it
        sys.path.insert(0, REPO)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.core.flags import all_flags, set_flags

    c = _contracts()
    rows, h, inter = c.MLP_ROWS, c.MLP_HIDDEN, c.MLP_INTER
    rng = np.random.RandomState(0)

    def arr(*s):
        return jnp.asarray(0.02 * rng.randn(*s), jnp.float32)

    x = arr(rows, h)
    mlp_args = (x, arr(h, inter), arr(inter), arr(inter, h), arr(h))
    glu_args = mlp_args + (arr(h, inter), arr(inter))
    detector = c.NoTemporary({inter}, c.MLP_MIN_ROWS)

    def _hlo(*a):
        # fresh jit per flag state: use_pallas_mlp is read at trace time
        from paddle_tpu.ops.pallas.mlp import fused_mlp
        return (jax.jit(lambda *b: fused_mlp(*b))
                .lower(*a).compile().as_text())

    out = {"rows": rows, "hidden": h, "inter": inter}
    saved = all_flags()
    try:
        set_flags({"pallas_interpret": True, "use_pallas_mlp": True})
        violations = []
        for name, a in (("mlp", mlp_args), ("glu", glu_args)):
            hlo = _hlo(*a)
            out[f"{name}_temporaries"] = detector.temporaries(hlo)
            violations += c.evaluate(c.CONTRACTS["mlp.fused"],
                                     c.ContractContext(hlo_text=hlo))
        out["violations"] = [v.format() for v in violations]
        out["clean"] = not violations
        if positive_control:
            set_flags({"use_pallas_mlp": False})
            ref_temps = detector.temporaries(_hlo(*glu_args))
            out["positive_control_trips"] = bool(ref_temps)
    finally:
        set_flags(saved)
    out["ok"] = bool(out.get("clean")
                     and out.get("positive_control_trips",
                                 not positive_control))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt")
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--timeout", type=float, default=600)
    ap.add_argument("--mesh", default=None,
                    help="compile the dp x tp sharded step on fake CPU "
                         "devices, e.g. dp2,tp2")
    ap.add_argument("--hlo-check", action="store_true",
                    help="with --mesh: enforce the sharded-HLO contract "
                         "(no [rows, V] temporary, no vocab-weight "
                         "all-gather) with a positive control")
    ap.add_argument("--autoplan", metavar="TOPOLOGY", default=None,
                    help="autoplan probe: resolve the mesh via "
                         "--mesh auto on the named topology (e.g. cpu4) "
                         "and enforce the train.<model>@auto HLO "
                         "contract")
    ap.add_argument("--mlp", action="store_true",
                    help="fused GLU/MLP probe: the compiled forward "
                         "holds no [rows, 4H] activation temporary "
                         "(positive control included)")
    ap.add_argument("--serve", action="store_true",
                    help="serving fast-path probe: the jitted serve step "
                         "compiles once across admissions and its paged "
                         "HLO holds no [rows, Tmax]-dense attention "
                         "temporary (positive control included)")
    args = ap.parse_args()
    if args.autoplan:
        out = autoplan_check(args.model, args.autoplan, args.timeout)
        print(json.dumps(out))
        if not out["clean"]:
            raise SystemExit("autoplan-mesh HLO contract violated")
        return
    if args.mlp:
        out = mlp_smoke()
        print(json.dumps(out))
        if not out["ok"]:
            raise SystemExit("fused-MLP contract violated")
        return
    if args.serve:
        out = serve_smoke()
        print(json.dumps(out))
        if not out["ok"]:
            raise SystemExit("serve-step contract violated")
        return
    if args.hlo_check:
        if not args.mesh:
            raise SystemExit("--hlo-check needs --mesh")
        out = sharded_vocab_check(args.model, args.mesh, args.timeout)
        print(json.dumps(out))
        if not out["clean"] or not out.get("positive_control_trips", True):
            raise SystemExit("sharded-HLO contract violated")
        return
    row = run(args.model, args.tiny, args.timeout, mesh=args.mesh)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
