#!/usr/bin/env python
"""CI smoke: prove the fused train step jit-compiles without silicon.

Runs ``python bench.py --compile-only --model <m>`` on the CPU backend and
asserts the compile-marker row lands. This is the tier-1 guard for the
step-fusion layer: the chunked fused cross-entropy (custom VJP), the
scan-over-layers + remat encoders, and the fused add+LN path all have to
lower and compile inside one jitted train step — a regression in any of
them trips here, not in the next silicon bench window.

Usage:
  python tools/compile_smoke.py                  # gpt, full-size config
  python tools/compile_smoke.py --tiny           # tiny config (CI budget)
  python tools/compile_smoke.py --model bert --tiny
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(model="gpt", tiny=False, timeout=600, extra_env=None):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)   # never touch the tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra_env or {})
    args = [sys.executable, os.path.join(REPO, "bench.py"),
            "--compile-only", "--model", model]
    if tiny:
        args.append("--tiny")
    proc = subprocess.run(args, stdout=subprocess.PIPE, text=True,
                          timeout=timeout, env=env, cwd=REPO)
    lines = proc.stdout.strip().splitlines()
    if not lines:
        raise SystemExit(f"no bench output (rc={proc.returncode})")
    row = json.loads(lines[-1])
    if not str(row.get("metric", "")).endswith("_compile_only"):
        raise SystemExit(f"fused step failed to compile: {row}")
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt")
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--timeout", type=float, default=600)
    args = ap.parse_args()
    row = run(args.model, args.tiny, args.timeout)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
