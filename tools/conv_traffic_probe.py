"""Isolate WHERE ResNet-50's 234 MB/image HBM traffic (vs ~130 ideal) lives.

Three micro-experiments on the real chip (see BASELINE.md roofline section):

1. C-sweep: one ConvBN-relu fwd+bwd at C in {64, 128, 256} with spatial
   sized so *logical* bytes moved are identical. If the (8,128) tile pads
   C=64 lanes, the C=64 point runs at ~half the logical GB/s of C=128.
2. Stem: the 7x7/s2 C=3->64 conv vs its exact space-to-depth rewrite.
3. Input copy: the NCHW->NHWC transpose + f32->bf16 cast of a batch-128
   image tensor (the per-step feed copy the NHWC_FEED bench row removes).

Prints one JSON line per experiment with ms and logical GB/s.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from tools._timing import device_time


def run():
    n_iter = int(os.environ.get("PT_PROBE_N", "10"))
    # PT_PROBE_TINY=1: shrink every shape ~64x for a 1-core CPU code-path
    # check (the numbers are meaningless off-silicon)
    tiny = os.environ.get("PT_PROBE_TINY", "0") == "1"
    B, BS, IMG = (2, 2, 32) if tiny else (32, 128, 224)
    from paddle_tpu.models.resnet import (_space_to_depth_nhwc,
                                          _stem_s2d_weights)
    from paddle_tpu.ops import nn as F

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform})", flush=True)
    rng = np.random.RandomState(0)

    # ---- 1. C-sweep at constant logical bytes -------------------------
    # 3x3 conv C->C, NHWC bf16, B=32. Logical activation bytes/call scale
    # with B*H*W*C; hold H*W*C fixed at 56*56*256.
    for c, hw in ((64, 112), (128, 79), (256, 56)):
        hw = hw // 4 if tiny else hw
        x = jnp.asarray(rng.randn(B, hw, hw, c), jnp.bfloat16)
        w = jnp.asarray(rng.randn(3, 3, c, c) * 0.05, jnp.bfloat16)

        def fwd_bwd(x, w):
            def loss(x, w):
                y = F.conv2d(x, w, padding=1, data_format="NHWC")
                return jnp.sum(jnp.maximum(y, 0.0).astype(jnp.float32))
            return jax.grad(loss, argnums=(0, 1))(x, w)

        t = device_time(fwd_bwd, (x, w), n=n_iter)
        # fwd: read x, write y; dgrad: read dy, write dx; wgrad: read x+dy
        # -> 6 activation-sized transfers of B*HW^2*C*2 bytes
        gb = 6 * B * hw * hw * c * 2 / 1e9
        print(json.dumps({"probe": f"convbn_c{c}_hw{hw}",
                          "ms": round(t * 1e3, 3),
                          "logical_gbps": round(gb / t, 1)}), flush=True)

    # ---- 2. stem: 7x7/s2 C=3 vs s2d 4x4/s1 C=12 ----------------------
    xs = jnp.asarray(rng.rand(BS, IMG, IMG, 3), jnp.bfloat16)
    w7 = jnp.asarray(rng.randn(7, 7, 3, 64) * 0.05, jnp.bfloat16)

    def stem7(x, w):
        def loss(x, w):
            y = F.conv2d(x, w, stride=2, padding=3, data_format="NHWC")
            return jnp.sum(y.astype(jnp.float32))
        return jax.grad(loss, argnums=(0, 1))(x, w)

    def stem_s2d(x, w):
        def loss(x, w):
            y = F.conv2d(_space_to_depth_nhwc(x), _stem_s2d_weights(w),
                         padding=((2, 1), (2, 1)), data_format="NHWC")
            return jnp.sum(y.astype(jnp.float32))
        return jax.grad(loss, argnums=(0, 1))(x, w)

    for name, fn in (("stem7x7_c3", stem7), ("stem_s2d_c12", stem_s2d)):
        t = device_time(fn, (xs, w7), n=n_iter)
        gb = (BS * IMG * IMG * 3 * 2 * 3 + BS * (IMG // 2) ** 2 * 64 * 2 * 2) / 1e9
        print(json.dumps({"probe": name, "ms": round(t * 1e3, 3),
                          "logical_gbps": round(gb / t, 1)}), flush=True)

    # ---- 3. the input feed copy --------------------------------------
    xc = jnp.asarray(rng.rand(BS, 3, IMG, IMG).astype(np.float32))

    def feed_copy(x):
        return jnp.transpose(x, (0, 2, 3, 1)).astype(jnp.bfloat16)

    t = device_time(feed_copy, (xc,), n=n_iter)
    gb = BS * 3 * IMG * IMG * (4 + 2) / 1e9
    print(json.dumps({"probe": "nchw_to_nhwc_bf16_copy",
                      "ms": round(t * 1e3, 3),
                      "logical_gbps": round(gb / t, 1)}), flush=True)


if __name__ == "__main__":
    run()
