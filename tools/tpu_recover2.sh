#!/usr/bin/env bash
# Second-generation tunnel-recovery bench sequence.
#
# Lessons from day 1 and the 03:16 window (both wedges followed a client
# hard-kill mid-compile):
#   * ONE attempt per row with a window long enough that bench.py never
#     kills a compile in flight (PT_BENCH_ATTEMPTS=1, 520 s timeout).
#   * Skip rows that already produced a number (tools/captured/<row>.json)
#     so a re-run after a wedge goes straight to the missing rows.
#   * Cheapest-compile rows first: a wedge costs the rest of the window,
#     so land the quick ones before risking the long compiles.
#
# Usage: bash tools/tpu_recover2.sh   (typically via tools/tpu_watchdog.sh)
set -u
cd "$(dirname "$0")/.."
LOG=tools/tpu_recover2.log
CAP=tools/captured
mkdir -p "$CAP"
say() { echo "== $*" | tee -a "$LOG"; }

# row <name> <cmd...>: skip if captured; on a metric row, record + commit it.
# Each row first runs a --compile-only prewarm (populates the persistent
# XLA cache) so the timed attempt never straddles a compile — both observed
# tunnel wedges followed a client kill mid-XLA-compile.
row() {
  name=$1; shift
  if [ -f "$CAP/$name.json" ]; then
    say "skip $name (captured)"
    return 0
  fi
  say "prewarm $name"
  PT_BENCH_ATTEMPTS=1 PT_BENCH_TIMEOUT=560 PT_BENCH_WALL=570 \
    timeout 590 "$@" --compile-only >> "$LOG" 2>&1
  say "row $name: $*"
  out=$(PT_BENCH_ATTEMPTS=1 PT_BENCH_TIMEOUT=520 PT_BENCH_WALL=540 \
        timeout 560 "$@" 2>&1)
  echo "$out" >> "$LOG"
  line=$(echo "$out" | grep '"metric"' | grep -v bench_failed \
         | grep -v '"cached": true' | tail -1)
  if [ -n "$line" ]; then
    echo "$line" > "$CAP/$name.json"
    say "captured $name: $line"
    git add "$CAP/$name.json" >> "$LOG" 2>&1 \
      && git commit -q -m "bench: capture $name silicon row" \
             -- "$CAP/$name.json" >> "$LOG" 2>&1 \
      && say "committed $name"
  else
    say "MISS $name"
  fi
}

say "$(date -u +%FT%TZ) recover2 start"

row bert            python bench.py --model bert --steps 10
row bert_b128       python bench.py --model bert --steps 10 --batch 128
row ernie           python bench.py --model ernie --steps 10
row ctr             python bench.py --model ctr --steps 10
row transformer_big python bench.py --model transformer_big --steps 10
row gpt             python bench.py --model gpt --steps 10
row resnet50        python bench.py --model resnet50 --steps 10
row resnet50_s2d    env PT_FLAGS_resnet_s2d_stem=1 python bench.py --model resnet50 --steps 10
row resnet50_nhwc   env PT_BENCH_NHWC_FEED=1 python bench.py --model resnet50 --steps 10
row resnet50_fast   env PT_FLAGS_resnet_s2d_stem=1 PT_BENCH_NHWC_FEED=1 PT_BENCH_BF16_VELOCITY=1 python bench.py --model resnet50 --steps 10
row resnet50_bf16v  env PT_BENCH_BF16_VELOCITY=1 python bench.py --model resnet50 --steps 10
row resnet50_novjp  env PT_FLAGS_conv_custom_vjp=0 python bench.py --model resnet50 --steps 10
row gpt2048         python bench.py --model gpt --steps 10 --seq 2048 --batch 4
row gpt_decode      python bench.py --model gpt_decode --steps 3 --batch 16
row gpt_decode_int8 env PT_BENCH_INT8_DECODE=1 python bench.py --model gpt_decode --steps 3 --batch 16
# per-fusion profile of the flagship row: the 0.43->0.45+ BERT tail attack
# needs to know where the non-flash milliseconds live
row bert_profile    env PT_BENCH_PROFILE=/tmp/pt_bert_prof python bench.py --model bert --steps 10

# tool <marker-name> <success-pattern> <timeout> <cmd...>: run to completion,
# THEN grep the captured output — `tee | grep -q` would SIGPIPE-kill the
# tool after its first matching line and lose the rest of its data.
tool() {
  marker=$1; pattern=$2; tmo=$3; shift 3
  if [ -f "$CAP/$marker.ok" ]; then
    say "skip $marker (captured)"
    return 0
  fi
  say "tool $marker: $*"
  out=$(timeout "$tmo" "$@" 2>&1)
  echo "$out" >> "$LOG"
  if echo "$out" | grep -q "$pattern"; then
    echo "$out" | tail -120 > "$CAP/$marker.txt"
    touch "$CAP/$marker.ok"
    say "captured $marker"
    git add "$CAP/$marker.txt" "$CAP/$marker.ok" >> "$LOG" 2>&1 \
      && git commit -q -m "bench: capture $marker silicon tool output" \
             -- "$CAP/$marker.txt" "$CAP/$marker.ok" >> "$LOG" 2>&1 \
      && say "committed $marker"
  else
    say "MISS $marker"
  fi
}

# patterns are each tool's FINAL output line so a mid-run timeout is a MISS
# axon,cpu: the probe's f64 ground truth needs a cpu backend registered
# alongside the TPU (plain "axon" would make jax.devices("cpu") raise)
tool causal_probe "fa_plain dv"   420 env JAX_PLATFORMS=axon,cpu python tools/causal_bwd_probe.py
tool conv_traffic "nchw_to_nhwc"  420 python tools/conv_traffic_probe.py
tool op_bench     "op_bench.*complete" 560 python tools/op_bench.py --n 20
tool flash_tune   "flip the flash" 560 python tools/flash_tune.py --quick
# full Pallas parity sweep with the f32-precision baseline — the 30/30
# answer to the window-2 causal-bwd question ('"ok": true' only prints
# when every check passed)
tool tpu_smoke    '"ok": true' 560 python tools/tpu_smoke.py --quick

# riskiest compile LAST (blew a 240 s window on day 1)
row resnet50_b256   python bench.py --model resnet50 --steps 10 --batch 256

say "$(date -u +%FT%TZ) recover2 done"
