"""TPU silicon smoke test for the Pallas kernel surface (VERDICT r2 #2).

Runs on the real chip (axon tunnel):
  1. fused layer-norm fwd+bwd parity vs the XLA twin
  2. flash attention fwd parity vs chunked XLA (causal / non-causal /
     kv-masked / tail shapes)
  3. flash attention bwd (Pallas dq/dkv) parity vs chunked autodiff
  4. conv custom-VJP parity vs XLA's native conv gradients
  5. micro-timings (flash vs chunked at BERT-base shapes)

Emits one PASS/FAIL line per check plus a JSON summary; exit code 0 only
if every numeric check passes. Results are recorded in BASELINE.md.

Usage:  timeout 560 python tools/tpu_smoke.py [--quick]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _maxdiff(a, b):
    return float(np.max(np.abs(np.asarray(a, np.float32)
                               - np.asarray(b, np.float32))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="skip the timing section")
    ap.add_argument("--interpret", action="store_true",
                    help="CPU harness self-check via the Pallas interpreter")
    args = ap.parse_args()

    t0 = time.time()
    import jax
    import jax.numpy as jnp

    if args.interpret:
        # CPU self-check must not touch the axon tunnel at all — a wedged
        # tunnel blocks jax.devices() forever (sitecustomize pre-imports
        # jax with the axon platform; config override still works before
        # the backend initializes)
        jax.config.update("jax_platforms", "cpu")

    dev = jax.devices()[0]
    print(f"device: {dev.device_kind} ({dev.platform}) "
          f"[init {time.time() - t0:.1f}s]", flush=True)

    from paddle_tpu.ops.pallas import on_tpu
    if args.interpret:
        from paddle_tpu.core.flags import set_flags as _sf
        _sf({"pallas_interpret": True})
    elif not on_tpu():
        print("NOT A TPU — smoke test requires the real chip", flush=True)
        sys.exit(2)
    interp = bool(args.interpret)

    results = {}
    failed = []

    def check(name, diff, tol):
        ok = diff < tol
        results[name] = {"maxdiff": diff, "tol": tol, "ok": ok}
        print(f"{'PASS' if ok else 'FAIL'} {name}: maxdiff={diff:.3e} "
              f"(tol {tol:.0e})", flush=True)
        if not ok:
            failed.append(name)

    # ---- 1. fused layer norm ------------------------------------------
    from paddle_tpu.ops.pallas.layer_norm import layer_norm_fused
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(256, 512).astype(np.float32))
    g = jnp.asarray((rng.rand(512) + 0.5).astype(np.float32))
    b = jnp.asarray(rng.randn(512).astype(np.float32))

    def ln_ref(x, g, b):
        m = jnp.mean(x, -1, keepdims=True)
        v = jnp.var(x, -1, keepdims=True)
        return (x - m) * jax.lax.rsqrt(v + 1e-5) * g + b

    out = jax.jit(layer_norm_fused)(x, g, b)
    ref = jax.jit(ln_ref)(x, g, b)
    check("ln_fwd", _maxdiff(out, ref), 1e-4)

    co = jnp.asarray(rng.randn(256, 512).astype(np.float32))
    gx, gg, gb = jax.jit(jax.grad(
        lambda *a: jnp.sum(layer_norm_fused(*a) * co), argnums=(0, 1, 2)))(
            x, g, b)
    rx, rg, rb = jax.jit(jax.grad(
        lambda *a: jnp.sum(ln_ref(*a) * co), argnums=(0, 1, 2)))(x, g, b)
    check("ln_bwd_dx", _maxdiff(gx, rx), 5e-3)
    check("ln_bwd_dgamma", _maxdiff(gg, rg), 5e-3)
    check("ln_bwd_dbeta", _maxdiff(gb, rb), 5e-3)

    # ---- 2/3. flash attention ------------------------------------------
    from paddle_tpu.ops.pallas.flash_attention import (
        _flash_attention_bwd_tpu, _flash_attention_fwd_tpu,
        chunked_attention, flash_attention)

    def qkvg(b_, h_, tq, d_, tk=None, seed=0):
        tk = tk or tq
        ks = jax.random.split(jax.random.key(seed), 4)
        return (jax.random.normal(ks[0], (b_, h_, tq, d_), jnp.float32),
                jax.random.normal(ks[1], (b_, h_, tk, d_), jnp.float32),
                jax.random.normal(ks[2], (b_, h_, tk, d_), jnp.float32),
                jax.random.normal(ks[3], (b_, h_, tq, d_), jnp.float32))

    cases = [
        ("fa_plain", dict(b=2, h=4, t=512, d=64, causal=False, mask=False)),
        ("fa_causal", dict(b=2, h=4, t=512, d=64, causal=True, mask=False)),
        ("fa_masked", dict(b=2, h=4, t=512, d=64, causal=False, mask=True)),
        ("fa_tail", dict(b=1, h=2, t=520, d=64, causal=False, mask=False)),
        ("fa_d128", dict(b=1, h=2, t=256, d=128, causal=True, mask=False)),
    ]
    for name, cfg in cases:
        q, k, v, go = qkvg(cfg["b"], cfg["h"], cfg["t"], cfg["d"])
        scale = 1.0 / cfg["d"] ** 0.5
        kv_mask = None
        if cfg["mask"]:
            lens = [cfg["t"] * 3 // 4] + [cfg["t"]] * (cfg["b"] - 1)
            m = np.zeros((cfg["b"], cfg["t"]), bool)
            for i, n in enumerate(lens):
                m[i, :n] = True
            kv_mask = jnp.asarray(m)
        bq = bk = 256
        try:
            out, lse = _flash_attention_fwd_tpu(
                q, k, v, scale, cfg["causal"], bq, bk, kv_mask=kv_mask,
                interpret=interp, return_lse=True)
            out.block_until_ready()
        except Exception as e:  # Mosaic compile failure is a result too
            results[name] = {"error": str(e)[:300], "ok": False}
            print(f"FAIL {name}: {type(e).__name__}: {str(e)[:200]}",
                  flush=True)
            failed.append(name)
            continue
        # The baseline's einsums must run at f32 matmul precision: at the
        # TPU default they truncate operands to bf16, and on causal shapes
        # (softmax mass concentrated on fewer keys -> larger p entries)
        # that puts ~1e-2 of absolute noise in the BASELINE — the window-2
        # "4/30 causal-bwd failures" signature. The Pallas kernels compute
        # their dots in f32, so compare against an f32 reference
        # (VERDICT r4 #4; tools/causal_bwd_probe.py decides this
        # independently on silicon).
        with jax.default_matmul_precision("float32"):
            ref = chunked_attention(q, k, v, scale=scale,
                                    causal=cfg["causal"],
                                    kv_mask=kv_mask, chunk_size=bk)
        check(name + "_fwd", _maxdiff(out, ref), 2e-3)

        try:
            dq, dk, dv = _flash_attention_bwd_tpu(
                q, k, v, out, lse, go, scale, cfg["causal"], bq, bk,
                kv_mask=kv_mask, interpret=interp)
            dq.block_until_ready()
        except Exception as e:
            results[name + "_bwd"] = {"error": str(e)[:300], "ok": False}
            print(f"FAIL {name}_bwd: {type(e).__name__}: {str(e)[:200]}",
                  flush=True)
            failed.append(name + "_bwd")
            continue
        with jax.default_matmul_precision("float32"):
            _, vjp = jax.vjp(lambda a, b_, c: chunked_attention(
                a, b_, c, scale=scale, causal=cfg["causal"],
                kv_mask=kv_mask, chunk_size=bk), q, k, v)
            rdq, rdk, rdv = vjp(go)
        check(name + "_dq", _maxdiff(dq, rdq), 5e-3)
        check(name + "_dk", _maxdiff(dk, rdk), 5e-3)
        check(name + "_dv", _maxdiff(dv, rdv), 5e-3)

    # ---- 4. conv custom VJP -------------------------------------------
    from paddle_tpu.core.flags import get_flag, set_flags
    from paddle_tpu.ops import nn as F
    xc = jnp.asarray(rng.randn(8, 56, 56, 64).astype(np.float32))
    wc = jnp.asarray(rng.randn(3, 3, 64, 64).astype(np.float32) * 0.05)
    gc = jnp.asarray(rng.randn(8, 56, 56, 64).astype(np.float32))

    def conv_loss(x_, w_):
        return jnp.sum(F.conv2d(x_, w_, stride=1, padding=1,
                                data_format="NHWC") * gc)

    old = get_flag("conv_custom_vjp")
    set_flags({"conv_custom_vjp": True})
    try:
        gxc, gwc = jax.jit(jax.grad(conv_loss, argnums=(0, 1)))(xc, wc)
        gxc.block_until_ready()
    finally:
        set_flags({"conv_custom_vjp": old})
    set_flags({"conv_custom_vjp": False})
    try:
        rxc, rwc = jax.jit(jax.grad(conv_loss, argnums=(0, 1)))(xc, wc)
    finally:
        set_flags({"conv_custom_vjp": old})
    check("conv_vjp_dx", _maxdiff(gxc, rxc), 5e-2)
    check("conv_vjp_dw", _maxdiff(gwc, rwc), 5e-2)

    # ---- 4b. maxpool grad (native SelectAndScatter executes on silicon) -
    # (the argmax-scatter alternative was removed after the 2026-07-31
    # silicon run: duplicate-index scatters serialize on TPU, 327 ms/step)
    xm = jnp.asarray(rng.randn(32, 112, 112, 64).astype(np.float32))


    def mp_loss(x_):
        return jnp.sum(F.pool2d(x_, 3, "max", 2, padding=1,
                                data_format="NHWC") ** 2)

    mp_ref = jax.jit(jax.grad(mp_loss))(xm)
    mp_ref.block_until_ready()
    # executed marker only: this exercises that select-and-scatter lowers and
    # runs on silicon; numeric maxpool-grad parity is covered by the CPU suite
    results["maxpool_grad_runs"] = {"ok": True}
    print("PASS maxpool_grad_runs (executed)", flush=True)

    # ---- 4c. ring flash attention fwd+bwd on silicon -------------------
    # a 1-device mesh runs the REAL ring code path (fori_loop + ppermute +
    # the Pallas per-block kernels and the custom ring VJP) on the chip
    # without needing multiple devices; parity vs the dense ring.
    from paddle_tpu.parallel.pipeline import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    from paddle_tpu.parallel.ring_attention import (ring_attention,
                                                    ring_flash_attention)
    ring_mesh = Mesh(np.array(jax.devices()[:1]), ("sp",))
    qr = jnp.asarray(rng.randn(1, 2, 128, 64).astype(np.float32))
    wr = jnp.asarray(rng.randn(1, 2, 128, 64).astype(np.float32))

    def ring_loss(fn):
        body = lambda a, b, c, w: jax.lax.psum(
            jnp.sum(fn(a, b, c, "sp", causal=True) * w), "sp")
        return shard_map(body, mesh=ring_mesh,
                         in_specs=(P(None, None, "sp", None),) * 4,
                         out_specs=P(), check_vma=False)

    rf_out = jax.jit(lambda q: ring_loss(ring_flash_attention)(
        q, qr, qr, wr))(qr)
    rd_out = jax.jit(lambda q: ring_loss(ring_attention)(
        q, qr, qr, wr))(qr)
    check("ring_flash_fwd", _maxdiff(rf_out, rd_out), 2e-2)
    rf_g = jax.jit(jax.grad(lambda q: ring_loss(ring_flash_attention)(
        q, qr, qr, wr)))(qr)
    rd_g = jax.jit(jax.grad(lambda q: ring_loss(ring_attention)(
        q, qr, qr, wr)))(qr)
    check("ring_flash_bwd_dq", _maxdiff(rf_g, rd_g), 5e-2)

    # ---- 4d. max_pool2d_with_index custom VJP --------------------------
    from paddle_tpu.ops.vision import max_pool2d_with_index
    xi = jnp.asarray(rng.randn(2, 3, 16, 16).astype(np.float32))
    gi = jax.jit(jax.grad(lambda x_: jnp.sum(
        max_pool2d_with_index(x_, 2, pool_stride=2)[0] ** 2)))(xi)
    ri = jax.jit(jax.grad(lambda x_: jnp.sum(
        F.pool2d(x_, 2, "max", 2) ** 2)))(xi)
    check("maxpool_index_vjp_dx", _maxdiff(gi, ri), 1e-3)

    # ---- 4e. fused-xent Pallas kernels (fwd stats + bwd dh/dw/db) ------
    # parity vs the chunked XLA twins, incl. an out-of-range label (the
    # vocab-sharded per-shard call path: that row's one-hot must vanish)
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.ops.fused import (_smooth_consts, _xent_bwd_impl,
                                      _xent_stats_xla)
    from paddle_tpu.ops.pallas.xent import xent_bwd_pallas, xent_stats
    xh = jnp.asarray(rng.randn(24, 64).astype(np.float32))
    xw = jnp.asarray(rng.randn(100, 64).astype(np.float32) * 0.1)
    xb = jnp.asarray(rng.randn(100).astype(np.float32) * 0.1)
    xl = jnp.asarray(rng.randint(0, 100, (24,)).astype(np.int32))
    xl = xl.at[3].set(150)  # out of range: never hits
    xg = jnp.asarray(rng.rand(24).astype(np.float32))
    logz_r, picked_r, sl_r = _xent_stats_xla(xh, xw, xb, xl, "vh", 32,
                                             True)
    st = xent_stats(xh, xw, xb, xl)
    if st is None:
        results["xent_fwd_stats"] = {"error": "kernel gated off",
                                     "ok": False}
        print("FAIL xent_fwd_stats: kernel gated off", flush=True)
    else:
        check("xent_fwd_stats", max(_maxdiff(st[0], logz_r),
                                    _maxdiff(st[1], picked_r),
                                    _maxdiff(st[2], sl_r)), 1e-3)
    sn, sp = _smooth_consts(100, 0.1)
    set_flags({"use_pallas_xent_bwd": False})
    dref = _xent_bwd_impl(xh, xw, xb, xl, logz_r, xg, "vh", sn, sp, 32)
    set_flags({"use_pallas_xent_bwd": True})
    dk = xent_bwd_pallas(xh, xw, xb, xl, logz_r, xg, sn, sp,
                         interpret=args.interpret)
    check("xent_bwd_dh", _maxdiff(dk[0], dref[0]), 1e-3)
    check("xent_bwd_dw", _maxdiff(dk[1], dref[1]), 1e-3)
    check("xent_bwd_db", _maxdiff(dk[2], dref[2]), 1e-3)

    # ---- 5. micro-timings ---------------------------------------------
    if not args.quick:
        from _timing import device_time

        def timeit(f, *a, n=20):
            # chained-scan timing (see tools/_timing.py): independent
            # dispatches fetched once are NOT a barrier on the tunnel
            return device_time(f, a, n=n)

        q, k, v, go = qkvg(8, 12, 512, 64, seed=1)
        scale = 1.0 / 8.0
        fl = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=False))
        ch = jax.jit(lambda q, k, v: chunked_attention(q, k, v, scale=scale))

        def fl_bwd(q, k, v):
            return jax.grad(lambda a: jnp.sum(
                flash_attention(a, k, v, causal=False)))(q)

        t_fl = timeit(fl, q, k, v)
        t_ch = timeit(ch, q, k, v)
        t_flb = timeit(jax.jit(fl_bwd), q, k, v)
        t_mp_ref = timeit(jax.jit(jax.grad(mp_loss)), xm)
        results["timing_ms"] = {
            "flash_fwd": round(t_fl * 1e3, 3),
            "chunked_fwd": round(t_ch * 1e3, 3),
            "flash_fwd_bwd": round(t_flb * 1e3, 3),
            "maxpool_grad_selscatter": round(t_mp_ref * 1e3, 3),
        }
        print(f"timing b8 h12 t512 d64: flash {t_fl*1e3:.3f} ms, "
              f"chunked {t_ch*1e3:.3f} ms, flash f+b {t_flb*1e3:.3f} ms; "
              f"maxpool-grad sel-scatter {t_mp_ref*1e3:.3f} ms",
              flush=True)

    print(json.dumps({"ok": not failed, "failed": failed,
                      "n_checks": len(results)}))
    sys.exit(1 if failed else 0)


if __name__ == "__main__":
    main()
