"""Render tools/captured/*.json into a markdown table for BASELINE.md.

Usage: python tools/captured_report.py
Prints one table row per captured bench row (plus tool markers), newest
last — paste into BASELINE.md after a silicon window, or just read it.
"""

import glob
import json
import os
import time

CAP = os.path.join(os.path.dirname(os.path.abspath(__file__)), "captured")


def main():
    rows = []
    for path in sorted(glob.glob(os.path.join(CAP, "*.json")),
                       key=os.path.getmtime):
        name = os.path.basename(path)[:-5]
        try:
            with open(path) as f:
                r = json.loads(f.read().strip())
        except ValueError:
            rows.append((name, "(corrupt capture)", "", "", "", ""))
            continue
        when = time.strftime("%m-%d %H:%MZ",
                             time.gmtime(os.path.getmtime(path)))
        perf = r.get("mfu", r.get("hbm_util", ""))
        rows.append((name, r.get("metric", "?"), r.get("value", ""),
                     r.get("unit", ""), perf, when))
    tools = [os.path.basename(p)[:-3]
             for p in sorted(glob.glob(os.path.join(CAP, "*.ok")))]

    print("| row | metric | value | unit | mfu/hbm | captured |")
    print("|---|---|---|---|---|---|")
    for name, metric, value, unit, perf, when in rows:
        print(f"| {name} | {metric} | {value} | {unit} | {perf} | {when} |")
    if tools:
        print(f"\ntool captures: {', '.join(tools)} "
              f"(outputs in tools/captured/<name>.txt)")
    if not rows and not tools:
        print("\n(no captures yet — tools/tpu_recover2.sh fills this on "
              "the next tunnel window)")


if __name__ == "__main__":
    main()
