"""Flash-attention block-size autotune on silicon.

The Pallas flash kernels default to (block_q, block_k) = (512, 512) —
chosen analytically, never measured on the chip (the round-3 tunnel
outage). This sweeps the block grid at the flagship shapes and prints the
fastest configuration per (shape, causal) so the defaults can be flipped
with evidence.

Usage:  timeout 560 python tools/flash_tune.py [--quick] [--interpret]
Each row: fwd and fwd+bwd wall time (dispatch-latency-cancelled, same
two-run trick as bench.py), best marked with '*'.
"""

import argparse
import itertools
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="one shape, fewer block pairs")
    ap.add_argument("--interpret", action="store_true",
                    help="CPU plumbing self-check (timings meaningless)")
    args = ap.parse_args()

    import os
    import numpy as np

    import jax
    import jax.numpy as jnp

    # share bench.py's persistent compile cache — this tool compiles up to
    # 36 distinct kernels, the exact cost the cache exists to amortize
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _enable_compile_cache
    _enable_compile_cache()

    from paddle_tpu.ops.pallas import on_tpu
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    if args.interpret:
        from paddle_tpu.core.flags import set_flags
        set_flags({"pallas_interpret": True})
    elif not on_tpu():
        print("NOT A TPU — pass --interpret for the CPU plumbing check")
        sys.exit(2)

    # flagship shapes: BERT-base (B=64, H=12, T=512, D=64) and GPT-small
    # (B=16, H=12, T=1024? max_position dependent) — trimmed under --quick
    shapes = [("bert_base", 64, 12, 512, 64, False),
              ("gpt_small", 16, 12, 512, 64, True)]
    blocks = [128, 256, 512]
    if args.quick:
        shapes = shapes[:1]
        blocks = [128, 512]
    if args.interpret:  # plumbing check only: tiny shape, 2 block pairs
        shapes = [("tiny", 1, 2, 128, 64, True)]
        blocks = [64, 128]

    def timed(f, *xs, n=10):
        out = f(*xs)
        jax.tree_util.tree_map(
            lambda t: t.block_until_ready() if hasattr(
                t, "block_until_ready") else t, out)

        def run(k):
            t0 = time.perf_counter()
            r = None
            for _ in range(k):
                r = f(*xs)
            jax.tree_util.tree_map(
                lambda t: float(jnp.sum(t)) if hasattr(t, "dtype") else t,
                r)  # host fetch = true barrier on the tunnel
            return time.perf_counter() - t0

        t1 = run(n)
        t2 = run(2 * n)
        return max(t2 - t1, 1e-9) / n

    rng = np.random.RandomState(0)
    for name, b, h, t, d, causal in shapes:
        q = jnp.asarray(rng.randn(b, h, t, d).astype(np.float32))
        rows = []
        print(f"\n{name} [B={b} H={h} T={t} D={d} causal={causal}]",
              flush=True)
        for bq, bk in itertools.product(blocks, blocks):
            fwd = jax.jit(lambda q_, bq=bq, bk=bk: flash_attention(
                q_, q_, q_, causal=causal, block_q=bq, block_k=bk))
            bwd = jax.jit(jax.grad(lambda q_, bq=bq, bk=bk: jnp.sum(
                flash_attention(q_, q_, q_, causal=causal, block_q=bq,
                                block_k=bk))))
            n = 2 if args.interpret else 10
            tf = timed(fwd, q, n=n)
            tb = timed(bwd, q, n=n)
            rows.append((bq, bk, tf, tb))
            # print as measured: a timeout mid-sweep keeps partial data
            print(f"  bq={bq:<4} bk={bk:<4} fwd {tf * 1e3:8.3f} ms   "
                  f"fwd+bwd {tb * 1e3:8.3f} ms", flush=True)
        bq, bk, tf, tb = min(rows, key=lambda r: r[3])
        print(f"  best fwd+bwd: bq={bq} bk={bk} ({tb * 1e3:.3f} ms; "
              f"fwd {tf * 1e3:.3f} ms)", flush=True)
    print("\nflip the flash_attention defaults to the best pair if it "
          "beats (512, 512) consistently")


if __name__ == "__main__":
    main()
