"""Flash-attention block-size autotune on silicon.

The Pallas flash kernels default to (block_q, block_k) = (512, 512) —
chosen analytically, never measured on the chip (the round-3 tunnel
outage). This sweeps the block grid at the flagship shapes and prints the
fastest configuration per (shape, causal) so the defaults can be flipped
with evidence.

Usage:  timeout 560 python tools/flash_tune.py [--quick] [--interpret]
Each row: fwd and fwd+bwd wall time (dispatch-latency-cancelled, same
two-run trick as bench.py), best marked with '*'.
"""

import argparse
import itertools
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="one shape, fewer block pairs")
    ap.add_argument("--interpret", action="store_true",
                    help="CPU plumbing self-check (timings meaningless)")
    args = ap.parse_args()

    import os
    import numpy as np

    import jax
    import jax.numpy as jnp

    # share bench.py's persistent compile cache — this tool compiles up to
    # 36 distinct kernels, the exact cost the cache exists to amortize
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench import _enable_compile_cache
    _enable_compile_cache()

    from paddle_tpu.ops.pallas import on_tpu
    from paddle_tpu.ops.pallas.flash_attention import flash_attention
    if args.interpret:
        from paddle_tpu.core.flags import set_flags
        set_flags({"pallas_interpret": True})
    elif not on_tpu():
        print("NOT A TPU — pass --interpret for the CPU plumbing check")
        sys.exit(2)

    # flagship shapes: BERT-base (B=64, H=12, T=512, D=64) and GPT-small
    # (B=16, H=12, T=1024? max_position dependent) — trimmed under --quick
    shapes = [("bert_base", 64, 12, 512, 64, False),
              ("gpt_small", 16, 12, 512, 64, True)]
    blocks = [128, 256, 512]
    if args.quick:
        shapes = shapes[:1]
        blocks = [128, 512]
    if args.interpret:  # plumbing check only: tiny shape, 2 block pairs
        shapes = [("tiny", 1, 2, 128, 64, True)]
        blocks = [64, 128]

    from _timing import device_time

    from paddle_tpu.ops.pallas.flash_attention import _legal_block

    rng = np.random.RandomState(0)
    for name, b, h, t, d, causal in shapes:
        # bf16 inputs — the bench path runs flash under the amp bf16
        # policy, and block optima can differ by dtype (VMEM footprint
        # halves). Interpret mode keeps f32 (Mosaic-free plumbing check).
        dtype = jnp.float32 if args.interpret else jnp.bfloat16
        q = jnp.asarray(rng.randn(b, h, t, d), dtype)
        rows = []
        print(f"\n{name} [B={b} H={h} T={t} D={d} causal={causal}]",
              flush=True)
        seen_eff = set()
        for bq, bk in itertools.product(blocks, blocks):
            # report the block sizes that actually execute (the kernel
            # legalizes sub-128 lanes); skip pairs that collapse to an
            # already-measured effective config
            ebq = _legal_block(bq, t, args.interpret)
            ebk = _legal_block(bk, t, args.interpret)
            if (ebq, ebk) in seen_eff:
                continue
            seen_eff.add((ebq, ebk))
            if (ebq, ebk) != (bq, bk):
                print(f"  (bq={bq} bk={bk} legalizes to {ebq},{ebk})",
                      flush=True)
            bq, bk = ebq, ebk
            fwd = lambda q_, bq=bq, bk=bk: flash_attention(
                q_, q_, q_, causal=causal, block_q=bq, block_k=bk)
            bwd = jax.grad(lambda q_, bq=bq, bk=bk: jnp.sum(
                flash_attention(q_, q_, q_, causal=causal, block_q=bq,
                                block_k=bk)))
            n = 2 if args.interpret else 10
            try:
                tf = device_time(fwd, (q,), n=n)
                tb = device_time(bwd, (q,), n=n)
            except Exception as e:  # keep sweeping past a bad config
                print(f"  bq={bq:<4} bk={bk:<4} ERROR "
                      f"{type(e).__name__}: {str(e)[:120]}", flush=True)
                continue
            rows.append((bq, bk, tf, tb))
            # print as measured: a timeout mid-sweep keeps partial data
            print(f"  bq={bq:<4} bk={bk:<4} fwd {tf * 1e3:8.3f} ms   "
                  f"fwd+bwd {tb * 1e3:8.3f} ms", flush=True)
        if not rows:
            print("  (no config succeeded)", flush=True)
            continue
        bq, bk, tf, tb = min(rows, key=lambda r: r[3])
        print(f"  best fwd+bwd: bq={bq} bk={bk} ({tb * 1e3:.3f} ms; "
              f"fwd {tf * 1e3:.3f} ms)", flush=True)
    print("\nflip the flash_attention defaults to the best pair if it "
          "beats (512, 512) consistently")


if __name__ == "__main__":
    main()
