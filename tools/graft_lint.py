#!/usr/bin/env python
"""graft-lint CLI: the repo's static-analysis front door.

Runs the AST rule layer (paddle_tpu/analysis/lint.py + rules/) over the
tree and exits non-zero on any finding. The heavy compile-contract layer
(paddle_tpu/analysis/contracts.py, evaluated against real compiled HLO)
is opt-in via --contracts because it compiles models.

Usage:
  python tools/graft_lint.py                    # whole tree, human output
  python tools/graft_lint.py --format json      # machine-readable
  python tools/graft_lint.py --changed-only     # pre-commit: only files
                                                #   this branch touches
                                                #   (merge-base w/ main)
  python tools/graft_lint.py --rules flag-drift,catalog-drift
  python tools/graft_lint.py --fail-on error    # warn-level findings
                                                #   report but exit 0
  python tools/graft_lint.py --list             # rules + contract table
  python tools/graft_lint.py --contracts serve.decode,train.gpt@dp2,tp2
  python tools/graft_lint.py --contracts all    # every CONTRACTS row
  python tools/graft_lint.py --contracts all --update-snapshots
                                                # re-bless HLO snapshots

tools/pre_commit.sh wraps the --changed-only form for .git/hooks.

The AST layer is stdlib-only and finishes in well under a second: the
repo package is entered through a namespace stub so paddle_tpu/__init__
(and with it jax) is never imported for a plain lint run.

Suppressions are per line, reason mandatory:
  x = np.asarray(d)  # graft-lint: disable=hot-path-sync (scheduler needs this)
"""

import argparse
import json
import os
import subprocess
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_analysis():
    """paddle_tpu.analysis without paddle_tpu/__init__'s jax import: a
    namespace stub with the real package __path__ keeps submodule
    resolution intact while skipping the parent's side effects."""
    if "paddle_tpu" not in sys.modules:
        pkg = types.ModuleType("paddle_tpu")
        pkg.__path__ = [os.path.join(REPO, "paddle_tpu")]
        sys.modules["paddle_tpu"] = pkg
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from paddle_tpu.analysis import lint
    return lint


def _git(*args):
    proc = subprocess.run(
        ["git", "-C", REPO] + list(args),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    return proc.stdout if proc.returncode == 0 else ""


def _changed_paths(base_branch="main"):
    """Repo-relative paths this branch touches: diff against the
    merge-base with ``base_branch`` (NOT plain HEAD — work already
    committed on the branch still lints in a pre-push run), plus
    staged/unstaged edits and untracked .py files."""
    base = _git("merge-base", "HEAD", base_branch).strip() or "HEAD"
    paths = set()
    for extra in ([], ["--cached"]):
        out = _git("diff", "--name-only", base, *extra)
        paths.update(p for p in out.splitlines() if p.strip())
    out = _git("ls-files", "--others", "--exclude-standard")
    paths.update(p for p in out.splitlines()
                 if p.strip() and p.endswith(".py"))
    return paths


def _parse_contract_names(spec, known):
    """Split a --contracts value into row names. Row names themselves
    contain commas (mesh specs: ``train.gpt@dp2,tp2``), so a plain
    split would shred them — accumulate tokens until they match a
    known name instead."""
    if spec == "all":
        return sorted(known)
    names, cur = [], ""
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        cur = f"{cur},{tok}" if cur else tok
        if cur in known:
            names.append(cur)
            cur = ""
    if cur:
        raise SystemExit(f"unknown contract {cur!r}; "
                         f"known: {sorted(known)}")
    return names


def _run_contracts(spec, update_snapshots=False):
    """Evaluate CONTRACTS rows named by the --contracts value (compiles
    models — minutes, and imports jax). Returns findings-shaped dicts.
    ``update_snapshots`` re-blesses the HloSnapshot records instead of
    judging them."""
    sys.modules.pop("paddle_tpu", None)   # drop the stub: real jax now
    import tools.compile_smoke as cs
    c = cs._contracts()
    names = _parse_contract_names(spec, c.CONTRACTS)
    out = []
    for name in names:
        if name.startswith("train."):
            model = name[len("train."):].split("@")[0]
            res = cs.sharded_vocab_check(
                model=model, positive_control=False,
                update_snapshots=update_snapshots)
        else:
            res = cs.serve_smoke(update_snapshots=update_snapshots)
        if "snapshot_blessed" in res:
            print(f"blessed {name} snapshot: {res['snapshot_blessed']}",
                  file=sys.stderr)
        for v in res.get("violations", []):
            out.append({"rule": f"contract:{name}", "path": name,
                        "line": 0, "message": v, "severity": "error"})
        if not res.get("clean", False) and not res.get("violations"):
            out.append({"rule": f"contract:{name}", "path": name,
                        "line": 0, "severity": "error",
                        "message": f"contract row failed: {res}"})
    return out


def _emit_metrics(records, contract_records):
    """Count findings into the process-global registry so a CI harness
    that snapshots/exports metrics can trend which detectors fire.
    observability.metrics is stdlib-only, so a plain lint run still
    never imports jax."""
    from paddle_tpu.observability import metrics
    for r in records:
        metrics.counter("lint.findings").inc(rule=r["rule"])
    for r in contract_records:
        contract = r["rule"].split(":", 1)[-1]
        metrics.counter("contracts.violations").inc(contract=contract)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="repo static analysis: AST rules + compile contracts")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files changed vs HEAD "
                         "(tree-wide rules still see the whole tree)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--list", action="store_true",
                    help="list rules and contract rows, then exit")
    ap.add_argument("--contracts", default=None,
                    help="also evaluate these CONTRACTS rows ('all' or "
                         "comma-separated names) — compiles models, "
                         "needs jax")
    ap.add_argument("--update-snapshots", action="store_true",
                    help="with --contracts: re-bless the HloSnapshot "
                         "records under tests/fixtures/hlo_snapshots/ "
                         "instead of judging against them")
    ap.add_argument("--fail-on", choices=("warn", "error"),
                    default="warn",
                    help="minimum severity that fails the run: 'warn' "
                         "(default — any finding) or 'error' (advisory "
                         "warn-level findings are reported but exit 0)")
    ap.add_argument("--root", default=REPO, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    lint = _import_analysis()

    if args.list:
        print("rules:")
        for name, help_ in lint.rule_help().items():
            print(f"  {name:20s} {help_}")
        from paddle_tpu.analysis import contracts
        print("contracts (--contracts, compiles models):")
        for name, row in contracts.CONTRACTS.items():
            print(f"  {name:30s} {', '.join(c.name for c in row)}")
        return 0

    rules = None
    if args.rules:
        rules = lint.make_rules(
            [r.strip() for r in args.rules.split(",") if r.strip()])

    paths = _changed_paths() if args.changed_only else None
    ctx = lint.LintContext(args.root)
    findings = lint.run_lint(ctx, rules=rules, paths=paths)
    records = [f.as_dict() for f in findings]

    contract_records = []
    if args.contracts:
        contract_records = _run_contracts(
            args.contracts.strip(),
            update_snapshots=args.update_snapshots)
    _emit_metrics(records, contract_records)
    records += contract_records

    failing = [r for r in records
               if args.fail_on == "warn"
               or r.get("severity", "error") == "error"]
    if args.format == "json":
        print(json.dumps({"findings": records, "ok": not failing}))
    else:
        for r in records:
            print(f"{r['path']}:{r['line']}: [{r['rule']}] {r['message']}")
        n = len(records)
        scope = f"{len(paths)} changed file(s)" if paths is not None \
            else "tree"
        print(f"graft-lint: {n} finding(s) over {scope}"
              + ("" if n else " — clean")
              + ("" if len(failing) == n
                 else f" ({n - len(failing)} warn-level, not failing)"))
    return 1 if failing else 0


if __name__ == "__main__":
    raise SystemExit(main())
