#!/usr/bin/env python
"""graft-lint CLI: the repo's static-analysis front door.

Runs the AST rule layer (paddle_tpu/analysis/lint.py + rules/) over the
tree and exits non-zero on any finding. The heavy compile-contract layer
(paddle_tpu/analysis/contracts.py, evaluated against real compiled HLO)
is opt-in via --contracts because it compiles models.

Usage:
  python tools/graft_lint.py                    # whole tree, human output
  python tools/graft_lint.py --format json      # machine-readable
  python tools/graft_lint.py --changed-only     # pre-commit: only files
                                                #   touched vs HEAD
  python tools/graft_lint.py --rules flag-drift,catalog-drift
  python tools/graft_lint.py --list             # rules + contract table
  python tools/graft_lint.py --contracts serve.decode,train.gpt@dp2,tp2
  python tools/graft_lint.py --contracts all    # every CONTRACTS row

The AST layer is stdlib-only and finishes in well under a second: the
repo package is entered through a namespace stub so paddle_tpu/__init__
(and with it jax) is never imported for a plain lint run.

Suppressions are per line, reason mandatory:
  x = np.asarray(d)  # graft-lint: disable=hot-path-sync (scheduler needs this)
"""

import argparse
import json
import os
import subprocess
import sys
import types

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _import_analysis():
    """paddle_tpu.analysis without paddle_tpu/__init__'s jax import: a
    namespace stub with the real package __path__ keeps submodule
    resolution intact while skipping the parent's side effects."""
    if "paddle_tpu" not in sys.modules:
        pkg = types.ModuleType("paddle_tpu")
        pkg.__path__ = [os.path.join(REPO, "paddle_tpu")]
        sys.modules["paddle_tpu"] = pkg
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from paddle_tpu.analysis import lint
    return lint


def _changed_paths():
    """Repo-relative paths touched vs HEAD (staged + unstaged + new)."""
    paths = set()
    for extra in (["--cached"], []):
        proc = subprocess.run(
            ["git", "-C", REPO, "diff", "--name-only", "HEAD"] + extra,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        if proc.returncode == 0:
            paths.update(p for p in proc.stdout.splitlines() if p.strip())
    proc = subprocess.run(
        ["git", "-C", REPO, "ls-files", "--others", "--exclude-standard"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
    if proc.returncode == 0:
        paths.update(p for p in proc.stdout.splitlines() if p.strip())
    return paths


def _run_contracts(names):
    """Evaluate CONTRACTS rows by name (compiles models — minutes, and
    imports jax). Returns findings-shaped dicts."""
    sys.modules.pop("paddle_tpu", None)   # drop the stub: real jax now
    import tools.compile_smoke as cs
    c = cs._contracts()
    if names == ["all"]:
        names = sorted(c.CONTRACTS)
    unknown = [n for n in names if n not in c.CONTRACTS]
    if unknown:
        raise SystemExit(f"unknown contracts {unknown}; "
                         f"known: {sorted(c.CONTRACTS)}")
    out = []
    for name in names:
        if name.startswith("train."):
            model = name[len("train."):].split("@")[0]
            res = cs.sharded_vocab_check(model=model,
                                         positive_control=False)
        else:
            res = cs.serve_smoke()
        for v in res.get("violations", []):
            out.append({"rule": f"contract:{name}", "path": name,
                        "line": 0, "message": v})
        if not res.get("clean", False) and not res.get("violations"):
            out.append({"rule": f"contract:{name}", "path": name,
                        "line": 0, "message": f"contract row failed: {res}"})
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="repo static analysis: AST rules + compile contracts")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--changed-only", action="store_true",
                    help="report only findings in files changed vs HEAD "
                         "(tree-wide rules still see the whole tree)")
    ap.add_argument("--format", choices=("human", "json"), default="human")
    ap.add_argument("--list", action="store_true",
                    help="list rules and contract rows, then exit")
    ap.add_argument("--contracts", default=None,
                    help="also evaluate these CONTRACTS rows ('all' or "
                         "comma-separated names) — compiles models, "
                         "needs jax")
    ap.add_argument("--root", default=REPO, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    lint = _import_analysis()

    if args.list:
        print("rules:")
        for name, help_ in lint.rule_help().items():
            print(f"  {name:20s} {help_}")
        from paddle_tpu.analysis import contracts
        print("contracts (--contracts, compiles models):")
        for name, row in contracts.CONTRACTS.items():
            print(f"  {name:30s} {', '.join(c.name for c in row)}")
        return 0

    rules = None
    if args.rules:
        rules = lint.make_rules(
            [r.strip() for r in args.rules.split(",") if r.strip()])

    paths = _changed_paths() if args.changed_only else None
    ctx = lint.LintContext(args.root)
    findings = lint.run_lint(ctx, rules=rules, paths=paths)
    records = [f.as_dict() for f in findings]

    if args.contracts:
        records.extend(_run_contracts(
            [c.strip() for c in args.contracts.split(",") if c.strip()]))

    if args.format == "json":
        print(json.dumps({"findings": records, "ok": not records}))
    else:
        for r in records:
            print(f"{r['path']}:{r['line']}: [{r['rule']}] {r['message']}")
        n = len(records)
        scope = f"{len(paths)} changed file(s)" if paths is not None \
            else "tree"
        print(f"graft-lint: {n} finding(s) over {scope}"
              + ("" if n else " — clean"))
    return 1 if records else 0


if __name__ == "__main__":
    raise SystemExit(main())
