"""Tunnel-safe device timing for the micro-bench tools.

On the axon-tunneled TPU, `block_until_ready` returns before the device
finishes and only a host fetch is a true barrier. Timing n *independent*
dispatches and fetching the last result is NOT a barrier for the first
n-1 (their executions can still be in flight), which is how op_bench r4
printed 0.0 ms rows on day 1. The fix: run the n iterations inside one
jitted `lax.scan` and make the value the host finally fetches
*data-depend on every iteration's output* — a scalar accumulator in the
carry that sums each iteration's first output leaf. Day 1 on silicon
showed that routing outputs through `lax.optimization_barrier` alone is
NOT enough: the barrier's unused output elements (and their producing
computation) were still eliminated, and matmul/conv rows read 0.0 ms.
A reduction the result depends on cannot be DCE'd or narrowed (XLA can
rewrite slice-of-dot to a smaller dot, but not sum-of-dot), and it
fuses into the producer's epilogue so it adds no extra HBM pass. The
inputs still pass through the barrier so the op cannot be hoisted out
of the loop or CSE'd across iterations.

The per-step time is the (2n-run − n-run) difference so the fixed
dispatch+fetch round trip cancels, same convention as bench.py
`_timed_steps`.
"""

import time

import jax
import jax.numpy as jnp


def _make_loop(f, n):
    @jax.jit
    def loop(*xs):
        def body(carry, _):
            xs, acc = carry
            y = f(*xs)
            leaves = tuple(jax.tree_util.tree_leaves(y))
            if leaves:
                # acc consumes every leaf: the final fetch of acc forces
                # every iteration's f to really execute on the device.
                # sum(|l|), not sum(l): a LINEAR reduction of a dot can be
                # algebraically folded (sum(A@B) == rowsum(A)·colsum(B),
                # O(n^2) — no matmul left to time); the abs makes the
                # reduction nonlinear so the full product must materialize,
                # and it still fuses into the producer's epilogue.
                acc = acc + sum(
                    jnp.sum(jnp.abs(l)).astype(jnp.float32) for l in leaves)
                out = jax.lax.optimization_barrier(tuple(xs) + leaves)
                xs = out[:len(xs)]
            return (xs, acc), None

        (xs, acc), _ = jax.lax.scan(
            body, (tuple(xs), jnp.float32(0.0)), None, length=n)
        return acc

    return loop


def device_time(f, args, n=10):
    """Seconds per call of f(*args), device time, dispatch cancelled."""
    # AOT-compile so warmup costs zero device iterations (a full-loop
    # warmup would double the device work inside the day-1 timeout)
    loop_n = _make_loop(f, n).lower(*args).compile()
    loop_2n = _make_loop(f, 2 * n).lower(*args).compile()

    def run(loop):
        t0 = time.perf_counter()
        out = loop(*args)
        float(out)                       # host fetch = true barrier
        return time.perf_counter() - t0

    run(loop_n)       # executable-load warmup (n iterations, no compile)
    run(loop_2n)      # same for the 2n executable — its load cost must
    t1 = run(loop_n)  # not land inside the timed 2n run
    t2 = run(loop_2n)
    return max(t2 - t1, 1e-9) / n
