"""Tunnel-safe device timing for the micro-bench tools.

On the axon-tunneled TPU, `block_until_ready` returns before the device
finishes and only a host fetch is a true barrier. Timing n *independent*
dispatches and fetching the last result is NOT a barrier for the first
n-1 (their executions can still be in flight), which is how op_bench r4
printed 0.0 ms rows on day 1. The fix: run the n iterations inside one
jitted `lax.scan` whose carry is threaded through
`lax.optimization_barrier` together with the op's output — every
iteration truly executes (no hoisting/CSE), the chain serializes them,
and one final host fetch waits for all n. The per-step time is the
(2n-run − n-run) difference so the fixed dispatch+fetch round trip
cancels, same convention as bench.py `_timed_steps`.
"""

import time

import jax
import jax.numpy as jnp


def _make_loop(f, n):
    @jax.jit
    def loop(*xs):
        def body(xs, _):
            y = f(*xs)
            # barrier EVERY output leaf: chaining only one would let XLA
            # dead-code-eliminate the others inside the loop
            leaves = tuple(jax.tree_util.tree_leaves(y))
            if leaves:
                out = jax.lax.optimization_barrier(tuple(xs) + leaves)
                xs = out[:len(xs)]
            return xs, None
        xs, _ = jax.lax.scan(body, tuple(xs), None, length=n)
        return xs

    return loop


def device_time(f, args, n=10):
    """Seconds per call of f(*args), device time, dispatch cancelled."""
    # AOT-compile so warmup costs zero device iterations (a full-loop
    # warmup would double the device work inside the day-1 timeout)
    loop_n = _make_loop(f, n).lower(*args).compile()
    loop_2n = _make_loop(f, 2 * n).lower(*args).compile()

    def run(loop):
        t0 = time.perf_counter()
        out = loop(*args)
        float(jnp.sum(jax.tree_util.tree_leaves(out)[0]))  # true barrier
        return time.perf_counter() - t0

    run(loop_n)      # executable-load warmup (n iterations, no compile)
    t1 = run(loop_n)
    t2 = run(loop_2n)
    return max(t2 - t1, 1e-9) / n
