"""Tile-shape autotuner CLI over the shared Pallas cache.

Sweeps the live kernels (flash attention, xent stats, layer norm, fused
MLP) through `ops/pallas/autotune.py` at a requested shape, then prints
the ranked tile table per entry — every candidate the sweep timed, best
first, winner marked '*'. The winners land in the JSON cache the flagged
runtime (`autotune=1`) and the autoplan cost model both read, so a sweep
here prices every later `predict()` on this chip with measured rates.

Usage:
  timeout 560 python tools/autotune.py sweep [--kernel all|...] [--json]
  python tools/autotune.py sweep --interpret   # CPU plumbing self-check
  python tools/autotune.py inspect [--json]    # dump the cache, ranked
  python tools/autotune.py clear               # drop the cache file

Like tools/flash_tune.py, silicon timings need a TPU; --interpret runs
the same plumbing on CPU (timings meaningless, cache still exercised).
"""

import argparse
import json
import os
import sys

KERNELS = ("flash_attention", "xent_stats", "layer_norm", "mlp")


def _rows(entries):
    """Human table: one block per cache entry, its sweep ranked."""
    for key, rec in sorted(entries.items()):
        print(f"\n{key}")
        swept = rec.get("swept") or []
        if not swept:
            print(f"  (no sweep recorded; blocks={rec.get('blocks')})")
            continue
        best = rec.get("blocks")
        for cand in swept:
            mark = "*" if cand.get("blocks") == best else " "
            t = cand.get("time_s")
            ts = f"{t * 1e3:9.3f} ms" if t is not None else "   failed"
            bl = " ".join(f"{k}={v}" for k, v in
                          sorted(cand.get("blocks", {}).items()))
            print(f"  {mark} {ts}  {bl}")
        if rec.get("flops") and swept[0].get("time_s"):
            rate = rec["flops"] / swept[0]["time_s"]
            print(f"  achieved {rate / 1e9:.2f} GFLOP/s at the winner "
                  f"(feeds the autoplan cost model)")


def _sweep(args):
    import numpy as np
    import jax.numpy as jnp

    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.ops.pallas import autotune, on_tpu

    flags = {"autotune": True}
    if args.cache:
        flags["autotune_cache"] = args.cache
    if args.interpret:
        flags["pallas_interpret"] = True
    elif not on_tpu():
        print("NOT A TPU — pass --interpret for the CPU plumbing check")
        sys.exit(2)
    set_flags(flags)

    tiny = args.interpret
    dtype = jnp.float32 if tiny else jnp.bfloat16
    b = args.batch or (1 if tiny else 8)
    h = args.heads or (2 if tiny else 12)
    t = args.seq or (128 if tiny else 512)
    d = args.hd or 64
    rows = args.rows or (64 if tiny else 4096)
    hidden = args.hidden or (128 if tiny else 768)
    vocab = args.vocab or (512 if tiny else 8192)
    inter = args.inter or 4 * hidden
    rng = np.random.RandomState(0)

    def _arr(*shape):
        return jnp.asarray(0.02 * rng.randn(*shape), dtype)

    kernels = KERNELS if args.kernel == "all" else (args.kernel,)
    before = set(autotune.cache().load().entries)
    for kernel in kernels:
        print(f"sweeping {kernel} ...", flush=True)
        if kernel == "flash_attention":
            from paddle_tpu.ops.pallas.flash_attention import flash_attention
            q = _arr(b, h, t, d)
            flash_attention(q, q, q, causal=args.causal).block_until_ready()
        elif kernel == "xent_stats":
            from paddle_tpu.ops.pallas.xent import xent_stats
            lbl = jnp.asarray(rng.randint(0, vocab, size=rows), jnp.int32)
            out = xent_stats(_arr(rows, hidden), _arr(vocab, hidden),
                             _arr(vocab), lbl)
            assert out is not None, "xent kernel refused (flag off?)"
            out[0].block_until_ready()
        elif kernel == "layer_norm":
            from paddle_tpu.ops.pallas.layer_norm import layer_norm_fused
            layer_norm_fused(_arr(rows, hidden), _arr(hidden),
                             _arr(hidden)).block_until_ready()
        else:
            from paddle_tpu.ops.pallas.mlp import fused_mlp
            fused_mlp(_arr(rows, hidden), _arr(hidden, inter), _arr(inter),
                      _arr(inter, hidden), _arr(hidden)).block_until_ready()

    entries = autotune.cache().load().entries
    touched = {k: v for k, v in entries.items()
               if v.get("kernel") in kernels}
    if args.json:
        print(json.dumps({"chip": autotune.chip_key(),
                          "new": sorted(set(touched) - before),
                          "entries": touched}, indent=2, sort_keys=True))
        return
    _rows(touched)
    cached = [k for k in touched if k in before]
    if cached:
        print(f"\n{len(cached)} entr{'y' if len(cached) == 1 else 'ies'} "
              f"served from cache (no re-sweep); `clear` to force")
    print(f"\ncache: {autotune.cache().path}")


def _inspect(args):
    from paddle_tpu.ops.pallas import autotune
    cache = autotune.cache(args.cache)
    entries = cache.load().entries
    if args.json:
        print(json.dumps({"path": cache.path, "entries": entries},
                         indent=2, sort_keys=True))
        return
    if not entries:
        print(f"cache empty: {cache.path}")
        return
    _rows(entries)
    rates = autotune.measured_rates(args.cache)
    for chip, rs in sorted(rates.items()):
        n = len(rs)
        hm = n / sum(1.0 / r for r in rs)
        print(f"\n{chip}: harmonic-mean achieved rate {hm / 1e9:.2f} "
              f"GFLOP/s over {n} entr{'y' if n == 1 else 'ies'} "
              f"(autoplan cost-model feed)")


def _clear(args):
    from paddle_tpu.ops.pallas import autotune
    cache = autotune.cache(args.cache)
    n = len(cache.load().entries)
    cache.clear()
    print(f"cleared {n} entr{'y' if n == 1 else 'ies'}: {cache.path}")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    sw = sub.add_parser("sweep", help="sweep kernels at a shape, print "
                                      "the ranked tile table")
    sw.add_argument("--kernel", default="all",
                    choices=("all",) + KERNELS)
    sw.add_argument("--json", action="store_true")
    sw.add_argument("--interpret", action="store_true",
                    help="CPU plumbing self-check (timings meaningless)")
    sw.add_argument("--causal", action="store_true",
                    help="causal flash variant (separate cache signature)")
    sw.add_argument("--batch", type=int, default=None)
    sw.add_argument("--heads", type=int, default=None)
    sw.add_argument("--seq", type=int, default=None)
    sw.add_argument("--hd", type=int, default=None,
                    help="attention head dim (multiple of 64)")
    sw.add_argument("--rows", type=int, default=None,
                    help="token rows for xent/layer_norm/mlp")
    sw.add_argument("--hidden", type=int, default=None)
    sw.add_argument("--vocab", type=int, default=None)
    sw.add_argument("--inter", type=int, default=None,
                    help="MLP intermediate width (default 4*hidden)")
    sw.add_argument("--cache", default=None,
                    help="cache file (default: the autotune_cache flag)")
    sw.set_defaults(fn=_sweep)
    for name, fn in (("inspect", _inspect), ("clear", _clear)):
        p = sub.add_parser(name)
        p.add_argument("--json", action="store_true")
        p.add_argument("--cache", default=None)
        p.set_defaults(fn=fn)
    args = ap.parse_args()
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    args.fn(args)


if __name__ == "__main__":
    main()
