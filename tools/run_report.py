#!/usr/bin/env python
"""Run report — join a telemetry RunLog with an optional XPlane trace.

The CLI successor of the reference's EnableProfiler/DisableProfiler
sorted event tables (platform/profiler.h:166) + tools/timeline.py: one
command turns a training run's artifacts into the human-readable story —

  * step-time percentiles (p50/p90/p95/p99) over the per-step records,
  * the MFU curve (bucketed ASCII sparkline) + tokens/s,
  * loss trajectory and device-memory peaks,
  * counter deltas (retries, Pallas fallbacks, torn-checkpoint skips,
    missed heartbeats, preemptions) from the final snapshot record,
  * the span table (Trainer ingest/stage/step phases), and
  * top-K device ops when given a jax.profiler trace dir
    (profiler.trace_op_table).

`--serve` renders the serving view instead: per-request lifecycles
reconstructed from the engine's trace events (submitted/admitted/
prefill_done/first_token/preempted/resumed/retired), an ASCII per-slot
Gantt of slot occupancy, TTFT + token-latency percentiles, goodput
against the configured SLOs, preemption attribution, the KV pool
footprint (kv_dtype + pool bytes, plus quantized-page / overflow-clamp
/ degraded-admission counters for serve_kv_dtype=int8 runs), and — for
serve_draft runs — the speculation story: the per-round acceptance-rate
trajectory (spec_proposed/spec_accepted step fields), tokens per target
step, and per-request speculative-vs-plain accounting (the spec_tokens
field each retirement carries).

`--fleet` renders the fleet live-ops view: the deploy/scale/canary
timeline from FleetRouter ops events (raw records, a dumped telemetry
snapshot's `ops_log`, or a PT_BENCH_FLEET_RAMP=1 bench row), the
per-version goodput table, and the goodput-vs-offered-load curve.

`--fleet-trace` takes SEVERAL RunLogs (one per replica) and renders the
distributed-tracing view: the logs merge into one causally ordered
timeline via their wall/monotonic anchor records (clock-skew
corrected), shown as a cross-replica per-request Gantt — a failover
re-route appears as the SAME trace id continuing on another replica,
and a disaggregated request's prefill -> decode handoff appears as a
'P' row handing to an 'H' row — plus the critical-path breakdown
(queue -> prefill -> first token -> decode) and a skew report.

`--train-health` renders the resilience view: guardian non-finite
skips, loss-spike episodes and mitigation-ladder actions, rollbacks
with their restore targets, watchdog anomalies, checkpoint-integrity
outcomes (corrupt leaves / fallbacks), ingest reader deaths, and the
AMP loss-scale trail.

Usage:
  python tools/run_report.py /runs/exp1/run.jsonl
  python tools/run_report.py run.jsonl --trace /tmp/prof --top 20
  python tools/run_report.py serve.jsonl --serve
  python tools/run_report.py fleet.jsonl --fleet
  python tools/run_report.py serve.jsonl.r0 serve.jsonl.r1 --fleet-trace
  python tools/run_report.py run.jsonl --train-health
  python tools/run_report.py --selftest      # tier-1 smoke: tiny GPT
                                             # through the Trainer with
                                             # telemetry on, then render
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    idx = (len(sorted_vals) - 1) * q
    lo, hi = int(idx), min(int(idx) + 1, len(sorted_vals) - 1)
    frac = idx - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def _flatten_counters(counters):
    """{'a': 3, 'b': {'op=x': 2}} -> {'a': 3, 'b{op=x}': 2}."""
    out = {}
    for name, v in (counters or {}).items():
        if isinstance(v, dict):
            for label, val in v.items():
                out[f"{name}{{{label}}}"] = val
        else:
            out[name] = v
    return out


def _bars(values, width=40):
    """One-line ASCII bar chart (the MFU curve): scaled to the max."""
    if not values:
        return "(no data)"
    blocks = " .:-=+*#%@"
    top = max(values) or 1.0
    return "".join(
        blocks[min(len(blocks) - 1,
                   int(round(v / top * (len(blocks) - 1))))]
        for v in values)


def _bucket(values, n_buckets=40):
    """Average `values` into at most n_buckets buckets, in order."""
    if len(values) <= n_buckets:
        return list(values)
    out = []
    per = len(values) / n_buckets
    for b in range(n_buckets):
        lo, hi = int(b * per), max(int((b + 1) * per), int(b * per) + 1)
        chunk = values[lo:hi]
        out.append(sum(chunk) / len(chunk))
    return out


def render_report(records, trace_dir=None, top=20, device_filter="TPU"):
    """The full text report from RunLog records (+ optional trace dir)."""
    steps = [r for r in records if "step" in r and not r.get("final")]
    finals = [r for r in records if r.get("final")]
    lines = ["=" * 72, "RUN REPORT", "=" * 72]

    # -- step-time percentiles --------------------------------------------
    walls = sorted(r["wall_s"] for r in steps
                   if isinstance(r.get("wall_s"), (int, float)))
    lines.append(f"\nstep records: {len(steps)}"
                 + (f"  (steps {steps[0]['step']}..{steps[-1]['step']})"
                    if steps else ""))
    if walls:
        lines.append("step time:   "
                     + "  ".join(
                         f"p{int(q * 100)}={_percentile(walls, q) * 1e3:.2f}ms"
                         for q in (0.50, 0.90, 0.95, 0.99))
                     + f"  mean={sum(walls) / len(walls) * 1e3:.2f}ms"
                     + f"  max={walls[-1] * 1e3:.2f}ms")
    tps = [r["tokens_per_s"] for r in steps
           if isinstance(r.get("tokens_per_s"), (int, float))]
    if tps:
        s_tps = sorted(tps)
        lines.append(f"tokens/s:    p50={_percentile(s_tps, 0.5):,.0f}  "
                     f"mean={sum(tps) / len(tps):,.0f}  "
                     f"max={s_tps[-1]:,.0f}")

    # -- MFU curve --------------------------------------------------------
    mfus = [r["mfu"] for r in steps
            if isinstance(r.get("mfu"), (int, float))]
    if mfus:
        lines.append(f"MFU:         min={min(mfus):.4f}  "
                     f"mean={sum(mfus) / len(mfus):.4f}  "
                     f"max={max(mfus):.4f}")
        lines.append(f"MFU curve:   [{_bars(_bucket(mfus))}]")

    # -- loss / memory ----------------------------------------------------
    losses = [(r["step"], r["loss"]) for r in steps
              if isinstance(r.get("loss"), (int, float))]
    if losses:
        lines.append(f"loss:        first={losses[0][1]:.6f} "
                     f"(step {losses[0][0]})  last={losses[-1][1]:.6f} "
                     f"(step {losses[-1][0]})  "
                     f"min={min(v for _, v in losses):.6f}")
    peaks = [r["memory"].get("peak_bytes_in_use") or
             r["memory"].get("bytes_in_use") for r in steps
             if isinstance(r.get("memory"), dict)]
    peaks = [p for p in peaks if p]
    lines.append(f"memory peak: {max(peaks) / 2 ** 20:.1f} MiB"
                 if peaks else
                 "memory peak: n/a (backend reports no allocator stats)")

    # -- counters (deltas when the log holds >1 snapshot) -----------------
    if finals:
        last = _flatten_counters(finals[-1].get("counters"))
        first = (_flatten_counters(finals[0].get("counters"))
                 if len(finals) > 1 else {})
        lines.append("\ncounters" + (" (delta since first snapshot)"
                                     if first else "") + ":")
        if not last:
            lines.append("  (none fired)")
        for name in sorted(last):
            delta = last[name] - first.get(name, 0)
            val = (f"{last[name]:.4f}" if isinstance(last[name], float)
                   else f"{last[name]}")
            suffix = (f"   (+{delta:g})" if first else "")
            lines.append(f"  {name:<52} {val:>12}{suffix}")

        spans = finals[-1].get("spans") or []
        if spans:
            lines.append("\nspans:")
            lines.append(f"  {'span':<28}{'calls':>8}{'total_s':>10}"
                         f"{'p50_ms':>10}{'p95_ms':>10}")
            for s in spans[:top]:
                lines.append(
                    f"  {s['name']:<28}{s['calls']:>8}"
                    f"{s['total_s']:>10.3f}{s.get('p50_ms', 0):>10.3f}"
                    f"{s.get('p95_ms', 0):>10.3f}")

    # -- device ops from the XPlane trace ---------------------------------
    if trace_dir:
        lines.append(f"\ntop device ops ({trace_dir}):")
        try:
            from paddle_tpu.profiler import trace_op_table
            n_steps = max(len(steps), 1)
            rows = trace_op_table(trace_dir, device_filter=device_filter,
                                  top=top, steps=n_steps)
            if not rows and device_filter not in (None, "CPU"):
                rows = trace_op_table(trace_dir, device_filter="CPU",
                                      top=top, steps=n_steps)
            if not rows:
                rows = trace_op_table(trace_dir, device_filter=None,
                                      top=top, steps=n_steps)
            width = max((len(r["name"]) for r in rows), default=10)
            width = min(width, 80)
            lines.append(f"  {'op':<{width}}  {'total_us':>12}  "
                         f"{'per_step':>10}  {'count':>6}")
            for r in rows:
                lines.append(f"  {r['name'][:width]:<{width}}  "
                             f"{r['total_us']:>12.0f}  "
                             f"{r['per_step_us']:>10.1f}  "
                             f"{r['count']:>6d}")
        except Exception as e:
            lines.append(f"  (trace unreadable: {e})")

    lines.append("=" * 72)
    return "\n".join(lines)


# -- training-health view -------------------------------------------------

def render_train_health(records):
    """The resilience story of a training run: guardian events (non-finite
    skip-applies, loss-spike episodes, mitigation-ladder actions,
    rollbacks), watchdog anomalies, checkpoint-integrity outcomes, ingest
    failures, and the AMP loss-scale trail — everything static/guardian.py
    and io/checkpoint.py wrote into the RunLog and the final metrics
    snapshot."""
    guardian = [r for r in records if "guardian" in r]
    anomalies = [r for r in records if "anomaly" in r]
    finals = [r for r in records if r.get("final")]
    counters = _flatten_counters(finals[-1].get("counters")) if finals else {}
    gauges = (finals[-1].get("gauges") or {}) if finals else {}
    lines = ["=" * 72, "TRAIN HEALTH", "=" * 72]

    def ctr(name):
        return sum(v for k, v in counters.items()
                   if k == name or k.startswith(name + "{"))

    # -- guardian ladder ---------------------------------------------------
    kinds = {}
    actions = {}
    for r in guardian:
        kinds[r["guardian"]] = kinds.get(r["guardian"], 0) + 1
        if r.get("action"):
            actions[r["action"]] = actions.get(r["action"], 0) + 1
    lines.append(f"\nguardian events: {len(guardian)}"
                 + (f"  ({', '.join(f'{k} {v}' for k, v in sorted(kinds.items()))})"
                    if kinds else "  (clean run)"))
    lines.append(f"non-finite skips:   {ctr('trainer.nonfinite_skips')}")
    lines.append(f"loss-spike episodes: {ctr('trainer.loss_spikes')}")
    if actions:
        lines.append("ladder actions:     "
                     + "  ".join(f"{a}={actions[a]}" for a in
                                 ("skip", "reread", "rollback")
                                 if a in actions))
    rb = [r for r in guardian if r["guardian"] == "rollback"]
    done = [r for r in guardian if r["guardian"] == "rollback_done"]
    lines.append(f"rollbacks:          {ctr('trainer.rollbacks')}")
    for r, d in zip(rb, done + [None] * len(rb)):
        lines.append(f"  at step {r.get('step')}"
                     + (f" -> restored step {d['restored_step']}"
                        if d else " (restore unrecorded)"))

    # -- watchdog anomalies ------------------------------------------------
    if anomalies:
        by_kind = {}
        for r in anomalies:
            by_kind.setdefault(r["anomaly"], []).append(r.get("step"))
        lines.append("\nwatchdog anomalies:")
        for k in sorted(by_kind):
            steps_s = ", ".join(str(s) for s in by_kind[k][:8])
            more = len(by_kind[k]) - 8
            lines.append(f"  {k:<18} x{len(by_kind[k])}  (steps {steps_s}"
                         + (f", +{more} more)" if more > 0 else ")"))
    else:
        lines.append("\nwatchdog anomalies: none")

    # -- checkpoint integrity / ingest / amp -------------------------------
    lines.append("\ncheckpoint integrity:")
    for name, label in (("checkpoint.saves", "saves"),
                        ("checkpoint.restores", "restores"),
                        ("checkpoint.corrupt_leaves", "corrupt leaves"),
                        ("checkpoint.integrity_fallbacks",
                         "integrity fallbacks"),
                        ("checkpoint.torn_skips", "torn-mirror skips")):
        lines.append(f"  {label:<20} {ctr(name)}")
    ingest = {k: v for k, v in counters.items()
              if k.startswith("trainer.ingest_errors")}
    lines.append("ingest reader deaths: "
                 + (", ".join(f"{k.split('{', 1)[-1].rstrip('}')} x{v}"
                              for k, v in sorted(ingest.items()))
                    if ingest else "0"))
    if "amp.loss_scale" in gauges or ctr("amp.skipped_steps"):
        lines.append(f"amp: loss_scale={gauges.get('amp.loss_scale')}  "
                     f"skipped_steps={ctr('amp.skipped_steps')}")

    # -- loss trajectory around the incidents ------------------------------
    steps = [r for r in records if "step" in r and not r.get("final")
             and "guardian" not in r and "anomaly" not in r]
    losses = [(r["step"], r["loss"]) for r in steps
              if isinstance(r.get("loss"), (int, float))]
    if losses:
        worst = max(losses, key=lambda sv: sv[1])
        lines.append(f"\nloss: first={losses[0][1]:.6g} "
                     f"last={losses[-1][1]:.6g} "
                     f"worst={worst[1]:.6g} (step {worst[0]})")
    verdict = ("DEGRADED (rollback budget was drawn on)" if rb
               else "contained" if guardian or anomalies else "clean")
    lines.append(f"verdict: {verdict}")
    lines.append("=" * 72)
    return "\n".join(lines)


# -- serving view ---------------------------------------------------------

_GANTT_CHARS = "0123456789abcdefghijklmnopqrstuvwxyz"


def _pctl_line(label, vals_s):
    vals = sorted(vals_s)
    if not vals:
        return f"{label} (no data)"
    return (label + "  ".join(
        f"p{int(q * 100)}={_percentile(vals, q) * 1e3:.1f}ms"
        for q in (0.50, 0.90, 0.99)) + f"  n={len(vals)}")


def _slot_gantt(events, width=64):
    """ASCII per-slot occupancy: each request renders as its id's base-36
    digit from admission (or resume) to preemption/retirement."""
    slotted = [e for e in events
               if "slot" in e and e["event"] in
               ("admitted", "resumed", "preempted", "retired")]
    if not slotted:
        return ["(no slot events)"]
    t0 = min(e["t"] for e in slotted)
    t1 = max(e["t"] for e in slotted)
    span = max(t1 - t0, 1e-9)

    def col(t):
        return min(int((t - t0) / span * (width - 1)), width - 1)

    slots = sorted({e["slot"] for e in slotted})
    rows = {s: [" "] * width for s in slots}
    open_at = {}                      # slot -> (req, start col)
    for e in sorted(slotted, key=lambda e: e["t"]):
        s = e["slot"]
        if e["event"] in ("admitted", "resumed"):
            open_at[s] = (e["req"], col(e["t"]))
        else:
            req, c0 = open_at.pop(s, (e["req"], col(e["t"])))
            c1 = col(e["t"])
            ch = _GANTT_CHARS[req % len(_GANTT_CHARS)]
            for c in range(c0, c1 + 1):
                rows[s][c] = ch
            if e["event"] == "preempted":
                rows[s][c1] = "!"
    for s, (req, c0) in open_at.items():    # still running at log end
        ch = _GANTT_CHARS[req % len(_GANTT_CHARS)]
        for c in range(c0, width):
            rows[s][c] = ch
    out = [f"slot timeline (t0=+0.000s, span={span:.3f}s, one request "
           f"= its id base-36; '!' = preemption):"]
    for s in slots:
        out.append(f"  slot {s:>2} |{''.join(rows[s])}|")
    return out


def render_serve_report(records, top=20, width=64):
    """The serving story from engine trace events + per-step records."""
    events = [r for r in records if "event" in r and "req" in r]
    steps = [r for r in records
             if r.get("phase") == "serve" and "step" in r
             and not r.get("final")]
    finals = [r for r in records if r.get("final")]
    lines = ["=" * 72, "SERVE REPORT", "=" * 72]
    if not events:
        lines.append("\n(no serve trace events in this RunLog — run the "
                     "engine with ServeConfig(run_log=...))")
        return "\n".join(lines + ["=" * 72])

    byreq = {}
    for e in sorted(events, key=lambda e: e["t"]):
        byreq.setdefault(e["req"], []).append(e)

    def last(req_events, name):
        hits = [e for e in req_events if e["event"] == name]
        return hits[-1] if hits else None

    retired = {r: ev for r, ev in byreq.items() if last(ev, "retired")}
    reasons = {}
    ttfts, tok_lats, slo_flags = [], [], []
    for r, ev in retired.items():
        ret = last(ev, "retired")
        reasons[ret.get("reason", "?")] = \
            reasons.get(ret.get("reason", "?"), 0) + 1
        sub, ft = last(ev, "submitted"), last(ev, "first_token")
        if sub and ft:
            ttfts.append(ft["t"] - sub["t"])
        ntok = ret.get("tokens", 0)
        if ft and ntok > 1:
            tok_lats.append((ret["t"] - ft["t"]) / (ntok - 1))
        if ret.get("slo_ok") is not None:
            slo_flags.append(bool(ret["slo_ok"]))
    preempted = {r: ev for r, ev in byreq.items()
                 if last(ev, "preempted")}

    lines.append(
        f"\nrequests: {len(byreq)} submitted, {len(retired)} retired "
        f"({', '.join(f'{k} {v}' for k, v in sorted(reasons.items()))})"
        + (f", {len(preempted)} preempted" if preempted else ""))
    lines.append(_pctl_line("TTFT:          ", ttfts))
    lines.append(_pctl_line("token latency: ", tok_lats))
    if slo_flags:
        good = sum(slo_flags) / len(slo_flags)
        slo = (finals[-1].get("slo") if finals else None) or {}
        viol = slo.get("violations") or {}
        tgt = ", ".join(f"{k}={slo[k]}" for k in
                        ("slo_ttft_s", "slo_token_latency_s")
                        if slo.get(k))
        lines.append(
            f"goodput:        {good:.4f} over {len(slo_flags)} retired"
            + (f"  (targets: {tgt})" if tgt else "  (no SLO configured)")
            + (f"  violations: "
               + ", ".join(f"{k}={v}" for k, v in sorted(viol.items()))
               if viol else ""))
    if steps:
        walls = [r["wall_s"] for r in steps
                 if isinstance(r.get("wall_s"), (int, float))]
        toks = sum(r.get("new_tokens") or 0 for r in steps)
        lines.append(_pctl_line(
            f"serve steps:    {len(steps)} ({toks} tokens)  step ",
            walls))

    # -- speculation: acceptance trajectory + spec-vs-plain accounting ----
    spec_steps = [r for r in steps
                  if isinstance(r.get("spec_proposed"), int)]
    if spec_steps:
        prop = sum(r["spec_proposed"] for r in spec_steps)
        acc = sum(r.get("spec_accepted") or 0 for r in spec_steps)
        toks = sum(r.get("new_tokens") or 0 for r in steps)
        lines.append(
            f"\nspeculation:    {len(spec_steps)}/{len(steps)} steps ran "
            f"a draft round; {prop} proposed, {acc} accepted, "
            f"{prop - acc} rolled back"
            + (f"  (acceptance {acc / prop:.4f})" if prop else ""))
        lines.append(f"tokens/target-step: {toks / len(steps):.4f} over "
                     f"{len(steps)} target steps (plain decoding is 1.0)")
        rates = [r["spec_accepted"] / r["spec_proposed"]
                 for r in spec_steps if r["spec_proposed"]]
        if rates:
            lines.append(f"acceptance trajectory (per round, max "
                         f"{max(rates):.2f}): [{_bars(_bucket(rates))}]")
        spec_reqs = [(r, last(ev, "retired")) for r, ev in retired.items()]
        spec_reqs = [(r, ret) for r, ret in spec_reqs
                     if ret.get("spec_tokens") is not None]
        if spec_reqs:
            won = [rr for rr in spec_reqs if rr[1]["spec_tokens"]]
            saved = sum(ret["spec_tokens"] for _, ret in spec_reqs)
            lines.append(
                f"spec-vs-plain:  {len(won)}/{len(spec_reqs)} retired "
                f"requests beat one token per step; {saved} target "
                "steps saved in total")
            for r, ret in sorted(
                    spec_reqs, key=lambda kv: -kv[1]["spec_tokens"])[:top]:
                ntok = ret.get("tokens", 0)
                lines.append(
                    f"  req {r}: {ntok} tokens in "
                    f"{ntok - ret['spec_tokens']} target steps "
                    f"(+{ret['spec_tokens']} speculative)")
    fin = finals[-1] if finals else {}
    if fin.get("kv_dtype") or fin.get("kv_pool_bytes"):
        counters = _flatten_counters(fin.get("counters"))
        gauges = fin.get("gauges") or {}

        def _near(table, name):
            return sum(v for k, v in table.items()
                       if k == name or k.startswith(name))

        kv = (f"KV pool:        {fin.get('kv_dtype') or 'f32'}, "
              f"{int(fin.get('kv_pool_bytes') or 0):,} bytes")
        if fin.get("kv_dtype") == "int8":
            kv += (
                f"  (quantized pages in use "
                f"{int(_near(gauges, 'serve.kv_quant_pages'))}, "
                f"overflow clamps "
                f"{int(_near(counters, 'quant.overflow_clamps'))}, "
                f"degraded admits "
                f"{int(_near(counters, 'serve.kv_quant_degraded'))})")
        lines.append(kv)
    lines.append("")
    lines.extend(_slot_gantt(events, width=width))

    if preempted:
        lines.append("\npreemption attribution:")
        for r in sorted(preempted):
            ev = byreq[r]
            for p in (e for e in ev if e["event"] == "preempted"):
                res = [e for e in ev if e["event"] == "resumed"
                       and e["t"] > p["t"]]
                lines.append(
                    f"  req {r}: preempted at slot {p.get('slot')} "
                    f"({p.get('tokens_dropped', 0)} tokens dropped, "
                    + (f"resumed +{res[0]['t'] - p['t']:.3f}s later)"
                       if res else "never resumed)"))

    lines.append(f"\nrequest lifecycles (top {top} by span):")
    t_base = min(e["t"] for ev in byreq.values() for e in ev)

    def req_span(ev):
        return ev[-1]["t"] - ev[0]["t"]

    for r, ev in sorted(byreq.items(), key=lambda kv: -req_span(kv[1]))[
            :top]:
        trace = ev[0].get("trace", "")
        parts = []
        for e in ev:
            tag = e["event"]
            if tag == "retired":
                tag += (f"[{e.get('reason')}, {e.get('tokens')} tok"
                        + (", slo_ok" if e.get("slo_ok")
                           else ", SLO MISS") + "]")
            parts.append(f"{tag} +{e['t'] - t_base:.3f}")
        lines.append(f"  req {r} [{trace}]: " + " -> ".join(parts))
    lines.append("=" * 72)
    return "\n".join(lines)


_FLEET_EVENTS = frozenset((
    "deploy_start", "deploy_done", "deploy_abort", "swap", "swap_fail",
    "scale_up", "scale_up_fail", "scale_down_begin", "scale_down",
    "scale_down_cancelled", "scale_down_fail", "canary_abort"))


def render_fleet_report(records, width=64):
    """The live-ops story of a fleet: the deploy/scale/canary timeline
    (FleetRouter.ops_log events, taken either as raw records or from any
    record carrying an `ops_log` list — e.g. a dumped telemetry snapshot
    or a `bench.py gpt_serve_fleet` ramp row) plus the per-version
    goodput table (`version_stats` snapshot when present, else
    reconstructed from engine trace `retired` events that carry a
    version tag) and, when a ramp row is present, the goodput-vs-
    offered-load curve."""
    ops = [r for r in records if r.get("event") in _FLEET_EVENTS]
    vstats, curve = None, None
    for r in records:
        if isinstance(r.get("ops_log"), list):
            ops.extend(e for e in r["ops_log"]
                       if e.get("event") in _FLEET_EVENTS)
        if isinstance(r.get("version_stats"), dict):
            vstats = r["version_stats"]
        if isinstance(r.get("curve"), list):
            curve = r["curve"]
    if vstats is None:
        # reconstruct from version-tagged retirements in the trace
        tally = {}
        for r in records:
            if r.get("event") == "retired" and r.get("version"):
                st = tally.setdefault(r["version"], [0, 0])
                st[0] += 1
                if r.get("slo_ok"):
                    st[1] += 1
        if tally:
            vstats = {v: {"retired": s[0], "slo_ok": s[1],
                          "goodput": round(s[1] / s[0], 4)}
                      for v, s in tally.items()}
    lines = ["=" * 72, "FLEET REPORT", "=" * 72]
    if not ops and vstats is None and curve is None:
        lines.append("\n(no fleet ops events in this RunLog — dump "
                     "router.telemetry() as a record, or feed a "
                     "PT_BENCH_FLEET_RAMP=1 bench row)")
        return "\n".join(lines + ["=" * 72])

    if ops:
        ops.sort(key=lambda e: e.get("t", 0.0))
        t0 = ops[0].get("t", 0.0)
        deploys = [e for e in ops if e["event"].startswith("deploy")]
        swaps = [e for e in ops if e["event"].startswith("swap")]
        scales = [e for e in ops if e["event"].startswith("scale")]
        aborts = [e for e in ops if e["event"] == "canary_abort"]
        lines.append(
            f"\nops events: {len(ops)} "
            f"({len(deploys)} deploy, {len(swaps)} swap, "
            f"{len(scales)} scale, {len(aborts)} canary_abort)")
        lines.append(f"\ndeploy timeline (t0=+0.000s over "
                     f"{ops[-1].get('t', t0) - t0:.3f}s):")
        for e in ops:
            extra = ", ".join(
                f"{k}={v}" for k, v in e.items()
                if k not in ("event", "t", "at_step"))
            lines.append(f"  +{e.get('t', t0) - t0:9.3f}  "
                         f"{e['event']:<21}" + (f" {extra}" if extra
                                                else ""))

    if vstats:
        lines.append("\nper-version goodput:")
        lines.append(f"  {'version':<16} {'retired':>8} {'slo_ok':>8} "
                     f"{'goodput':>8}")
        for v in sorted(vstats):
            st = vstats[v]
            lines.append(f"  {v:<16} {st.get('retired', 0):>8} "
                         f"{st.get('slo_ok', 0):>8} "
                         f"{st.get('goodput', 0.0):>8.4f}")

    if curve:
        lines.append("\noffered-load ramp (goodput bar scaled to 1.0):")
        lines.append(f"  {'offered':>7} {'done':>5} {'replicas':>8} "
                     f"{'tok/s':>8} {'deploy_s':>8} {'goodput':>8}")
        barw = max(8, width - 52)
        for row in curve:
            g = float(row.get("goodput", 0.0))
            bar = "#" * int(round(g * barw))
            lines.append(
                f"  {row.get('offered', 0):>7} "
                f"{row.get('completed', 0):>5} "
                f"{row.get('replicas', 0):>8} "
                f"{row.get('tokens_per_sec', 0.0):>8} "
                f"{row.get('deploy_s', 0.0):>8} {g:>8.4f} |{bar}|")
    lines.append("=" * 72)
    return "\n".join(lines)


def render_fleet_trace(record_lists, top=20, width=64):
    """The fleet-wide distributed-tracing story: per-replica RunLogs
    merged into ONE causally ordered timeline (per-process wall/mono
    anchor records correct clock skew), then rendered as a clock-skew
    report, a cross-replica per-request Gantt (failover / deploy-drain
    re-admission / preemption / disaggregated prefill->decode handoff
    annotated), and the critical-path phase breakdown (queue ->
    dispatch -> prefill -> first token -> decode -> retire) over
    retired requests. ``record_lists`` maps a source name (one per
    replica RunLog) to its records."""
    from paddle_tpu.observability.trace import (group_by_trace,
                                                merge_fleet_trace)
    merged = merge_fleet_trace(record_lists)
    events = merged["events"]
    lines = ["=" * 72, "FLEET TRACE", "=" * 72]

    lines.append("\nclock-skew report (anchor offsets, relative to the "
                 "earliest source):")
    for src in sorted(merged["skew"]):
        sk = merged["skew"][src]
        if not sk["anchored"]:
            lines.append(f"  {src:<24} NO ANCHOR — raw times, causal "
                         "order not guaranteed")
        else:
            lines.append(f"  {src:<24} offset {sk['offset']:+.3f}s  "
                         f"skew {sk['skew_s']:+.6f}s")

    req_events = [e for e in events if "req" in e and e.get("trace")]
    if not req_events:
        lines.append("\n(no request trace events across these RunLogs)")
        return "\n".join(lines + ["=" * 72])
    traces = group_by_trace(req_events)
    traces.pop(None, None)
    t0 = min(e["wall_t"] for e in req_events)
    t1 = max(e["wall_t"] for e in req_events)
    span_t = max(t1 - t0, 1e-9)

    def col(t):
        return min(width - 1, int((t - t0) / span_t * width))

    def trace_span(evs):
        return evs[-1]["wall_t"] - evs[0]["wall_t"]

    shown = sorted(traces.items(), key=lambda kv: -trace_span(kv[1]))[:top]
    lines.append(
        f"\ncross-replica request Gantt ({len(traces)} traces over "
        f"{span_t:.3f}s; top {len(shown)} by span — one row per "
        "replica a trace touched; A=adopted F=failover-adopt "
        "P=prefill-leg H=handoff-adopt !=preempted .=event R=retired):")
    mark = {"adopted": "A", "preempted": "!", "retired": "R"}
    origin_mark = {"failover": "F", "prefill": "P", "handoff": "H"}
    for tid, evs in shown:
        lines.append(f"  {tid}:")
        sources = sorted({e["source"] for e in evs})
        for src in sources:
            mine = [e for e in evs if e["source"] == src]
            row = [" "] * width
            lo, hi = col(mine[0]["wall_t"]), col(mine[-1]["wall_t"])
            for c in range(lo, hi + 1):
                row[c] = "-"
            # letters outrank "." when events share a column
            rank = {" ": 0, "-": 0, ".": 1}
            for e in mine:
                m = mark.get(e["event"], ".")
                if e["event"] == "adopted":
                    m = origin_mark.get(e.get("origin"), m)
                c = col(e["wall_t"])
                if rank.get(m, 2) < rank.get(row[c], 2):
                    continue
                if rank.get(row[c], 2) >= 2 and row[c] != m:
                    # two letters share a column (e.g. the handoff-adopt
                    # and the retirement of a short decode leg): nudge
                    # sideways so both stay visible
                    for alt in (c + 1, c - 1):
                        if 0 <= alt < width and rank.get(row[alt], 2) < 2:
                            c = alt
                            break
                row[c] = m
            note = ""
            hops = {e.get("span") for e in mine if e.get("span")}
            if hops:
                note = " " + ",".join(sorted(hops))
            ver = next((e.get("version") for e in mine
                        if e.get("version")), None)
            if ver:
                note += f" [{ver}]"
            lines.append(f"    {src:<20} |{''.join(row)}|{note}")

    # critical-path breakdown over retired traces: each phase edge is
    # the time between consecutive lifecycle events (failover restarts
    # a phase; the LAST occurrence wins, matching what the user waited)
    phases = {"queue": [], "prefill": [], "first_token": [],
              "decode": [], "total": []}
    retired_n = 0
    for tid, evs in traces.items():
        def last_t(name, evs=evs):
            hit = [e for e in evs if e["event"] == name]
            return hit[-1]["wall_t"] if hit else None
        start = min(e["wall_t"] for e in evs)
        adopt = last_t("adopted") or last_t("submitted") or start
        admit = max(filter(None, (last_t("admitted"),
                                  last_t("resumed"))), default=None)
        pf, ft, ret = (last_t("prefill_done"), last_t("first_token"),
                       last_t("retired"))
        if ret is None:
            continue
        retired_n += 1
        if admit is not None:
            phases["queue"].append(admit - adopt)
        if pf is not None and admit is not None:
            phases["prefill"].append(pf - admit)
        if ft is not None and pf is not None:
            phases["first_token"].append(ft - pf)
        if ft is not None:
            phases["decode"].append(ret - ft)
        phases["total"].append(ret - start)
    if retired_n:
        lines.append(f"\ncritical-path breakdown ({retired_n} retired "
                     "traces; last occurrence per phase wins across "
                     "failover hops):")
        for name in ("queue", "prefill", "first_token", "decode",
                     "total"):
            lines.append(_pctl_line(f"{name:<15}", phases[name]))
    lines.append("=" * 72)
    return "\n".join(lines)


def _selftest():
    """Tier-1 smoke (CPU-only): a tiny GPT trained through the Trainer
    with telemetry on must produce a RunLog whose records carry wall
    time, tokens/s, MFU, loss, and a memory field, whose final snapshot
    holds pallas-fallback and checkpoint counters — and this CLI must
    render it. Exit 0 + 'SELFTEST OK' on success."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import tempfile

    import jax
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.observability import TelemetryConfig, read_records
    from paddle_tpu.static import Trainer, TrainerConfig

    cfg = GPTConfig.tiny()
    cfg.dropout = 0.0
    model = GPT(cfg)
    params = model.init(jax.random.key(0))["params"]
    opt = pt.optimizer.Adam(1e-3)
    state = {"params": params, "opt": opt.init(params)}

    @jax.jit
    def step(st, ids):
        def loss_fn(p):
            # fused .loss() path: on CPU the Pallas xent/flash kernels
            # refuse and count their fallbacks — the selftest asserts
            # those counters reach the RunLog snapshot
            return model.apply({"params": p, "state": {}}, ids,
                               method="loss")
        loss, grads = jax.value_and_grad(loss_fn)(st["params"])
        p, o = opt.apply_gradients(st["params"], grads, st["opt"])
        return loss, {"params": p, "opt": o}

    B, S, n_steps = 2, 16, 6
    rng = np.random.RandomState(0)
    ds = pt.data.InMemoryDataset(
        [(rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32),)
         for _ in range(n_steps)])
    tmp = tempfile.mkdtemp(prefix="pt_run_report_selftest_")
    run_log = os.path.join(tmp, "run.jsonl")
    tcfg = TrainerConfig(
        num_ingest_threads=1,
        telemetry=TelemetryConfig(enabled=True, run_log=run_log,
                                  every_n_steps=1),
        checkpoint_dir=os.path.join(tmp, "ck"), checkpoint_every=3)
    _, stats = Trainer(step, tcfg).train(state, ds)
    assert stats["steps"] == n_steps, stats

    records = read_records(run_log)
    steps = [r for r in records if "step" in r and not r.get("final")]
    finals = [r for r in records if r.get("final")]
    assert len(steps) == n_steps, [r.get("step") for r in records]
    ids = [r["step"] for r in steps]
    assert ids == sorted(ids) and len(set(ids)) == len(ids), ids
    for r in steps:
        for key in ("wall_s", "tokens_per_s", "mfu", "loss", "memory"):
            assert key in r, (key, r)
        assert isinstance(r["loss"], float), r
        assert isinstance(r["mfu"], float), r    # cost analysis worked
        assert r["tokens_per_s"] > 0, r
    assert finals, "final snapshot record missing"
    counters = finals[-1]["counters"]
    assert "pallas.fallback" in counters, counters
    assert "checkpoint.saves" in counters, counters

    report = render_report(records, trace_dir=None)
    print(report)
    assert "step time:" in report and "counters" in report
    print("SELFTEST OK")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("runlog", nargs="?", help="RunLog JSONL path "
                    "(rotated siblings are folded in automatically)")
    ap.add_argument("extra_runlogs", nargs="*",
                    help="additional per-replica RunLog paths "
                         "(--fleet-trace merges them into one timeline)")
    ap.add_argument("--trace", default=None,
                    help="jax.profiler trace dir to join (top-K op table "
                         "via profiler.trace_op_table)")
    ap.add_argument("--top", type=int, default=20,
                    help="rows for the span/op tables")
    ap.add_argument("--device-filter", default="TPU",
                    help="trace lane substring ('TPU', 'CPU'; falls back "
                         "automatically when empty)")
    ap.add_argument("--serve", action="store_true",
                    help="render the serving view: per-request "
                         "lifecycles, per-slot Gantt, TTFT/token-"
                         "latency percentiles, goodput, preemption "
                         "attribution")
    ap.add_argument("--fleet", action="store_true",
                    help="render the fleet live-ops view: deploy/scale/"
                         "canary timeline, per-version goodput table, "
                         "and (from a ramp bench row) the goodput-vs-"
                         "offered-load curve")
    ap.add_argument("--fleet-trace", action="store_true",
                    help="merge the given per-replica RunLogs into one "
                         "skew-corrected timeline: cross-replica "
                         "per-request Gantt, critical-path breakdown, "
                         "clock-skew report")
    ap.add_argument("--train-health", action="store_true",
                    help="render the training-resilience view: guardian "
                         "skips/spikes/rollbacks, watchdog anomalies, "
                         "checkpoint-integrity outcomes, ingest "
                         "failures, AMP loss-scale trail")
    ap.add_argument("--selftest", action="store_true",
                    help="train a tiny GPT with telemetry on (CPU) and "
                         "render its report — the tier-1 smoke")
    args = ap.parse_args()
    if args.selftest:
        _selftest()
        return
    if not args.runlog:
        ap.error("a RunLog path is required (or --selftest)")
    from paddle_tpu.observability.runlog import read_records
    if args.fleet_trace:
        paths = [args.runlog] + list(args.extra_runlogs)
        lists = {}
        for p in paths:
            name = os.path.basename(p)
            lists[p if name in lists else name] = read_records(p)
        print(render_fleet_trace(lists, top=args.top))
        return
    if args.extra_runlogs:
        ap.error("multiple RunLogs only make sense with --fleet-trace")
    records = read_records(args.runlog)
    if not records:
        raise SystemExit(f"no records in {args.runlog}")
    if args.serve:
        print(render_serve_report(records, top=args.top))
        return
    if args.fleet:
        print(render_fleet_report(records))
        return
    if args.train_health:
        print(render_train_health(records))
        return
    print(render_report(records, trace_dir=args.trace, top=args.top,
                        device_filter=args.device_filter))


if __name__ == "__main__":
    main()
