#!/usr/bin/env bash
# One-shot TPU validation the moment a chip is reachable (the round-3
# tunnel outage staged all of this; see BASELINE.md "Round 3 status").
# Runs: aliveness probe -> Pallas silicon smoke (parity + timings) ->
# all four bench rows. Appends everything to tools/tpu_day1.log.
#
# Usage: bash tools/tpu_day1.sh
set -u
cd "$(dirname "$0")/.."
LOG=tools/tpu_day1.log
say() { echo "== $*" | tee -a "$LOG"; }

say "$(date -u +%FT%TZ) tpu_day1 start"

say "probe"
if ! timeout 100 python -c "
import jax, jax.numpy as jnp
d = jax.devices()
print('PROBE_OK', float((jnp.ones((128,128))@jnp.ones((128,128))).sum()),
      d[0].device_kind)" 2>&1 | tee -a "$LOG" | grep -q PROBE_OK; then
  say "tunnel down — aborting"
  exit 2
fi

say "pallas smoke (parity + timings)"
timeout 560 python tools/tpu_smoke.py 2>&1 | tee -a "$LOG"

say "flash block-size autotune"
timeout 560 python tools/flash_tune.py --quick 2>&1 | tee -a "$LOG"

say "per-op latency harness"
timeout 560 python tools/op_bench.py --n 20 2>&1 | tee -a "$LOG"

say "bench bert (flash+mask default)"
PT_BENCH_WALL=420 timeout 460 python bench.py --model bert --steps 10 \
  2>&1 | tee -a "$LOG"

say "bench resnet50 (NHWC bf16 + conv_custom_vjp) + per-fusion profile"
PT_BENCH_PROFILE=/tmp/pt_prof_resnet PT_BENCH_WALL=420 timeout 460 \
  python bench.py --model resnet50 --steps 10 2>&1 | tee -a "$LOG"

say "bench resnet50 batch 256 (HBM-residency probe from the r2 plan)"
PT_BENCH_WALL=420 timeout 460 python bench.py --model resnet50 --steps 10 \
  --batch 256 2>&1 | tee -a "$LOG"

say "bench transformer_big"
PT_BENCH_WALL=420 timeout 460 python bench.py --model transformer_big \
  --steps 10 2>&1 | tee -a "$LOG"

say "bench gpt"
PT_BENCH_WALL=420 timeout 460 python bench.py --model gpt --steps 10 \
  2>&1 | tee -a "$LOG"

say "bench gpt long-context (seq 2048, single-chip flash)"
PT_BENCH_WALL=420 timeout 460 python bench.py --model gpt --steps 10 \
  --seq 2048 --batch 4 2>&1 | tee -a "$LOG"

say "bench ernie"
PT_BENCH_WALL=420 timeout 460 python bench.py --model ernie --steps 10 \
  2>&1 | tee -a "$LOG"

say "bench ctr (DeepFM sparse pull-push)"
PT_BENCH_WALL=420 timeout 460 python bench.py --model ctr --steps 10 \
  2>&1 | tee -a "$LOG"

say "$(date -u +%FT%TZ) tpu_day1 done — record rows in BASELINE.md; flip"
say "any flash defaults guarded by smoke results"
