#!/usr/bin/env bash
# Probe the tunnel every PERIOD seconds; on recovery run the given
# script (default tools/tpu_recover2.sh) once, then keep watching so a
# later recovery re-runs it (recover2 skips rows already captured under
# tools/captured/, so re-runs go straight to the missing rows).
#
# Usage: bash tools/tpu_watchdog.sh [script] [period_s] [max_runs]
set -u
cd "$(dirname "$0")/.."
SCRIPT=${1:-tools/tpu_recover2.sh}
PERIOD=${2:-600}
MAX=${3:-3}
LOG=tools/tpu_watchdog.log
runs=0
while [ "$runs" -lt "$MAX" ]; do
  # devices()-only probe: no compile RPC in flight, so the timeout kill
  # cannot reproduce the kill-mid-compile wedge BASELINE.md documents
  # (bench.py's own probe covers compute aliveness per row)
  if timeout 120 python -c "
import jax
jax.devices()
print('PROBE_OK')" 2>/dev/null | grep -q PROBE_OK; then
    echo "$(date -u +%FT%TZ) tunnel up — running $SCRIPT" | tee -a "$LOG"
    bash "$SCRIPT"
    runs=$((runs + 1))
  else
    echo "$(date -u +%FT%TZ) tunnel down" >> "$LOG"
  fi
  sleep "$PERIOD"
done
