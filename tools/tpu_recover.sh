#!/usr/bin/env bash
# Tunnel-recovery bench sequence: the rows day 1 lost when the tunnel
# wedged mid-run (2026-07-31 01:22 UTC), in priority order — flagship
# BERT first, then the small causal-bwd precision probe, then the rest
# of the BASELINE matrix. Each row re-probes via bench.py's built-in
# aliveness check, so a wedged tunnel costs 75 s per row, not a hang.
#
# Usage: bash tools/tpu_recover.sh  (typically via tpu_watchdog.sh)
set -u
cd "$(dirname "$0")/.."
LOG=tools/tpu_recover.log
say() { echo "== $*" | tee -a "$LOG"; }

say "$(date -u +%FT%TZ) recover start"

say "bench bert (flagship — lost on day 1)"
PT_BENCH_WALL=420 timeout 460 python bench.py --model bert --steps 10 \
  2>&1 | tee -a "$LOG"

say "causal bwd precision probe (fa_causal/fa_d128 smoke fails)"
timeout 300 python tools/causal_bwd_probe.py 2>&1 | tee -a "$LOG"

say "bench gpt"
PT_BENCH_WALL=420 timeout 460 python bench.py --model gpt --steps 10 \
  2>&1 | tee -a "$LOG"

say "bench transformer_big"
PT_BENCH_WALL=420 timeout 460 python bench.py --model transformer_big \
  --steps 10 2>&1 | tee -a "$LOG"

say "bench ernie"
PT_BENCH_WALL=420 timeout 460 python bench.py --model ernie --steps 10 \
  2>&1 | tee -a "$LOG"

say "bench ctr (DeepFM sparse pull-push)"
PT_BENCH_WALL=420 timeout 460 python bench.py --model ctr --steps 10 \
  2>&1 | tee -a "$LOG"

say "bench gpt long-context (seq 2048)"
PT_BENCH_WALL=420 timeout 460 python bench.py --model gpt --steps 10 \
  --seq 2048 --batch 4 2>&1 | tee -a "$LOG"

say "per-op latency harness (re-run with the DCE-proof timing fix)"
timeout 560 python tools/op_bench.py --n 20 2>&1 | tee -a "$LOG"

say "bench resnet50 WITHOUT conv_custom_vjp (isolate the VJP delta)"
PT_FLAGS_conv_custom_vjp=0 PT_BENCH_WALL=420 timeout 460 \
  python bench.py --model resnet50 --steps 10 2>&1 | tee -a "$LOG"

# LAST on purpose: the day-1 run wedged the tunnel right after this row's
# 240 s attempt-kill (a client killed mid-compile seems to wedge the
# server side). Generous windows, one attempt, nothing scheduled after.
say "bench resnet50 batch 256 (longer window — compile blew 240 s on day 1)"
PT_BENCH_WALL=560 PT_BENCH_TIMEOUT=540 PT_BENCH_ATTEMPTS=1 timeout 600 \
  python bench.py --model resnet50 --steps 10 --batch 256 \
  2>&1 | tee -a "$LOG"

say "$(date -u +%FT%TZ) recover done"
