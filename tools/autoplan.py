#!/usr/bin/env python
"""Auto-parallelism planner CLI — rank dp x tp x pp meshes for a model
on a topology, no accelerator (and no jax) required.

The search+cost-model live in paddle_tpu/parallel/autoplan/; this tool
is the operator front door: a human ranked-candidate table (every
pruned factorization with its recorded reason) plus the repo-standard
last-line JSON row for scripting. `bench.py --mesh auto` consumes the
same plan at run time; this tool answers "what would it pick, and why"
ahead of time.

Usage:
  python tools/autoplan.py --model gpt --topology cpu4
  python tools/autoplan.py --model bert --topology v5e-8 --batch 32
  python tools/autoplan.py --model gpt --topology 2xv5e-16 --json
  python tools/autoplan.py --selftest        # host-math sanity (tier-1)
  python tools/autoplan.py --model gpt --calibrate   # vs XLA cost_analysis
  python tools/autoplan.py --model gpt --serve-spec  # speculative-decoding
                                         # break-even acceptance/topology
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _config(model, tiny):
    if model == "gpt":
        from paddle_tpu.models.gpt import GPTConfig
        return GPTConfig.tiny() if tiny else GPTConfig.small()
    if model == "bert":
        from paddle_tpu.models.bert import BertConfig
        return BertConfig.tiny() if tiny else BertConfig.base()
    if model == "ernie":
        from paddle_tpu.models.ernie import ErnieConfig
        return ErnieConfig.tiny() if tiny else ErnieConfig.base()
    if model == "transformer":
        from paddle_tpu.models.transformer import TransformerConfig
        return TransformerConfig.tiny() if tiny else TransformerConfig.big()
    raise SystemExit(f"unknown model {model!r}")


def selftest():
    """Fast host-math assertions over the planner stack (no jax import
    — stdlib only). Tier-1 runs this as a subprocess."""
    from paddle_tpu.parallel.autoplan import (
        MeshPlan, ModelSpec, Topology, layouts, search, train_flops)

    # factorization enumeration is exhaustive and exact
    f8 = search.factorizations(8)
    assert all(dp * tp * pp == 8 for dp, tp, pp in f8), f8
    assert (8, 1, 1) in f8 and (1, 8, 1) in f8 and (2, 2, 2) in f8
    assert len(f8) == len(set(f8))

    # LM layout table: the one source of truth answers the known rows
    t, _ = layouts.lm_layout(("tok_emb", "weight"), (50304, 64))
    assert t == ("tp", None), t
    t, _ = layouts.lm_layout(("out_proj", "weight"), (64, 50304))
    assert t == (None, "tp"), t
    t, reason = layouts.lm_layout(("out_proj", "weight"), (64, 50305),
                                  tp_size=4)
    assert t == (None, None) and "SKIPPED" in reason, (t, reason)

    # flop model scales linearly in tokens
    s1 = ModelSpec(name="x", vocab=1000, hidden=64, layers=2, heads=4,
                   intermediate=128, seq=32, batch=4)
    s2 = ModelSpec(name="x", vocab=1000, hidden=64, layers=2, heads=4,
                   intermediate=128, seq=32, batch=8)
    assert train_flops(s2) > 1.9 * train_flops(s1)

    # a huge-vocab model on a tiny-HBM chip must land on tp > 1, and the
    # pure-dp candidate must be pruned with a memory reason on record
    tight = Topology(name="tight4", num_chips=4, hbm_bytes=3 * 2 ** 30,
                     peak_flops=1e12, intra_bw=1e11, inter_bw=1e10)
    big = ModelSpec(name="big-vocab", vocab=512 * 1024, hidden=1024,
                    layers=4, heads=16, intermediate=4096, seq=128,
                    batch=8)
    p = search.plan(big, topology=tight, allow_pp=False)
    assert p.tp > 1, p.axes
    dp_only = next(c for c in p.candidates if c.dp == 4 and c.tp == 1)
    assert not dp_only.feasible and any(
        "HBM" in r or "GiB" in r for r in dp_only.reasons), dp_only.reasons

    # a tiny model on a roomy slice stays pure dp (simplest mesh wins)
    roomy = Topology(name="roomy8", num_chips=8, hbm_bytes=32 * 2 ** 30,
                     peak_flops=1e14, intra_bw=2e11, inter_bw=2.5e10)
    small = ModelSpec(name="tiny", vocab=1024, hidden=64, layers=2,
                      heads=4, intermediate=128, seq=32, batch=64)
    p2 = search.plan(small, topology=roomy, allow_pp=True)
    assert p2.axes == {"dp": 8}, p2.axes
    # pp never exceeds the layer count; the refusal is on record
    pp8 = next(c for c in p2.candidates if c.pp == 8)
    assert not pp8.feasible and any("layers" in r for r in pp8.reasons)

    # the whole decision record survives a JSON round-trip
    rt = MeshPlan.from_json(json.loads(p.dumps()))
    assert rt.axes == p.axes and len(rt.candidates) == len(p.candidates)
    return {"ok": True, "checks": 8}


def calibrate(model, batch, seq):
    """Analytic flops vs XLA's compile().cost_analysis() for a tiny
    value_and_grad train step on CPU."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.parallel.autoplan import costmodel

    cfg = _config(model, tiny=True)
    cfg.dropout = 0.0
    rng = np.random.RandomState(0)
    if model == "gpt":
        from paddle_tpu.models.gpt import GPT
        m = GPT(cfg)
        v = m.init(jax.random.key(0))
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq))
                          .astype(np.int32))

        def step(p):
            return m.apply({"params": p, "state": {}}, ids, pad_id=0,
                           method="loss")
    elif model in ("bert", "ernie"):
        from paddle_tpu.models.bert import BertForPretraining
        from paddle_tpu.models.ernie import ErnieForPretraining
        m = (ErnieForPretraining if model == "ernie"
             else BertForPretraining)(cfg)
        v = m.init(jax.random.key(0))
        n_mask = max(1, int(0.15 * seq))
        ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq))
                          .astype(np.int32))
        pos = jnp.asarray(np.stack(
            [np.sort(rng.choice(seq, n_mask, replace=False))
             for _ in range(batch)]).astype(np.int32))
        mlm_l = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                        (batch, n_mask)).astype(np.int32))
        nsp_l = jnp.asarray(rng.randint(0, 2, (batch,)).astype(np.int32))
        mm = jnp.asarray(np.ones((batch, n_mask), dtype=np.float32))

        def step(p):
            return m.apply({"params": p, "state": {}}, ids, mlm_l, nsp_l,
                           mm, mask_positions=pos, method="loss")
    else:
        raise SystemExit(f"--calibrate supports gpt/bert/ernie, "
                         f"not {model!r}")
    jitted = jax.jit(jax.value_and_grad(step))
    spec = costmodel.ModelSpec.from_config(cfg, batch=batch, seq=seq,
                                           name=model)
    return costmodel.calibration_report(spec, jitted, v["params"])


def serve_spec_report(model, tiny, topology, spec_k, slots, context,
                      draft_tiny):
    """Price speculative decoding per topology: what acceptance rate a
    draft must clear before spec_k-token rounds beat plain decode, and
    the projected speedup at a few representative acceptance rates.
    Pure host math over costmodel.predict_decode — no jax import."""
    from paddle_tpu.parallel.autoplan import (
        ModelSpec, costmodel, get_topology)
    from paddle_tpu.parallel.autoplan.topology import PRESETS

    cfg = _config(model, tiny)
    spec = ModelSpec.from_config(cfg, batch=slots, seq=context,
                                 name=model)
    draft_spec = None
    if draft_tiny and not tiny:
        # a separate (smaller) draft model instead of self-draft:
        # price the tiny config of the same architecture
        draft_spec = ModelSpec.from_config(
            _config(model, tiny=True), batch=slots, seq=context,
            name=f"{model}-tiny")
    names = ([topology] if topology
             else [n for n in PRESETS if not n.startswith("cpu")
                   or n == "cpu4"])
    probes = (0.3, 0.5, 0.7, 0.9)
    rows = []
    for name in names:
        topo = get_topology(name)
        pred = costmodel.predict_decode(
            spec, topo, slots=slots, context=context, spec_k=spec_k,
            draft_spec=draft_spec)
        row = {
            "topology": name,
            "draft": pred["draft"],
            "spec_k": spec_k,
            "rate_source": pred["rate_source"],
            "draft_overhead": round(pred["draft_overhead"], 4),
            # flops break-even: >= 1.0 by construction (verify work is
            # real) — the energy story, kept for the record
            "break_even_accept_rate":
                round(pred["break_even_accept_rate"], 4),
            # roofline (wall-clock) break-even: the decision figure —
            # memory-bound decode amortizes the weight/KV stream over
            # the verify window
            "break_even_accept_rate_s":
                round(pred["break_even_accept_rate_s"], 4),
        }
        for r in probes:
            p = costmodel.predict_decode(
                spec, topo, slots=slots, context=context,
                spec_k=spec_k, draft_spec=draft_spec, accept_rate=r)
            row[f"speedup@{r}"] = round(p["speedup_vs_plain_s"], 3)
        rows.append(row)
    head = (f"{'topology':<12} {'draft':<10} {'break-even(t)':>13} "
            f"{'(flops)':>8} {'overhead':>9} "
            + " ".join(f"x@{r:<5}" for r in probes))
    print(head)
    print("-" * len(head))
    for row in rows:
        print(f"{row['topology']:<12} {row['draft']:<10} "
              f"{row['break_even_accept_rate_s']:>13.4f} "
              f"{row['break_even_accept_rate']:>8.4f} "
              f"{row['draft_overhead']:>9.4f} "
              + " ".join(f"{row[f'speedup@{r}']:<7.3f}"
                         for r in probes))
    return {"tool": "autoplan", "mode": "serve_spec", "model": model,
            "slots": slots, "context": context, "spec_k": spec_k,
            "rows": rows}


def main():
    ap = argparse.ArgumentParser(
        description="rank dp x tp x pp meshes for a model on a topology")
    ap.add_argument("--model", default="gpt",
                    choices=["gpt", "bert", "ernie", "transformer"])
    ap.add_argument("--topology", default=None,
                    help="preset name (cpu4, v4-8, v5e-16, 2xv5e-16 ...); "
                         "default: PT_FLAGS_autoplan_topology or live "
                         "jax.devices() detection")
    ap.add_argument("--batch", type=int, default=None,
                    help="global batch (default 16, tiny 8)")
    ap.add_argument("--seq", type=int, default=None,
                    help="sequence length (default 512, tiny 64)")
    ap.add_argument("--tiny", action="store_true")
    ap.add_argument("--top", type=int, default=None,
                    help="show only the best N candidates")
    ap.add_argument("--json", action="store_true",
                    help="emit the full plan JSON (every candidate, every "
                         "prune reason) instead of the human table")
    ap.add_argument("--no-pp", action="store_true",
                    help="prune pipeline candidates (caller has no "
                         "pipeline executor)")
    ap.add_argument("--selftest", action="store_true",
                    help="host-math sanity assertions; prints {'ok': true}")
    ap.add_argument("--calibrate", action="store_true",
                    help="compare analytic flops vs XLA cost_analysis for "
                         "a tiny train step on CPU")
    ap.add_argument("--serve-spec", action="store_true",
                    help="speculative-decoding break-even acceptance "
                         "rate per topology (host math, no jax)")
    ap.add_argument("--spec-k", type=int, default=7,
                    help="draft tokens per speculation round")
    ap.add_argument("--slots", type=int, default=16,
                    help="decode slots priced (--serve-spec)")
    ap.add_argument("--context", type=int, default=None,
                    help="KV context length priced (--serve-spec; "
                         "default --seq)")
    ap.add_argument("--draft-tiny", action="store_true",
                    help="price a tiny-config draft model instead of "
                         "self-draft (--serve-spec)")
    args = ap.parse_args()

    if args.selftest:
        print(json.dumps(selftest()))
        return
    batch = args.batch or (8 if args.tiny else 16)
    seq = args.seq or (64 if args.tiny else 512)
    if args.serve_spec:
        out = serve_spec_report(
            args.model, args.tiny, args.topology, args.spec_k,
            args.slots, args.context or seq, args.draft_tiny)
        print(json.dumps(out))
        return
    if args.calibrate:
        out = calibrate(args.model, batch, seq)
        print(json.dumps(out))
        return

    from paddle_tpu.parallel.autoplan import (
        ModelSpec, get_topology, plan)
    cfg = _config(args.model, args.tiny)
    spec = ModelSpec.from_config(cfg, batch=batch, seq=seq,
                                 name=args.model)
    topo = get_topology(args.topology)
    p = plan(spec, topology=topo, allow_pp=not args.no_pp)
    if args.json:
        print(p.dumps(indent=2))
        return
    print(p.describe(top=args.top))
    print(json.dumps(p.summary()))


if __name__ == "__main__":
    main()
