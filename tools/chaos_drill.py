"""End-to-end fault drill: train -> flaky mirror (degrade) -> SIGTERM
preemption (checkpoint + exit 75) -> hard crash -> resume -> verify.

One ElasticRunner-supervised worker trains against a remote checkpoint
store served by ChaosFS(DirFS) — a directory-backed "object store" that
survives process restarts but injects deterministic faults:

  generation 0: the first mirror push hits 2 injected write failures
                (exhausting the tightened retry budget) -> the step is
                queued, training continues; then a SIGTERM lands mid-run
                -> forced checkpoint at the step boundary, exit 75;
  generation 1: resumes at the preemption step, then hard-crashes
                (os._exit) mid-step -> ElasticRunner restarts it;
  generation 2: resumes from the last committed step and finishes.

The drill verifies: exactly 1 preemption + 1 crash restart, every
remotely-visible step carries a COMMIT marker, retention pruned to the
keep window, and the final committed step equals the step count.

Usage:
    python tools/chaos_drill.py [--steps 8] [--workdir DIR]

Also exercised as a slow-marked test (tests/test_chaos.py).
"""

import argparse
import hashlib
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """\
import os, signal, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import jax.numpy as jnp
from paddle_tpu.core import flags as F
from paddle_tpu.io import fs
from paddle_tpu.testing import chaos
from paddle_tpu.static.trainer import Trainer, TrainerConfig

gen = int(os.environ['PT_ELASTIC_GENERATION'])
max_steps = {steps}
F.set_flags({{'retry_max_attempts': 2, 'retry_backoff_base_s': 0.001,
             'retry_jitter': 0.0}})
# deterministic chaos: the first mirrored step's push fails both retry
# attempts of its first object, then the store heals
plan = chaos.FaultPlan(seed=7).fail('write', path='/2/', times=2)
fs.register_filesystem('drill', chaos.ChaosFS(chaos.DirFS({root!r}), plan))

def reader():
    for i in range(1000):
        yield (np.ones((1,), np.float32),)

def step(state, x):
    w = float(state['w'])
    if gen == 0 and w == 3.0:
        os.kill(os.getpid(), signal.SIGTERM)   # preemption notice
    if gen == 1 and w == 5.0:
        os._exit(17)                           # simulated hard crash
    return jnp.sum(x), {{'w': state['w'] + 1.0}}

cfg = TrainerConfig(num_ingest_threads=1, max_steps=max_steps,
                    checkpoint_dir='drill://ck', checkpoint_every=2,
                    prefetch=False, handle_preemption=True)
state, stats = Trainer(step, cfg).train({{'w': jnp.zeros(())}},
                                        lambda: reader())
assert stats['steps'] == max_steps, stats
assert float(state['w']) == float(max_steps), state
with open({out!r}, 'a') as f:
    f.write('gen %d: steps=%d run_steps=%d\\n'
            % (gen, stats['steps'], stats['run_steps']))
print('[drill worker] generation', gen, 'finished', stats)
"""


def _staging_of(url):
    tag = hashlib.sha1(url.rstrip("/").encode()).hexdigest()[:16]
    return os.path.join(tempfile.gettempdir(), "pt_ckpt_staging", tag)


def run_drill(workdir, steps=8, timeout=600):
    """Run the drill under `workdir`; returns a summary dict (raises on
    any verification failure)."""
    sys.path.insert(0, REPO)
    from paddle_tpu.parallel.elastic import ElasticRunner

    workdir = os.path.abspath(workdir)
    root = os.path.join(workdir, "remote_store")
    out = os.path.join(workdir, "drill_log.txt")
    os.makedirs(workdir, exist_ok=True)
    # the staging dir is deterministic per URL and 'drill://ck' is shared
    # across drill invocations — start from a clean slate
    shutil.rmtree(_staging_of("drill://ck"), ignore_errors=True)
    script = os.path.join(workdir, "drill_worker.py")
    with open(script, "w") as f:
        f.write(_WORKER.format(repo=REPO, steps=steps, root=root, out=out))

    runner = ElasticRunner(1, script, max_restarts=2, restart_delay_s=0.1,
                           crash_window_s=300.0)
    res = runner.run(timeout=timeout)

    assert res["preemptions"] == [1], res
    assert res["restarts"] == [1], res
    ck = os.path.join(root, "ck")
    committed = sorted(int(n) for n in os.listdir(ck)
                       if n.isdigit()
                       and os.path.exists(os.path.join(ck, n, "COMMIT")))
    torn = sorted(int(n) for n in os.listdir(ck)
                  if n.isdigit()
                  and not os.path.exists(os.path.join(ck, n, "COMMIT")))
    assert torn == [], f"uncommitted steps visible remotely: {torn}"
    assert committed[-1] == steps, committed
    assert len(committed) <= 3, f"retention failed: {committed}"
    log = open(out).read()
    summary = dict(restarts=res["restarts"], preemptions=res["preemptions"],
                   committed_steps=committed, worker_log=log.strip())
    shutil.rmtree(_staging_of("drill://ck"), ignore_errors=True)
    return summary


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: fresh temp dir, removed "
                         "on success)")
    args = ap.parse_args()
    workdir = args.workdir or tempfile.mkdtemp(prefix="pt_chaos_drill_")
    summary = run_drill(workdir, steps=args.steps)
    print("\n=== chaos drill PASSED ===")
    for k, v in summary.items():
        print(f"  {k}: {v}")
    if args.workdir is None:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    main()
