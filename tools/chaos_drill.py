"""End-to-end fault drills for the recovery paths.

Train drill (default): train -> flaky mirror (degrade) -> SIGTERM
preemption (checkpoint + exit 75) -> hard crash -> resume -> verify.

One ElasticRunner-supervised worker trains against a remote checkpoint
store served by ChaosFS(DirFS) — a directory-backed "object store" that
survives process restarts but injects deterministic faults:

  generation 0: the first mirror push hits 2 injected write failures
                (exhausting the tightened retry budget) -> the step is
                queued, training continues; then a SIGTERM lands mid-run
                -> forced checkpoint at the step boundary, exit 75;
  generation 1: resumes at the preemption step, then hard-crashes
                (os._exit) mid-step -> ElasticRunner restarts it;
  generation 2: resumes from the last committed step and finishes.

The drill verifies: exactly 1 preemption + 1 crash restart, every
remotely-visible step carries a COMMIT marker, retention pruned to the
keep window, and the final committed step equals the step count.

Serve drill (--serve): in-process serving resilience — mixed-length
traffic (including prompts > prefill_len, admitted via chunked
prefill), injected `serve.prefill`/`serve.step` faults mid-stream,
queue overload past `serve_queue_limit`, an infeasible deadline, an
expiring deadline, and a client cancellation. Verifies that 100% of
submitted requests reach a terminal status (done / rejected / shed /
cancelled), that every COMPLETED greedy request is token-exact vs a
per-request generate() reference despite the recoveries, and that each
injected fault produced exactly one engine recovery. A closing
quantized-KV wave re-runs shared-prefix traffic through an int8 page
pool with an injected `quant.kv_write` fault: the faulted admission
degrades to private pages, everything stays terminal and traced-once.
A final speculation wave re-runs greedy traffic through a self-draft
engine (serve_draft) with an injected `spec.verify` fault: the faulted
round degrades to ONE plain decode step, completions stay token-exact
vs generate(), and the draft/verify jits stay traced-once.

Fleet drill (--fleet): 3 in-process engine replicas behind a
FleetRouter — mixed traffic, one replica killed mid-decode, one
injected `fleet.heartbeat` stall. Verifies 100% terminal requests,
token-exact greedy completions through the failover replay,
`fleet.failovers` == injected kills (the stall recovers, it does not
fail over), and every replica inside its respawn RetryBudget. A
closing flight-recorder leg injects a `flight.dump` fault (the dump is
swallowed, no half-bundle lands) then raises a real anomaly and
verifies exactly ONE complete evidence bundle (manifest listing every
section) fans out across the fleet.

Guardian drill (--train): training-side numerical resilience, two
phases. Containment (in-process): a 16-step run eats a NaN batch
(skip-apply leaves state bit-identical), then a mis-scaled spike batch
whose applied update wrecks the weights — the guardian ladder escalates
tolerate -> re-read -> rollback, the rollback finds its newest safe
checkpoint silently corrupted (crc32 manifest catches it, restore
degrades to the previous step), and the run still finishes converged.
Bit-exact resume (subprocess): an ElasticRunner-supervised worker is
SIGKILLed from its reader thread mid-run; the respawn resumes from the
checkpoint + meta and every per-step loss either generation recorded is
bit-identical to an undisturbed reference run.

Fleet live-ops drill (--fleet-ops): one run combining a rolling weight
deploy (crc32-gated), a kill -9 mid-swap, an overload ramp under the
autoscaler, and a corrupt-manifest push. Verifies 100% terminal
requests, ZERO cross-version token leaks (every greedy completion is
token-exact under the weights of the version that retired it), version
tags on every retirement, failovers == kills, at least one autoscale
spawn + retire, and the corrupt deploy aborting with the fleet still
serving the deployed version.

Usage:
    python tools/chaos_drill.py [--steps 8] [--workdir DIR]
    python tools/chaos_drill.py --serve
    python tools/chaos_drill.py --fleet
    python tools/chaos_drill.py --fleet-ops
    python tools/chaos_drill.py --train

Also exercised as tests (tests/test_chaos.py slow-marked train drill;
tests/test_serve_resilience.py serve drill; tests/test_fleet_router.py
fleet drill; tests/test_guardian.py slow-marked guardian drill).
"""

import argparse
import hashlib
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = """\
import os, signal, sys
sys.path.insert(0, {repo!r})
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import jax.numpy as jnp
from paddle_tpu.core import flags as F
from paddle_tpu.io import fs
from paddle_tpu.testing import chaos
from paddle_tpu.static.trainer import Trainer, TrainerConfig

gen = int(os.environ['PT_ELASTIC_GENERATION'])
max_steps = {steps}
F.set_flags({{'retry_max_attempts': 2, 'retry_backoff_base_s': 0.001,
             'retry_jitter': 0.0}})
# deterministic chaos: the first mirrored step's push fails both retry
# attempts of its first object, then the store heals
plan = chaos.FaultPlan(seed=7).fail('write', path='/2/', times=2)
fs.register_filesystem('drill', chaos.ChaosFS(chaos.DirFS({root!r}), plan))

def reader():
    for i in range(1000):
        yield (np.ones((1,), np.float32),)

def step(state, x):
    w = float(state['w'])
    if gen == 0 and w == 3.0:
        os.kill(os.getpid(), signal.SIGTERM)   # preemption notice
    if gen == 1 and w == 5.0:
        os._exit(17)                           # simulated hard crash
    return jnp.sum(x), {{'w': state['w'] + 1.0}}

cfg = TrainerConfig(num_ingest_threads=1, max_steps=max_steps,
                    checkpoint_dir='drill://ck', checkpoint_every=2,
                    prefetch=False, handle_preemption=True)
state, stats = Trainer(step, cfg).train({{'w': jnp.zeros(())}},
                                        lambda: reader())
assert stats['steps'] == max_steps, stats
assert float(state['w']) == float(max_steps), state
with open({out!r}, 'a') as f:
    f.write('gen %d: steps=%d run_steps=%d\\n'
            % (gen, stats['steps'], stats['run_steps']))
print('[drill worker] generation', gen, 'finished', stats)
"""


# -- guardian train drill (--train) ----------------------------------------

_TRAIN_WORKER = """\
import os, signal, sys, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import jax.numpy as jnp
from paddle_tpu.core import flags as F
from paddle_tpu.io import checkpoint as ckpt_mod
ckpt_mod._HAS_ORBAX = False   # synchronous numpy saves: durable under kill -9
from paddle_tpu.observability.telemetry import TelemetryConfig
from paddle_tpu.static import GuardianConfig, Trainer, TrainerConfig

gen = int(os.environ['PT_ELASTIC_GENERATION'])
F.set_flags({{'retry_backoff_base_s': 0.001, 'retry_jitter': 0.0}})
max_steps = {steps}

def batch(i):
    rng = np.random.RandomState(1000 + i)
    x = rng.randn(8).astype(np.float32)
    return (x, (3.0 * x).astype(np.float32))

class DS:
    def __init__(self):
        self.pos = 0
    def seek(self, step):
        self.pos = int(step)
    def reader(self):
        def feed():
            i = self.pos
            while i < 1000:
                if gen == 0 and i == {kill_index}:
                    # the kill must come from host code that still runs
                    # per batch — the READER thread; python inside the
                    # jitted step only executes at trace time. The pause
                    # lets the buffered steps retire and their interval
                    # checkpoint land before the lights go out.
                    time.sleep(1.0)
                    os.kill(os.getpid(), signal.SIGKILL)
                yield batch(i)
                i += 1
        return feed

def step(state, x, y):
    pred = state['w'] * x + state['b']
    loss = jnp.mean((pred - y) ** 2)
    gw = jnp.mean(2.0 * (pred - y) * x)
    gb = jnp.mean(2.0 * (pred - y))
    return loss, {{'w': state['w'] - 0.05 * gw,
                  'b': state['b'] - 0.05 * gb}}

cfg = TrainerConfig(
    num_ingest_threads=1, prefetch=False, channel_capacity=2,
    max_steps=max_steps, checkpoint_dir={ck!r}, checkpoint_every=2,
    guardian=GuardianConfig(min_samples=4),
    telemetry=TelemetryConfig(enabled=True, every_n_steps=1,
                              run_log={runlog!r}.format(gen=gen)))
state, stats = Trainer(step, cfg).train(
    {{'w': jnp.zeros(()), 'b': jnp.zeros(())}}, DS())
assert stats['steps'] == max_steps, stats
print('[train drill worker] generation', gen, 'finished', stats)
"""


def _train_batch(i, poison=None):
    """Deterministic linear-regression batch keyed by stream index: the
    drill's seekable dataset re-derives the exact same bytes on replay."""
    import numpy as np
    rng = np.random.RandomState(1000 + i)
    x = rng.randn(8).astype(np.float32)
    y = (3.0 * x).astype(np.float32)
    if poison == "nan":
        x = np.full_like(x, np.nan)
    elif poison == "spike":
        x, y = x * 1e4, y * 1e4   # mis-scaled batch: finite, wrecks w
    return x, y


class _DrillDataset:
    """Seekable index-keyed stream with ONE-SHOT fault injections: each
    poisoned index and side-effect hook fires once (marker files), so the
    replay after a guardian rollback reads clean data — exactly a
    transient bad-batch incident."""

    def __init__(self, n, marker_dir, faults=None, hooks=None):
        self.n = n
        self.pos = 0
        self.marker_dir = marker_dir
        self.faults = dict(faults or {})   # index -> "nan" | "spike"
        self.hooks = dict(hooks or {})     # index -> callable (fired once)

    def seek(self, step):
        self.pos = int(step)

    def _first_time(self, tag):
        path = os.path.join(self.marker_dir, tag)
        if os.path.exists(path):
            return False
        open(path, "w").close()
        return True

    def reader(self):
        def feed():
            i = self.pos
            while i < self.n:
                hook = self.hooks.get(i)
                if hook is not None and self._first_time(f"hook{i}"):
                    hook()
                poison = self.faults.get(i)
                if poison is not None and not self._first_time(f"fault{i}"):
                    poison = None
                yield _train_batch(i, poison)
                i += 1
        return feed


def run_train_drill(workdir, timeout=600):
    """Guardian end-to-end drill under `workdir`; returns a summary dict
    (raises on any verification failure). Two phases:

    containment (in-process): a 16-step run eats a NaN batch (skip-apply
    keeps state bit-identical), then a mis-scaled spike batch whose
    applied update wrecks the weights — the ladder escalates tolerate ->
    re-read -> rollback; the newest safe checkpoint has meanwhile been
    silently corrupted, so the verified restore counts the bad leaves and
    degrades to the previous step. The run still finishes all 16 steps
    with a converged loss, and the RunLog renders through
    run_report.py --train-health.

    bit-exact resume (subprocess): an ElasticRunner-supervised worker is
    SIGKILLed mid-run from its reader thread; the respawned generation
    resumes from the checkpoint (+ RNG/guardian meta) and every per-step
    loss either generation recorded is bit-identical to an undisturbed
    in-process reference run."""
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import json
    import math

    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.core import flags as F
    from paddle_tpu.io import checkpoint as ckpt_mod
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.observability.runlog import read_records
    from paddle_tpu.observability.telemetry import TelemetryConfig
    from paddle_tpu.parallel.elastic import ElasticRunner
    from paddle_tpu.static import GuardianConfig, Trainer, TrainerConfig

    workdir = os.path.abspath(workdir)
    os.makedirs(workdir, exist_ok=True)

    def csum(name):
        return sum(_metrics.counter(name).snapshot().values())

    def train_step(state, x, y):
        pred = state["w"] * x + state["b"]
        loss = jnp.mean((pred - y) ** 2)
        gw = jnp.mean(2.0 * (pred - y) * x)
        gb = jnp.mean(2.0 * (pred - y))
        return loss, {"w": state["w"] - 0.05 * gw,
                      "b": state["b"] - 0.05 * gb}

    saved_flags = F.all_flags()
    had_orbax = ckpt_mod._HAS_ORBAX
    try:
        F.set_flags({"retry_backoff_base_s": 0.001, "retry_jitter": 0.0})
        # numpy checkpoint mode: saves are synchronous files the drill can
        # corrupt deterministically (and kill -9 can't catch half-async)
        ckpt_mod._HAS_ORBAX = False

        # -- phase 1: containment (NaN skip -> spike ladder -> rollback
        # through a corrupted checkpoint) --------------------------------
        ckdir = os.path.join(workdir, "ck_containment")
        markers = os.path.join(workdir, "markers")
        os.makedirs(markers, exist_ok=True)
        run_log = os.path.join(workdir, "train_drill.jsonl")

        def corrupt_step8():
            # silent bit rot on the newest safe rollback target: valid
            # npz, plausible values, wrong bytes — only the crc32
            # manifest can tell
            p = os.path.join(ckdir, "8", "state.npz")
            data = dict(np.load(p))
            key = sorted(data)[0]
            data[key] = data[key] + np.float32(1.0)
            np.savez(p, **data)

        ds = _DrillDataset(
            40, markers,
            faults={4: "nan",     # consumed at step 5: skip-apply
                    9: "spike"},  # consumed at step 10: applied, wrecks w
            # fires once the reader reaches index 11 — after step 8's
            # interval save landed, before the ladder's rollback restores
            hooks={11: corrupt_step8})
        before = {n: csum(n) for n in
                  ("checkpoint.corrupt_leaves",
                   "checkpoint.integrity_fallbacks")}
        cfg = TrainerConfig(
            num_ingest_threads=1, prefetch=False, channel_capacity=2,
            max_steps=16, checkpoint_dir=ckdir, checkpoint_every=2,
            guardian=GuardianConfig(min_samples=4), watchdog=True,
            telemetry=TelemetryConfig(enabled=True, every_n_steps=1,
                                      run_log=run_log))
        tr = Trainer(train_step, cfg)
        state, stats = tr.train({"w": jnp.zeros(()), "b": jnp.zeros(())},
                                ds)
        guard = tr.guardian
        assert stats["steps"] == 16, stats
        assert guard.skips == 1, f"nonfinite skips: {guard.skips}"
        assert guard.spikes == 1, f"spike episodes: {guard.spikes}"
        assert guard.rollbacks == 1, f"rollbacks: {guard.rollbacks}"
        corrupt = (csum("checkpoint.corrupt_leaves")
                   - before["checkpoint.corrupt_leaves"])
        fallbacks = (csum("checkpoint.integrity_fallbacks")
                     - before["checkpoint.integrity_fallbacks"])
        assert corrupt >= 1, f"corrupt leaves: {corrupt}"
        assert fallbacks == 1, f"integrity fallbacks: {fallbacks}"
        assert math.isfinite(stats["final_loss"]), stats
        assert stats["final_loss"] < 5.0, (
            f"run did not re-converge after rollback: {stats}")

        records = read_records(run_log)
        g_recs = [r for r in records if "guardian" in r]
        assert any(r.get("action") == "rollback" for r in g_recs), g_recs
        assert any(r.get("anomaly") == "loss_spike" for r in records), (
            "no loss_spike watchdog anomaly in the RunLog")
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from run_report import render_train_health
        health = render_train_health(records)
        assert "rollback" in health and "integrity fallbacks" in health

        # -- phase 2: kill -9 + bit-exact resume --------------------------
        ck2 = os.path.join(workdir, "ck_resume")
        runlog_pat = os.path.join(workdir, "resume_g{gen}.jsonl")
        script = os.path.join(workdir, "train_drill_worker.py")
        resume_steps = 12
        with open(script, "w") as f:
            f.write(_TRAIN_WORKER.format(repo=REPO, steps=resume_steps,
                                         kill_index=10, ck=ck2,
                                         runlog=runlog_pat))
        runner = ElasticRunner(1, script, max_restarts=2,
                               restart_delay_s=0.1, crash_window_s=300.0)
        res = runner.run(timeout=timeout)
        assert res["restarts"] == [1], res
        assert res["preemptions"] == [0], res

        # undisturbed in-process reference: same step fn, same guardian
        # wrap, same data — the trajectory both generations must hit
        ref_tr = Trainer(train_step, TrainerConfig(
            num_ingest_threads=1, prefetch=False, channel_capacity=2,
            max_steps=resume_steps, guardian=GuardianConfig(min_samples=4),
            telemetry=TelemetryConfig(enabled=True, every_n_steps=1)))
        ref_ds = _DrillDataset(1000, markers)   # no faults
        ref_tr.train({"w": jnp.zeros(()), "b": jnp.zeros(())}, ref_ds)
        ref = {r["step"]: r["loss"] for r in ref_tr.telemetry.records
               if "step" in r and not r.get("final")}
        assert sorted(ref) == list(range(1, resume_steps + 1)), ref

        def gen_losses(gen):
            path = runlog_pat.format(gen=gen)
            if not os.path.exists(path):
                return {}
            return {r["step"]: r["loss"] for r in read_records(path)
                    if "step" in r and not r.get("final")}
        g0, g1 = gen_losses(0), gen_losses(1)
        assert g1, "the respawned generation wrote no step records"
        resume_at = min(g1) - 1
        assert resume_at >= 2 and resume_at % 2 == 0, (
            f"resume step {resume_at} is not a checkpoint boundary")
        assert sorted(g1) == list(range(resume_at + 1,
                                        resume_steps + 1)), g1
        assert sorted(g0) == list(range(1, max(g0) + 1)), g0
        assert max(g0) >= resume_at - 1, (g0.keys(), resume_at)
        # the loss written at the step the kill checkpointed may be the
        # one record the crash dropped on the floor; everything else of
        # 1..12 must be covered
        covered = set(g0) | set(g1)
        missing = set(range(1, resume_steps + 1)) - covered
        assert missing <= {resume_at}, f"uncovered steps: {missing}"
        # bit-exact: every recorded loss, from either generation —
        # including the overlap a torn final save forces gen 1 to replay
        # — equals the undisturbed reference exactly (json round-trips
        # floats losslessly, so == here is bitwise)
        for losses, who in ((g0, "gen0"), (g1, "gen1")):
            for s, v in losses.items():
                assert v == ref[s], (
                    f"{who} step {s}: loss {v!r} != reference {ref[s]!r} "
                    "— resume is not bit-exact")

        return dict(
            containment=dict(
                steps=stats["steps"], final_loss=stats["final_loss"],
                nonfinite_skips=guard.skips, spike_episodes=guard.spikes,
                rollbacks=guard.rollbacks, corrupt_leaves=corrupt,
                integrity_fallbacks=fallbacks),
            resume=dict(
                restarts=res["restarts"], resumed_at=resume_at,
                gen0_steps=sorted(g0), gen1_steps=sorted(g1),
                bit_exact_steps=len(g0) + len(g1)),
            train_health=health)
    finally:
        ckpt_mod._HAS_ORBAX = had_orbax
        F.set_flags(saved_flags)


def _staging_of(url):
    tag = hashlib.sha1(url.rstrip("/").encode()).hexdigest()[:16]
    return os.path.join(tempfile.gettempdir(), "pt_ckpt_staging", tag)


def run_drill(workdir, steps=8, timeout=600):
    """Run the drill under `workdir`; returns a summary dict (raises on
    any verification failure)."""
    sys.path.insert(0, REPO)
    from paddle_tpu.parallel.elastic import ElasticRunner

    workdir = os.path.abspath(workdir)
    root = os.path.join(workdir, "remote_store")
    out = os.path.join(workdir, "drill_log.txt")
    os.makedirs(workdir, exist_ok=True)
    # the staging dir is deterministic per URL and 'drill://ck' is shared
    # across drill invocations — start from a clean slate
    shutil.rmtree(_staging_of("drill://ck"), ignore_errors=True)
    script = os.path.join(workdir, "drill_worker.py")
    with open(script, "w") as f:
        f.write(_WORKER.format(repo=REPO, steps=steps, root=root, out=out))

    runner = ElasticRunner(1, script, max_restarts=2, restart_delay_s=0.1,
                           crash_window_s=300.0)
    res = runner.run(timeout=timeout)

    assert res["preemptions"] == [1], res
    assert res["restarts"] == [1], res
    ck = os.path.join(root, "ck")
    committed = sorted(int(n) for n in os.listdir(ck)
                       if n.isdigit()
                       and os.path.exists(os.path.join(ck, n, "COMMIT")))
    torn = sorted(int(n) for n in os.listdir(ck)
                  if n.isdigit()
                  and not os.path.exists(os.path.join(ck, n, "COMMIT")))
    assert torn == [], f"uncommitted steps visible remotely: {torn}"
    assert committed[-1] == steps, committed
    assert len(committed) <= 3, f"retention failed: {committed}"
    log = open(out).read()
    summary = dict(restarts=res["restarts"], preemptions=res["preemptions"],
                   committed_steps=committed, worker_log=log.strip())
    shutil.rmtree(_staging_of("drill://ck"), ignore_errors=True)
    return summary


def run_serve_drill(seed=0):
    """In-process serving resilience drill; returns a summary dict
    (raises on any verification failure). Deterministic: greedy
    decoding + a seeded FaultPlan, so completed outputs are checked
    token-exact against per-request generate() references. Ends with a
    shared-prefix wave whose first admission takes an injected
    serve.prefix_cache fault (degrade to private pages, never corrupt)
    while the rest must still hit the cache, then a quantized-KV wave
    through an int8 pool whose first admission takes an injected
    quant.kv_write fault (degrade to private pages, terminal, one
    trace), then a speculation wave through a self-draft engine whose
    second round takes an injected spec.verify fault (degrade to one
    plain decode step, token-exact, traced-once)."""
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.core import flags as F
    from paddle_tpu.models.gpt import GPTConfig, GPTDecoder
    from paddle_tpu.serving import ServeConfig, ServingEngine
    from paddle_tpu.testing import chaos

    saved = F.all_flags()
    try:
        F.set_flags({"retry_backoff_base_s": 0.001, "retry_jitter": 0.0})
        cfg = GPTConfig.tiny()
        cfg.dropout = 0.0
        cfg.use_flash = False
        model = GPTDecoder(cfg)
        variables = model.init(jax.random.key(0))
        engine = ServingEngine(model, variables, ServeConfig(
            num_slots=2, page_size=8, max_len=64, prefill_len=16,
            queue_limit=6, step_retries=4))
        rng = np.random.RandomState(seed)

        # traffic: short prompts and two chunked ones (30, 45 > 16)
        specs = [(5, 6), (30, 8), (9, 5), (45, 10), (3, 7)]
        prompts = [rng.randint(0, cfg.vocab_size, (L,), dtype=np.int32)
                   for L, _ in specs]
        accepted = [engine.submit(p, max_new=mn)
                    for p, (_, mn) in zip(prompts, specs)]
        # 6th queued request carries a deadline that expires before the
        # first step runs -> shed
        expiring = engine.submit(
            rng.randint(0, cfg.vocab_size, (8,), dtype=np.int32),
            max_new=4, deadline_s=0.004)
        # queue is now at serve_queue_limit=6: overload is rejected
        overload = [engine.submit(
            rng.randint(0, cfg.vocab_size, (4,), dtype=np.int32),
            max_new=4) for _ in range(3)]
        infeasible = engine.submit(
            rng.randint(0, cfg.vocab_size, (4,), dtype=np.int32),
            max_new=4, deadline_s=0.0)
        cancelled = accepted.pop()          # cancel the last queued one
        assert engine.cancel(cancelled)
        _time.sleep(0.02)                   # let the 0.004s deadline pass

        # three injected faults: two mid-stream decode steps, one
        # admission prefill (lands mid-chunk of a long prompt)
        plan = chaos.FaultPlan(seed=seed)
        plan.fail("fault_point", path=r"^serve\.step$", nth=3, times=1)
        plan.fail("fault_point", path=r"^serve\.step$", nth=8, times=1)
        plan.fail("fault_point", path=r"^serve\.prefill$", nth=4,
                  times=1)
        with chaos.active(plan):
            engine.drain()

        # -- verify ------------------------------------------------------
        statuses = {rid: r.status for rid, r in engine.requests.items()}
        terminal = {"done", "rejected", "shed", "cancelled", "failed"}
        stuck = {rid: s for rid, s in statuses.items()
                 if s not in terminal}
        assert not stuck, f"non-terminal requests after drain: {stuck}"
        assert all(statuses[rid] == "done" for rid in accepted), statuses
        assert all(statuses[rid] == "rejected" for rid in overload)
        assert all(engine.requests[rid].retriable for rid in overload)
        assert statuses[infeasible] == "rejected"
        assert statuses[expiring] == "shed"
        assert statuses[cancelled] == "cancelled"
        faults = plan.fired("fault_point")
        assert faults == 3, f"expected 3 injected faults, got {faults}"
        assert engine.recoveries == faults, (engine.recoveries, faults)
        recovered = [r for r in engine.requests.values()
                     if r.recoveries and r.status == "done"]
        assert recovered, "no recovered request finished"
        for rid, (p, (_, mn)) in zip(list(range(len(specs))),
                                     zip(prompts, specs)):
            if rid not in accepted:
                continue
            ref = model.apply(variables, jnp.asarray(p[None, :]),
                              method=lambda pr: model.generate(pr, mn))
            got = engine.requests[rid].output
            assert np.array_equal(got, np.asarray(ref)[0]), (
                f"request {rid} not token-exact after recovery")
        assert engine.decode_traces == 1 and engine.prefill_traces == 1

        # -- shared-prefix wave: three requests opening with the same
        # 20-token prefix (page 8 -> two full cacheable pages). The
        # FIRST admission's prefix-cache lookup takes an injected fault
        # (a hash collision / evict-under-use stand-in) and must
        # degrade to private pages; the later two hit the pages it
        # published. All three must stay token-exact vs generate().
        pc = engine._prefix_cache
        hits0 = pc.hits if pc else 0
        wave_plan = chaos.FaultPlan(seed=seed)
        wave_plan.fail("fault_point", path=r"^serve\.prefix_cache$",
                       nth=1, times=1)
        shared = rng.randint(0, cfg.vocab_size, (20,), dtype=np.int32)
        wave_prompts = [
            np.concatenate([shared, rng.randint(0, cfg.vocab_size, (k,),
                                                dtype=np.int32)])
            for k in (4, 7, 5)]
        with chaos.active(wave_plan):
            wave_ids = [engine.submit(p, max_new=6)
                        for p in wave_prompts]
            engine.drain()
        prefix_faults = wave_plan.fired("fault_point")
        assert prefix_faults == 1, (
            f"expected 1 injected prefix-cache fault, {prefix_faults}")
        wave_hits = (pc.hits - hits0) if pc else 0
        assert wave_hits > 0, "shared-prefix wave produced no cache hits"
        for rid, p in zip(wave_ids, wave_prompts):
            assert engine.requests[rid].status == "done"
            ref = model.apply(variables, jnp.asarray(p[None, :]),
                              method=lambda pr: model.generate(pr, 6))
            assert np.array_equal(engine.requests[rid].output,
                                  np.asarray(ref)[0]), (
                f"wave request {rid} not token-exact under the "
                "degraded prefix cache")
        assert engine.decode_traces == 1 and engine.prefill_traces == 1

        # -- quantized-KV wave: a shared-prefix wave through an int8
        # page pool (serve_kv_dtype=int8). The FIRST admission takes an
        # injected quant.kv_write fault and must degrade to private
        # pages (no prefix-cache mapping or publish — the containment
        # boundary for a suspect quantized write); later admissions
        # prefill and publish normally and the tail request must hit
        # the cache. Greedy decode over int8 KV is deterministic, so
        # the degraded request and an identical normally-admitted
        # request must emit identical tokens. Wave terminal, traces
        # stay 1.
        from paddle_tpu.observability import metrics as _metrics
        qengine = ServingEngine(model, variables, ServeConfig(
            num_slots=2, page_size=8, max_len=64, prefill_len=16,
            kv_dtype="int8"))
        deg0 = _metrics.counter("serve.kv_quant_degraded").total()
        qpc = qengine._prefix_cache
        qhits0 = qpc.hits if qpc else 0
        qplan = chaos.FaultPlan(seed=seed)
        qplan.fail("fault_point", path=r"^quant\.kv_write$", nth=1,
                   times=1)
        qshared = rng.randint(0, cfg.vocab_size, (20,), dtype=np.int32)
        qprompts = [
            np.concatenate([qshared, rng.randint(0, cfg.vocab_size, (k,),
                                                 dtype=np.int32)])
            for k in (4, 4, 6)]
        qprompts[1] = qprompts[0].copy()   # identical degraded/normal pair
        with chaos.active(qplan):
            q_ids = [qengine.submit(p, max_new=6) for p in qprompts]
            qengine.drain()
        quant_faults = qplan.fired("fault_point")
        assert quant_faults == 1, (
            f"expected 1 injected quant.kv_write fault, {quant_faults}")
        quant_degraded = int(
            _metrics.counter("serve.kv_quant_degraded").total() - deg0)
        assert quant_degraded == 1, (
            "the faulted admission did not degrade to private pages "
            f"(serve.kv_quant_degraded delta {quant_degraded})")
        for rid in q_ids:
            assert qengine.requests[rid].status == "done", (
                rid, qengine.requests[rid].status)
        quant_hits = (qpc.hits - qhits0) if qpc else 0
        assert quant_hits > 0, (
            "post-fault admissions never hit the quantized prefix cache")
        assert np.array_equal(qengine.requests[q_ids[0]].output,
                              qengine.requests[q_ids[1]].output), (
            "degraded (private-page) request diverged from its "
            "identical shared-path twin over the same int8 pool")
        assert (qengine.decode_traces == 1
                and qengine.prefill_traces == 1), "int8 engine retraced"
        qengine.close()

        # -- speculation wave: greedy mixed traffic through a self-draft
        # engine (draft == target). One round's verify takes an injected
        # spec.verify fault and must degrade to ONE plain decode step —
        # every completion stays token-exact vs generate() either way,
        # and the degraded round shows up as target_steps > rounds.
        # Draft/verify/decode jits each trace exactly once.
        sengine = ServingEngine(model, variables, ServeConfig(
            num_slots=2, page_size=8, max_len=64, prefill_len=16,
            draft=True, spec_k=4))
        splan = chaos.FaultPlan(seed=seed)
        splan.fail("fault_point", path=r"^spec\.verify$", nth=2, times=1)
        sprompts = [rng.randint(0, cfg.vocab_size, (L,), dtype=np.int32)
                    for L in (6, 30, 11)]
        with chaos.active(splan):
            s_ids = [sengine.submit(p, max_new=8) for p in sprompts]
            sengine.drain()
        spec_faults = splan.fired("fault_point")
        assert spec_faults == 1, (
            f"expected 1 injected spec.verify fault, {spec_faults}")
        sstats = sengine.spec_stats()
        assert sstats["enabled"] and sstats["rounds"] >= 1, sstats
        assert sstats["target_steps"] > sstats["rounds"], (
            "the faulted round did not run as a plain decode step",
            sstats)
        assert sstats["tokens_per_target_step"] > 1.0, sstats
        for rid, p in zip(s_ids, sprompts):
            assert sengine.requests[rid].status == "done", (
                rid, sengine.requests[rid].status)
            ref = model.apply(variables, jnp.asarray(p[None, :]),
                              method=lambda pr: model.generate(pr, 8))
            assert np.array_equal(sengine.requests[rid].output,
                                  np.asarray(ref)[0]), (
                f"speculative request {rid} not token-exact under the "
                "degraded verify")
        assert (sengine.draft_traces == 1
                and sengine.verify_traces == 1
                and sengine.decode_traces == 1), (
            "speculative engine retraced", sengine.draft_traces,
            sengine.verify_traces, sengine.decode_traces)
        sengine.close()
        engine.close()
        return dict(
            submitted=len(statuses),
            statuses={s: sum(1 for v in statuses.values() if v == s)
                      for s in sorted(set(statuses.values()))},
            injected_faults=faults, recoveries=engine.recoveries,
            recovered_done=[r.id for r in recovered],
            chunked_prompts=[rid for rid in accepted
                             if engine.requests[rid].prompt.size > 16],
            token_exact=len(accepted),
            prefix_wave=len(wave_ids), prefix_hits=wave_hits,
            prefix_faults=prefix_faults,
            wave_token_exact=len(wave_ids),
            quant_wave=len(q_ids), quant_faults=quant_faults,
            quant_degraded=quant_degraded, quant_hits=quant_hits,
            spec_wave=len(s_ids), spec_faults=spec_faults,
            spec_rounds=sstats["rounds"],
            spec_tokens_per_target_step=sstats["tokens_per_target_step"])
    finally:
        F.set_flags(saved)


def run_fleet_drill(seed=0):
    """Fleet failover drill: 3 in-process replicas behind a FleetRouter,
    mixed traffic (chunked prompts, priorities, an expiring deadline, an
    infeasible one), one replica killed mid-decode plus one injected
    heartbeat stall. Verifies 100% of submitted requests reach a
    terminal status, completions that survived the failover are
    token-exact vs per-request generate() references,
    `fleet.failovers` == injected kills (the transient stall must NOT
    count), no replica exceeds its respawn RetryBudget, and
    `jit.retraces{fn=serve.decode}` stays flat across the failover.
    A closing flight-recorder leg asserts a fault-injected
    `flight.dump` is swallowed bundle-less, then a real anomaly lands
    exactly one complete bundle (manifest lists every section)."""
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import time as _time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.core import flags as F
    from paddle_tpu.models.gpt import GPTConfig, GPTDecoder
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.serving import FleetConfig, FleetRouter, ServeConfig
    from paddle_tpu.testing import chaos

    def _decode_retraces():
        snap = _metrics.counter("jit.retraces").snapshot()
        return sum(v for k, v in snap.items() if "serve.decode" in k)

    saved = F.all_flags()
    router = None
    try:
        F.set_flags({"retry_backoff_base_s": 0.001, "retry_jitter": 0.0})
        cfg = GPTConfig.tiny()
        cfg.dropout = 0.0
        cfg.use_flash = False
        model = GPTDecoder(cfg)
        variables = model.init(jax.random.key(0))
        router = FleetRouter(
            model, variables,
            # dead_factor sized so a replica silent only because its
            # SIBLINGS are cold-compiling (one router round serializes
            # all three engines' first decode+prefill jits) is never
            # declared dead: 0.04 x 600 = 24s of headroom on CPU. The
            # kill below is detected by the process-died check, not
            # this timeout, and the 0.1s stall only needs heartbeat_s.
            FleetConfig(num_replicas=3, heartbeat_s=0.04,
                        heartbeat_dead_factor=600.0, respawn_budget=3),
            serve_config=ServeConfig(num_slots=2, page_size=8,
                                     max_len=64, prefill_len=16,
                                     step_retries=4))
        rng = np.random.RandomState(seed)

        # mixed traffic: short + chunked (> prefill_len) prompts, a
        # priority spread, generous deadlines on two of them
        specs = [(5, 6, 0, None), (30, 8, 1, None), (9, 5, 0, 30.0),
                 (45, 10, 2, None), (3, 7, 0, None), (12, 6, 1, 30.0),
                 (20, 8, 0, None), (7, 5, 0, None), (26, 9, 1, None)]
        prompts = [rng.randint(0, cfg.vocab_size, (L,), dtype=np.int32)
                   for L, _, _, _ in specs]
        accepted = [router.submit(p, max_new=mn, priority=pr,
                                  deadline_s=dl)
                    for p, (_, mn, pr, dl) in zip(prompts, specs)]
        expiring = router.submit(
            rng.randint(0, cfg.vocab_size, (8,), dtype=np.int32),
            max_new=4, deadline_s=0.004)
        infeasible = router.submit(
            rng.randint(0, cfg.vocab_size, (4,), dtype=np.int32),
            max_new=4, deadline_s=0.0)
        _time.sleep(0.02)              # let the 0.004s deadline pass

        retraces0 = _decode_retraces()
        missed0 = sum(_metrics.counter(
            "heartbeat.missed").snapshot().values())
        plan = chaos.FaultPlan(seed=seed)
        # one heartbeat stall: the ping is dropped after a 0.1s wedge,
        # so the next scan sees age > heartbeat_s and marks the replica
        # stalled — it must recover on the following ping, NOT fail over
        plan.fail("fault_point", path=r"^fleet\.heartbeat$", nth=4,
                  times=1, latency_s=0.1,
                  exc=chaos.InjectedFault("heartbeat publisher wedged"))
        kills = 0
        with chaos.active(plan):
            for _ in range(4):
                router.step()
            stalled_seen = "stalled" in router._states
            busy = [i for i in range(3)
                    if router._replicas[i].load() > 0]
            router.kill_replica(busy[-1])   # process death mid-decode
            kills += 1
            router.drain()

        # -- verify ------------------------------------------------------
        statuses = {fid: r.status for fid, r in router.requests.items()}
        terminal = {"done", "rejected", "shed", "cancelled", "failed"}
        stuck = {fid: s for fid, s in statuses.items()
                 if s not in terminal}
        assert not stuck, f"non-terminal requests after drain: {stuck}"
        assert all(statuses[fid] == "done" for fid in accepted), statuses
        assert statuses[expiring] == "shed", statuses
        assert statuses[infeasible] == "rejected", statuses
        assert not any(s == "failed" for s in statuses.values())
        assert router.failovers == kills, (router.failovers, kills)
        hb_faults = len([e for e in plan.log
                         if e[2].startswith("raise")])
        assert hb_faults == 1, f"expected 1 injected stall, {hb_faults}"
        missed = sum(_metrics.counter(
            "heartbeat.missed").snapshot().values()) - missed0
        assert stalled_seen or missed >= 1, (
            "the injected heartbeat stall was never observed")
        budget = router.cfg.respawn_budget
        over = [b.failures for b in router._budgets
                if b.failures > budget]
        assert not over, f"replica exceeded its RetryBudget: {over}"
        rerouted = [fid for fid in accepted
                    if router.requests[fid].reroutes]
        assert rerouted, "no request actually failed over"
        for fid, p, (_, mn, _, _) in zip(accepted, prompts, specs):
            ref = model.apply(variables, jnp.asarray(p[None, :]),
                              method=lambda pr: model.generate(pr, mn))
            got = router.requests[fid].output
            assert np.array_equal(got, np.asarray(ref)[0]), (
                f"request {fid} not token-exact after failover")
        assert _decode_retraces() == retraces0, (
            "serve.decode retraced across failover")
        for h in router._replicas:
            if h.alive() and h.engine.decode_traces:
                assert h.engine.decode_traces == 1, h.engine.decode_traces

        # -- flight recorder --------------------------------------------
        # an anomaly must land exactly ONE complete evidence bundle
        # (the manifest is written last, so its presence certifies the
        # bundle); a dump that faults mid-write is swallowed — the
        # anomaly handler keeps the fleet serving — and leaves NO
        # half-bundle behind.
        from paddle_tpu.observability import flight as _flight
        flight_dir = tempfile.mkdtemp(prefix="pt_flight_")
        F.set_flags({"flight_dir": flight_dir})
        err0 = _metrics.counter("flight.dumps").snapshot().get(
            "status=error", 0)
        fplan = chaos.FaultPlan(seed=seed)
        fplan.fail("fault_point", path=r"^flight\.dump$", times=1,
                   exc=chaos.InjectedFault("dump aborted mid-write"))
        with chaos.active(fplan):
            router._on_replica_anomaly(
                0, {"anomaly": "drill_faulted_dump", "step": 0})
        dump_faults = fplan.fired("fault_point")
        assert dump_faults == 1, (
            f"expected 1 injected flight.dump fault, {dump_faults}")
        assert _flight.list_bundles(flight_dir) == [], (
            "a fault-injected dump left a bundle behind")
        dump_errors = _metrics.counter("flight.dumps").snapshot().get(
            "status=error", 0) - err0
        assert dump_errors == 1, (
            "the swallowed dump failure was not counted on "
            f"flight.dumps{{status=error}} (delta {dump_errors})")

        # real anomaly, different kind (the router latches one bundle
        # per kind): the sink path fans ONE fleet-level dump carrying
        # every replica's RunLog tail + the fleet state summary
        router._on_replica_anomaly(
            0, {"anomaly": "drill_flight_check", "step": 0})
        bundles = _flight.list_bundles(flight_dir)
        assert len(bundles) == 1, (
            f"expected exactly 1 complete bundle, got {bundles}")
        manifest = _flight.read_manifest(bundles[0])
        missing = [s for s in ("metrics.json", "ring.jsonl",
                               "runlog_tail.jsonl", "config.json")
                   if s not in manifest["sections"]]
        assert not missing, f"bundle is missing sections: {missing}"
        assert manifest["reason"] == "drill_flight_check", manifest

        return dict(
            submitted=len(statuses),
            statuses={s: sum(1 for v in statuses.values() if v == s)
                      for s in sorted(set(statuses.values()))},
            injected_kills=kills, failovers=router.failovers,
            heartbeat_stalls=missed, rerouted=rerouted,
            respawn_failures=[b.failures for b in router._budgets],
            token_exact=len(accepted),
            flight_faulted_dumps=dump_faults,
            flight_bundle=bundles[0],
            flight_sections=manifest["sections"])
    finally:
        if router is not None:
            router.close()
        F.set_flags(saved)


def run_fleet_ops_drill(seed=0, workdir=None):
    """Live fleet operations drill — one run combining a rolling weight
    deploy, a kill -9 mid-swap, an overload ramp under the autoscaler,
    and a corrupt-manifest deploy. Verifies 100% of requests reach a
    terminal status, ZERO cross-version token leaks (every greedy
    completion is token-exact vs generate() under the weights of the
    version that retired it), every retirement carries a version tag,
    `fleet.failovers` == injected kills, the autoscaler both spawned
    and retired replicas, and the corrupt-manifest push aborted with
    the fleet still serving the deployed version."""
    sys.path.insert(0, REPO)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import json

    import jax
    import jax.numpy as jnp
    import numpy as np
    from paddle_tpu.core import flags as F
    from paddle_tpu.io.checkpoint import CheckpointManager
    from paddle_tpu.models.gpt import GPTConfig, GPTDecoder
    from paddle_tpu.observability import metrics as _metrics
    from paddle_tpu.serving import (DeployAborted, FleetConfig,
                                    FleetRouter, ServeConfig)

    saved = F.all_flags()
    router = None
    own_dir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="pt_fleet_ops_")
    try:
        F.set_flags({"retry_backoff_base_s": 0.001, "retry_jitter": 0.0})
        cfg = GPTConfig.tiny()
        cfg.dropout = 0.0
        cfg.use_flash = False
        model = GPTDecoder(cfg)
        weights = {"v0": model.init(jax.random.key(0)),
                   "v1": model.init(jax.random.key(1))}

        # the deployable artifacts: step 1 is a healthy v1 checkpoint,
        # step 2 the same weights with a TAMPERED crc32 manifest — the
        # corrupt push the rollout must refuse before touching a replica
        ck = os.path.join(workdir, "ck")
        with CheckpointManager(ck) as mgr:
            mgr.save(1, weights["v1"], force=True, version="v1")
            mgr.save(2, weights["v1"], force=True, version="v-bad")
        meta_path = os.path.join(ck, "2.meta.json")
        with open(meta_path) as f:
            meta = json.load(f)
        leaf = sorted(meta["crc32"])[0]
        meta["crc32"][leaf]["crc32"] ^= 0xDEADBEEF
        with open(meta_path, "w") as f:
            json.dump(meta, f)

        router = FleetRouter(
            model, weights["v0"],
            # dead_factor headroom per the --fleet drill: sibling cold
            # compiles must never read as heartbeat death
            # autoscaling is armed for phase 3 (cooldown 0 on a real
            # clock would shrink the fleet between phases otherwise)
            FleetConfig(num_replicas=3, heartbeat_s=0.04,
                        heartbeat_dead_factor=600.0, respawn_budget=3,
                        autoscale_min=1, autoscale_max=0,
                        scale_cooldown_s=0.0),
            serve_config=ServeConfig(num_slots=2, page_size=8,
                                     max_len=64, prefill_len=16,
                                     step_retries=4))
        rng = np.random.RandomState(seed)
        traffic = {}                  # fid -> (prompt, max_new)

        def submit_wave(n, mn=6):
            out = []
            for _ in range(n):
                p = rng.randint(0, cfg.vocab_size,
                                (int(rng.randint(3, 28)),),
                                dtype=np.int32)
                fid = router.submit(p, max_new=mn)
                traffic[fid] = (p, mn)
                out.append(fid)
            return out

        # -- phase 1: steady traffic on v0, all replicas warm ------------
        submit_wave(6)
        for _ in range(6):
            router.step()

        # -- phase 2: rolling deploy v0 -> v1 with a kill -9 mid-swap ----
        submit_wave(6)                # in flight across the rollout
        kills = {"n": 0}
        orig_step = router.step

        def step_with_midswap_kill():
            if kills["n"] == 0 and router._deploying is not None:
                # the replica currently draining toward its swap, caught
                # with work still on it: the sharpest interleave — its
                # victims re-route pinned to v0, it respawns on v0, and
                # the swap completes on the respawned corpse
                busy_swap = [i for i in router._pending_swaps
                             if router._replicas[i].alive()
                             and router._replicas[i].load() > 0]
                if busy_swap:
                    router.kill_replica(busy_swap[0])
                    kills["n"] += 1
            return orig_step()

        router.step = step_with_midswap_kill
        deployed = router.deploy(ck, step=1)
        router.step = orig_step
        assert deployed == "v1", deployed
        assert kills["n"] == 1, "the mid-swap kill never fired"
        assert router._baseline_version == "v1"
        live_versions = {router._versions[i]
                         for i, s in enumerate(router._states)
                         if s in ("live", "stalled", "draining")}
        assert live_versions == {"v1"}, live_versions

        # -- phase 3: overload ramp under the autoscaler -----------------
        router.cfg.autoscale_max = 5      # arm the autoscaler
        scale0 = dict(_metrics.counter("fleet.scale_events").snapshot())
        submit_wave(18, mn=4)         # backlog past 3 replicas' queues
        for _ in range(200):
            router.step()
            if all(r.status in ("done", "rejected", "shed", "cancelled",
                                "failed") for r in
                   router.requests.values()):
                break
        for _ in range(80):           # idle: sustained slack drains
            router.step()
            snap = _metrics.counter("fleet.scale_events").snapshot()
            if (snap.get("direction=down", 0)
                    - scale0.get("direction=down", 0)) >= 1:
                break
        snap = _metrics.counter("fleet.scale_events").snapshot()
        ups = snap.get("direction=up", 0) - scale0.get("direction=up", 0)
        downs = (snap.get("direction=down", 0)
                 - scale0.get("direction=down", 0))
        assert ups >= 1, "overload ramp never spawned a replica"
        assert downs >= 1, "idle fleet never retired a replica"

        # -- phase 4: corrupt-manifest deploy must abort -----------------
        versions_before = list(router._versions)
        try:
            router.deploy(ck, step=2)
            raise AssertionError("corrupt-manifest deploy did not abort")
        except DeployAborted:
            pass
        assert router._versions == versions_before
        assert router._baseline_version == "v1"
        probe = submit_wave(3, mn=4)  # the fleet still serves
        router.drain()
        assert all(router.requests[f].status == "done" for f in probe)

        # -- verify ------------------------------------------------------
        statuses = {fid: r.status for fid, r in router.requests.items()}
        terminal = {"done", "rejected", "shed", "cancelled", "failed"}
        stuck = {f: s for f, s in statuses.items() if s not in terminal}
        assert not stuck, f"non-terminal requests: {stuck}"
        assert not any(s == "failed" for s in statuses.values()), statuses
        assert router.failovers == kills["n"], (router.failovers, kills)
        untagged = [f for f, r in router.requests.items()
                    if r.version is None]
        assert not untagged, f"retirements without a version: {untagged}"
        # zero cross-version token leaks: every greedy completion must
        # be bit-identical to generate() under the weights of the
        # version stamped on it — a single adopted token computed on
        # the other version's weights would break this
        refs = {}
        leaks = []
        for fid, (p, mn) in traffic.items():
            rec = router.requests[fid]
            if rec.status != "done":
                continue
            key = (rec.version, p.tobytes(), mn)
            if key not in refs:
                refs[key] = np.asarray(model.apply(
                    weights[rec.version], jnp.asarray(p[None, :]),
                    method=lambda pr: model.generate(pr, mn)))[0]
            if not np.array_equal(rec.output, refs[key]):
                leaks.append(fid)
        assert not leaks, f"cross-version token leaks: {leaks}"
        deploy_counts = dict(
            _metrics.counter("fleet.deploys").snapshot())
        events = [e["event"] for e in router.ops_log]
        assert "deploy_start" in events and "deploy_done" in events
        assert "deploy_abort" in events, events
        assert "scale_up" in events and "scale_down" in events, events
        return dict(
            submitted=len(statuses),
            statuses={s: sum(1 for v in statuses.values() if v == s)
                      for s in sorted(set(statuses.values()))},
            deployed=deployed, injected_kills=kills["n"],
            failovers=router.failovers,
            scale_ups=ups, scale_downs=downs,
            deploys=deploy_counts,
            version_retirements=dict(_metrics.counter(
                "fleet.version_retirements").snapshot()),
            token_exact=sum(1 for s in statuses.values() if s == "done"),
            cross_version_leaks=0,
            ops_events=events)
    finally:
        if router is not None:
            router.close()
        F.set_flags(saved)
        if own_dir:
            shutil.rmtree(workdir, ignore_errors=True)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--workdir", default=None,
                    help="scratch dir (default: fresh temp dir, removed "
                         "on success)")
    ap.add_argument("--serve", action="store_true",
                    help="run the serving resilience drill instead of "
                         "the train drill")
    ap.add_argument("--fleet", action="store_true",
                    help="run the fleet router failover drill instead "
                         "of the train drill")
    ap.add_argument("--fleet-ops", action="store_true",
                    help="run the live fleet operations drill: rolling "
                         "deploy + kill -9 mid-swap + overload ramp + "
                         "corrupt-manifest abort in one run")
    ap.add_argument("--train", action="store_true",
                    help="run the guardian drill: NaN/spike containment, "
                         "rollback through a corrupted checkpoint, and "
                         "kill-9 bit-exact resume")
    args = ap.parse_args()
    if args.serve:
        summary = run_serve_drill()
        print("\n=== serve chaos drill PASSED ===")
        for k, v in summary.items():
            print(f"  {k}: {v}")
        return
    if args.fleet:
        summary = run_fleet_drill()
        print("\n=== fleet chaos drill PASSED ===")
        for k, v in summary.items():
            print(f"  {k}: {v}")
        return
    if args.fleet_ops:
        summary = run_fleet_ops_drill(workdir=args.workdir)
        print("\n=== fleet live-ops drill PASSED ===")
        for k, v in summary.items():
            print(f"  {k}: {v}")
        return
    if args.train:
        workdir = args.workdir or tempfile.mkdtemp(prefix="pt_train_drill_")
        summary = run_train_drill(workdir)
        print(summary.pop("train_health"))
        print("\n=== guardian train drill PASSED ===")
        for k, v in summary.items():
            print(f"  {k}: {v}")
        if args.workdir is None:
            shutil.rmtree(workdir, ignore_errors=True)
        return
    workdir = args.workdir or tempfile.mkdtemp(prefix="pt_chaos_drill_")
    summary = run_drill(workdir, steps=args.steps)
    print("\n=== chaos drill PASSED ===")
    for k, v in summary.items():
        print(f"  {k}: {v}")
    if args.workdir is None:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    main()
