"""Optimizer suite — functional, pytree-based.

Ref: /root/reference/python/paddle/fluid/optimizer.py:54 (base Optimizer:
backward :488, apply_gradients :557, minimize :641) and the per-op C++
kernels in /root/reference/paddle/fluid/operators/optimizers/ (sgd_op,
momentum_op, lars_momentum_op, adam_op, adamax_op, adagrad_op,
decayed_adagrad_op, adadelta_op, rmsprop_op, ftrl_op, lamb_op, dpsgd_op).

TPU-first: an optimizer is (init(params) -> state, update per-leaf math);
the whole update fuses into the jitted train step, and under pjit the state
shards like the params. `minimize(loss_fn, params, ...)` gives the
reference's one-call API on top.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.optimizer.lr_scheduler import make_schedule


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _moment_slots(p, state_dtype):
    """Adam-family moment slots. moment1 stores in state_dtype (or the
    param dtype); moment2 is pinned to f32 whenever state_dtype is
    narrower than 32 bits — beta2=0.999's 1e-3 relative decay step is
    below bf16's half-ulp, so a narrow moment2 freezes at its historical
    max instead of decaying. zeros_like keeps the param's sharding."""
    m1_dt = state_dtype or p.dtype
    if state_dtype is not None and jnp.finfo(state_dtype).bits < 32:
        m2_dt = jnp.float32
    else:
        m2_dt = state_dtype or p.dtype
    return {"moment1": jnp.zeros_like(p, dtype=m1_dt),
            "moment2": jnp.zeros_like(p, dtype=m2_dt)}


class Optimizer:
    """Base (ref: optimizer.py:54). Subclasses define slots() and
    _update_leaf(g, p, slots, lr, hyper) -> (new_p, new_slots)."""

    def __init__(self, learning_rate=0.01, regularization=None,
                 grad_clip=None):
        self.lr = make_schedule(learning_rate)
        self.regularization = regularization
        self.grad_clip = grad_clip
        # bound at construction so the state pytree structure is stable for
        # this instance even if the global flag is toggled mid-run
        from paddle_tpu.core.flags import get_flag
        self._check_nan_inf = get_flag("check_nan_inf")

    # -- subclass API --
    def slots(self, p):
        """Per-param slot init: dict name -> array."""
        return {}

    def _update_leaf(self, g, p, slots, lr, step):
        raise NotImplementedError

    # -- public API --
    def init(self, params):
        state = {
            "step": jnp.zeros((), jnp.int32),
            "slots": _tmap(lambda p: self.slots(p), params,
                           ),
        }
        if self._check_nan_inf:
            # ref flags.cc:44 FLAGS_check_nan_inf. Under jit the step can't
            # raise, so bad steps are *skipped* and counted here; eager calls
            # raise EnforceError immediately (see apply_gradients).
            state["nan_inf_steps"] = jnp.zeros((), jnp.int32)
        return state

    def apply_gradients(self, params, grads, state, _decay_mask=None):
        """ref: optimizer.py apply_gradients :557 (clip → regularize →
        per-param update ops).

        With flag check_nan_inf set at construction (ref flags.cc:44): eager
        calls raise EnforceError on non-finite gradients; traced (jit) calls
        skip the whole update and increment state['nan_inf_steps'] instead,
        since device code cannot raise on TPU (no host callbacks on the PJRT
        tunnel). The flag is bound in __init__ so the state structure can't
        change mid-run.

        _decay_mask: optional bool pytree (True = apply this optimizer's
        self.wd to the leaf) used by the decoupled-decay optimizers; kept
        inside this method so the masked path shares the nan/inf guard and
        state structure with the plain one. Mask leaves must be concrete
        (Python/np bools) — the mask picks code, not values.
        """
        check = self._check_nan_inf
        grads_in = grads
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        if self.regularization is not None:
            grads = self.regularization(grads, params)
        if check:
            import jax.core as jcore
            leaves = [g for g in jax.tree_util.tree_leaves(grads_in)
                      if g is not None]
            finite = jnp.array(True)
            for g in leaves:
                if jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating):
                    finite = finite & jnp.all(jnp.isfinite(g))
            if not isinstance(finite, jcore.Tracer) and not jnp.all(finite):
                from paddle_tpu.core.enforce import check_numerics
                check_numerics(grads_in, "gradients")
            params_in, state_in = params, state
        step = state["step"]
        lr = self.lr(step)
        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_s = treedef.flatten_up_to(state["slots"])
        if _decay_mask is None:
            flat_m = [True] * len(flat_p)
        else:
            flat_m = [bool(m) for m in treedef.flatten_up_to(_decay_mask)]
        new_p, new_s = [], []
        saved_wd = getattr(self, "wd", None)
        try:
            for g, p, s, use_decay in zip(flat_g, flat_p, flat_s, flat_m):
                if g is None:
                    new_p.append(p)
                    new_s.append(s)
                    continue
                if _decay_mask is not None:
                    self.wd = saved_wd if use_decay else 0.0
                np_, ns_ = self._update_leaf(g, p, s, lr, step)
                new_p.append(np_)
                new_s.append(ns_)
        finally:
            if _decay_mask is not None:
                self.wd = saved_wd
        params = jax.tree_util.tree_unflatten(treedef, new_p)
        slots = jax.tree_util.tree_unflatten(treedef, new_s)
        new_state = {"step": step + 1, "slots": slots}
        if check:
            # Skip the whole update on a bad step (AMP-scaler-style guard).
            keep = lambda new, old: _tmap(
                lambda a, b: jnp.where(finite, a, b), new, old)
            params = keep(params, params_in)
            new_state = keep(new_state, {k: v for k, v in state_in.items()
                                         if k != "nan_inf_steps"})
            new_state["nan_inf_steps"] = (
                state_in.get("nan_inf_steps", jnp.zeros((), jnp.int32))
                + jnp.where(finite, 0, 1))
        return params, new_state

    def _apply_gradients_decay_masked(self, params, grads, state, mask):
        """Per-leaf weight-decay masking for decoupled-decay optimizers
        (AdamW decay_mask_fn, Lamb exclude_from_weight_decay_fn). mask:
        bool pytree, True = apply this optimizer's self.wd to the leaf.
        Delegates to the base apply_gradients so the masked path keeps the
        check_nan_inf skip/count guard and the exact state structure."""
        return Optimizer.apply_gradients(self, params, grads, state,
                                         _decay_mask=mask)

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        """ref: optimizer.py minimize :641 — returns
        (loss, new_params, new_state, aux)."""
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, *args, **kwargs)
        params, state = self.apply_gradients(params, grads, state)
        return loss, params, state, aux


class SGD(Optimizer):
    """ref: operators/optimizers/sgd_op.cc"""

    def _update_leaf(self, g, p, s, lr, step):
        return p - lr * g.astype(p.dtype), s


class Momentum(Optimizer):
    """ref: operators/optimizers/momentum_op.h (velocity = mu*v + g;
    p -= lr * (g + mu*v) if nesterov else lr*v).

    state_dtype: storage dtype for the velocity slot (default: param
    dtype). bf16 velocity halves the optimizer's HBM traffic — for
    HBM-bound models (ResNet-50: ~100 MB of f32 velocity r+w per step)
    that is ~1 ms/step on v5e at the cost of ~3 decimal digits on a
    quantity that is itself a lossy running average. Update math runs in
    the WIDER of (param, state) dtype, so f32 state over bf16 params is
    a true master velocity."""

    def __init__(self, learning_rate=0.01, momentum=0.9, use_nesterov=False,
                 state_dtype=None, **kw):
        super().__init__(learning_rate, **kw)
        self.mu = momentum
        self.nesterov = use_nesterov
        self.state_dtype = state_dtype

    def slots(self, p):
        # zeros_like keeps the param's sharding for the slot (pjit init)
        dt = self.state_dtype or p.dtype
        return {"velocity": jnp.zeros_like(p, dtype=dt)}

    def _update_leaf(self, g, p, s, lr, step):
        # compute in the WIDER of (param, state) dtype so an f32
        # state_dtype over bf16 params acts as a true master velocity,
        # not f32 storage of a bf16-computed value
        cd = jnp.promote_types(p.dtype, s["velocity"].dtype)
        g = g.astype(cd)
        v = self.mu * s["velocity"].astype(cd) + g
        if self.nesterov:
            p = (p.astype(cd) - lr * (g + self.mu * v)).astype(p.dtype)
        else:
            p = (p.astype(cd) - lr * v).astype(p.dtype)
        # single source of truth for storage dtype: whatever slots() chose
        return p, {"velocity": v.astype(s["velocity"].dtype)}


class LarsMomentum(Optimizer):
    """LARS (ref: operators/optimizers/lars_momentum_op.cc): layer-wise
    adaptive rate = lr * coeff * ||p|| / (||g|| + lambda*||p||)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, lars_coeff=1e-3,
                 lars_weight_decay=5e-4, epsilon=1e-9, **kw):
        super().__init__(learning_rate, **kw)
        self.mu = momentum
        self.coeff = lars_coeff
        self.wd = lars_weight_decay
        self.eps = epsilon

    def slots(self, p):
        return {"velocity": jnp.zeros_like(p)}

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        pn = jnp.sqrt(jnp.sum(jnp.square(pf)))
        gn = jnp.sqrt(jnp.sum(jnp.square(g)))
        local = self.coeff * pn / (gn + self.wd * pn + self.eps)
        local = jnp.where(pn > 0, local, 1.0)
        v = self.mu * s["velocity"] + lr * local * (g + self.wd * pf)
        return (pf - v).astype(p.dtype), {"velocity": v}


class Adagrad(Optimizer):
    """ref: operators/optimizers/adagrad_op.cc"""

    def __init__(self, learning_rate=0.01, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self.eps = epsilon
        self.init_acc = initial_accumulator_value

    def slots(self, p):
        return {"moment": jnp.full_like(p, self.init_acc)}

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(p.dtype)
        m = s["moment"] + jnp.square(g)
        p = p - lr * g / (jnp.sqrt(m) + self.eps)
        return p, {"moment": m}


class DecayedAdagrad(Optimizer):
    """ref: operators/optimizers/decayed_adagrad_op.cc"""

    def __init__(self, learning_rate=0.01, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.eps = decay, epsilon

    def slots(self, p):
        return {"moment": jnp.zeros_like(p)}

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(p.dtype)
        m = self.decay * s["moment"] + (1 - self.decay) * jnp.square(g)
        return p - lr * g / (jnp.sqrt(m) + self.eps), {"moment": m}


class Adadelta(Optimizer):
    """ref: operators/optimizers/adadelta_op.cc"""

    def __init__(self, learning_rate=1.0, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self.eps, self.rho = epsilon, rho

    def slots(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(p.dtype)
        asg = self.rho * s["avg_squared_grad"] + (1 - self.rho) * jnp.square(g)
        upd = g * jnp.sqrt(s["avg_squared_update"] + self.eps) / \
            jnp.sqrt(asg + self.eps)
        asu = self.rho * s["avg_squared_update"] + (1 - self.rho) * jnp.square(upd)
        return p - lr * upd, {"avg_squared_grad": asg,
                              "avg_squared_update": asu}


class RMSProp(Optimizer):
    """ref: operators/optimizers/rmsprop_op.cc (centered + momentum variants)."""

    def __init__(self, learning_rate=0.01, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.eps, self.mu, self.centered = rho, epsilon, momentum, centered

    def slots(self, p):
        s = {"mean_square": jnp.zeros_like(p), "moment": jnp.zeros_like(p)}
        if self.centered:
            s["mean_grad"] = jnp.zeros_like(p)
        return s

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(p.dtype)
        ms = self.rho * s["mean_square"] + (1 - self.rho) * jnp.square(g)
        out = {"mean_square": ms}
        if self.centered:
            mg = self.rho * s["mean_grad"] + (1 - self.rho) * g
            denom = jnp.sqrt(ms - jnp.square(mg) + self.eps)
            out["mean_grad"] = mg
        else:
            denom = jnp.sqrt(ms + self.eps)
        mom = self.mu * s["moment"] + lr * g / denom
        out["moment"] = mom
        return p - mom, out


class Adam(Optimizer):
    """ref: operators/optimizers/adam_op.h — bias-corrected.

    state_dtype: storage dtype for the moment1 slot (default: param
    dtype). bf16 moment1 cuts the optimizer-state traffic by a quarter
    (BERT-base Adam: ~880 MB of f32 moments r+w per step on v5e).
    moment2 is PINNED to f32 whenever state_dtype is narrower than 32
    bits: with beta2=0.999 the per-step relative decay (1e-3) is below
    bf16's half-ulp (~2e-3), so a bf16 moment2 can never decay — it
    freezes at its historical max and permanently suppresses the
    effective lr. moment1's 1-beta1=0.1 step is safely representable.
    Update math always runs in f32; slot dtypes apply at store time.

    lazy_mode is accepted for reference API compatibility but is a
    documented no-op: it exists in the reference to restrict updates to
    rows touched by sparse (SelectedRows) gradients, and the TPU-first
    sparse-row path here is `parallel.sparse.SparseTable` pull/push with
    its own per-row optimizer, so dense Adam never sees row-sparse
    gradients."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, state_dtype=None, **kw):
        super().__init__(learning_rate, **kw)
        self.b1, self.b2, self.eps = beta1, beta2, epsilon
        self.state_dtype = state_dtype

    def slots(self, p):
        return _moment_slots(p, self.state_dtype)

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(jnp.float32)
        t = (step + 1).astype(jnp.float32)
        m = self.b1 * s["moment1"].astype(jnp.float32) + (1 - self.b1) * g
        v = self.b2 * s["moment2"].astype(jnp.float32) \
            + (1 - self.b2) * jnp.square(g)
        mhat = m / (1 - self.b1 ** t)
        vhat = v / (1 - self.b2 ** t)
        new_p = p.astype(jnp.float32) - lr * mhat / (jnp.sqrt(vhat) + self.eps)
        # store in the slot dtype slots() chose (also keeps the state
        # pytree dtype-stable across steps when params are not f32)
        return new_p.astype(p.dtype), {
            "moment1": m.astype(s["moment1"].dtype),
            "moment2": v.astype(s["moment2"].dtype)}


class AdamW(Adam):
    """Decoupled weight decay (modern; reference era used L2 regularizer)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, weight_decay=0.01, decay_mask_fn=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kw)
        self.wd = weight_decay
        self.decay_mask_fn = decay_mask_fn

    def _update_leaf(self, g, p, s, lr, step):
        new_p, slots = super()._update_leaf(g, p, s, lr, step)
        decay = self.wd
        if decay:
            new_p = new_p - lr * decay * p
        return new_p, slots

    def apply_gradients(self, params, grads, state):
        if self.decay_mask_fn is not None:
            mask = self.decay_mask_fn(params)
            return self._apply_gradients_decay_masked(
                params, grads, state, mask)
        return super().apply_gradients(params, grads, state)


class Adamax(Optimizer):
    """ref: operators/optimizers/adamax_op.h"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.b1, self.b2, self.eps = beta1, beta2, epsilon

    def slots(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(p.dtype)
        t = (step + 1).astype(jnp.float32)
        m = self.b1 * s["moment"] + (1 - self.b1) * g
        u = jnp.maximum(self.b2 * s["inf_norm"], jnp.abs(g))
        p = p - (lr / (1 - self.b1 ** t)) * m / (u + self.eps)
        return p, {"moment": m, "inf_norm": u}


class Ftrl(Optimizer):
    """ref: operators/optimizers/ftrl_op.h"""

    def __init__(self, learning_rate=0.01, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def slots(self, p):
        return {"squared": jnp.zeros_like(p), "linear": jnp.zeros_like(p)}

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(p.dtype)
        new_sq = s["squared"] + jnp.square(g)
        lp = -self.lr_power
        sigma = (jnp.power(new_sq, lp) - jnp.power(s["squared"], lp)) / lr
        lin = s["linear"] + g - sigma * p
        quad = jnp.power(new_sq, lp) / lr + 2 * self.l2
        pre = -lin + jnp.sign(lin) * self.l1
        p = jnp.where(jnp.abs(lin) > self.l1, pre / quad, 0.0)
        return p, {"squared": new_sq, "linear": lin}


class Lamb(Optimizer):
    """ref: operators/optimizers/lamb_op.h — layer-wise adaptation for large
    batch (BERT-scale). state_dtype: same reduced-precision moment1
    storage as Adam (f32 math, slot-dtype store, f32-pinned moment2).
    exclude_from_weight_decay_fn(params) -> bool pytree, True = exclude
    that leaf from weight decay (the BERT recipe excludes LayerNorm
    scales and biases)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, state_dtype=None, **kw):
        super().__init__(learning_rate, **kw)
        self.wd = lamb_weight_decay
        self.b1, self.b2, self.eps = beta1, beta2, epsilon
        self.exclude_fn = exclude_from_weight_decay_fn
        self.state_dtype = state_dtype

    def slots(self, p):
        return _moment_slots(p, self.state_dtype)

    def apply_gradients(self, params, grads, state):
        if self.exclude_fn is not None:
            excl = self.exclude_fn(params)
            mask = jax.tree_util.tree_map(lambda e: not bool(e), excl)
            return self._apply_gradients_decay_masked(
                params, grads, state, mask)
        return super().apply_gradients(params, grads, state)

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(jnp.float32)
        pf = p.astype(jnp.float32)
        t = (step + 1).astype(jnp.float32)
        m = self.b1 * s["moment1"].astype(jnp.float32) + (1 - self.b1) * g
        v = self.b2 * s["moment2"].astype(jnp.float32) \
            + (1 - self.b2) * jnp.square(g)
        mhat = m / (1 - self.b1 ** t)
        vhat = v / (1 - self.b2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self.eps) + self.wd * pf
        pn = jnp.sqrt(jnp.sum(jnp.square(pf)))
        rn = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((pn > 0) & (rn > 0), pn / rn, 1.0)
        return (pf - lr * trust * r).astype(p.dtype), \
            {"moment1": m.astype(s["moment1"].dtype),
             "moment2": v.astype(s["moment2"].dtype)}


class Dpsgd(Optimizer):
    """Differentially-private SGD (ref: operators/optimizers/dpsgd_op.cc):
    clip per-update + Gaussian noise."""

    def __init__(self, learning_rate=0.01, clip=10.0, batch_size=16.0,
                 sigma=1.0, seed=0, **kw):
        super().__init__(learning_rate, **kw)
        self.clip_v, self.batch_size, self.sigma = clip, batch_size, sigma
        self.seed = seed

    def slots(self, p):
        return {}

    def apply_gradients(self, params, grads, state):
        # reset the trace-time leaf counter so each parameter draws
        # INDEPENDENT noise (leaf order is fixed by the treedef)
        self._leaf_idx = 0
        return super().apply_gradients(params, grads, state)

    def _update_leaf(self, g, p, s, lr, step):
        g = g.astype(p.dtype)
        leaf_idx = getattr(self, "_leaf_idx", 0)
        self._leaf_idx = leaf_idx + 1
        key = jax.random.fold_in(jax.random.key(self.seed), step)
        key = jax.random.fold_in(key, leaf_idx)
        gn = jnp.sqrt(jnp.sum(jnp.square(g)))
        g = g * jnp.minimum(1.0, self.clip_v / jnp.maximum(gn, 1e-12))
        noise = self.sigma * self.clip_v / self.batch_size * \
            jax.random.normal(key, g.shape, g.dtype)
        return p - lr * (g + noise), s
