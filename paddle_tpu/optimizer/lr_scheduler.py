"""Learning-rate schedules.

Ref: /root/reference/python/paddle/fluid/layers/learning_rate_scheduler.py
(noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup) — the
reference builds these as graph ops over a global step variable; here each is
a pure function `step -> lr` traced into the update step.
"""

import jax.numpy as jnp


def constant(lr):
    return lambda step: jnp.asarray(lr, jnp.float32)


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    def sched(step):
        s = jnp.maximum(step.astype(jnp.float32), 1.0)
        return learning_rate * d_model ** -0.5 * jnp.minimum(
            s ** -0.5, s * warmup_steps ** -1.5)
    return sched


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    def sched(step):
        e = step.astype(jnp.float32) / decay_steps
        if staircase:
            e = jnp.floor(e)
        return learning_rate * jnp.power(decay_rate, e)
    return sched


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    def sched(step):
        e = step.astype(jnp.float32) / decay_steps
        if staircase:
            e = jnp.floor(e)
        return learning_rate * jnp.exp(-decay_rate * e)
    return sched


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    def sched(step):
        e = step.astype(jnp.float32) / decay_steps
        if staircase:
            e = jnp.floor(e)
        return learning_rate / (1.0 + decay_rate * e)
    return sched


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    def sched(step):
        s = step.astype(jnp.float32)
        if cycle:
            mult = jnp.maximum(1.0, jnp.ceil(s / decay_steps))
            ds = decay_steps * mult
        else:
            ds = decay_steps
            s = jnp.minimum(s, decay_steps)
        return (learning_rate - end_learning_rate) * \
            jnp.power(1.0 - s / ds, power) + end_learning_rate
    return sched


def piecewise_decay(boundaries, values):
    def sched(step):
        s = step.astype(jnp.float32)
        lr = jnp.asarray(values[0], jnp.float32)
        for b, v in zip(boundaries, values[1:]):
            lr = jnp.where(s >= b, v, lr)
        return lr
    return sched


def cosine_decay(learning_rate, step_each_epoch, epochs):
    def sched(step):
        ep = jnp.floor(step.astype(jnp.float32) / step_each_epoch)
        return learning_rate * 0.5 * (jnp.cos(ep * jnp.pi / epochs) + 1.0)
    return sched


def cosine_decay_steps(learning_rate, total_steps, min_lr=0.0):
    """Continuous cosine over steps (modern variant)."""
    def sched(step):
        t = jnp.minimum(step.astype(jnp.float32) / total_steps, 1.0)
        return min_lr + (learning_rate - min_lr) * 0.5 * (1 + jnp.cos(jnp.pi * t))
    return sched


def linear_lr_warmup(base_sched, warmup_steps, start_lr, end_lr):
    base = base_sched if callable(base_sched) else constant(base_sched)

    def sched(step):
        s = step.astype(jnp.float32)
        warm = start_lr + (end_lr - start_lr) * s / max(warmup_steps, 1)
        return jnp.where(s < warmup_steps, warm, base(step))
    return sched


def make_schedule(lr):
    """Normalize float | callable to a schedule fn."""
    return lr if callable(lr) else constant(lr)
