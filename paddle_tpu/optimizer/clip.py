"""Gradient clipping.

Ref: /root/reference/python/paddle/fluid/clip.py — GradientClipByValue,
GradientClipByNorm, GradientClipByGlobalNorm (525 LoC). Each clip is a pure
pytree→pytree transform applied before the optimizer update.
"""

import jax
import jax.numpy as jnp


class ClipByValue:
    """ref: clip.py GradientClipByValue"""

    def __init__(self, min, max=None):
        if max is None:
            min, max = -abs(min), abs(min)
        self.min, self.max = min, max

    def __call__(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipByNorm:
    """Per-tensor L2 clip (ref: clip.py GradientClipByNorm)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip_one(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            return g * jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
        return jax.tree_util.tree_map(clip_one, grads)


class ClipByGlobalNorm:
    """Global-norm clip (ref: clip.py GradientClipByGlobalNorm)."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        gnorm = jnp.sqrt(
            jnp.sum(jnp.array([jnp.sum(jnp.square(g.astype(jnp.float32)))
                               for g in leaves])))
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype),
                                      grads)


def global_norm(grads):
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(jnp.sum(jnp.array(
        [jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves])))
