"""Optimizer package.

Ref: /root/reference/python/paddle/fluid/optimizer.py (3.7k LoC, SGD through
Lamb + wrappers) and paddle/fluid/operators/optimizers/ (42 files).
"""

from paddle_tpu.optimizer.optimizers import (
    Adadelta,
    Adagrad,
    Adam,
    AdamW,
    Adamax,
    DecayedAdagrad,
    Dpsgd,
    Ftrl,
    Lamb,
    LarsMomentum,
    Momentum,
    Optimizer,
    RMSProp,
    SGD,
)
from paddle_tpu.optimizer.wrappers import (
    DGCMomentum,
    ExponentialMovingAverage,
    Lookahead,
    ModelAverage,
    RecomputeOptimizer,
)
from paddle_tpu.optimizer import clip, lr_scheduler, regularizer
from paddle_tpu.optimizer.clip import (
    ClipByGlobalNorm,
    ClipByNorm,
    ClipByValue,
    global_norm,
)
from paddle_tpu.optimizer.regularizer import L1Decay, L2Decay
