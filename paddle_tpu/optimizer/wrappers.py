"""Meta-optimizers and averaging wrappers.

Ref: /root/reference/python/paddle/fluid/optimizer.py — ModelAverage:2449,
EMA (ExponentialMovingAverage):2751, RecomputeOptimizer:3278,
LookaheadOptimizer:3571, DGCMomentumOptimizer:870.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.optimizer.optimizers import Momentum, Optimizer


class ExponentialMovingAverage:
    """ref: optimizer.py:2751 — shadow = decay*shadow + (1-decay)*param with
    optional thres_steps debiasing."""

    def __init__(self, decay=0.999):
        self.decay = decay

    def init(self, params):
        return {"shadow": jax.tree_util.tree_map(jnp.copy, params),
                "step": jnp.zeros((), jnp.int32)}

    def update(self, ema_state, params):
        step = ema_state["step"] + 1
        d = jnp.minimum(self.decay, (1.0 + step) / (10.0 + step))
        shadow = jax.tree_util.tree_map(
            lambda s, p: d * s + (1 - d) * p, ema_state["shadow"], params)
        return {"shadow": shadow, "step": step}

    def apply(self, ema_state):
        """Returns averaged params for eval (ref: EMA.apply context)."""
        return ema_state["shadow"]


class ModelAverage:
    """ref: optimizer.py:2449 ModelAverage +
    operators/average_accumulates_op.h — the full reference window policy:

      per update:  num_updates+=1; num_accumulates+=1; sum_1 += p
      precision:   every 16384 updates, fold sum_1 into sum_2
      restart:     when num_accumulates >= min_average_window AND
                   >= min(max_average_window, num_updates*average_window_rate)
                   -> sum_3 = sum_1+sum_2; sum_1=sum_2=0;
                      old_num_accumulates = num_accumulates; num_accumulates=0
      apply():     (sum_1+sum_2+sum_3) / (num_accumulates+old_num_accumulates)
    """

    _MAX_NUM_ACCUMULATES = 16384  # kMaxNumAccumulates, avg_accumulates_op.h:45

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000):
        from paddle_tpu.core.enforce import enforce_le
        enforce_le(min_average_window, max_average_window,
                   "min_average_window shouldn't be larger than "
                   "max_average_window")
        self.rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window

    def init(self, params):
        zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"sum_1": zeros(), "sum_2": zeros(), "sum_3": zeros(),
                "num_updates": jnp.zeros((), jnp.int64
                                         if jax.config.jax_enable_x64
                                         else jnp.int32),
                "num_accumulates": jnp.zeros((), jnp.int32),
                "old_num_accumulates": jnp.zeros((), jnp.int32)}

    def update(self, st, params):
        tmap = jax.tree_util.tree_map
        num_updates = st["num_updates"] + 1
        num_acc = st["num_accumulates"] + 1
        s1 = tmap(lambda a, p: a + p, st["sum_1"], params)
        s2, s3 = st["sum_2"], st["sum_3"]
        # precision fold (avg_accumulates_op.h:88)
        fold = (num_updates % self._MAX_NUM_ACCUMULATES) == 0
        s2 = tmap(lambda b, a: jnp.where(fold, b + a, b), s2, s1)
        s1 = tmap(lambda a: jnp.where(fold, jnp.zeros_like(a), a), s1)
        # window restart (avg_accumulates_op.h:94)
        window = jnp.minimum(
            jnp.asarray(float(self.max_window)),
            num_updates.astype(jnp.float32) * self.rate)
        restart = (num_acc >= self.min_window) & \
            (num_acc.astype(jnp.float32) >= window)
        s3 = tmap(lambda c, a, b: jnp.where(restart, a + b, c), s3, s1, s2)
        s1 = tmap(lambda a: jnp.where(restart, jnp.zeros_like(a), a), s1)
        s2 = tmap(lambda b: jnp.where(restart, jnp.zeros_like(b), b), s2)
        old_num = jnp.where(restart, num_acc, st["old_num_accumulates"])
        num_acc = jnp.where(restart, 0, num_acc)
        return {"sum_1": s1, "sum_2": s2, "sum_3": s3,
                "num_updates": num_updates, "num_accumulates": num_acc,
                "old_num_accumulates": old_num}

    def apply(self, st):
        denom = (st["num_accumulates"]
                 + st["old_num_accumulates"]).astype(jnp.float32)
        denom = jnp.maximum(denom, 1.0)
        return jax.tree_util.tree_map(
            lambda a, b, c: (a + b + c) / denom,
            st["sum_1"], st["sum_2"], st["sum_3"])


class Lookahead:
    """ref: optimizer.py LookaheadOptimizer:3571 — slow/fast weights."""

    def __init__(self, inner: Optimizer, alpha=0.5, k=5):
        self.inner = inner
        self.alpha, self.k = alpha, k

    def init(self, params):
        return {"inner": self.inner.init(params),
                "slow": jax.tree_util.tree_map(jnp.copy, params),
                "step": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, params, grads, state):
        params, inner_state = self.inner.apply_gradients(
            params, grads, state["inner"])
        step = state["step"] + 1
        sync = (step % self.k) == 0
        slow = jax.tree_util.tree_map(
            lambda s, f: jnp.where(sync, s + self.alpha * (f - s), s),
            state["slow"], params)
        params = jax.tree_util.tree_map(
            lambda s, f: jnp.where(sync, s, f), slow, params)
        return params, {"inner": inner_state, "slow": slow, "step": step}

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, *args, **kwargs)
        params, state = self.apply_gradients(params, grads, state)
        return loss, params, state, aux


class RecomputeOptimizer:
    """Activation recomputation (ref: optimizer.py:3278 +
    backward.py:576 _append_backward_ops_with_checkpoints_).

    TPU-native: wraps segments of the loss function in `jax.checkpoint`
    (rematerialization) — XLA re-runs the forward inside backward instead of
    storing activations, the same FLOPs-for-HBM trade the reference's
    checkpoint segmentation does.
    """

    def __init__(self, inner: Optimizer, policy=None):
        self.inner = inner
        self.policy = policy  # jax.checkpoint_policies.* or None

    def init(self, params):
        return self.inner.init(params)

    def apply_gradients(self, params, grads, state):
        return self.inner.apply_gradients(params, grads, state)

    def wrap(self, fn):
        if self.policy is not None:
            return jax.checkpoint(fn, policy=self.policy)
        return jax.checkpoint(fn)

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        # delegate to inner.minimize so grad-computation wrappers compose:
        # Recompute(amp(...)) checkpoints the loss the amp path differentiates
        return self.inner.minimize(self.wrap(loss_fn), params, state,
                                   *args, **kwargs)


class DGCMomentum(Momentum):
    """Deep-gradient-compression momentum (ref: optimizer.py:870
    DGCMomentumOptimizer + operators/dgc_op.cc + sparse_all_reduce).

    Single-process semantics: top-k sparsify the gradient with local
    accumulation of the residual (momentum correction per DGC paper); the
    distributed compressed-allreduce lives in parallel/dgc.py.
    """

    def __init__(self, learning_rate=0.01, momentum=0.9,
                 rampup_begin_step=0, sparsity=0.999, **kw):
        super().__init__(learning_rate, momentum, **kw)
        self.begin = rampup_begin_step
        self.sparsity = sparsity

    def slots(self, p):
        s = super().slots(p)
        s["residual"] = jnp.zeros_like(p)
        return s

    def _update_leaf(self, g, p, s, lr, step):
        from paddle_tpu.parallel.dgc import topk_sparsify
        g = g.astype(p.dtype)
        acc = s["residual"] + g
        use_dgc = step >= self.begin
        sparse_g, residual = topk_sparsify(acc, self.sparsity)
        g_eff = jnp.where(use_dgc, sparse_g, g)
        new_res = jnp.where(use_dgc, residual, s["residual"])
        new_p, ms = super()._update_leaf(g_eff, p,
                                        {"velocity": s["velocity"]}, lr, step)
        return new_p, {"velocity": ms["velocity"], "residual": new_res}
