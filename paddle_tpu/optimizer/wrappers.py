"""Meta-optimizers and averaging wrappers.

Ref: /root/reference/python/paddle/fluid/optimizer.py — ModelAverage:2449,
EMA (ExponentialMovingAverage):2751, RecomputeOptimizer:3278,
LookaheadOptimizer:3571, DGCMomentumOptimizer:870.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.optimizer.optimizers import Momentum, Optimizer


class ExponentialMovingAverage:
    """ref: optimizer.py:2751 — shadow = decay*shadow + (1-decay)*param with
    optional thres_steps debiasing."""

    def __init__(self, decay=0.999):
        self.decay = decay

    def init(self, params):
        return {"shadow": jax.tree_util.tree_map(jnp.copy, params),
                "step": jnp.zeros((), jnp.int32)}

    def update(self, ema_state, params):
        step = ema_state["step"] + 1
        d = jnp.minimum(self.decay, (1.0 + step) / (10.0 + step))
        shadow = jax.tree_util.tree_map(
            lambda s, p: d * s + (1 - d) * p, ema_state["shadow"], params)
        return {"shadow": shadow, "step": step}

    def apply(self, ema_state):
        """Returns averaged params for eval (ref: EMA.apply context)."""
        return ema_state["shadow"]


class ModelAverage:
    """ref: optimizer.py:2449 — running accumulation of params over a window;
    apply() yields sum/num for eval."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000):
        self.max_window = max_average_window

    def init(self, params):
        return {"sum": jax.tree_util.tree_map(jnp.zeros_like, params),
                "num": jnp.zeros((), jnp.float32)}

    def update(self, st, params):
        num = st["num"] + 1
        s = jax.tree_util.tree_map(lambda a, p: a + p, st["sum"], params)
        # restart window when exceeding max (simplified restart policy)
        reset = num > self.max_window
        num = jnp.where(reset, 1.0, num)
        s = jax.tree_util.tree_map(
            lambda a, p: jnp.where(reset, p, a), s, params)
        return {"sum": s, "num": num}

    def apply(self, st):
        return jax.tree_util.tree_map(lambda a: a / st["num"], st["sum"])


class Lookahead:
    """ref: optimizer.py LookaheadOptimizer:3571 — slow/fast weights."""

    def __init__(self, inner: Optimizer, alpha=0.5, k=5):
        self.inner = inner
        self.alpha, self.k = alpha, k

    def init(self, params):
        return {"inner": self.inner.init(params),
                "slow": jax.tree_util.tree_map(jnp.copy, params),
                "step": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, params, grads, state):
        params, inner_state = self.inner.apply_gradients(
            params, grads, state["inner"])
        step = state["step"] + 1
        sync = (step % self.k) == 0
        slow = jax.tree_util.tree_map(
            lambda s, f: jnp.where(sync, s + self.alpha * (f - s), s),
            state["slow"], params)
        params = jax.tree_util.tree_map(
            lambda s, f: jnp.where(sync, s, f), slow, params)
        return params, {"inner": inner_state, "slow": slow, "step": step}

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, *args, **kwargs)
        params, state = self.apply_gradients(params, grads, state)
        return loss, params, state, aux


class RecomputeOptimizer:
    """Activation recomputation (ref: optimizer.py:3278 +
    backward.py:576 _append_backward_ops_with_checkpoints_).

    TPU-native: wraps segments of the loss function in `jax.checkpoint`
    (rematerialization) — XLA re-runs the forward inside backward instead of
    storing activations, the same FLOPs-for-HBM trade the reference's
    checkpoint segmentation does.
    """

    def __init__(self, inner: Optimizer, policy=None):
        self.inner = inner
        self.policy = policy  # jax.checkpoint_policies.* or None

    def init(self, params):
        return self.inner.init(params)

    def apply_gradients(self, params, grads, state):
        return self.inner.apply_gradients(params, grads, state)

    def wrap(self, fn):
        if self.policy is not None:
            return jax.checkpoint(fn, policy=self.policy)
        return jax.checkpoint(fn)

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        # delegate to inner.minimize so grad-computation wrappers compose:
        # Recompute(amp(...)) checkpoints the loss the amp path differentiates
        return self.inner.minimize(self.wrap(loss_fn), params, state,
                                   *args, **kwargs)


class DGCMomentum(Momentum):
    """Deep-gradient-compression momentum (ref: optimizer.py:870
    DGCMomentumOptimizer + operators/dgc_op.cc + sparse_all_reduce).

    Single-process semantics: top-k sparsify the gradient with local
    accumulation of the residual (momentum correction per DGC paper); the
    distributed compressed-allreduce lives in parallel/dgc.py.
    """

    def __init__(self, learning_rate=0.01, momentum=0.9,
                 rampup_begin_step=0, sparsity=0.999, **kw):
        super().__init__(learning_rate, momentum, **kw)
        self.begin = rampup_begin_step
        self.sparsity = sparsity

    def slots(self, p):
        s = super().slots(p)
        s["residual"] = jnp.zeros_like(p)
        return s

    def _update_leaf(self, g, p, s, lr, step):
        from paddle_tpu.parallel.dgc import topk_sparsify
        g = g.astype(p.dtype)
        acc = s["residual"] + g
        use_dgc = step >= self.begin
        sparse_g, residual = topk_sparsify(acc, self.sparsity)
        g_eff = jnp.where(use_dgc, sparse_g, g)
        new_res = jnp.where(use_dgc, residual, s["residual"])
        new_p, ms = super()._update_leaf(g_eff, p,
                                        {"velocity": s["velocity"]}, lr, step)
        return new_p, {"velocity": ms["velocity"], "residual": new_res}
