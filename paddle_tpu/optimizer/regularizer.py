"""Weight regularization.

Ref: /root/reference/python/paddle/fluid/regularizer.py — L1DecayRegularizer,
L2DecayRegularizer (276 LoC). Applied as a gradient transform
(grad += coeff * sign(w) or coeff * w) before the optimizer update, matching
the reference's append_regularization_ops.
"""

import jax
import jax.numpy as jnp


class L2Decay:
    def __init__(self, coeff):
        self.coeff = coeff

    def __call__(self, grads, params):
        return jax.tree_util.tree_map(
            lambda g, p: g + self.coeff * p, grads, params)


class L1Decay:
    def __init__(self, coeff):
        self.coeff = coeff

    def __call__(self, grads, params):
        return jax.tree_util.tree_map(
            lambda g, p: g + self.coeff * jnp.sign(p), grads, params)
