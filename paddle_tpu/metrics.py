"""Streaming metrics.

Ref: /root/reference/python/paddle/fluid/metrics.py (1k LoC: MetricBase,
Accuracy, Auc, Precision, Recall, EditDistance, ChunkEvaluator,
CompositeMetric). Host-side accumulators over per-batch op results
(ops/metrics_ops.py computes the device-side pieces).
"""

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    """ref: metrics.py Accuracy — weighted running mean of batch accuracy."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        return self.value / max(self.weight, 1e-12)


class Precision(MetricBase):
    """ref: metrics.py Precision (binary)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += float(np.sum((preds == 1) & (labels == 1)))
        self.fp += float(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        return self.tp / max(self.tp + self.fp, 1e-12)


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += float(np.sum((preds == 1) & (labels == 1)))
        self.fn += float(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        return self.tp / max(self.tp + self.fn, 1e-12)


class Auc(MetricBase):
    """ref: metrics.py Auc — threshold-bucket accumulation across batches."""

    def __init__(self, num_thresholds=4096, name=None):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self.pos = np.zeros(self.num_thresholds)
        self.neg = np.zeros(self.num_thresholds)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim > 1 and preds.shape[-1] == 2:
            preds = preds[..., 1]
        preds = preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        bucket = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                         self.num_thresholds - 1)
        np.add.at(self.pos, bucket, labels == 1)
        np.add.at(self.neg, bucket, labels == 0)

    def eval(self):
        pos_c = np.cumsum(self.pos[::-1])
        neg_c = np.cumsum(self.neg[::-1])
        tot_pos, tot_neg = pos_c[-1], neg_c[-1]
        pos_prev = np.concatenate([[0], pos_c[:-1]])
        neg_prev = np.concatenate([[0], neg_c[:-1]])
        area = np.sum((neg_c - neg_prev) * (pos_c + pos_prev) / 2.0)
        return float(area / max(tot_pos * tot_neg, 1e-12))


class EditDistance(MetricBase):
    """ref: metrics.py EditDistance + operators/edit_distance_op.cc."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    @staticmethod
    def _levenshtein(a, b):
        m, n = len(a), len(b)
        dp = np.arange(n + 1, dtype=np.float64)
        for i in range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, n + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (a[i - 1] != b[j - 1]))
        return dp[n]

    def update(self, hyps, refs, normalized=True):
        for h, r in zip(hyps, refs):
            d = self._levenshtein(list(h), list(r))
            if normalized:
                d = d / max(len(r), 1)
            self.total += d
            self.count += 1

    def eval(self):
        return self.total / max(self.count, 1)


class ChunkEvaluator(MetricBase):
    """ref: metrics.py ChunkEvaluator — F1 over detected chunks."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer = 0.0
        self.num_label = 0.0
        self.num_correct = 0.0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer += float(num_infer_chunks)
        self.num_label += float(num_label_chunks)
        self.num_correct += float(num_correct_chunks)

    def eval(self):
        precision = self.num_correct / max(self.num_infer, 1e-12)
        recall = self.num_correct / max(self.num_label, 1e-12)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        return precision, recall, f1


class CompositeMetric(MetricBase):
    """ref: metrics.py CompositeMetric"""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args, **kwargs):
        for m in self._metrics:
            m.update(*args, **kwargs)

    def eval(self):
        return [m.eval() for m in self._metrics]


class DetectionMAP(MetricBase):
    """Detection mean average precision (ref metrics.py:805 DetectionMAP +
    operators/detection/detection_map_op.cc).

    Host-side accumulator over per-image results:
        update(detections, gt_labels, gt_boxes, gt_difficult=None)
    detections: [M, 6] rows of (class_label, score, xmin, ymin, xmax, ymax)
    gt_labels:  [N] int class per ground-truth box
    gt_boxes:   [N, 4] (xmin, ymin, xmax, ymax)
    eval() -> mAP over classes with ground truth, via '11point' or
    'integral' AP (the reference's two ap_version modes).
    """

    def __init__(self, class_num, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral",
                 background_label=0, name=None):
        super().__init__(name)
        if ap_version not in ("integral", "11point"):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self.class_num = class_num
        self.thresh = overlap_threshold
        self.eval_difficult = evaluate_difficult
        self.ap_version = ap_version
        self.background = background_label
        self.reset()

    def reset(self):
        # per class: list of (score, is_tp) + count of (non-difficult) GTs
        self._scored = {c: [] for c in range(self.class_num)}
        self._npos = np.zeros(self.class_num, np.int64)

    @staticmethod
    def _iou(box, boxes):
        x1 = np.maximum(box[0], boxes[:, 0])
        y1 = np.maximum(box[1], boxes[:, 1])
        x2 = np.minimum(box[2], boxes[:, 2])
        y2 = np.minimum(box[3], boxes[:, 3])
        inter = np.maximum(x2 - x1, 0) * np.maximum(y2 - y1, 0)
        a = (box[2] - box[0]) * (box[3] - box[1])
        b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        return inter / np.maximum(a + b - inter, 1e-12)

    def update(self, detections, gt_labels, gt_boxes, gt_difficult=None):
        det = np.asarray(detections, np.float64).reshape(-1, 6)
        gl = np.asarray(gt_labels).reshape(-1).astype(int)
        gb = np.asarray(gt_boxes, np.float64).reshape(-1, 4)
        diff = (np.zeros(len(gl), bool) if gt_difficult is None
                else np.asarray(gt_difficult).reshape(-1).astype(bool))
        for c in np.unique(gl):
            # out-of-range labels (e.g. -1 padding) are not ground truth
            if c == self.background or c < 0 or c >= self.class_num:
                continue
            sel = gl == c
            self._npos[c] += int((~diff[sel]).sum()) if not \
                self.eval_difficult else int(sel.sum())
        for c in range(self.class_num):
            if c == self.background:
                continue
            dc = det[det[:, 0] == c]
            gsel = gl == c
            gboxes = gb[gsel]
            gdiff = diff[gsel]
            taken = np.zeros(len(gboxes), bool)
            # match high-score first (detection_map_op.cc sorts by score)
            for row in dc[np.argsort(-dc[:, 1])]:
                score, box = row[1], row[2:6]
                if len(gboxes) == 0:
                    self._scored[c].append((score, False))
                    continue
                ious = self._iou(box, gboxes)
                j = int(np.argmax(ious))
                # strict >, matching detection_map_op.cc:395
                if ious[j] > self.thresh:
                    if not self.eval_difficult and gdiff[j]:
                        continue  # difficult GT ignored entirely
                    if not taken[j]:
                        taken[j] = True
                        self._scored[c].append((score, True))
                    else:
                        self._scored[c].append((score, False))
                else:
                    self._scored[c].append((score, False))

    def _ap(self, scored, npos):
        # reference CalcMAP averages only classes that have BOTH ground
        # truth and detections (detection_map_op.h: labels absent from the
        # true-positive map are skipped, count not incremented)
        if npos == 0 or not scored:
            return None
        arr = sorted(scored, key=lambda t: -t[0])
        tp = np.cumsum([1 if t else 0 for _, t in arr])
        fp = np.cumsum([0 if t else 1 for _, t in arr])
        recall = tp / npos
        precision = tp / np.maximum(tp + fp, 1)
        if self.ap_version == "11point":
            ap = 0.0
            for r in np.linspace(0, 1, 11):
                p = precision[recall >= r]
                ap += (p.max() if len(p) else 0.0) / 11.0
            return float(ap)
        # integral: sum precision at each true-positive hit / npos
        ap = 0.0
        for p, (_, is_tp) in zip(precision, arr):
            if is_tp:
                ap += p
        return float(ap / npos)

    def eval(self):
        aps = [self._ap(self._scored[c], self._npos[c])
               for c in range(self.class_num) if c != self.background]
        aps = [a for a in aps if a is not None]
        return float(np.mean(aps)) if aps else 0.0


def ctr_metric_bundle(pred, label):
    """ref contrib/layers/metric_op.py ctr_metric_bundle — per-batch local
    sums for CTR metrics; the caller accumulates (and psum-reduces under
    dp) then finishes: MAE = abserr/n, RMSE = sqrt(sqrerr/n),
    predicted_ctr = prob/n, q = q_sum/n.

    pred: [N, 1] probabilities; label: [N, 1] 0/1.
    Returns dict(sqrerr, abserr, prob, q, pos_num, ins_num) scalars —
    functional redesign of the reference's persistable accumulator vars
    (carry the dict in train state and add per step)."""
    import jax
    import jax.numpy as jnp
    pred = pred.reshape(-1).astype(jnp.float32)
    label = label.reshape(-1).astype(jnp.float32)
    err = pred - label
    return {
        "sqrerr": jnp.sum(err * err),
        "abserr": jnp.sum(jnp.abs(err)),
        "prob": jnp.sum(pred),
        # the reference's local_q re-applies sigmoid to its input even
        # when it is already a probability — keep that exact contract
        "q": jnp.sum(jax.nn.sigmoid(pred)),
        "pos_num": jnp.sum(label),
        "ins_num": jnp.asarray(float(pred.shape[0])),
    }
