"""Streaming metrics.

Ref: /root/reference/python/paddle/fluid/metrics.py (1k LoC: MetricBase,
Accuracy, Auc, Precision, Recall, EditDistance, ChunkEvaluator,
CompositeMetric). Host-side accumulators over per-batch op results
(ops/metrics_ops.py computes the device-side pieces).
"""

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    """ref: metrics.py Accuracy — weighted running mean of batch accuracy."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        return self.value / max(self.weight, 1e-12)


class Precision(MetricBase):
    """ref: metrics.py Precision (binary)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fp = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += float(np.sum((preds == 1) & (labels == 1)))
        self.fp += float(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        return self.tp / max(self.tp + self.fp, 1e-12)


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0.0
        self.fn = 0.0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).astype(np.int64).reshape(-1)
        self.tp += float(np.sum((preds == 1) & (labels == 1)))
        self.fn += float(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        return self.tp / max(self.tp + self.fn, 1e-12)


class Auc(MetricBase):
    """ref: metrics.py Auc — threshold-bucket accumulation across batches."""

    def __init__(self, num_thresholds=4096, name=None):
        super().__init__(name)
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self.pos = np.zeros(self.num_thresholds)
        self.neg = np.zeros(self.num_thresholds)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim > 1 and preds.shape[-1] == 2:
            preds = preds[..., 1]
        preds = preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        bucket = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                         self.num_thresholds - 1)
        np.add.at(self.pos, bucket, labels == 1)
        np.add.at(self.neg, bucket, labels == 0)

    def eval(self):
        pos_c = np.cumsum(self.pos[::-1])
        neg_c = np.cumsum(self.neg[::-1])
        tot_pos, tot_neg = pos_c[-1], neg_c[-1]
        pos_prev = np.concatenate([[0], pos_c[:-1]])
        neg_prev = np.concatenate([[0], neg_c[:-1]])
        area = np.sum((neg_c - neg_prev) * (pos_c + pos_prev) / 2.0)
        return float(area / max(tot_pos * tot_neg, 1e-12))


class EditDistance(MetricBase):
    """ref: metrics.py EditDistance + operators/edit_distance_op.cc."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total = 0.0
        self.count = 0

    @staticmethod
    def _levenshtein(a, b):
        m, n = len(a), len(b)
        dp = np.arange(n + 1, dtype=np.float64)
        for i in range(1, m + 1):
            prev = dp.copy()
            dp[0] = i
            for j in range(1, n + 1):
                dp[j] = min(prev[j] + 1, dp[j - 1] + 1,
                            prev[j - 1] + (a[i - 1] != b[j - 1]))
        return dp[n]

    def update(self, hyps, refs, normalized=True):
        for h, r in zip(hyps, refs):
            d = self._levenshtein(list(h), list(r))
            if normalized:
                d = d / max(len(r), 1)
            self.total += d
            self.count += 1

    def eval(self):
        return self.total / max(self.count, 1)


class ChunkEvaluator(MetricBase):
    """ref: metrics.py ChunkEvaluator — F1 over detected chunks."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer = 0.0
        self.num_label = 0.0
        self.num_correct = 0.0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer += float(num_infer_chunks)
        self.num_label += float(num_label_chunks)
        self.num_correct += float(num_correct_chunks)

    def eval(self):
        precision = self.num_correct / max(self.num_infer, 1e-12)
        recall = self.num_correct / max(self.num_label, 1e-12)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        return precision, recall, f1


class CompositeMetric(MetricBase):
    """ref: metrics.py CompositeMetric"""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args, **kwargs):
        for m in self._metrics:
            m.update(*args, **kwargs)

    def eval(self):
        return [m.eval() for m in self._metrics]
