"""Probability distributions (ref: python/paddle/fluid/layers/
distributions.py — Uniform, Normal, Categorical, MultivariateNormalDiag
with sample / log_prob / entropy / kl_divergence).

TPU-first: sampling takes an explicit PRNG key (counter-based TPU RNG)
instead of the reference's graph-level seed attr; everything else is the
same math, jit-compatible.
"""

import math

import jax
import jax.numpy as jnp


class Distribution:
    def sample(self, key, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """ref distributions.py:113 — U[low, high)."""

    def __init__(self, low, high):
        self.low = jnp.asarray(low, jnp.float32)
        self.high = jnp.asarray(high, jnp.float32)

    def sample(self, key, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.low.shape,
                                                    self.high.shape)
        u = jax.random.uniform(key, shape)
        return self.low + u * (self.high - self.low)

    def log_prob(self, value):
        inside = (value >= self.low) & (value < self.high)
        lp = -jnp.log(self.high - self.low)
        return jnp.where(inside, lp, -jnp.inf)

    def entropy(self):
        return jnp.log(self.high - self.low)


class Normal(Distribution):
    """ref distributions.py:247."""

    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)

    def sample(self, key, shape=()):
        shape = tuple(shape) + jnp.broadcast_shapes(self.loc.shape,
                                                    self.scale.shape)
        return self.loc + self.scale * jax.random.normal(key, shape)

    def log_prob(self, value):
        var = self.scale ** 2
        return (-((value - self.loc) ** 2) / (2 * var)
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)

    def kl_divergence(self, other):
        """ref distributions.py:382 — KL(self || other), both Normal."""
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))


class Categorical(Distribution):
    """ref distributions.py:400 — over unnormalized logits."""

    def __init__(self, logits):
        self.logits = jnp.asarray(logits, jnp.float32)

    def _log_probs(self):
        return self.logits - jax.nn.logsumexp(self.logits, -1,
                                              keepdims=True)

    def sample(self, key, shape=()):
        return jax.random.categorical(key, self.logits, -1,
                                      shape=tuple(shape)
                                      + self.logits.shape[:-1])

    def log_prob(self, value):
        lp = self._log_probs()
        return jnp.take_along_axis(
            lp, jnp.asarray(value)[..., None].astype(jnp.int32),
            -1)[..., 0]

    def entropy(self):
        lp = self._log_probs()
        return -jnp.sum(jnp.exp(lp) * lp, -1)

    def kl_divergence(self, other):
        lp = self._log_probs()
        lq = other._log_probs()
        return jnp.sum(jnp.exp(lp) * (lp - lq), -1)


class MultivariateNormalDiag(Distribution):
    """ref distributions.py:493 — diagonal-covariance Gaussian; `scale` is
    the diagonal of the covariance-scale (stddev) per dimension."""

    def __init__(self, loc, scale):
        self.loc = jnp.asarray(loc, jnp.float32)
        self.scale = jnp.asarray(scale, jnp.float32)   # [..., D] stddevs

    def sample(self, key, shape=()):
        shape = tuple(shape) + self.loc.shape
        return self.loc + self.scale * jax.random.normal(key, shape)

    def log_prob(self, value):
        d = self.loc.shape[-1]
        z = (value - self.loc) / self.scale
        return (-0.5 * jnp.sum(z * z, -1)
                - jnp.sum(jnp.log(self.scale), -1)
                - 0.5 * d * math.log(2 * math.pi))

    def entropy(self):
        d = self.loc.shape[-1]
        return (0.5 * d * (1.0 + math.log(2 * math.pi))
                + jnp.sum(jnp.log(self.scale), -1))

    def kl_divergence(self, other):
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return 0.5 * jnp.sum(var_ratio + t1 - 1.0 - jnp.log(var_ratio), -1)
