"""Declarative compile-contract engine.

tools/compile_smoke.py used to hold one ad-hoc regex per model for its
HLO assertions (``vocab_temporaries`` / ``weight_all_gathers`` /
``dense_score_temporaries``). This module promotes those checks to
first-class contract objects evaluated against a
:class:`ContractContext` (compiled HLO text, jaxpr text, runtime trace
counts), plus the single per-model table :data:`CONTRACTS` covering the
fused+sharded train steps (gpt / bert / transformer_big) and the
serving prefill/decode steps. compile_smoke stays the thing that
*compiles*; this module is the thing that *judges* — and the planted-
violation fixtures in tests/test_lint.py prove each judge actually
fires.

Two judge families live here beyond the structural HLO checks:

* **budget contracts** (:class:`MaxHloFlops` / :class:`MaxHloBytes`) —
  the compiled module's XLA ``cost_analysis()`` figures may not exceed
  what the autoplan cost model predicted times a calibrated tolerance.
  No hand-written byte constants: retuning the cost model retunes the
  budget.
* **snapshot gates** (:class:`HloSnapshot`, :data:`CONTRACT_SNAPSHOTS`)
  — the normalized opcode histogram of the compiled module must match
  the blessed record under tests/fixtures/hlo_snapshots/; structural
  drift fails until re-blessed with
  ``tools/graft_lint.py --contracts --update-snapshots``.

Stdlib-only: contracts see text and cost dicts, never jax objects, so
the table is importable by the lint CLI without paying the jax import
(the cost model and topology table it prices budgets with are loaded by
file path and are themselves stdlib-only).
"""

import dataclasses
import hashlib
import importlib.util
import json
import math
import os
import re

# every HLO dtype token we may meet in shapes, with its bit width
DTYPE_BITS = {
    "pred": 1, "s2": 2, "s4": 4, "s8": 8, "s16": 16, "s32": 32,
    "s64": 64, "u2": 2, "u4": 4, "u8": 8, "u16": 16, "u32": 32,
    "u64": 64, "f8e4m3fn": 8, "f8e5m2": 8, "f8e4m3b11fnuz": 8,
    "f8e4m3fnuz": 8, "f8e5m2fnuz": 8, "f16": 16, "bf16": 16, "f32": 32,
    "f64": 64, "c64": 64, "c128": 128,
}

_SHAPE_RE = re.compile(
    r"\b(" + "|".join(sorted(DTYPE_BITS, key=len, reverse=True))
    + r")\[([0-9,]*)\]")


def hlo_shapes(text, dtypes=("f32", "bf16")):
    """All (dtype, shape-tuple) pairs in an HLO module's text, filtered
    to ``dtypes`` (None = all)."""
    out = []
    for m in _SHAPE_RE.finditer(text):
        if dtypes is not None and m.group(1) not in dtypes:
            continue
        dims = m.group(2)
        shp = tuple(int(d) for d in dims.split(",")) if dims else ()
        out.append((m.group(1), shp))
    return out


@dataclasses.dataclass
class Violation:
    contract: str
    message: str

    def format(self):
        return f"[{self.contract}] {self.message}"


@dataclasses.dataclass
class ContractContext:
    """What a compile produced, as text: per-device compiled HLO
    (``.compile().as_text()``), lowered/jaxpr text when the caller has
    it, runtime trace counts for the TracedOnce contract, and the
    normalized ``cost_analysis()`` dict for the budget contracts."""
    hlo_text: str = None
    jaxpr_text: str = None
    trace_counts: dict = None
    cost: dict = None


def normalize_cost(raw):
    """``compiled.cost_analysis()`` returns a dict on some jax versions
    and a per-device list of dicts on others; flatten to one
    {metric: float} dict (None when there is nothing to judge)."""
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not raw:
        return None
    return {str(k): float(v) for k, v in raw.items()}


class Contract:
    """One statically-checkable compile invariant. ``check`` returns
    violation messages (empty = the contract holds)."""

    name = None

    def check(self, ctx):
        raise NotImplementedError

    def violations(self, ctx):
        return [Violation(self.name, m) for m in self.check(ctx)]


class NoTemporary(Contract):
    """No f32/bf16 temporary carrying any dim in ``dims`` next to >=
    ``min_rows`` row elements — i.e. no materialized [rows, dim]-scale
    tensor in the per-device module. ``min_rows`` is chosen ABOVE the
    model width so a [dim, hidden] weight shard (a legitimate resident
    on that axis) never trips it."""

    def __init__(self, dims, min_rows, dtypes=("f32", "bf16"),
                 what="temporary"):
        self.dims = frozenset(int(d) for d in dims)
        self.min_rows = int(min_rows)
        self.dtypes = tuple(dtypes)
        self.what = what
        self.name = f"no-temporary({sorted(self.dims)}, rows>={min_rows})"

    def temporaries(self, hlo_text):
        """The offending shapes, sorted — compile_smoke reports these."""
        hits = set()
        for _, shp in hlo_shapes(hlo_text, self.dtypes):
            for d in shp:
                if d in self.dims and d and math.prod(shp) // d >= self.min_rows:
                    hits.add(shp)
        return sorted(hits)

    def check(self, ctx):
        if ctx.hlo_text is None:
            return []
        return [f"{self.what} {shp} materialized in the compiled module"
                for shp in self.temporaries(ctx.hlo_text)]


class NoKvDequantTemporary(Contract):
    """int8-paged-KV serve contract: no wide-float tensor at paged-KV
    layout scale in the compiled module. The page pools are laid out
    [..., page_size, head_dim]; with serve_kv_dtype=int8 the only
    f32 KV values allowed are the kernel's per-page dequant tiles, so
    any f32/bf16 tensor that (a) ends in head_dim, (b) carries
    page_size on an earlier axis, and (c) holds >= ``min_rows`` x
    (page_size x head_dim) elements is a dequantized pool or
    pool-gather materialized outside the kernel — the exact temporary
    int8 storage exists to avoid. ``min_rows`` sits above the kernel's
    per-tile dequant (block_h rows) and below the smallest whole-pool
    dequant, so the f32-pool engine is the positive control that trips
    it."""

    def __init__(self, page_size, head_dim, min_rows,
                 dtypes=("f32", "bf16")):
        self.page_size = int(page_size)
        self.head_dim = int(head_dim)
        self.min_rows = int(min_rows)
        self.dtypes = tuple(dtypes)
        self.name = (f"no-kv-dequant-temporary([...,{page_size},"
                     f"{head_dim}], rows>={min_rows})")

    def temporaries(self, hlo_text):
        hits = set()
        tile = self.page_size * self.head_dim
        for _, shp in hlo_shapes(hlo_text, self.dtypes):
            if (len(shp) >= 3 and shp[-1] == self.head_dim
                    and self.page_size in shp[:-1]
                    and math.prod(shp) // tile >= self.min_rows):
                hits.add(shp)
        return sorted(hits)

    def check(self, ctx):
        if ctx.hlo_text is None:
            return []
        return [f"f32 KV temporary {shp} at page-pool scale in the "
                "compiled int8 serve step — dequantization escaped the "
                "kernel's per-page tiles"
                for shp in self.temporaries(ctx.hlo_text)]


class NoOpMatching(Contract):
    """No HLO instruction line matching ``pattern`` — optionally only
    lines where some bracketed shape satisfies ``shape_test`` (e.g.
    all-gathers at vocab-weight scale, not the benign small ones)."""

    _BRACKET_RE = re.compile(r"\[([0-9,]+)\]")

    def __init__(self, pattern, shape_test=None, what=None):
        self.pattern = re.compile(pattern)
        self.shape_test = shape_test
        self.what = what or f"op matching /{pattern}/"
        self.name = f"no-op-matching({pattern})"

    def matches(self, hlo_text):
        hits = []
        for line in hlo_text.splitlines():
            if not self.pattern.search(line):
                continue
            if self.shape_test is not None:
                ok = False
                for m in self._BRACKET_RE.finditer(line):
                    shp = tuple(int(d) for d in m.group(1).split(","))
                    if self.shape_test(shp):
                        ok = True
                        break
                if not ok:
                    continue
            hits.append(line.strip()[:160])
        return hits

    def check(self, ctx):
        if ctx.hlo_text is None:
            return []
        return [f"{self.what}: {line}" for line in self.matches(ctx.hlo_text)]


class TracedOnce(Contract):
    """Every tracked function was traced exactly once across the run —
    the continuous-batching shapes are slot-fixed; a retrace means a
    shape or dtype leaked into the traced signature."""

    name = "traced-once"

    def __init__(self, fns=None):
        self.fns = tuple(fns) if fns is not None else None

    def check(self, ctx):
        counts = ctx.trace_counts or {}
        out = []
        names = self.fns if self.fns is not None else sorted(counts)
        for fn in names:
            n = counts.get(fn)
            if n is None:
                out.append(f"{fn}: no trace count recorded")
            elif n != 1:
                out.append(f"{fn}: traced {n}x (expected exactly once)")
        return out


class DonationRespected(Contract):
    """The compiled module aliases >= ``min_aliases`` inputs to outputs
    (``input_output_alias={ {0}: (1, {}, may-alias) ... }`` in the
    module header) — donated buffers (KV pools, optimizer state) really
    were reused rather than silently copied."""

    _ENTRY_RE = re.compile(r"\{[0-9,\s]*\}:\s*\(")

    def __init__(self, min_aliases=1):
        self.min_aliases = int(min_aliases)
        self.name = f"donation-respected(>={min_aliases})"

    def check(self, ctx):
        if ctx.hlo_text is None:
            return []
        m = re.search(r"input_output_alias=\{(.*)", ctx.hlo_text)
        n = len(self._ENTRY_RE.findall(m.group(1))) if m else 0
        if n < self.min_aliases:
            return [f"only {n} input->output aliases in the compiled "
                    f"module (expected >= {self.min_aliases}) — a "
                    "donated buffer is being copied"]
        return []


class NoHostCallback(Contract):
    """No host round-trip inside the compiled step: no infeed/outfeed
    and no callback custom-call in the HLO; no pure_callback /
    io_callback / debug_callback primitive in the jaxpr (a stray
    jax.debug.print in a hot kernel shows up here)."""

    name = "no-host-callback"

    _HLO_PATTERNS = (re.compile(r"\binfeed\b"), re.compile(r"\boutfeed\b"),
                     re.compile(r"custom-call[^\n]*callback"))
    _JAXPR_RE = re.compile(
        r"\b(pure_callback|io_callback|debug_callback)\b")

    def check(self, ctx):
        out = []
        if ctx.hlo_text is not None:
            for pat in self._HLO_PATTERNS:
                for line in ctx.hlo_text.splitlines():
                    if pat.search(line):
                        out.append(f"host callback in HLO: "
                                   f"{line.strip()[:160]}")
        if ctx.jaxpr_text is not None:
            for m in self._JAXPR_RE.finditer(ctx.jaxpr_text):
                out.append(f"{m.group(1)} primitive in the jaxpr — host "
                           "round-trip inside the staged step")
        return out


class MaxDtypeWidth(Contract):
    """No float/complex tensor wider than ``max_bits`` in the compiled
    module (f64 creeping into a TPU step means an accidental float64
    promotion — x64 math runs at a fraction of MXU rate). Integer types
    are allowlisted by default: RNG and iota legitimately use u64/s64
    counters."""

    def __init__(self, max_bits=32, allow=("s64", "u64", "c64")):
        self.max_bits = int(max_bits)
        self.allow = frozenset(allow)
        self.name = f"max-dtype-width({max_bits})"

    def offending(self, text):
        seen = {}
        for dt, shp in hlo_shapes(text, dtypes=None):
            if dt in self.allow or DTYPE_BITS[dt] <= self.max_bits:
                continue
            seen.setdefault(dt, shp)
        return seen

    def check(self, ctx):
        out = []
        for text in (ctx.hlo_text, ctx.jaxpr_text):
            if text is None:
                continue
            for dt, shp in sorted(self.offending(text).items()):
                out.append(f"{dt} tensor (e.g. {dt}{list(shp)}) exceeds "
                           f"{self.max_bits}-bit width — accidental "
                           "wide-precision promotion")
        return out


class MaxHloCost(Contract):
    """Budget contract: one XLA ``cost_analysis()`` metric of the
    compiled module may not exceed ``predicted * tolerance``, where
    ``predicted`` comes from the autoplan cost model (never a
    hand-written constant). Holds vacuously when the context carries no
    cost dict — text-only evaluations judge the structural contracts
    only."""

    metric = None   # short label ("flops" / "bytes")
    key = None      # cost_analysis dict key

    def __init__(self, predicted, tolerance, source=""):
        self.predicted = float(predicted)
        self.tolerance = float(tolerance)
        self.budget = self.predicted * self.tolerance
        self.source = source
        self.name = f"max-hlo-{self.metric}(<={self.budget:.4g})"

    def with_tolerance(self, tolerance):
        """Clone at a different tolerance — ``with_tolerance(0)`` is the
        positive control proving the detector trips on any real
        compile."""
        return type(self)(self.predicted, tolerance, source=self.source)

    def check(self, ctx):
        if ctx.cost is None:
            return []
        actual = ctx.cost.get(self.key)
        if actual is None:
            return [f"cost analysis carries no {self.key!r} metric — "
                    "cannot judge the budget"]
        if actual > self.budget:
            return [f"compiled {self.metric} {actual:.4g} exceeds budget "
                    f"{self.budget:.4g} (= {self.predicted:.4g} predicted"
                    f" by {self.source or 'the cost model'} x "
                    f"{self.tolerance:g} tolerance)"]
        return []


class MaxHloFlops(MaxHloCost):
    metric = "flops"
    key = "flops"


class MaxHloBytes(MaxHloCost):
    metric = "bytes"
    key = "bytes accessed"


# --- differential snapshot gate --------------------------------------

# one HLO instruction: "%name = <types> opcode(operands), ..." — the
# opcode is the first bare lowercase token followed by '(' after the '='
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*[^=]*?([a-z][a-z0-9\-]*)\(")


def hlo_op_histogram(text):
    """Opcode -> count over every instruction in an HLO module's text.
    Instruction *names* and shapes are ignored, so the histogram is
    stable across recompiles; a pass-pipeline or fusion-decision change
    shows up as a count shift."""
    ops = {}
    for line in text.splitlines():
        m = _HLO_INSTR_RE.match(line)
        if m:
            ops[m.group(1)] = ops.get(m.group(1), 0) + 1
    return ops


def _ops_hash(ops):
    blob = json.dumps(sorted(ops.items())).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


_SNAPSHOT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "tests", "fixtures", "hlo_snapshots")


class HloSnapshot(Contract):
    """Differential gate: the opcode histogram of the compiled module
    must hash-match the blessed record for ``key``. Unexplained drift
    (a new op, a vanished op, a count shift) is a violation until the
    change is re-blessed with
    ``tools/graft_lint.py --contracts --update-snapshots``."""

    def __init__(self, key, snapshot_dir=None):
        self.key = key
        self.snapshot_dir = snapshot_dir or _SNAPSHOT_DIR
        self.name = f"hlo-snapshot({key})"

    @property
    def path(self):
        fname = re.sub(r"[^\w.@,-]", "_", self.key) + ".json"
        return os.path.join(self.snapshot_dir, fname)

    def load(self):
        try:
            with open(self.path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def bless(self, hlo_text):
        ops = hlo_op_histogram(hlo_text)
        rec = {"key": self.key, "hash": _ops_hash(ops),
               "ops": dict(sorted(ops.items()))}
        os.makedirs(self.snapshot_dir, exist_ok=True)
        with open(self.path, "w") as f:
            json.dump(rec, f, indent=1, sort_keys=True)
            f.write("\n")
        return rec

    def check(self, ctx):
        if ctx.hlo_text is None:
            return []
        blessed = self.load()
        if blessed is None:
            return [f"no blessed snapshot at {self.path} — bless one "
                    "with tools/graft_lint.py --contracts "
                    "--update-snapshots"]
        ops = hlo_op_histogram(ctx.hlo_text)
        if _ops_hash(ops) == blessed.get("hash"):
            return []
        old = blessed.get("ops", {})
        added = sorted(set(ops) - set(old))
        removed = sorted(set(old) - set(ops))
        changed = sorted(op for op in set(ops) & set(old)
                         if ops[op] != old[op])
        detail = "; ".join(p for p in (
            added and ("new ops: " + ", ".join(added[:6])),
            removed and ("vanished ops: " + ", ".join(removed[:6])),
            changed and ("count drift: " + ", ".join(
                f"{op} {old[op]}->{ops[op]}" for op in changed[:6])),
        ) if p)
        return ["op histogram drifted from blessed snapshot "
                f"({detail or 'hash mismatch'}) — if the change is "
                "intended, re-bless with --update-snapshots"]


def evaluate(contracts, ctx):
    """Run each contract; return the flat violation list (empty = every
    contract holds)."""
    out = []
    for c in contracts:
        out.extend(c.violations(ctx))
    return out


# --- the per-model contract table ------------------------------------
#
# Sharded train steps: tiny configs with batch/seq picked so no
# legitimate dim collides with {V, V/tp} and the row threshold clears
# the model width with >= 2x margin (xent_chunk=64 keeps even the fused
# path's per-chunk logits tile far below it). The serve step keys on
# the padded slot capacity Tmax=48, every other dim distinct.

@dataclasses.dataclass(frozen=True)
class ShardedCase:
    """Compile shapes for one model's dp x tp contract run. The depth
    fields (layers/heads/intermediate/max_position) are only filled for
    models with priced budget rows — they must mirror the tiny config
    bench.py compiles (a drift-guard test in tests/test_lint.py pins the
    gpt row to GPTConfig.tiny)."""
    batch: int
    seq: int
    vocab: int
    hidden: int
    loss_rows: staticmethod   # (batch, seq) -> rows entering the loss
    layers: int = None
    heads: int = None
    intermediate: int = None
    max_position: int = None

    def min_rows(self, dp=2):
        return self.loss_rows(self.batch, self.seq) // dp // 2


SHARDED_TRAIN_CASES = {
    "gpt": ShardedCase(16, 128, 512, 64, lambda b, s: b * s,
                       layers=2, heads=4, intermediate=128,
                       max_position=128),
    # BERT's MLM head only scores the 15% masked positions
    "bert": ShardedCase(32, 128, 1024, 64,
                        lambda b, s: b * max(1, int(0.15 * s))),
    # NMT transformer: every target position enters the loss
    "transformer_big": ShardedCase(16, 128, 1000, 64, lambda b, s: b * s),
}


def sharded_train_contracts(model, dp=2, tp=2):
    """The fused+sharded train-step contract for one model: no
    [rows, vocab]-scale temporary, no vocab-weight all-gather, no f64,
    no host callback."""
    c = SHARDED_TRAIN_CASES[model]
    vocab, hidden = c.vocab, c.hidden
    return [
        NoTemporary({vocab, vocab // tp}, c.min_rows(dp),
                    what="[rows, vocab]-scale logits temporary"),
        NoOpMatching(
            "all-gather",
            shape_test=lambda shp: (vocab in shp
                                    and math.prod(shp) >= vocab * hidden),
            what="vocab-weight-scale all-gather"),
        MaxDtypeWidth(32),
        NoHostCallback(),
    ]


# fused-MLP probe dims: rows=512, H=256, I=1024 (the 4H convention).
# I must exceed the kernel's 512 intermediate-tile cap so the fused path
# genuinely blocks the I axis — at I <= 512 the single [rows, I] block
# IS the activation and the detector could not tell fused from unfused.
# MLP_MIN_ROWS sits above H=256 so the [H, I] / [I, H] weights
# (legitimate I-axis residents) never trip; the [512, 1024] activation
# of the unfused composition does.
MLP_ROWS = 512
MLP_HIDDEN = 256
MLP_INTER = 1024
MLP_MIN_ROWS = 320


def fused_mlp_contracts(inter=MLP_INTER, min_rows=MLP_MIN_ROWS):
    """The fused GLU/MLP forward contract: the [rows, 4H] activation
    never materializes in the compiled module (the kernel streams
    I-axis tiles through a [block_rows, H] accumulator)."""
    return [
        NoTemporary({inter}, min_rows,
                    what="[rows, 4H] MLP activation temporary"),
        MaxDtypeWidth(32),
        NoHostCallback(),
    ]


SERVE_TMAX = 48
SERVE_MIN_ROWS = 8
# the serve probe's paged-KV layout (tools/compile_smoke._serve_engine:
# GPTConfig.tiny heads=4 x hd=16, page_size=8, 13 pages). KV_MIN_ROWS
# sits above the kernel's per-tile dequant (block_h<=4 rows of ps x hd)
# and below both the whole-pool dequant (13 x 4 = 52 rows) and the
# dense gather (slots x Pmax x heads = 48 rows).
SERVE_PAGE_SIZE = 8
SERVE_HEAD_DIM = 16
SERVE_KV_MIN_ROWS = 24


def serve_decode_contracts(tmax=SERVE_TMAX, min_rows=SERVE_MIN_ROWS):
    """The paged decode-step contract: no [rows, Tmax]-dense gathered
    K/V or score temporary, the one trace, donated pools really
    aliased, no host callback, no f64."""
    return [
        NoTemporary({tmax}, min_rows,
                    what="[rows, Tmax]-dense attention temporary"),
        TracedOnce(("serve.decode",)),
        DonationRespected(min_aliases=1),
        NoHostCallback(),
        MaxDtypeWidth(32),
    ]


def serve_prefill_contracts():
    return [TracedOnce(("serve.prefill",))]


# speculative-verify probe dims (tools/compile_smoke._verify_engine):
# slots=16 and spec_k=7 give a slots x window = 128-row verify batch, so
# MIN_ROWS=96 sits ABOVE the model width (the tiny gpt's [vocab=512,
# hidden=64] tied embedding carries 64 rows per vocab column — a
# legitimate resident) and BELOW the 128-row dense lattice a verify step
# that materialized [slots, window, vocab] logits would compile. The
# detector works because the engine applies the vocab head + sampling
# PER WINDOW POSITION: no legitimate [slots*window, vocab] tensor exists
# in the module.
SERVE_VERIFY_SLOTS = 16
SERVE_VERIFY_SPEC_K = 7
SERVE_VERIFY_MIN_ROWS = 96
# probe pool: enough pages for the smoke's admission waves plus window
# growth; the byte budget prices the donated pool pass-through from this
# (pool_rows = pages * page_size), so the probe and the budget derive
# from the one constant
SERVE_VERIFY_PAGES = 31


def serve_verify_contracts():
    """The speculative verify-step contract: one trace each for the
    decode / draft / verify entry points, donated pools really aliased,
    no host callback, no f64, and NO dense [slots, window, vocab]
    logits lattice — the head is applied per window position, so
    sampling temporaries stay [slots, vocab]. (The [rows, Tmax] score
    detector of the decode row deliberately does NOT apply: the verify
    window legitimately re-attends the gathered prefix, amortized over
    up to window emitted tokens.)"""
    c = SHARDED_TRAIN_CASES["gpt"]
    return [
        NoTemporary({c.vocab}, SERVE_VERIFY_MIN_ROWS,
                    what="[slots*window, vocab]-dense verify logits "
                         "lattice"),
        TracedOnce(("serve.decode", "serve.draft", "serve.verify")),
        DonationRespected(min_aliases=1),
        NoHostCallback(),
        MaxDtypeWidth(32),
    ]


def serve_verify_budget_contracts(slots=SERVE_VERIFY_SLOTS,
                                  context=SERVE_TMAX,
                                  spec_k=SERVE_VERIFY_SPEC_K):
    """Budget row for the speculative verify step, priced by
    ``costmodel.predict_decode(spec_k=...)`` — zero hand-written
    constants: raising spec_k or slots re-derives the budget from the
    same cost model tools/autoplan.py reports break-even acceptance
    with."""
    cm, topo, rate = _pricing()
    pred = cm.predict_decode(
        _train_spec("gpt"), topo, slots=slots, context=context,
        rate=rate, spec_k=spec_k,
        pool_rows=SERVE_VERIFY_PAGES * SERVE_PAGE_SIZE)
    src = (f"costmodel.predict_decode(gpt, slots={slots}, "
           f"Tmax={context}, spec_k={spec_k})")
    return [
        MaxHloFlops(pred["verify_flops_per_chip"],
                    SERVE_VERIFY_BUDGET_TOLERANCE["flops"], source=src),
        MaxHloBytes(pred["verify_hlo_bytes"],
                    SERVE_VERIFY_BUDGET_TOLERANCE["bytes"], source=src),
    ]


# --- cost-model-priced budgets ---------------------------------------
#
# Tolerances are calibrated against the measured tiny-config compiles
# on jax-cpu (tests/test_compile_smoke.py re-measures every run):
# measured/predicted sits at ~0.85 (train flops), ~4.3 (train bytes —
# the traffic estimate undercounts XLA's interpret-mode and rematerial-
# ization traffic), ~1.02 (decode flops), ~2.1 (decode bytes), so each
# budget leaves ~1.4-1.5x headroom over today's compiles while a real
# regression (an unfused xent materializing [rows, V] traffic, a dense
# Tmax attention) blows through it.
TRAIN_BUDGET_TOLERANCE = {"flops": 1.25, "bytes": 6.0}
SERVE_BUDGET_TOLERANCE = {"flops": 1.5, "bytes": 3.0}
# verify: measured/predicted sits at ~1.4 (flops) and ~9.2 (bytes — the
# per-position head + sampling unroll re-reads the tied embedding and
# its [slots, vocab] rows window times; that re-read traffic is exactly
# the price of never materializing the [slots, window, vocab] lattice,
# and the analytic model prices each row once). Same ~1.4x headroom
# convention as above.
SERVE_VERIFY_BUDGET_TOLERANCE = {"flops": 2.0, "bytes": 13.0}
SERVE_SLOTS = 2

_AUTOPLAN_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "parallel", "autoplan")
_MOD_CACHE = {}


def _load_autoplan(stem):
    """Load a parallel/autoplan module by file path — keeps this module
    importable without the paddle_tpu package (and without jax); the
    cost model and topology table are themselves stdlib-only."""
    mod = _MOD_CACHE.get(stem)
    if mod is None:
        path = os.path.join(_AUTOPLAN_DIR, stem + ".py")
        spec = importlib.util.spec_from_file_location(
            "_contracts_" + stem, path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _MOD_CACHE[stem] = mod
    return mod


def _train_spec(model):
    cm = _load_autoplan("costmodel")
    c = SHARDED_TRAIN_CASES[model]
    return cm.ModelSpec(
        name=model, vocab=c.vocab, hidden=c.hidden, layers=c.layers,
        heads=c.heads, intermediate=c.intermediate, seq=c.seq,
        batch=c.batch, max_position=c.max_position)


def _pricing():
    """(costmodel module, topology, fixed rate) — the rate is pinned to
    the analytic ``peak * MFU_ASSUMED`` so pricing never consults the
    autotune cache (which would drag jax into a stdlib-only import)."""
    cm = _load_autoplan("costmodel")
    topo = _load_autoplan("topology").get_topology("cpu4")
    return cm, topo, topo.peak_flops * cm.MFU_ASSUMED


def train_budget_contracts(model="gpt", dp=2, tp=2):
    """Budget row for one model's dp x tp train step, priced by
    ``costmodel.predict()``."""
    cm, topo, rate = _pricing()
    pred = cm.predict(_train_spec(model), topo, dp=dp, tp=tp, pp=1,
                      rate=rate)
    src = f"costmodel.predict({model}@dp{dp},tp{tp})"
    return [
        MaxHloFlops(pred["flops_per_chip"],
                    TRAIN_BUDGET_TOLERANCE["flops"], source=src),
        MaxHloBytes(pred["hlo_bytes"],
                    TRAIN_BUDGET_TOLERANCE["bytes"], source=src),
    ]


def serve_budget_contracts(slots=SERVE_SLOTS, context=SERVE_TMAX,
                           kv_dtype=None):
    """Budget row for the paged decode step, priced by
    ``costmodel.predict_decode()`` on the same tiny-gpt spec the serve
    smoke compiles. ``kv_dtype="int8"`` re-derives the byte budget from
    the quantized pool's traffic (1 byte/value + scales) — the int8
    serve row's budget shrinks automatically with the KV footprint."""
    cm, topo, rate = _pricing()
    pred = cm.predict_decode(_train_spec("gpt"), topo, slots=slots,
                             context=context, rate=rate,
                             kv_dtype=kv_dtype)
    src = (f"costmodel.predict_decode(gpt, slots={slots}, Tmax={context}"
           + (f", kv_dtype={kv_dtype}" if kv_dtype else "") + ")")
    return [
        MaxHloFlops(pred["flops_per_chip"],
                    SERVE_BUDGET_TOLERANCE["flops"], source=src),
        MaxHloBytes(pred["hlo_bytes"],
                    SERVE_BUDGET_TOLERANCE["bytes"], source=src),
    ]


def serve_decode_int8_contracts():
    """The quantized-KV serve row: everything the f32 row demands, plus
    the no-f32-KV-temporary detector, with the byte budget re-derived
    from the int8 pool footprint."""
    return (serve_decode_contracts()
            + [NoKvDequantTemporary(SERVE_PAGE_SIZE, SERVE_HEAD_DIM,
                                    SERVE_KV_MIN_ROWS)]
            + serve_budget_contracts(kv_dtype="int8"))


# name -> contract list; tools/compile_smoke.py compiles each target and
# evaluates its row (tools/graft_lint.py --contracts is the CLI front
# door). tests/test_lint.py proves every contract class fires on a
# planted violation.
CONTRACTS = {
    "train.gpt@dp2,tp2": (sharded_train_contracts("gpt")
                          + train_budget_contracts("gpt")),
    # autoplan-resolved mesh (bench --mesh auto on 4 virtual devices):
    # the planner may pick any dp in {1, 2, 4}; dp=4 gives the smallest
    # per-shard row count, so this row is the strictest of the three
    "train.gpt@auto": sharded_train_contracts("gpt", dp=4),
    "train.bert@dp2,tp2": sharded_train_contracts("bert"),
    "train.transformer_big@dp2,tp2":
        sharded_train_contracts("transformer_big"),
    "serve.decode": serve_decode_contracts() + serve_budget_contracts(),
    "serve.decode@int8": serve_decode_int8_contracts(),
    "serve.prefill": serve_prefill_contracts(),
    "serve.verify": (serve_verify_contracts()
                     + serve_verify_budget_contracts()),
    "mlp.fused": fused_mlp_contracts(),
}

# Differential snapshot gates, keyed like CONTRACTS rows but kept in a
# separate registry: a snapshot judges the module against a blessed
# on-disk record, so it only belongs in runs that really compiled the
# canonical target (compile_smoke wires it in; text-only fixture
# evaluations of CONTRACTS stay self-contained).
CONTRACT_SNAPSHOTS = {
    "train.gpt@dp2,tp2": HloSnapshot("train.gpt@dp2,tp2"),
    "serve.decode": HloSnapshot("serve.decode"),
    "serve.decode@int8": HloSnapshot("serve.decode@int8"),
    "serve.verify": HloSnapshot("serve.verify"),
}
