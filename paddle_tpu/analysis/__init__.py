"""graft-lint — the repo's static-analysis suite.

Two layers (ROADMAP: every perf/robustness claim checked statically,
once, for the whole tree):

* ``lint`` + ``rules/``: visitor-based AST rules over the source tree —
  hot-path sync hazards, tracer leaks, flag/metric/fault-point drift,
  committed log artifacts — with per-line
  ``# graft-lint: disable=<rule> (<reason>)`` suppressions.
* ``contracts``: declarative compile-contract objects (NoTemporary,
  NoOpMatching, TracedOnce, DonationRespected, NoHostCallback,
  MaxDtypeWidth) evaluated against compiled HLO / jaxpr text, with the
  per-model contract table ``CONTRACTS`` that tools/compile_smoke.py
  enforces in tier-1.

Everything here is stdlib-only so ``tools/graft_lint.py`` can run the
rule layer without paying the jax import (the contract layer's
*evaluation* compiles models and lives behind compile_smoke).
"""

from paddle_tpu.analysis import contracts, lint  # noqa: F401
from paddle_tpu.analysis.lint import (  # noqa: F401
    Finding, LintContext, Rule, make_rules, register, rule_names,
    run_lint)
