"""lock-order: the cross-module lock-acquisition graph is acyclic.

Two threads acquiring the same pair of locks in opposite orders is the
classic static deadlock; the motivating surface here is the
FleetRouter -> engine -> registry chain, where a router step holds the
router lock while stepping engines (which take theirs while recording
metrics) and any callback that re-enters the router from under an
engine lock would close the loop.

The rule computes, per function, its direct lock acquisitions and the
locks transitively acquired by its resolvable callees (fixpoint over
the shared call graph), then adds an edge L -> M whenever M is
acquired — directly or through a call — while L is held. Any strongly
connected component of two or more locks is a finding. Reentrant
self-edges (L -> L) are deliberately ignored: the tree's hot locks are
RLocks and same-lock reentry is how synchronous callbacks are allowed
to re-enter their owner.

Lock identities are class-qualified (see rules/callgraph.py), and an
acquisition only counts when the ``with`` expression is recognizably a
lock (``self.<attr>`` or a bare name matching /lock|mutex/i).
"""

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules import callgraph


@register
class LockOrder(Rule):
    name = "lock-order"
    help = ("cycle in the with-lock acquisition graph across the "
            "concurrent module set (static deadlock)")

    DEFAULT_MODULES = (
        "paddle_tpu/serving/fleet.py",
        "paddle_tpu/serving/engine.py",
        "paddle_tpu/observability/metrics.py",
        "paddle_tpu/observability/watchdog.py",
        "paddle_tpu/observability/exporter.py",
        "paddle_tpu/parallel/heartbeat.py",
    )

    def __init__(self, modules=None):
        self.module_paths = tuple(modules or self.DEFAULT_MODULES)

    def check(self, ctx):
        mods, method_owner = callgraph.build_index(ctx, self.module_paths)
        scans = {}
        resolved = {}   # (rel, qn) -> [(target key, held, lineno)]
        for rel, mod in mods.items():
            for qn in list(mod.functions):
                sc = callgraph.scan_function(mods, rel, qn)
                scans[(rel, qn)] = sc
                calls = []
                for call, held in sc.calls:
                    tgt = callgraph.resolve_call(
                        mods, method_owner, mod, qn, call,
                        resolve_nested=True, resolve_module_aliases=True)
                    if tgt is not None:
                        calls.append((tgt, held, call.lineno))
                resolved[(rel, qn)] = calls
        # transitive acquired-lock sets, to a fixpoint
        acq = {key: {lid for lid, _, _ in sc.acquires}
               for key, sc in scans.items()}
        changed = True
        while changed:
            changed = False
            for key, calls in resolved.items():
                mine = acq[key]
                for tgt, _, _ in calls:
                    if tgt in acq and not acq[tgt] <= mine:
                        mine |= acq[tgt]
                        changed = True
        # edges: M held -> L acquired (directly or through a call)
        edges = {}
        for key, sc in scans.items():
            rel, qn = key
            for lid, held, lineno in sc.acquires:
                for m in held:
                    if m != lid:
                        edges.setdefault((m, lid), (rel, lineno, qn))
            for tgt, held, lineno in resolved[key]:
                if tgt not in acq:
                    continue
                for m in held:
                    for n in acq[tgt]:
                        if m != n:
                            edges.setdefault((m, n), (rel, lineno, qn))
        for comp in self._cycles(edges):
            comp = sorted(comp)
            labels = " -> ".join(callgraph.lock_label(l) for l in comp)
            labels += f" -> {callgraph.lock_label(comp[0])}"
            sites = [edges[(a, b)] for a in comp for b in comp
                     if (a, b) in edges]
            rel, lineno, qn = min(sites, key=lambda s: (s[0], s[1]))
            yield Finding(
                self.name, rel, lineno,
                f"lock-order cycle: {labels} (one edge acquired here, "
                f"in {qn}) — impose a single global order or move the "
                "inner call outside the lock")

    @staticmethod
    def _cycles(edges):
        """Strongly connected components of size >= 2 (Tarjan)."""
        adj = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index = {}
        low = {}
        on_stack = {}
        stack = []
        counter = [0]
        out = []

        def strongconnect(v):
            work = [(v, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on_stack[node] = True
                recurse = False
                succ = adj.get(node, [])
                for i in range(pi, len(succ)):
                    w = succ[i]
                    if w not in index:
                        work[-1] = (node, i + 1)
                        work.append((w, 0))
                        recurse = True
                        break
                    if on_stack.get(w):
                        low[node] = min(low[node], index[w])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack[w] = False
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) >= 2:
                        out.append(comp)
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        return out
