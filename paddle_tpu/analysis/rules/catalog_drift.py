"""catalog-drift: every literal metric call site is cataloged, with the
cataloged kind.

The AST port of tests/test_catalog.py's regex lint: each
``.counter("x")`` / ``.gauge("x")`` / ``.histogram("x")`` call with a
literal first argument in the framework source must name a metric in
``observability/catalog.py``'s CATALOG (exact match, or a registered
``"family."`` prefix), declared with the same kind — so the exporter's
HELP lines, dashboards, and alert rules never chase a renamed or ad-hoc
metric. The catalog itself is parsed statically (dict literal of
``MetricSpec(kind, ...)``), keeping the rule importable without jax.
"""

import ast

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules._common import (call_name, str_arg,
                                               walk_calls)

_KINDS = ("counter", "gauge", "histogram")


def parse_catalog(sf):
    """{metric name: kind} from a catalog module's CATALOG literal."""
    if sf is None or sf.tree is None:
        return None
    for node in sf.tree.body:
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "CATALOG"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            continue
        catalog = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            kind = None
            if isinstance(v, ast.Call):
                kind = str_arg(v)
                if kind is None:
                    for kw in v.keywords:
                        if (kw.arg == "kind"
                                and isinstance(kw.value, ast.Constant)):
                            kind = kw.value.value
            if isinstance(kind, str):
                catalog[k.value] = kind
        return catalog
    return None


def lookup(catalog, name):
    """catalog.lookup semantics: exact, else longest '.'-prefix."""
    if name in catalog:
        return catalog[name]
    best = None
    for key, kind in catalog.items():
        if key.endswith(".") and name.startswith(key):
            if best is None or len(key) > len(best[0]):
                best = (key, kind)
    return best[1] if best else None


@register
class CatalogDrift(Rule):
    name = "catalog-drift"
    help = ("literal .counter()/.gauge()/.histogram() call sites must "
            "be in observability/catalog.py CATALOG with that kind")

    DEFAULT_CATALOG_PATH = "paddle_tpu/observability/catalog.py"
    DEFAULT_SCOPE = ("paddle_tpu/**/*.py", "paddle_tpu/*.py", "bench.py",
                     "tools/*.py")
    # below this many sites the detection itself has rotted (the tree
    # holds ~40 wired metric call sites today)
    MIN_SITES = 25

    def __init__(self, catalog_path=None, scope=None, min_sites=None):
        self.catalog_path = catalog_path or self.DEFAULT_CATALOG_PATH
        self.scope = tuple(scope or self.DEFAULT_SCOPE)
        self.min_sites = (self.MIN_SITES if min_sites is None
                          else min_sites)

    def sites(self, ctx):
        """Every literal metric call site: (sf, lineno, kind, name)."""
        out = []
        for sf in ctx.glob(*self.scope):
            if sf.tree is None or sf.relpath == self.catalog_path:
                continue
            for call in walk_calls(sf.tree):
                f = call.func
                if not (isinstance(f, ast.Attribute) and f.attr in _KINDS):
                    continue
                name = str_arg(call)
                if name is not None:
                    out.append((sf, call.lineno, f.attr, name))
        return out

    def check(self, ctx):
        catalog = parse_catalog(ctx.file(self.catalog_path))
        if catalog is None:
            yield Finding(self.name, self.catalog_path, 1,
                          "CATALOG dict literal not found — the rule's "
                          "anchor rotted")
            return
        sites = self.sites(ctx)
        if len(sites) < self.min_sites:
            yield Finding(
                self.name, self.catalog_path, 1,
                f"only {len(sites)} metric call sites detected (expected "
                f">= {self.min_sites}) — the site detection rotted")
        for sf, lineno, kind, name in sites:
            cataloged = lookup(catalog, name)
            if cataloged is None:
                yield Finding(
                    self.name, sf.relpath, lineno,
                    f"{kind}({name!r}) is not in "
                    "observability/catalog.py CATALOG")
            elif cataloged != kind:
                yield Finding(
                    self.name, sf.relpath, lineno,
                    f"{name!r} called as {kind} but cataloged as "
                    f"{cataloged}")
