"""event-drift: trace.EVENTS and the literal trace-plane event writers
(``_trace_event(req, "...")`` / ``note_event("...")``) may never drift
apart, either way.

Mirror of catalog-drift/fault-point-drift for the trace plane: an event
kind emitted but not registered is invisible to the fleet-trace
collector's consumers (the Gantt/critical-path renderers key on known
kinds), and a registered kind with no emitter documents an event that
never happens. The catalog is parsed statically from the EVENTS dict
literal in observability/trace.py.

Event args that are conditional expressions over string literals
(``"resumed" if req.preemptions else "admitted"``) contribute every
branch; fully dynamic args are out of static reach and stay silent.
"""

import ast

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules._common import call_name, walk_calls


def parse_events(sf):
    """{event kind: lineno} from the trace module's EVENTS literal."""
    if sf is None or sf.tree is None:
        return None
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "EVENTS"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


def _event_literals(node):
    """String literals an event argument can evaluate to: a Constant
    yields itself, an IfExp yields both branches, anything else is
    dynamic (empty)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.IfExp):
        return _event_literals(node.body) + _event_literals(node.orelse)
    return []


# writer name -> positional index of the event-kind argument
WRITERS = {"_trace_event": 1, "note_event": 0}


@register
class EventDrift(Rule):
    name = "event-drift"
    help = ("literal _trace_event/note_event kinds and trace.EVENTS "
            "must match in both directions")

    DEFAULT_CATALOG_PATH = "paddle_tpu/observability/trace.py"
    DEFAULT_SCOPE = ("paddle_tpu/**/*.py", "paddle_tpu/*.py")
    MIN_SITES = 8   # the wiring exists; below this the detection rotted

    def __init__(self, catalog_path=None, scope=None, min_sites=None):
        self.catalog_path = catalog_path or self.DEFAULT_CATALOG_PATH
        self.scope = tuple(scope or self.DEFAULT_SCOPE)
        self.min_sites = (self.MIN_SITES if min_sites is None
                          else min_sites)

    def sites(self, ctx):
        """{event kind: [(relpath, lineno), ...]} from literal writer
        call sites (the catalog module's own writers count too — its
        helpers emit anchor/span events)."""
        out = {}
        for sf in ctx.glob(*self.scope):
            if sf.tree is None:
                continue
            for call in walk_calls(sf.tree):
                cn = call_name(call)
                if cn is None:
                    continue
                index = WRITERS.get(cn.split(".")[-1])
                if index is None or len(call.args) <= index:
                    continue
                for kind in _event_literals(call.args[index]):
                    out.setdefault(kind, []).append(
                        (sf.relpath, call.lineno))
        return out

    def check(self, ctx):
        registered = parse_events(ctx.file(self.catalog_path))
        if registered is None:
            yield Finding(self.name, self.catalog_path, 1,
                          "EVENTS dict literal not found — the rule's "
                          "anchor rotted")
            return
        sites = self.sites(ctx)
        n_sites = sum(len(v) for v in sites.values())
        if n_sites < self.min_sites:
            yield Finding(
                self.name, self.catalog_path, 1,
                f"only {n_sites} trace-event writer sites detected "
                f"(expected >= {self.min_sites}) — the site detection "
                "rotted")
        for kind, locs in sorted(sites.items()):
            if kind not in registered:
                rel, lineno = locs[0]
                yield Finding(
                    self.name, rel, lineno,
                    f"trace event {kind!r} is not registered in "
                    "trace.EVENTS — the fleet-trace collector's "
                    "consumers cannot see it")
        for kind, lineno in sorted(registered.items()):
            if kind not in sites:
                yield Finding(
                    self.name, self.catalog_path, lineno,
                    f"trace.EVENTS entry {kind!r} has no writer call "
                    "site — it documents an event that never happens")
