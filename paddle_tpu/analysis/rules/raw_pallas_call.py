"""raw-pallas-call: every ``pl.pallas_call`` must live in
ops/pallas/core.py.

The shared primitive core (ops/pallas/core.py kernel_call) owns
interpret-mode plumbing, grid/grid_spec handling, and the fallback
telemetry contract; a kernel calling ``pl.pallas_call`` directly
re-opens the per-kernel drift the PR-11 refactor closed (private
interpret flags, missed autotune hooks, untracked fallbacks). The rule
is the enforcement half of that refactor: new kernels route through
:func:`kernel_call` or they are a finding.
"""

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules._common import call_name, walk_calls


@register
class RawPallasCall(Rule):
    name = "raw-pallas-call"
    help = ("pl.pallas_call outside ops/pallas/core.py — kernels must "
            "route through the shared kernel_call wrapper")

    DEFAULT_ALLOWED = "paddle_tpu/ops/pallas/core.py"
    DEFAULT_SCOPE = ("paddle_tpu/**/*.py", "paddle_tpu/*.py")
    MIN_SITES = 1   # core.py holds the one real site; 0 => detection rotted

    def __init__(self, allowed=None, scope=None, min_sites=None):
        self.allowed = allowed or self.DEFAULT_ALLOWED
        self.scope = tuple(scope or self.DEFAULT_SCOPE)
        self.min_sites = (self.MIN_SITES if min_sites is None
                          else min_sites)

    def sites(self, ctx):
        """[(relpath, lineno), ...] of every pallas_call call site,
        the allowed wrapper module included."""
        out = []
        for sf in ctx.glob(*self.scope):
            if sf.tree is None:
                continue
            for call in walk_calls(sf.tree):
                cn = call_name(call)
                if cn is not None and cn.split(".")[-1] == "pallas_call":
                    out.append((sf.relpath, call.lineno))
        return out

    def check(self, ctx):
        sites = self.sites(ctx)
        if len(sites) < self.min_sites:
            yield Finding(
                self.name, self.allowed, 1,
                f"only {len(sites)} pallas_call sites detected "
                f"(expected >= {self.min_sites}) — the site detection "
                "rotted")
        for rel, lineno in sorted(sites):
            if rel != self.allowed:
                yield Finding(
                    self.name, rel, lineno,
                    "pl.pallas_call outside the shared wrapper — use "
                    "ops/pallas/core.py kernel_call (owns interpret "
                    "mode, grid plumbing, and fallback telemetry)")
