"""fault-point-drift: chaos.FAULT_POINTS and the literal
``fault_point("...")`` call sites may never drift apart, either way.

The AST port of tests/test_chaos.py's TestFaultPointRegistry greps: a
chaos plan targeting a renamed hook would silently inject nothing
(unregistered call site), and a registry entry with no call site is a
drill that tests nothing. The registry is parsed statically from the
FAULT_POINTS dict literal.
"""

import ast

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules._common import (call_name, str_arg,
                                               walk_calls)


def parse_fault_points(sf):
    """{name: lineno} from a chaos module's FAULT_POINTS literal."""
    if sf is None or sf.tree is None:
        return None
    for node in sf.tree.body:
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "FAULT_POINTS"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            return {k.value: k.lineno for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


@register
class FaultPointDrift(Rule):
    name = "fault-point-drift"
    help = ("literal fault_point(\"...\") call sites and "
            "chaos.FAULT_POINTS must match in both directions")

    DEFAULT_CHAOS_PATH = "paddle_tpu/testing/chaos.py"
    DEFAULT_SCOPE = ("paddle_tpu/**/*.py", "paddle_tpu/*.py")
    MIN_SITES = 6   # the wiring exists; below this the detection rotted

    def __init__(self, chaos_path=None, scope=None, min_sites=None):
        self.chaos_path = chaos_path or self.DEFAULT_CHAOS_PATH
        self.scope = tuple(scope or self.DEFAULT_SCOPE)
        self.min_sites = (self.MIN_SITES if min_sites is None
                          else min_sites)

    def sites(self, ctx):
        """{fault point name: [(relpath, lineno), ...]}."""
        out = {}
        for sf in ctx.glob(*self.scope):
            if sf.tree is None or sf.relpath == self.chaos_path:
                continue
            for call in walk_calls(sf.tree):
                cn = call_name(call)
                if cn is None or cn.split(".")[-1] != "fault_point":
                    continue
                name = str_arg(call)
                if name is not None:
                    out.setdefault(name, []).append(
                        (sf.relpath, call.lineno))
        return out

    def check(self, ctx):
        registered = parse_fault_points(ctx.file(self.chaos_path))
        if registered is None:
            yield Finding(self.name, self.chaos_path, 1,
                          "FAULT_POINTS dict literal not found — the "
                          "rule's anchor rotted")
            return
        sites = self.sites(ctx)
        n_sites = sum(len(v) for v in sites.values())
        if n_sites < self.min_sites:
            yield Finding(
                self.name, self.chaos_path, 1,
                f"only {n_sites} fault_point call sites detected "
                f"(expected >= {self.min_sites}) — the site detection "
                "rotted")
        for name, locs in sorted(sites.items()):
            if name not in registered:
                rel, lineno = locs[0]
                yield Finding(
                    self.name, rel, lineno,
                    f"fault_point({name!r}) is not registered in "
                    "chaos.FAULT_POINTS — a chaos plan targeting it "
                    "would silently inject nothing")
        for name, lineno in sorted(registered.items()):
            if name not in sites:
                yield Finding(
                    self.name, self.chaos_path, lineno,
                    f"chaos.FAULT_POINTS entry {name!r} has no "
                    "fault_point call site — the drill tests nothing")
