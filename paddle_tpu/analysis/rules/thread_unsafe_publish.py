"""thread-unsafe-publish: a container one method iterates lazily while
another method mutates it must be copied or locked.

Python dicts and lists raise (or silently skip) when mutated during
iteration — and in a threaded process the mutator is frequently
another thread: the watchdog polling its watched-jit table while
``watch_jit`` registers a new function, an exporter rendering a
registry while a request thread creates a metric. The fix is cheap and
local — iterate a snapshot (``list(self.A)`` /
``list(self.A.items())``) or hold a common lock at both sites — so the
rule insists on one of the two.

Fires when, within one class: a self-attribute is iterated *lazily*
(``for x in self.A``, a comprehension over ``self.A.items()``, or
either wrapped only in enumerate/zip/...) in one method, some *other*
method mutates that attribute (mutator call, subscript store/delete,
rebind) outside ``__init__``, the attribute is not ``graft-guard``-ed
(guarded attributes belong to unguarded-shared-state), and the two
sites share no lexically-held lock.
"""

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules import callgraph


@register
class ThreadUnsafePublish(Rule):
    name = "thread-unsafe-publish"
    help = ("self container iterated lazily in one method and mutated "
            "in another with no common lock — iterate a copy")

    DEFAULT_MODULES = (
        "paddle_tpu/serving/fleet.py",
        "paddle_tpu/serving/engine.py",
        "paddle_tpu/observability/metrics.py",
        "paddle_tpu/observability/watchdog.py",
        "paddle_tpu/observability/exporter.py",
        "paddle_tpu/parallel/heartbeat.py",
    )

    def __init__(self, modules=None):
        self.module_paths = tuple(modules or self.DEFAULT_MODULES)

    def check(self, ctx):
        mods, _ = callgraph.build_index(ctx, self.module_paths)
        guards = callgraph.build_guards(mods)
        for rel in sorted(mods):
            mod = mods[rel]
            for cls in sorted(mod.classes):
                yield from self._check_class(mods, rel, mod, cls, guards)

    def _check_class(self, mods, rel, mod, cls, guards):
        iters = []      # (attr, method, held, lineno)
        mutations = {}  # attr -> [(method, held, lineno)]
        for qn in sorted(mod.functions):
            if not qn.startswith(cls + "."):
                continue
            sc = callgraph.scan_function(mods, rel, qn)
            if sc.cls != cls:
                continue
            for expr, held, lineno in sc.iterations:
                attr = callgraph.iterated_self_attr(expr)
                if attr is not None:
                    iters.append((attr, qn, held, lineno))
            if qn.endswith("__init__"):
                continue
            for attr, held, lineno in sc.mutations:
                mutations.setdefault(attr, []).append((qn, held, lineno))
        seen = set()
        for attr, method, held, lineno in iters:
            if (rel, cls, attr) in guards or (rel, lineno, attr) in seen:
                continue
            racing = [(m, h, ln) for m, h, ln in mutations.get(attr, [])
                      if m != method and not (h & held)]
            if not racing:
                continue
            seen.add((rel, lineno, attr))
            racing.sort(key=lambda r: (r[0], r[2]))
            mutator, _, mut_line = racing[0]
            yield Finding(
                self.name, rel, lineno,
                f"self.{attr} iterated lazily in {method} while "
                f"{mutator} mutates it (line {mut_line}) — a concurrent "
                f"mutation breaks iteration; iterate a snapshot "
                f"(list(self.{attr})) or hold a common lock")
