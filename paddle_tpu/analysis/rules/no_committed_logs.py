"""no-committed-logs: no ``*.log`` artifact may be tracked by git.

The tpu_*.sh drivers tee their output into ``tools/*.log``; a round of
those once landed in history and shipped stale silicon transcripts with
every clone. The pattern is gitignored now — this rule keeps the class
of mistake from returning via ``git add -f`` or a new un-ignored
location. Only *tracked* files count: a local, ignored log from running
the scripts is fine.
"""

import os
import subprocess

from paddle_tpu.analysis.lint import (DEFAULT_EXCLUDES, Finding, Rule,
                                      register)


@register
class NoCommittedLogs(Rule):
    name = "no-committed-logs"
    help = "no *.log artifact tracked by git (gitignore tools/*.log)"

    def __init__(self, use_git=None):
        # None = use git when the tree is a work tree, else walk the
        # filesystem (fixture trees aren't git roots)
        self.use_git = use_git

    def _git_logs(self, root):
        try:
            proc = subprocess.run(
                ["git", "-C", root, "ls-files", "--", "*.log"],
                stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
                text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        return [p for p in proc.stdout.splitlines() if p.strip()]

    def _walk_logs(self, root):
        out = []
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != ".git"]
            for f in sorted(filenames):
                if f.endswith(".log"):
                    rel = os.path.relpath(os.path.join(dirpath, f), root)
                    out.append(rel.replace(os.sep, "/"))
        return out

    def check(self, ctx):
        logs = None
        if self.use_git is not False:
            logs = self._git_logs(ctx.root)
        if logs is None:
            if self.use_git is True:
                yield Finding(self.name, ".", 1,
                              "git ls-files failed — cannot enforce "
                              "no-committed-logs")
                return
            logs = self._walk_logs(ctx.root)
        for rel in logs:
            if any(part in rel for part in DEFAULT_EXCLUDES):
                continue
            yield Finding(
                self.name, rel, 1,
                "committed *.log artifact — remove it and rely on the "
                ".gitignore'd tools/*.log pattern")
