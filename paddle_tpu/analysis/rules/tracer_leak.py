"""tracer-leak: Python control flow on traced values inside staged
functions.

A function handed to ``jax.jit`` / ``lax.scan`` / ``shard_map`` /
``pl.pallas_call`` runs once at trace time; a Python ``if``/``while``
(or ``bool()``) over one of its *traced* arguments either crashes with a
ConcretizationTypeError on first use or — worse — silently bakes the
tracing-time branch into the compiled program. The dynamic failure shows
up only when that branch is reached; this rule finds the pattern
statically, tree-wide.

Detection is deliberately conservative (a lint that cries wolf gets
disabled): a finding needs BOTH a function we can prove is staged
(``@jax.jit``-style decorator, or passed by name/lambda to a staging
call, ``functools.partial`` unwrapped, jit's literal
``static_argnums``/``static_argnames`` honored) AND a test expression
rooted at a traced parameter via truthiness — a bare
name/attribute/subscript, ``not`` of one, a ``bool()`` call, or a
boolean combination. Comparisons, ``is None`` checks, and the static
attributes (``.shape``/``.ndim``/``.dtype``/``.size``) never fire.
Taint propagates through straight-line assignments; calls like ``len``
/ ``isinstance`` and shape arithmetic stay static.
"""

import ast

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules._common import (assign_name_targets,
                                               call_name, dotted_name)

# attributes of a traced array that are static python values at trace
# time — tests on them are fine
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                 "itemsize", "weak_type"}
# calls whose RESULT is static regardless of traced args
_STATIC_CALLS = {"len", "isinstance", "hasattr", "type", "getattr",
                 "range", "enumerate", "zip", "id", "repr", "str",
                 "format"}
# calls producing a python container: its truthiness is len-based and
# static under tracing even when the ELEMENTS are tracers (matched on
# the last dotted segment, so jax.tree_util.tree_leaves counts)
_CONTAINER_CALLS = {"tuple", "list", "set", "dict", "frozenset", "sorted",
                    "tree_leaves"}

# staging call -> (reported name, positions of the staged callables)
_STAGING_CALLS = {
    "jax.jit": ("jax.jit", (0,)), "jit": ("jax.jit", (0,)),
    "jax.pjit": ("jax.jit", (0,)), "pjit": ("jax.jit", (0,)),
    "lax.scan": ("lax.scan", (0,)), "jax.lax.scan": ("lax.scan", (0,)),
    "shard_map": ("shard_map", (0,)),
    "jax.experimental.shard_map.shard_map": ("shard_map", (0,)),
    "pl.pallas_call": ("pl.pallas_call", (0,)),
    "pallas_call": ("pl.pallas_call", (0,)),
    "lax.while_loop": ("lax.while_loop", (0, 1)),
    "jax.lax.while_loop": ("lax.while_loop", (0, 1)),
    "lax.fori_loop": ("lax.fori_loop", (2,)),
    "jax.lax.fori_loop": ("lax.fori_loop", (2,)),
    "lax.cond": ("lax.cond", (1, 2)),
    "jax.lax.cond": ("lax.cond", (1, 2)),
    "lax.map": ("lax.map", (0,)), "jax.lax.map": ("lax.map", (0,)),
    "jax.vmap": ("jax.vmap", (0,)), "vmap": ("jax.vmap", (0,)),
    "jax.grad": ("jax.grad", (0,)),
    "jax.value_and_grad": ("jax.value_and_grad", (0,)),
    "jax.checkpoint": ("jax.checkpoint", (0,)),
    "jax.remat": ("jax.checkpoint", (0,)),
}
_DECORATOR_STAGERS = {"jax.jit", "jit", "jax.pjit", "pjit",
                      "jax.checkpoint", "jax.remat"}
_PARTIAL_NAMES = {"functools.partial", "partial"}


def _static_params(call):
    """Parameter positions/names jit treats as static (literal
    static_argnums / static_argnames only)."""
    nums, names = set(), set()
    for kw in call.keywords:
        v = kw.value
        if kw.arg == "static_argnums":
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in vals:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    nums.add(e.value)
        elif kw.arg == "static_argnames":
            vals = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
            for e in vals:
                if isinstance(e, ast.Constant) and isinstance(e.value, str):
                    names.add(e.value)
    return nums, names


def _unwrap_partial(node):
    """partial(f, ...) -> f (one level is all the tree uses)."""
    if (isinstance(node, ast.Call)
            and call_name(node) in _PARTIAL_NAMES and node.args):
        return node.args[0]
    return node


class _TracedFn:
    def __init__(self, fn, via, static_nums=(), static_names=()):
        self.fn = fn            # FunctionDef or Lambda
        self.via = via          # 'jax.jit' / 'lax.scan' / ...
        args = fn.args
        params = [a.arg for a in args.posonlyargs + args.args]
        traced = []
        for i, p in enumerate(params):
            if p in ("self", "cls"):
                continue
            if i in static_nums or p in static_names:
                continue
            traced.append(p)
        self.traced = set(traced)


def _collect_traced(tree):
    """Every function in the module we can prove is staged."""
    # name -> def nodes (any nesting level) for by-name resolution
    defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)

    out = []
    seen = set()

    def _add(fn, via, nums=(), names=()):
        if id(fn) not in seen:
            seen.add(id(fn))
            out.append(_TracedFn(fn, via, nums, names))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    name = call_name(dec)
                    if (name in _PARTIAL_NAMES and dec.args
                            and dotted_name(dec.args[0])
                            in _DECORATOR_STAGERS):
                        nums, names_ = _static_params(dec)
                        _add(node, "jax.jit", nums, names_)
                    elif name in _DECORATOR_STAGERS:
                        nums, names_ = _static_params(dec)
                        _add(node, "jax.jit", nums, names_)
                elif dotted_name(dec) in _DECORATOR_STAGERS:
                    _add(node, "jax.jit")
        elif isinstance(node, ast.Call):
            staged = _STAGING_CALLS.get(call_name(node))
            if staged is None:
                continue
            via, positions = staged
            nums, names = (_static_params(node)
                           if via == "jax.jit" else (set(), set()))
            for pos in positions:
                if pos >= len(node.args):
                    continue
                fn_arg = _unwrap_partial(node.args[pos])
                if isinstance(fn_arg, ast.Lambda):
                    _add(fn_arg, via, nums, names)
                elif isinstance(fn_arg, ast.Name):
                    cands = defs.get(fn_arg.id, [])
                    if len(cands) == 1:
                        _add(cands[0], via, nums, names)
    return out


class _LeakScan:
    """One staged function: propagate taint, flag truthiness tests."""

    def __init__(self, traced_fn):
        self.tf = traced_fn
        self.tainted = set(traced_fn.traced)
        self.containers = set()   # tainted names with static truthiness

    def _static_truthy(self, node):
        """Containers (and names holding them) have len-based
        truthiness, static at trace time regardless of contents."""
        if isinstance(node, ast.Name):
            return node.id in self.containers
        if isinstance(node, (ast.Tuple, ast.List, ast.Set, ast.Dict)):
            return True
        if isinstance(node, ast.Call):
            cn = call_name(node)
            return (cn is not None
                    and cn.split(".")[-1] in _CONTAINER_CALLS)
        return False

    def _rooted(self, node):
        """Is this expression's value the traced data itself (via
        names, non-static attributes, subscripts)?"""
        if self._static_truthy(node):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._rooted(node.value)
        if isinstance(node, ast.Subscript):
            return self._rooted(node.value)
        if isinstance(node, ast.Call):
            if call_name(node) == "bool" and node.args:
                return (not self._static_truthy(node.args[0])
                        and self._mentions_traced(node.args[0]))
            return False
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return self._rooted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._rooted(v) for v in node.values)
        return False

    def _mentions_traced(self, node):
        """Does the expression carry traced data (descending past
        static attrs / static calls returns False)?"""
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self._mentions_traced(node.value)
        if isinstance(node, ast.Call):
            f = call_name(node)
            if f in _STATIC_CALLS:
                return False
            return any(self._mentions_traced(a) for a in node.args)
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        return any(self._mentions_traced(c) for c in ast.iter_child_nodes(node))

    def findings(self, rule, relpath):
        body = (self.tf.fn.body if isinstance(self.tf.fn.body, list)
                else [self.tf.fn.body])   # Lambda body is an expr
        # taint propagation through straight-line assignments, in
        # source order (good enough for trace-time code)
        fn_nodes = []
        for stmt in body:
            fn_nodes.extend(ast.walk(stmt))
        for node in fn_nodes:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                value = getattr(node, "value", None)
                if value is not None and self._mentions_traced(value):
                    targets = assign_name_targets(node)
                    self.tainted.update(targets)
                    tgt_nodes = (node.targets if isinstance(node, ast.Assign)
                                 else [node.target])
                    if (len(tgt_nodes) == 1
                            and isinstance(tgt_nodes[0], ast.Name)
                            and self._static_truthy(value)):
                        self.containers.add(tgt_nodes[0].id)
                    else:
                        self.containers.difference_update(targets)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                # nested defs run at trace time too: their params carry
                # traced values when called on them
                args = node.args
                for a in args.posonlyargs + args.args:
                    if a.arg not in ("self", "cls"):
                        self.tainted.add(a.arg)

        where = getattr(self.tf.fn, "name", "<lambda>")
        for node in fn_nodes:
            if isinstance(node, (ast.If, ast.While)):
                if self._rooted(node.test):
                    kw = "while" if isinstance(node, ast.While) else "if"
                    yield Finding(
                        rule, relpath, node.lineno,
                        f"python `{kw}` on a traced value in {where} "
                        f"(staged via {self.tf.via}) — trace-time "
                        "branch on runtime data; use lax.cond/jnp.where")
            elif isinstance(node, ast.IfExp) and self._rooted(node.test):
                yield Finding(
                    rule, relpath, node.lineno,
                    f"`x if <traced> else y` in {where} (staged via "
                    f"{self.tf.via}) — trace-time branch on runtime "
                    "data; use jnp.where")
            elif (isinstance(node, ast.Call)
                  and call_name(node) == "bool" and node.args
                  and not self._static_truthy(node.args[0])
                  and self._mentions_traced(node.args[0])):
                yield Finding(
                    rule, relpath, node.lineno,
                    f"bool() on a traced value in {where} (staged via "
                    f"{self.tf.via}) — concretizes the tracer")


@register
class TracerLeak(Rule):
    name = "tracer-leak"
    help = ("python if/while/bool() over traced values inside functions "
            "staged by jax.jit / lax.scan / shard_map / pl.pallas_call")

    DEFAULT_SCOPE = ("paddle_tpu/**/*.py", "paddle_tpu/*.py", "bench.py",
                     "tools/*.py", "examples/*.py")

    def __init__(self, scope=None):
        self.scope = tuple(scope or self.DEFAULT_SCOPE)

    def check(self, ctx):
        for sf in ctx.glob(*self.scope):
            if sf.tree is None:
                continue
            for tf in _collect_traced(sf.tree):
                yield from _LeakScan(tf).findings(self.name, sf.relpath)
