"""stale-suppression: a disable comment whose rule no longer fires.

A reasoned ``# graft-lint: disable=<rule>`` earns its keep by swallowing
a real finding. Once the code changes and the violation is gone, the
comment is dead weight — worse, it silently masks the NEXT real finding
of that rule on the line. This rule turns every such dead suppression
into a finding of its own.

The detection cannot live in ``check()``: it needs to know which
suppressions actually swallowed a finding during THIS pass, which only
:func:`paddle_tpu.analysis.lint.run_lint` sees. This registration gives
the name a ``--rules``/``--list`` entry (and keeps ``bad-suppression``
from flagging it as unknown); the enforcement rides the run itself.
"""

from paddle_tpu.analysis.lint import Rule, register


@register
class StaleSuppression(Rule):

    name = "stale-suppression"
    severity = "warn"
    help = ("reasoned `# graft-lint: disable=<rule>` comment whose rule "
            "ran but no longer fires on that line — dead suppressions "
            "mask the next real finding")

    def check(self, ctx):
        # enforced inside lint.run_lint — see the module docstring
        return ()
