"""hot-path-sync: no device synchronization reachable from the serving
or training hot loops.

The "no sync on the hot path" invariant was previously enforced only
dynamically, by flush-spy tests covering two call sites. This rule makes
it static and whole-tree: build the call graph over the hot-path module
set, walk everything reachable from ``ServingEngine.submit/step/drain``
and ``Trainer.train``, and flag the synchronizing primitives —
``.block_until_ready()``, ``jax.device_get(...)``, ``.item()``, and
``np.asarray``/``np.array`` applied to a *device* value (a result of a
``jax.jit``-built callable, tracked by a light per-function taint pass;
``np.asarray`` over host lists/prompts is staging, not syncing, and is
deliberately not flagged).

Deliberate syncs (the scheduler consuming this step's sampled tokens,
telemetry's trailing loss fetch) stay in the tree under
``# graft-lint: disable=hot-path-sync (<why>)`` — the rule's job is to
make every *new* sync a reviewed decision, not to pretend zero exist.

Call resolution, in order: ``self.m()`` to the same class; bare ``f()``
to the module (or a ``from paddle_tpu.x import f`` target inside the
module set); ``obj.m()`` to ``Cls.m`` when exactly one analyzed class
defines ``m`` (ambiguous names are skipped, never guessed). Nested defs
are analyzed as part of their enclosing function.
"""

import ast

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules._common import (assign_name_targets,
                                               call_name)

_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}
_NP_ROOTS = {"np", "numpy"}


def _is_jit_call(call):
    name = call_name(call)
    if name in _JIT_NAMES:
        return True
    if name in _PARTIAL_NAMES and call.args:
        inner = call.args[0]
        return (isinstance(inner, (ast.Attribute, ast.Name))
                and (ast.unparse(inner) if hasattr(ast, "unparse")
                     else "") in _JIT_NAMES)
    return False


class _Module:
    """Function/class/import index of one analyzed source file."""

    def __init__(self, sf):
        self.sf = sf
        self.relpath = sf.relpath
        self.functions = {}     # qualname -> FunctionDef
        self.classes = {}       # class name -> {method name: qualname}
        self.jitted_attrs = {}  # class name -> {self attrs bound to jit}
        self.imports = {}       # local name -> (module relpath, name)
        tree = sf.tree
        if tree is None:
            return
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                methods = {}
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qn = f"{node.name}.{item.name}"
                        self.functions[qn] = item
                        methods[item.name] = qn
                self.classes[node.name] = methods
                self.jitted_attrs[node.name] = self._find_jitted_attrs(node)
            elif isinstance(node, ast.ImportFrom) and node.module:
                rel = node.module.replace(".", "/") + ".py"
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        rel, alias.name)
        # function-local from-imports (the repo defers heavy imports)
        for fn in list(self.functions.values()):
            for node in ast.walk(fn):
                if isinstance(node, ast.ImportFrom) and node.module:
                    rel = node.module.replace(".", "/") + ".py"
                    for alias in node.names:
                        self.imports.setdefault(
                            alias.asname or alias.name, (rel, alias.name))

    @staticmethod
    def _find_jitted_attrs(class_node):
        """self attributes assigned a jax.jit/pjit result anywhere in
        the class — calls through them produce device values."""
        attrs = set()
        for node in ast.walk(class_node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _is_jit_call(node.value)):
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    attrs.add(t.attr)
        return attrs


@register
class HotPathSync(Rule):
    name = "hot-path-sync"
    help = ("block_until_ready / jax.device_get / .item() / np.asarray-"
            "on-device reachable from ServingEngine.submit/step or the "
            "Trainer step loop")

    DEFAULT_MODULES = (
        "paddle_tpu/serving/engine.py",
        "paddle_tpu/static/trainer.py",
        "paddle_tpu/observability/telemetry.py",
        "paddle_tpu/observability/watchdog.py",
        "paddle_tpu/data/loader.py",
    )
    DEFAULT_ROOTS = (
        ("paddle_tpu/serving/engine.py", "ServingEngine.submit"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.step"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.drain"),
        ("paddle_tpu/static/trainer.py", "Trainer.train"),
    )

    def __init__(self, modules=None, roots=None):
        self.module_paths = tuple(modules or self.DEFAULT_MODULES)
        self.roots = tuple(roots or self.DEFAULT_ROOTS)

    # --- call graph ---

    def _index(self, ctx):
        mods = {}
        for rel in self.module_paths:
            sf = ctx.file(rel)
            if sf is not None and sf.tree is not None:
                mods[rel] = _Module(sf)
        method_owner = {}   # method name -> [(relpath, qualname)]
        for rel, mod in mods.items():
            for cls, methods in mod.classes.items():
                for m, qn in methods.items():
                    method_owner.setdefault(m, []).append((rel, qn))
        return mods, method_owner

    def _edges(self, mods, method_owner, rel, qualname):
        """(relpath, qualname) call targets of one function body."""
        mod = mods[rel]
        fn = mod.functions.get(qualname)
        if fn is None:
            return
        cls = qualname.split(".")[0] if "." in qualname else None
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name):
                if f.id in mod.functions:
                    yield rel, f.id
                elif f.id in mod.imports:
                    tgt_rel, tgt_name = mod.imports[f.id]
                    tgt = mods.get(tgt_rel)
                    if tgt is not None and tgt_name in tgt.functions:
                        yield tgt_rel, tgt_name
            elif isinstance(f, ast.Attribute):
                recv = f.value
                if (isinstance(recv, ast.Name) and recv.id == "self"
                        and cls is not None):
                    qn = mod.classes.get(cls, {}).get(f.attr)
                    if qn is not None:
                        yield rel, qn
                else:
                    owners = method_owner.get(f.attr, [])
                    if len(owners) == 1:
                        yield owners[0]

    # --- device-value taint + sync detection inside one function ---

    @staticmethod
    def _mentions(node, names):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in names:
                return True
        return False

    def _device_names(self, mod, qualname, fn):
        """Local names bound (possibly via unpack) to results of jitted
        callables: self.<jitted attr>(...), a local jax.jit(...) value,
        or an expression that mentions an already-tainted name."""
        cls = qualname.split(".")[0] if "." in qualname else None
        jitted_attrs = mod.jitted_attrs.get(cls, set())
        local_jits = set()
        tainted = set()

        def _device_call(call):
            f = call.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self" and f.attr in jitted_attrs):
                return True
            return isinstance(f, ast.Name) and f.id in local_jits

        for node in ast.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = getattr(node, "value", None)
            if value is None:
                continue
            targets = assign_name_targets(node)
            if isinstance(value, ast.Call) and _is_jit_call(value):
                local_jits.update(targets)
                continue
            taint = ((isinstance(value, ast.Call) and _device_call(value))
                     or self._mentions(value, tainted))
            if not taint:
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Call) and _device_call(sub):
                        taint = True
                        break
            if taint:
                tainted.update(targets)
        return tainted

    def _sync_findings(self, mod, rel, qualname, root_desc):
        fn = mod.functions.get(qualname)
        if fn is None:
            return
        device = self._device_names(mod, qualname, fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = call_name(node)
            if isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
                yield Finding(
                    self.name, rel, node.lineno,
                    f".block_until_ready() in {qualname} — device sync "
                    f"reachable from {root_desc}")
            elif name in ("jax.device_get", "device_get"):
                yield Finding(
                    self.name, rel, node.lineno,
                    f"jax.device_get in {qualname} — device fetch "
                    f"reachable from {root_desc}")
            elif (isinstance(f, ast.Attribute) and f.attr == "item"
                  and not node.args and not node.keywords):
                yield Finding(
                    self.name, rel, node.lineno,
                    f".item() in {qualname} — scalar device fetch "
                    f"reachable from {root_desc}")
            elif (name is not None and "." in name
                  and name.split(".")[0] in _NP_ROOTS
                  and name.split(".")[-1] in ("asarray", "array")
                  and any(self._mentions(a, device) for a in node.args)):
                yield Finding(
                    self.name, rel, node.lineno,
                    f"{name} over a jitted-call result in {qualname} — "
                    f"host sync reachable from {root_desc}")

    def check(self, ctx):
        mods, method_owner = self._index(ctx)
        seen = set()
        queue = []
        for rel, qn in self.roots:
            mod = mods.get(rel)
            if mod is None or qn not in mod.functions:
                yield Finding(
                    self.name, rel, 1,
                    f"hot-path root {qn!r} not found — the rule's root "
                    "list rotted; update HotPathSync.DEFAULT_ROOTS")
                continue
            queue.append((rel, qn, qn))
            seen.add((rel, qn))
        while queue:
            rel, qn, root = queue.pop()
            yield from self._sync_findings(mods[rel], rel, qn, root)
            for tgt in self._edges(mods, method_owner, rel, qn):
                if tgt not in seen:
                    seen.add(tgt)
                    queue.append((tgt[0], tgt[1], root))
