"""hot-path-sync: no device synchronization reachable from the serving
or training hot loops.

The "no sync on the hot path" invariant was previously enforced only
dynamically, by flush-spy tests covering two call sites. This rule makes
it static and whole-tree: build the call graph over the hot-path module
set, walk everything reachable from ``ServingEngine.submit/step/drain``
and ``Trainer.train``, and flag the synchronizing primitives —
``.block_until_ready()``, ``jax.device_get(...)``, ``.item()``, and
``np.asarray``/``np.array`` applied to a *device* value (a result of a
``jax.jit``-built callable, tracked by a light per-function taint pass;
``np.asarray`` over host lists/prompts is staging, not syncing, and is
deliberately not flagged).

Deliberate syncs (the scheduler consuming this step's sampled tokens,
telemetry's trailing loss fetch) stay in the tree under
``# graft-lint: disable=hot-path-sync (<why>)`` — the rule's job is to
make every *new* sync a reviewed decision, not to pretend zero exist.

Call resolution (shared with the concurrency rules via
``rules/callgraph.py``), in order: ``self.m()`` to the same class; bare
``f()`` to the module (or a ``from paddle_tpu.x import f`` target
inside the module set); ``obj.m()`` to ``Cls.m`` when exactly one
analyzed class defines ``m`` (ambiguous names are skipped, never
guessed). Nested defs are analyzed as part of their enclosing function.
"""

import ast

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules import callgraph
from paddle_tpu.analysis.rules._common import (assign_name_targets,
                                               call_name)

_NP_ROOTS = {"np", "numpy"}


@register
class HotPathSync(Rule):
    name = "hot-path-sync"
    help = ("block_until_ready / jax.device_get / .item() / np.asarray-"
            "on-device reachable from ServingEngine.submit/step or the "
            "Trainer step loop")

    DEFAULT_MODULES = (
        "paddle_tpu/serving/engine.py",
        "paddle_tpu/static/trainer.py",
        "paddle_tpu/static/guardian.py",
        "paddle_tpu/observability/telemetry.py",
        "paddle_tpu/observability/watchdog.py",
        "paddle_tpu/observability/trace.py",
        "paddle_tpu/observability/flight.py",
        "paddle_tpu/data/loader.py",
    )
    DEFAULT_ROOTS = (
        ("paddle_tpu/serving/engine.py", "ServingEngine.submit"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.step"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.drain"),
        ("paddle_tpu/static/trainer.py", "Trainer.train"),
    )

    def __init__(self, modules=None, roots=None):
        self.module_paths = tuple(modules or self.DEFAULT_MODULES)
        self.roots = tuple(roots or self.DEFAULT_ROOTS)

    # --- call graph (built by rules/callgraph.py, PR 8 semantics) ---

    def _index(self, ctx):
        return callgraph.build_index(ctx, self.module_paths)

    def _edges(self, mods, method_owner, rel, qualname):
        """(relpath, qualname) call targets of one function body."""
        yield from callgraph.call_edges(mods, method_owner, rel, qualname)

    # --- device-value taint + sync detection inside one function ---

    @staticmethod
    def _mentions(node, names):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in names:
                return True
        return False

    def _device_names(self, mod, qualname, fn):
        """Local names bound (possibly via unpack) to results of jitted
        callables: self.<jitted attr>(...), a local jax.jit(...) value,
        or an expression that mentions an already-tainted name."""
        cls = qualname.split(".")[0] if "." in qualname else None
        jitted_attrs = mod.jitted_attrs.get(cls, set())
        local_jits = set()
        tainted = set()

        def _device_call(call):
            f = call.func
            if (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "self" and f.attr in jitted_attrs):
                return True
            return isinstance(f, ast.Name) and f.id in local_jits

        for node in ast.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = getattr(node, "value", None)
            if value is None:
                continue
            targets = assign_name_targets(node)
            if isinstance(value, ast.Call) and callgraph.is_jit_call(value):
                local_jits.update(targets)
                continue
            taint = ((isinstance(value, ast.Call) and _device_call(value))
                     or self._mentions(value, tainted))
            if not taint:
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Call) and _device_call(sub):
                        taint = True
                        break
            if taint:
                tainted.update(targets)
        return tainted

    def _sync_findings(self, mod, rel, qualname, root_desc):
        fn = mod.functions.get(qualname)
        if fn is None:
            return
        device = self._device_names(mod, qualname, fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = call_name(node)
            if isinstance(f, ast.Attribute) and f.attr == "block_until_ready":
                yield Finding(
                    self.name, rel, node.lineno,
                    f".block_until_ready() in {qualname} — device sync "
                    f"reachable from {root_desc}")
            elif name in ("jax.device_get", "device_get"):
                yield Finding(
                    self.name, rel, node.lineno,
                    f"jax.device_get in {qualname} — device fetch "
                    f"reachable from {root_desc}")
            elif (isinstance(f, ast.Attribute) and f.attr == "item"
                  and not node.args and not node.keywords):
                yield Finding(
                    self.name, rel, node.lineno,
                    f".item() in {qualname} — scalar device fetch "
                    f"reachable from {root_desc}")
            elif (name is not None and "." in name
                  and name.split(".")[0] in _NP_ROOTS
                  and name.split(".")[-1] in ("asarray", "array")
                  and any(self._mentions(a, device) for a in node.args)):
                yield Finding(
                    self.name, rel, node.lineno,
                    f"{name} over a jitted-call result in {qualname} — "
                    f"host sync reachable from {root_desc}")

    def check(self, ctx):
        mods, method_owner = self._index(ctx)
        seen = set()
        queue = []
        for rel, qn in self.roots:
            mod = mods.get(rel)
            if mod is None or qn not in mod.functions:
                yield Finding(
                    self.name, rel, 1,
                    f"hot-path root {qn!r} not found — the rule's root "
                    "list rotted; update HotPathSync.DEFAULT_ROOTS")
                continue
            queue.append((rel, qn, qn))
            seen.add((rel, qn))
        while queue:
            rel, qn, root = queue.pop()
            yield from self._sync_findings(mods[rel], rel, qn, root)
            for tgt in self._edges(mods, method_owner, rel, qn):
                if tgt not in seen:
                    seen.add(tgt)
                    queue.append((tgt[0], tgt[1], root))
