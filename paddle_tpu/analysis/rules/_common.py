"""Shared AST helpers for the rule modules (stdlib-only)."""

import ast


def dotted_name(node):
    """'jax.lax.scan' for Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call):
    """Dotted name of a Call's func, else None."""
    return dotted_name(call.func)


def str_arg(call, index=0):
    """The literal str at positional ``index`` of a Call, else None."""
    if len(call.args) > index:
        a = call.args[index]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return None


def walk_calls(tree):
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def assign_name_targets(node):
    """Plain Name targets of an Assign/AnnAssign/For/withitem binding,
    flattening tuple/list unpacks. Attribute/Subscript targets are
    dropped (we only track local-name dataflow)."""
    out = []

    def _collect(t):
        if isinstance(t, ast.Name):
            out.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                _collect(e)
        elif isinstance(t, ast.Starred):
            _collect(t.value)

    if isinstance(node, ast.Assign):
        for t in node.targets:
            _collect(t)
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        _collect(node.target)
    return out
