"""unguarded-shared-state: every access to a ``# graft-guard:``-ed
attribute reachable from a thread entry point holds the declared lock.

The serving tree is genuinely concurrent — FleetRouter clients submit
from their own threads while a round thread steps, the ThreadingHTTP
metrics exporter scrapes registries, HeartBeatMonitor runs a daemon
loop, and watchdog/anomaly callbacks re-enter the engine. The locking
discipline for all of that is declared with ``graft-guard``
annotations (see rules/callgraph.py for the three declaration forms)
and this rule makes the declaration enforceable: BFS the call graph
from every thread entry point — explicit client-facing roots plus
statically discovered ``Thread(target=...)`` registrations, ``run()``
overrides, ``do_*`` HTTP handlers, and callback keywords — carrying
the set of locks held across each call edge, and flag any guarded
attribute touched at a site where its lock is not held.

Lock identity is class-qualified ((module, class, attr)), so
FleetRouter._lock never satisfies a ServingEngine guard just because
both are spelled ``self._lock``. ``__init__`` bodies are exempt: the
constructing thread owns the object before it is published. Nested
defs are only analyzed when an edge actually reaches them
(Thread targets, resolved bare calls) — with the locks held at *their*
entry, not their lexical parent's.
"""

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules import callgraph


@register
class UnguardedSharedState(Rule):
    name = "unguarded-shared-state"
    help = ("graft-guard'ed attribute accessed outside its declared "
            "lock on a path reachable from a thread entry point")

    DEFAULT_MODULES = (
        "paddle_tpu/serving/fleet.py",
        "paddle_tpu/serving/engine.py",
        "paddle_tpu/observability/metrics.py",
        "paddle_tpu/observability/watchdog.py",
        "paddle_tpu/observability/exporter.py",
        "paddle_tpu/parallel/heartbeat.py",
    )
    # the client-raced public surfaces: callers are free to invoke
    # these from any thread, concurrently with the round/scraper
    # threads the entry-point discovery finds on its own
    DEFAULT_ROOTS = (
        ("paddle_tpu/serving/fleet.py", "FleetRouter.submit"),
        ("paddle_tpu/serving/fleet.py", "FleetRouter.cancel"),
        ("paddle_tpu/serving/fleet.py", "FleetRouter.step"),
        ("paddle_tpu/serving/fleet.py", "FleetRouter.drain"),
        ("paddle_tpu/serving/fleet.py", "FleetRouter.shed_pending"),
        ("paddle_tpu/serving/fleet.py", "FleetRouter.telemetry"),
        ("paddle_tpu/serving/fleet.py", "FleetRouter.goodput"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.submit"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.adopt"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.cancel"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.step"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.drain"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.export_inflight"),
        ("paddle_tpu/serving/engine.py", "ServingEngine.shed_queued"),
    )

    def __init__(self, modules=None, roots=None):
        self.module_paths = tuple(modules or self.DEFAULT_MODULES)
        self.roots = tuple(roots if roots is not None
                           else self.DEFAULT_ROOTS)

    def check(self, ctx):
        mods, method_owner = callgraph.build_index(ctx, self.module_paths)
        guards = callgraph.build_guards(mods)
        roots = []
        for rel, qn in self.roots:
            mod = mods.get(rel)
            if mod is None or qn not in mod.functions:
                yield Finding(
                    self.name, rel, 1,
                    f"shared-state root {qn!r} not found — the rule's "
                    "root list rotted; update "
                    "UnguardedSharedState.DEFAULT_ROOTS")
                continue
            roots.append((rel, qn, f"client-facing {qn}"))
        roots.extend(callgraph.entry_points(mods, method_owner))
        if not guards:
            return

        scans = {}

        def scan(rel, qn):
            key = (rel, qn)
            if key not in scans:
                scans[key] = callgraph.scan_function(mods, rel, qn)
            return scans[key]

        findings = {}
        seen = set()
        queue = []
        for rel, qn, desc in roots:
            state = (rel, qn, frozenset())
            if state not in seen:
                seen.add(state)
                queue.append((rel, qn, frozenset(), desc))
        while queue:
            rel, qn, held, desc = queue.pop()
            sc = scan(rel, qn)
            mod = mods[rel]
            if sc.cls is not None and not qn.endswith("__init__"):
                for node, site_locks in sc.accesses:
                    lock = guards.get((rel, sc.cls, node.attr))
                    if lock is None or lock in held or lock in site_locks:
                        continue
                    fkey = (rel, node.lineno, node.attr)
                    if fkey not in findings:
                        findings[fkey] = Finding(
                            self.name, rel, node.lineno,
                            f"self.{node.attr} (graft-guard: "
                            f"{callgraph.lock_label(lock)}) accessed "
                            f"without its lock in {qn} — reachable "
                            f"from {desc}")
            for call, site_locks in sc.calls:
                tgt = callgraph.resolve_call(
                    mods, method_owner, mod, qn, call,
                    resolve_nested=True, resolve_module_aliases=True)
                if tgt is None:
                    continue
                nxt = (tgt[0], tgt[1], held | site_locks)
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append((tgt[0], tgt[1], held | site_locks,
                                  desc))
        for key in sorted(findings):
            yield findings[key]
