"""graft-lint rule set — importing this package registers every rule.

Each module holds one rule (plus its helpers); keep them independent so
a fixture test can instantiate a single rule against a planted tree.
"""

from paddle_tpu.analysis.rules import (  # noqa: F401
    catalog_drift, event_drift, fault_point_drift, flag_drift,
    hot_path_sync, lock_order, no_committed_logs, raw_pallas_call,
    stale_suppression, thread_unsafe_publish, tracer_leak,
    unguarded_shared_state)
