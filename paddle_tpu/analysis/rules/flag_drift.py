"""flag-drift: core/flags.py, the README flag table, and every literal
flag read must agree — in all directions.

Three checks:

* every ``define_flag("name", ...)`` appears in a README flag table
  (a markdown table whose header's first cell is ``Flag``);
* every backticked lowercase token in a flag-table row's first cell
  names a defined flag (so the README can't advertise a knob that
  doesn't exist — spell non-flag knobs like ``cfg.scan_layers`` with
  their dotted owner to keep them out of the flag namespace);
* every literal ``get_flag("x")`` call and every literal key of a
  ``set_flags({...})`` dict names a defined flag.

Flags resolved dynamically (``get_flag(name)``) are out of static
reach and deliberately skipped.
"""

import ast
import re

from paddle_tpu.analysis.lint import Finding, Rule, register
from paddle_tpu.analysis.rules._common import (call_name, str_arg,
                                               walk_calls)

# a backticked flag token: lowercase snake_case only, so env spellings
# (PT_FLAGS_x) and dotted config knobs (cfg.scan_layers) never register
_FLAG_TOKEN = re.compile(r"`([a-z][a-z0-9_]*)`")


def _table_rows(lines):
    """(lineno, first_cell) for data rows of every markdown table whose
    header row's first cell is exactly 'Flag'."""
    in_flag_table = False
    for i, line in enumerate(lines, 1):
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_flag_table = False
            continue
        cells = [c.strip() for c in stripped.strip("|").split("|")]
        if not cells:
            continue
        if cells[0] == "Flag":
            in_flag_table = True
            continue
        if set(cells[0]) <= {"-", ":", " "}:
            continue                      # the |---|---| separator
        if in_flag_table:
            yield i, cells[0]


@register
class FlagDrift(Rule):
    name = "flag-drift"
    help = ("core/flags.py definitions, the README flag table, and "
            "literal get_flag()/set_flags() sites must agree both ways")

    DEFAULT_FLAGS_PATH = "paddle_tpu/core/flags.py"
    DEFAULT_README_PATH = "README.md"
    DEFAULT_SCOPE = ("paddle_tpu/**/*.py", "paddle_tpu/*.py", "bench.py",
                     "tools/*.py", "examples/*.py", "tests/*.py")

    def __init__(self, flags_path=None, readme_path=None, scope=None):
        self.flags_path = flags_path or self.DEFAULT_FLAGS_PATH
        self.readme_path = readme_path or self.DEFAULT_README_PATH
        self.scope = tuple(scope or self.DEFAULT_SCOPE)

    def _defined(self, ctx):
        """{flag name: lineno} of define_flag literals in flags.py."""
        sf = ctx.file(self.flags_path)
        if sf is None or sf.tree is None:
            return None, None
        defined = {}
        for call in walk_calls(sf.tree):
            if call_name(call) in ("define_flag", "flags.define_flag"):
                name = str_arg(call)
                if name is not None:
                    defined[name] = call.lineno
        return defined, sf

    def _documented(self, ctx):
        sf = ctx.file(self.readme_path)
        if sf is None:
            return {}, None
        documented = {}
        for lineno, cell in _table_rows(sf.lines):
            for tok in _FLAG_TOKEN.findall(cell):
                documented.setdefault(tok, lineno)
        return documented, sf

    def check(self, ctx):
        defined, flags_sf = self._defined(ctx)
        if defined is None:
            yield Finding(self.name, self.flags_path, 1,
                          f"flag registry {self.flags_path} missing or "
                          "unparseable — the rule's anchor rotted")
            return
        documented, readme_sf = self._documented(ctx)
        if readme_sf is None:
            yield Finding(self.name, self.readme_path, 1,
                          f"{self.readme_path} not found — flag table "
                          "unavailable")
            return

        for flag, lineno in sorted(defined.items()):
            if flag not in documented:
                yield Finding(
                    self.name, flags_sf.relpath, lineno,
                    f"flag {flag!r} is defined but missing from the "
                    f"{self.readme_path} flag table")
        for flag, lineno in sorted(documented.items()):
            if flag not in defined:
                yield Finding(
                    self.name, readme_sf.relpath, lineno,
                    f"flag table documents {flag!r} but core/flags.py "
                    "defines no such flag (non-flag knobs belong "
                    "outside the `Flag` column's bare-name namespace)")

        for sf in ctx.glob(*self.scope):
            if sf.tree is None or sf.relpath == self.flags_path:
                continue
            for call in walk_calls(sf.tree):
                cn = call_name(call)
                if cn is not None and cn.split(".")[-1] == "get_flag":
                    name = str_arg(call)
                    if name is not None and name not in defined:
                        yield Finding(
                            self.name, sf.relpath, call.lineno,
                            f"get_flag({name!r}) reads an undefined "
                            "flag")
                elif cn is not None and cn.split(".")[-1] == "set_flags":
                    if call.args and isinstance(call.args[0], ast.Dict):
                        for k in call.args[0].keys:
                            if (isinstance(k, ast.Constant)
                                    and isinstance(k.value, str)
                                    and k.value not in defined):
                                yield Finding(
                                    self.name, sf.relpath, k.lineno,
                                    f"set_flags key {k.value!r} is not "
                                    "a defined flag")
