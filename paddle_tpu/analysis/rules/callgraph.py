"""Shared call-graph index for the cross-function rules.

``hot-path-sync`` (PR 8) grew a small call-graph: a per-module
function/class/import index, a method-owner table, and a conservative
call resolver (``self.m()`` to the same class; bare ``f()`` to the
module or a from-import target; ``obj.m()`` to ``Cls.m`` when exactly
one analyzed class defines ``m`` — ambiguous names are skipped, never
guessed). The concurrency rules of v2 (``unguarded-shared-state``,
``lock-order``, ``thread-unsafe-publish``) need the same machinery plus
three extensions, so it lives here now:

- nested ``def``s are indexed with dotted qualnames
  (``Cls.method.inner``) and bare-name calls resolve through the
  enclosing-scope chain — ``threading.Thread(target=loop)`` where
  ``loop`` is defined inside ``start()`` is the motivating case
  (parallel/heartbeat.py does exactly this);
- module-alias imports (``from paddle_tpu.observability import metrics
  as _metrics``) resolve ``_metrics.counter(...)`` into the aliased
  module when it is part of the analyzed set;
- thread entry-point discovery: ``Thread(target=...)`` registrations,
  ``run()`` on Thread subclasses, ``do_*`` on HTTP handler classes, and
  callback keywords (``action=``, ``on_stall=``, ``anomaly_sink=``)
  whose value resolves statically.

Both extensions are opt-in flags on ``call_edges`` so hot-path-sync
keeps its PR 8 edge set byte-for-byte.

The lock vocabulary lives here too: ``# graft-guard: <lockattr>``
annotations (inline on the assignment, in a class docstring as
``graft-guard: <attr> by <lockattr>``, or in a module-level
``GUARDED_BY`` dict literal) parse into a per-module guard table, and
``with self._lock:`` acquisitions parse into class-qualified lock ids —
``(relpath, class, "self._lock")`` — so FleetRouter._lock and
ServingEngine._lock never collide just because both spell it ``_lock``.
"""

import ast
import re

from paddle_tpu.analysis.rules._common import call_name

_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}
_PARTIAL_NAMES = {"functools.partial", "partial"}

_THREAD_NAMES = {"threading.Thread", "Thread"}
CALLBACK_KWARGS = ("action", "on_stall", "anomaly_sink")

_LOCKY = re.compile(r"lock|mutex", re.I)
GUARD_RE = re.compile(
    r"#[^#\n]*graft-guard:\s*(self\.[A-Za-z_]\w*|[A-Za-z_]\w*)")
GUARD_DOC_RE = re.compile(
    r"graft-guard:\s*([A-Za-z_]\w*)\s+by\s+(self\.[A-Za-z_]\w*|[A-Za-z_]\w*)")


def is_jit_call(call):
    name = call_name(call)
    if name in _JIT_NAMES:
        return True
    if name in _PARTIAL_NAMES and call.args:
        inner = call.args[0]
        return (isinstance(inner, (ast.Attribute, ast.Name))
                and (ast.unparse(inner) if hasattr(ast, "unparse")
                     else "") in _JIT_NAMES)
    return False


class ModuleIndex:
    """Function/class/import index of one analyzed source file."""

    def __init__(self, sf):
        self.sf = sf
        self.relpath = sf.relpath
        self.functions = {}       # qualname -> FunctionDef (incl. nested)
        self.classes = {}         # class name -> {method name: qualname}
        self.class_nodes = {}     # class name -> ClassDef
        self.class_bases = {}     # class name -> (dotted base names,)
        self.jitted_attrs = {}    # class name -> {self attrs bound to jit}
        self.imports = {}         # local name -> (module relpath, name)
        self.module_aliases = {}  # local name -> module relpath
        tree = sf.tree
        if tree is None:
            return
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
                self._index_nested(node.name, node)
            elif isinstance(node, ast.ClassDef):
                methods = {}
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        qn = f"{node.name}.{item.name}"
                        self.functions[qn] = item
                        methods[item.name] = qn
                        self._index_nested(qn, item)
                self.classes[node.name] = methods
                self.class_nodes[node.name] = node
                self.class_bases[node.name] = tuple(
                    self._dotted(b) for b in node.bases)
                self.jitted_attrs[node.name] = self._find_jitted_attrs(node)
            elif isinstance(node, ast.ImportFrom) and node.module:
                rel = node.module.replace(".", "/") + ".py"
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.imports[local] = (rel, alias.name)
                    self.module_aliases[local] = (
                        f"{node.module}.{alias.name}".replace(".", "/")
                        + ".py")
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.module_aliases[alias.asname] = (
                            alias.name.replace(".", "/") + ".py")
        # function-local from-imports (the repo defers heavy imports)
        for fn in list(self.functions.values()):
            for node in ast.walk(fn):
                if isinstance(node, ast.ImportFrom) and node.module:
                    rel = node.module.replace(".", "/") + ".py"
                    for alias in node.names:
                        self.imports.setdefault(
                            alias.asname or alias.name, (rel, alias.name))

    def _index_nested(self, qual, fn):
        for child in ast.iter_child_nodes(fn):
            self._index_nested_in(qual, child)

    def _index_nested_in(self, qual, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qn = f"{qual}.{node.name}"
            self.functions[qn] = node
            self._index_nested(qn, node)
        elif not isinstance(node, (ast.ClassDef, ast.Lambda)):
            for child in ast.iter_child_nodes(node):
                self._index_nested_in(qual, child)

    @staticmethod
    def _dotted(expr):
        try:
            return ast.unparse(expr)
        except Exception:
            return ""

    @staticmethod
    def _find_jitted_attrs(class_node):
        """self attributes assigned a jax.jit/pjit result anywhere in
        the class — calls through them produce device values."""
        attrs = set()
        for node in ast.walk(class_node):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and is_jit_call(node.value)):
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    attrs.add(t.attr)
        return attrs


def build_index(ctx, paths):
    """(mods, method_owner) over one module set.

    mods: relpath -> ModuleIndex. method_owner: method name ->
    [(relpath, qualname)] across every analyzed class — the ``obj.m()``
    resolver fires only when the list has exactly one entry.
    """
    mods = {}
    for rel in paths:
        sf = ctx.file(rel)
        if sf is not None and sf.tree is not None:
            mods[rel] = ModuleIndex(sf)
    method_owner = {}
    for rel, mod in mods.items():
        for cls, methods in mod.classes.items():
            for m, qn in methods.items():
                method_owner.setdefault(m, []).append((rel, qn))
    return mods, method_owner


def _scope_prefixes(mod, qualname):
    """Enclosing function scopes of a qualname, innermost first —
    skipping the bare class level (a class body is not a call scope)."""
    parts = qualname.split(".")
    stop = 1 if parts and parts[0] in mod.classes else 0
    for i in range(len(parts), stop, -1):
        yield ".".join(parts[:i])


def resolve_bare(mods, mod, qualname, name,
                 resolve_nested=False):
    """A bare-name call/reference inside (mod, qualname) ->
    (relpath, qualname) or None."""
    if resolve_nested:
        for prefix in _scope_prefixes(mod, qualname):
            qn = f"{prefix}.{name}"
            if qn in mod.functions:
                return mod.relpath, qn
    if name in mod.functions:
        return mod.relpath, name
    if name in mod.imports:
        tgt_rel, tgt_name = mod.imports[name]
        tgt = mods.get(tgt_rel)
        if tgt is not None and tgt_name in tgt.functions:
            return tgt_rel, tgt_name
    return None


def resolve_callable(mods, method_owner, mod, qualname, expr,
                     resolve_nested=True):
    """A callable expression (Thread target, callback kwarg value) ->
    (relpath, qualname) or None. Handles bare names (through the
    nested-scope chain) and ``self.method``."""
    cls = qualname.split(".")[0] if "." in qualname else None
    if isinstance(expr, ast.Name):
        return resolve_bare(mods, mod, qualname, expr.id,
                            resolve_nested=resolve_nested)
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)):
        if expr.value.id == "self" and cls is not None:
            qn = mod.classes.get(cls, {}).get(expr.attr)
            if qn is not None:
                return mod.relpath, qn
        owners = method_owner.get(expr.attr, [])
        if len(owners) == 1:
            return owners[0]
    return None


def resolve_call(mods, method_owner, mod, qualname, call,
                 resolve_nested=False, resolve_module_aliases=False):
    """One Call node inside (mod, qualname) -> (relpath, qualname) or
    None. PR 8 semantics by default; ``resolve_nested`` adds the
    enclosing-scope chain for bare names, ``resolve_module_aliases``
    adds ``alias.f()`` into analyzed modules. A ``self.m()`` whose
    method is unknown resolves to nothing — a dynamically-bound self
    attribute never falls through to the owner table."""
    f = call.func
    cls = qualname.split(".")[0] if "." in qualname else None
    if isinstance(f, ast.Name):
        return resolve_bare(mods, mod, qualname, f.id,
                            resolve_nested=resolve_nested)
    if isinstance(f, ast.Attribute):
        recv = f.value
        if (isinstance(recv, ast.Name) and recv.id == "self"
                and cls is not None):
            qn = mod.classes.get(cls, {}).get(f.attr)
            if qn is not None:
                return mod.relpath, qn
            return None
        if (resolve_module_aliases and isinstance(recv, ast.Name)
                and recv.id in mod.module_aliases):
            tgt_rel = mod.module_aliases[recv.id]
            tgt = mods.get(tgt_rel)
            if tgt is not None and f.attr in tgt.functions:
                return tgt_rel, f.attr
        owners = method_owner.get(f.attr, [])
        if len(owners) == 1:
            return owners[0]
    return None


def call_edges(mods, method_owner, rel, qualname,
               resolve_nested=False, resolve_module_aliases=False):
    """(relpath, qualname) call targets of one function body (full
    walk, nested defs included — PR 8 semantics)."""
    mod = mods[rel]
    fn = mod.functions.get(qualname)
    if fn is None:
        return
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            tgt = resolve_call(
                mods, method_owner, mod, qualname, node,
                resolve_nested=resolve_nested,
                resolve_module_aliases=resolve_module_aliases)
            if tgt is not None:
                yield tgt


# --- thread entry points ---


def entry_points(mods, method_owner):
    """Statically-discoverable thread entry points across a module set:
    [(relpath, qualname, description)].

    - ``threading.Thread(target=X)`` where X resolves (nested def,
      ``self.method``, module function);
    - ``run()`` overrides on classes whose base name ends in Thread;
    - ``do_*`` methods on classes whose base mentions RequestHandler
      (each request runs on a fresh server thread);
    - callback keywords (``action=``, ``on_stall=``, ``anomaly_sink=``)
      whose value resolves — these are invoked from watchdog/heartbeat/
      engine contexts the registering code does not control.
    """
    out = []
    seen = set()

    def add(tgt, desc):
        if tgt is not None and tgt not in seen:
            seen.add(tgt)
            out.append((tgt[0], tgt[1], desc))

    for rel, mod in mods.items():
        for cls, bases in mod.class_bases.items():
            base_tail = " ".join(b.rsplit(".", 1)[-1] for b in bases)
            if "Thread" in base_tail:
                qn = mod.classes[cls].get("run")
                if qn:
                    add((rel, qn), f"{cls}.run (Thread subclass)")
            if "RequestHandler" in base_tail:
                for m, qn in mod.classes[cls].items():
                    if m.startswith("do_"):
                        add((rel, qn),
                            f"{cls}.{m} (HTTP handler thread)")
        for qualname, fn in list(mod.functions.items()):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                is_thread = call_name(node) in _THREAD_NAMES
                for kw in node.keywords:
                    if kw.arg == "target" and is_thread:
                        add(resolve_callable(mods, method_owner, mod,
                                             qualname, kw.value),
                            f"Thread(target=...) registered in "
                            f"{qualname}")
                    elif kw.arg in CALLBACK_KWARGS:
                        add(resolve_callable(mods, method_owner, mod,
                                             qualname, kw.value),
                            f"{kw.arg}= callback registered in "
                            f"{qualname}")
    return out


# --- guard tables and lock identities ---


def lock_id(expr, rel, cls):
    """The lock identity acquired by a ``with`` context expression, or
    None when the expression is not recognizably a lock. Identities are
    class-qualified: (relpath, class, source text)."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self" and _LOCKY.search(expr.attr)):
        return (rel, cls or "", "self." + expr.attr)
    if isinstance(expr, ast.Name) and _LOCKY.search(expr.id):
        return (rel, "", expr.id)
    return None


def with_lock_ids(with_node, rel, cls):
    out = []
    for item in with_node.items:
        lid = lock_id(item.context_expr, rel, cls)
        if lid is not None:
            out.append(lid)
    return out


def lock_label(lid):
    rel, cls, name = lid
    return f"{cls}.{name[len('self.'):]}" if cls else name


def _normalize_lock(value, rel, cls):
    value = value.strip()
    if value.startswith("self."):
        return (rel, cls or "", value)
    return (rel, "", value)


def guard_table(mod):
    """{(class name, attr): lock id} for one module, merged from the
    three declaration forms (inline comment wins on conflict)."""
    guards = {}
    lines = mod.sf.lines
    # module-level GUARDED_BY table
    if mod.sf.tree is not None:
        for node in mod.sf.tree.body:
            if not (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "GUARDED_BY"
                            for t in node.targets)
                    and isinstance(node.value, ast.Dict)):
                continue
            for k, v in zip(node.value.keys, node.value.values):
                if (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)
                        and "." in k.value):
                    cls, attr = k.value.rsplit(".", 1)
                    guards[(cls, attr)] = _normalize_lock(
                        v.value, mod.relpath, cls)
    for cls, node in mod.class_nodes.items():
        # class docstring "graft-guard: <attr> by <lockattr>" lines
        doc = ast.get_docstring(node) or ""
        for m in GUARD_DOC_RE.finditer(doc):
            guards[(cls, m.group(1))] = _normalize_lock(
                m.group(2), mod.relpath, cls)
        # inline "# graft-guard: <lockattr>" on self.<attr> assignments
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            attrs = [t.attr for t in targets
                     if isinstance(t, ast.Attribute)
                     and isinstance(t.value, ast.Name)
                     and t.value.id == "self"]
            if not attrs or sub.lineno > len(lines):
                continue
            # the marker may ride any line of the statement, or a
            # comment line directly above it
            lo = sub.lineno - 1
            hi = min(getattr(sub, "end_lineno", sub.lineno), len(lines))
            cand = lines[lo:hi]
            if lo > 0 and lines[lo - 1].lstrip().startswith("#"):
                cand.append(lines[lo - 1])
            for text in cand:
                m = GUARD_RE.search(text)
                if m:
                    for attr in attrs:
                        guards[(cls, attr)] = _normalize_lock(
                            m.group(1), mod.relpath, cls)
                    break
    return guards


def build_guards(mods):
    """{(relpath, class, attr): lock id} across a module set."""
    out = {}
    for rel, mod in mods.items():
        for (cls, attr), lid in guard_table(mod).items():
            out[(rel, cls, attr)] = lid
    return out


# --- lock-aware single-function scan ---

_MUTATORS = frozenset((
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "setdefault",
    "update"))
_LAZY_WRAPPERS = frozenset(("enumerate", "zip", "reversed", "filter",
                            "map", "iter"))
_VIEW_METHODS = frozenset(("items", "values", "keys"))


def _self_attr(expr):
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return expr.attr
    return None


def iterated_self_attr(expr):
    """The self attribute an iteration expression walks *lazily* —
    ``self.A``, ``self.A.items()/values()/keys()``, or either wrapped
    in a lazy iterator (enumerate/zip/...). None when the expression
    snapshots first (list()/sorted()/dict()/...) or is not a self
    attribute."""
    attr = _self_attr(expr)
    if attr is not None:
        return attr
    if isinstance(expr, ast.Call):
        f = expr.func
        if (isinstance(f, ast.Attribute) and f.attr in _VIEW_METHODS
                and not expr.args and not expr.keywords):
            return _self_attr(f.value)
        if (isinstance(f, ast.Name) and f.id in _LAZY_WRAPPERS):
            for a in expr.args:
                attr = iterated_self_attr(a)
                if attr is not None:
                    return attr
    return None


class FunctionScan(ast.NodeVisitor):
    """Lock-aware scan of one function body.

    Records, each with the frozenset of lock ids lexically held at the
    site: self-attribute accesses, call sites, lock acquisitions,
    iteration expressions (for/comprehension iterables), and container
    mutations of self attributes. Nested defs and lambdas are NOT
    descended into — they run on whatever thread eventually calls them
    and are reached through their own call-graph edges.
    """

    def __init__(self, rel, cls):
        self.rel = rel
        self.cls = cls
        self._active = []
        self.accesses = []    # (Attribute node, held)
        self.calls = []       # (Call node, held)
        self.acquires = []    # (lock id, held-before, lineno)
        self.iterations = []  # (iter expr, held, lineno)
        self.mutations = []   # (attr, held, lineno)

    def _held(self):
        return frozenset(self._active)

    # lock scopes
    def visit_With(self, node):
        added = 0
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
            lid = lock_id(item.context_expr, self.rel, self.cls)
            if lid is not None:
                self.acquires.append((lid, self._held(), node.lineno))
                self._active.append(lid)
                added += 1
        for stmt in node.body:
            self.visit(stmt)
        if added:
            del self._active[-added:]

    visit_AsyncWith = visit_With

    # nested defs run on the caller-of-the-callback's thread
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        pass

    # sites
    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            self.accesses.append((node, self._held()))
        self.generic_visit(node)

    def visit_Call(self, node):
        self.calls.append((node, self._held()))
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _self_attr(f.value)
            if attr is not None:
                self.mutations.append((attr, self._held(), node.lineno))
        self.generic_visit(node)

    def visit_For(self, node):
        self.iterations.append((node.iter, self._held(), node.lineno))
        self.generic_visit(node)

    def _visit_comp(self, node):
        for gen in node.generators:
            self.iterations.append((gen.iter, self._held(),
                                    node.lineno))
        self.generic_visit(node)

    visit_ListComp = visit_SetComp = visit_DictComp = \
        visit_GeneratorExp = _visit_comp

    def _mutating_targets(self, targets):
        for t in targets:
            attr = None
            if isinstance(t, ast.Subscript):
                attr = _self_attr(t.value)
            elif isinstance(t, ast.Attribute):
                attr = _self_attr(t)
            if attr is not None:
                self.mutations.append((attr, self._held(), t.lineno))

    def visit_Assign(self, node):
        self._mutating_targets(node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._mutating_targets([node.target])
        self.generic_visit(node)

    def visit_Delete(self, node):
        self._mutating_targets(node.targets)
        self.generic_visit(node)


def scan_function(mods, rel, qualname):
    """FunctionScan over one indexed function's body."""
    mod = mods[rel]
    fn = mod.functions[qualname]
    parts = qualname.split(".")
    cls = parts[0] if parts[0] in mod.classes else None
    scan = FunctionScan(rel, cls)
    for stmt in fn.body:
        scan.visit(stmt)
    return scan
