"""graft-lint AST layer: rule framework, registry, suppressions.

A rule is a class with a ``name``, a ``help`` line, and a
``check(ctx) -> Iterable[Finding]`` method; ``@register`` puts it in the
process-wide registry and ``make_rules()`` instantiates the default set
(importing ``paddle_tpu.analysis.rules`` for its registration side
effects). Rules are *tree*-scoped: they receive one :class:`LintContext`
holding lazily-parsed :class:`SourceFile` objects for every ``*.py``
under the root, so cross-file rules (call-graph reachability, drift
between a registry and its call sites) are first-class rather than
bolted on.

Suppressions are per line::

    toks = np.asarray(toks_dev)  # graft-lint: disable=hot-path-sync (the scheduler needs this step's tokens)

The parenthesized reason is mandatory — a disable comment without one
does not suppress and is itself reported as ``bad-suppression``, so
every silenced finding carries its justification in the diff that
silenced it. Several rules may be named, comma-separated.

Stdlib-only: the CLI (tools/graft_lint.py) runs this layer without
importing jax.
"""

import ast
import dataclasses
import fnmatch
import os
import re

# rule names are kebab-case; the reason group is everything inside the
# trailing parens (may mention rules/files — kept free-form)
SUPPRESS_RE = re.compile(
    r"#\s*graft-lint:\s*disable=([A-Za-z0-9_,-]+)"
    r"(?:\s*\(([^()]*(?:\([^()]*\)[^()]*)*)\))?")

# paths never scanned: planted-violation fixtures ARE violations
DEFAULT_EXCLUDES = ("tests/fixtures", "__pycache__", ".git",
                    ".pytest_cache", "csrc/build")


@dataclasses.dataclass
class Finding:
    """One lint hit, anchored to a repo-relative path and 1-based line.
    ``severity`` is "error" (build breaker) or "warn" (advisory —
    ``graft_lint.py --fail-on error`` reports it without failing)."""
    rule: str
    path: str
    line: int
    message: str
    severity: str = "error"

    def format(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self):
        return dataclasses.asdict(self)


class SourceFile:
    """Lazily-read, lazily-parsed source file. ``tree`` is None when the
    file does not parse — the syntax error surfaces as its own finding
    via :meth:`LintContext.parse_errors`, and AST rules simply skip the
    file instead of each crashing on it."""

    def __init__(self, root, relpath):
        self.root = root
        self.relpath = relpath.replace(os.sep, "/")
        self.path = os.path.join(root, relpath)
        self._text = None
        self._lines = None
        self._tree = None
        self._parsed = False
        self.syntax_error = None

    @property
    def text(self):
        if self._text is None:
            with open(self.path, encoding="utf-8") as fh:
                self._text = fh.read()
        return self._text

    @property
    def lines(self):
        if self._lines is None:
            self._lines = self.text.splitlines()
        return self._lines

    @property
    def tree(self):
        if not self._parsed:
            self._parsed = True
            try:
                self._tree = ast.parse(self.text, filename=self.relpath)
            except SyntaxError as e:
                self.syntax_error = e
        return self._tree

    def line_text(self, lineno):
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class LintContext:
    """The tree a lint run sees: every ``*.py`` under ``root`` (plus any
    non-Python files a rule asks for via :meth:`file`), minus
    ``excludes`` path fragments."""

    def __init__(self, root, excludes=DEFAULT_EXCLUDES):
        self.root = os.path.abspath(root)
        self.excludes = tuple(excludes)
        self._by_rel = {}
        self.files = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            rel_dir = os.path.relpath(dirpath, self.root)
            rel_dir = "" if rel_dir == "." else rel_dir.replace(os.sep, "/")
            dirnames[:] = sorted(
                d for d in dirnames
                if not self._excluded(f"{rel_dir}/{d}" if rel_dir else d))
            for f in sorted(filenames):
                if not f.endswith(".py"):
                    continue
                rel = f"{rel_dir}/{f}" if rel_dir else f
                if self._excluded(rel):
                    continue
                sf = SourceFile(self.root, rel)
                self.files.append(sf)
                self._by_rel[rel] = sf

    def _excluded(self, rel):
        return any(part in rel for part in self.excludes)

    def file(self, relpath):
        """The SourceFile at ``relpath`` (repo-relative, '/'-separated);
        files outside the initial walk (README.md, a *.py under an
        excluded dir a rule explicitly wants) are admitted on demand."""
        rel = relpath.replace(os.sep, "/")
        sf = self._by_rel.get(rel)
        if sf is None and os.path.isfile(os.path.join(self.root, rel)):
            sf = SourceFile(self.root, rel)
            self._by_rel[rel] = sf
        return sf

    def glob(self, *patterns):
        """Scanned python files whose relpath fnmatches any pattern."""
        return [sf for sf in self.files
                if any(fnmatch.fnmatch(sf.relpath, p) for p in patterns)]

    def parse_errors(self):
        for sf in self.files:
            if sf.tree is None and sf.syntax_error is not None:
                yield Finding(
                    "parse-error", sf.relpath,
                    sf.syntax_error.lineno or 1,
                    f"file does not parse: {sf.syntax_error.msg}")


class Rule:
    """Base class: subclasses set ``name``/``help`` and implement
    ``check``. Constructor kwargs configure paths/roots so the same rule
    instance can run against a planted-violation fixture tree.
    ``severity`` stamps every finding the rule yields (unless the rule
    set one itself)."""

    name = None
    help = ""
    severity = "error"

    def check(self, ctx):
        raise NotImplementedError


_REGISTRY = {}


def register(cls):
    """Class decorator: add a Rule subclass to the default set."""
    if not cls.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    _REGISTRY[cls.name] = cls
    return cls


def _load_default_rules():
    # import for registration side effects; lazy so `import
    # paddle_tpu.analysis.lint` alone stays dependency-free
    from paddle_tpu.analysis import rules  # noqa: F401


def rule_names():
    _load_default_rules()
    return sorted(_REGISTRY)


def rule_help():
    _load_default_rules()
    return {n: _REGISTRY[n].help for n in sorted(_REGISTRY)}


def make_rules(names=None):
    """Instantiate the registered rules (all, or the named subset)."""
    _load_default_rules()
    if names is None:
        names = sorted(_REGISTRY)
    unknown = [n for n in names if n not in _REGISTRY]
    if unknown:
        raise KeyError(f"unknown rules {unknown}; known: {sorted(_REGISTRY)}")
    return [_REGISTRY[n]() for n in names]


def parse_suppressions(line_text):
    """(rules, reason) for the first graft-lint disable comment on the
    line, or None. ``reason`` is '' when the mandatory parenthesized
    justification is missing."""
    m = SUPPRESS_RE.search(line_text)
    if not m:
        return None
    rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
    reason = (m.group(2) or "").strip()
    return rules, reason


def _suppression_findings(ctx):
    """bad-suppression findings: disable comments missing their reason,
    or naming a rule the registry has never heard of."""
    _load_default_rules()
    known = set(_REGISTRY) | {"parse-error", "bad-suppression"}
    for sf in ctx.files:
        for i, line in enumerate(sf.lines, 1):
            sup = parse_suppressions(line)
            if sup is None:
                continue
            rules, reason = sup
            if not reason:
                yield Finding(
                    "bad-suppression", sf.relpath, i,
                    "suppression without a reason — write "
                    "`# graft-lint: disable=<rule> (<why>)`")
            for r in rules:
                if r not in known:
                    yield Finding(
                        "bad-suppression", sf.relpath, i,
                        f"suppression names unknown rule {r!r} "
                        f"(known: {', '.join(sorted(_REGISTRY))})")


# the framework's own sources (and the CLI) show the suppression syntax
# in docstring examples; judging those as live or stale is meaningless
_STALE_EXEMPT = ("paddle_tpu/analysis/", "tools/graft_lint.py")


def _stale_suppression_findings(ctx, ran, used):
    """stale-suppression findings: a reasoned disable comment whose
    named rule RAN this pass but had nothing to swallow on that line —
    the violation it silenced is gone, and the dead comment would mask
    the next real finding. Only rules that actually ran are judged, so
    a ``--rules`` subset pass never flags the others' suppressions."""
    for sf in ctx.files:
        if sf.relpath.startswith(_STALE_EXEMPT):
            continue
        for i, line in enumerate(sf.lines, 1):
            sup = parse_suppressions(line)
            if sup is None or not sup[1]:
                continue
            for r in sup[0]:
                if (r in ran and r in _REGISTRY
                        and (sf.relpath, i, r) not in used):
                    yield Finding(
                        "stale-suppression", sf.relpath, i,
                        f"suppression of {r!r} no longer fires here — "
                        "the silenced violation is gone; delete the "
                        "comment", severity="warn")


def run_lint(ctx, rules=None, paths=None):
    """Run ``rules`` (default: the full registry) over ``ctx``; apply
    per-line suppressions; return findings sorted by location. ``paths``
    (a set of repo-relative paths) post-filters findings for
    --changed-only runs — tree-wide drift rules still SEE the whole
    tree, only the reporting narrows. Suppressions that swallowed
    nothing surface as ``stale-suppression`` findings."""
    if rules is None:
        rules = make_rules()
    findings = list(ctx.parse_errors())
    findings.extend(_suppression_findings(ctx))
    for rule in rules:
        for f in rule.check(ctx):
            if f.severity == "error":
                f.severity = getattr(rule, "severity", "error")
            findings.append(f)
    kept = []
    used = set()   # (path, line, rule) suppressions that swallowed one
    for f in findings:
        sf = ctx.file(f.path)
        if sf is not None and f.rule != "bad-suppression":
            sup = parse_suppressions(sf.line_text(f.line))
            if sup is not None and f.rule in sup[0] and sup[1]:
                used.add((f.path, f.line, f.rule))
                continue
        if paths is not None and f.path not in paths:
            continue
        kept.append(f)
    ran = {r.name for r in rules if r.name}
    for f in _stale_suppression_findings(ctx, ran, used):
        if paths is None or f.path in paths:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return kept
