"""Inference model export/load — the AnalysisPredictor-path successor.

Ref: /root/reference/python/paddle/fluid/io.py save_inference_model :997 /
load_inference_model :1201 (pruned ProgramDesc + params on disk) and the C++
inference engine (paddle/fluid/inference/api/analysis_predictor.h — load,
run analysis passes, execute via NaiveExecutor).

TPU-first: export = StableHLO bytecode of the jitted forward (+ a params
archive + a JSON signature). XLA *is* the analysis pipeline (fusion,
memory planning, constant folding replace the reference's ir passes). The
C++ serving runtime (csrc/) consumes the same artifact via PJRT — no Python
at serve time, mirroring paddle/fluid/train + inference/api.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np


def save_inference_model(path, fn, example_args, params):
    """Export fn(params, *inputs) with inputs fixed to example shapes.

    Produces:
      model.stablehlo   portable serialized program (ProgramDesc equivalent)
      params.npz        flattened parameters
      signature.json    input/output shapes+dtypes and param tree structure
    """
    os.makedirs(path, exist_ok=True)

    def infer_fn(p, *inputs):
        return fn(p, *inputs)

    lowered = jax.jit(infer_fn).lower(params, *example_args)
    hlo_text = lowered.as_text(dialect="stablehlo")
    with open(os.path.join(path, "model.stablehlo"), "w") as f:
        f.write(hlo_text)
    _write_jax_export(os.path.join(path, "model.jaxexport"), infer_fn,
                      (params, *example_args))

    flat, treedef = jax.tree_util.tree_flatten(params)
    np.savez(os.path.join(path, "params.npz"),
             **{f"p{i}": np.asarray(x) for i, x in enumerate(flat)})
    _write_params_bin(os.path.join(path, "params.bin"), flat)

    _write_params_bin(os.path.join(path, "inputs.bin"),
                      [jnp.asarray(a) for a in example_args])

    sig = {
        "mode": "infer",
        "inputs": [{"shape": list(np.shape(a)),
                    "dtype": str(np.asarray(a).dtype)}
                   for a in example_args],
        "num_params": len(flat),
        "treedef": str(treedef),
    }
    with open(os.path.join(path, "signature.json"), "w") as f:
        json.dump(sig, f, indent=2)
    return path


def save_train_program(path, train_step, state, example_batch):
    """Export ONE optimizer step for the Python-free C++ training loop.

    Ref: /root/reference/paddle/fluid/train/ (test_train_recognize_digits.cc
    — load a train ProgramDesc, loop Executor::Run in pure C++). Here the
    artifact is a StableHLO program of the whole jitted step; the C++ loop
    (csrc/predictor --train) feeds each iteration's state outputs back in.

    train_step(state, *batch) -> (loss, new_state); state is any pytree
    (params + optimizer slots). Program signature:
      inputs  = [*flat(state), *batch]      (flat(state) = params.bin)
      outputs = [loss, *flat(new_state)]    (output 1+j feeds input j)
    """
    os.makedirs(path, exist_ok=True)
    flat, treedef = jax.tree_util.tree_flatten(state)
    n = len(flat)

    def step_flat(*args):
        st = jax.tree_util.tree_unflatten(treedef, args[:n])
        loss, new_state = train_step(st, *args[n:])
        new_flat = treedef.flatten_up_to(new_state)
        return (loss, *new_flat)

    lowered = jax.jit(step_flat).lower(*flat, *example_batch)
    with open(os.path.join(path, "model.stablehlo"), "w") as f:
        f.write(lowered.as_text(dialect="stablehlo"))
    _write_jax_export(os.path.join(path, "model.jaxexport"), step_flat,
                      (*flat, *example_batch))
    _write_params_bin(os.path.join(path, "params.bin"), flat)
    _write_params_bin(os.path.join(path, "inputs.bin"),
                      [jnp.asarray(a) for a in example_batch])
    np.savez(os.path.join(path, "params.npz"),
             **{f"p{i}": np.asarray(x) for i, x in enumerate(flat)})
    sig = {
        "mode": "train",
        "inputs": [{"shape": list(np.shape(a)),
                    "dtype": str(np.asarray(a).dtype)}
                   for a in example_batch],
        "num_params": n,
        "feedback": [[1 + j, j] for j in range(n)],
        "treedef": str(treedef),
    }
    with open(os.path.join(path, "signature.json"), "w") as f:
        json.dump(sig, f, indent=2)
    return path


# PJRT_Buffer_Type codes (xla/pjrt/c/pjrt_c_api.h) for the C++ predictor
_PJRT_DTYPE = {
    np.dtype(np.bool_): 1, np.dtype(np.int8): 2, np.dtype(np.int16): 3,
    np.dtype(np.int32): 4, np.dtype(np.int64): 5, np.dtype(np.uint8): 6,
    np.dtype(np.uint16): 7, np.dtype(np.uint32): 8, np.dtype(np.uint64): 9,
    np.dtype(np.float16): 10, np.dtype(np.float32): 11,
    np.dtype(np.float64): 12,
}


def _write_params_bin(path, flat):
    """Framework binary params for the C++ predictor (csrc/predictor):
    magic PTPB | u32 version | u32 n | per-tensor u32 dtype, u32 ndim,
    i64 dims[], u64 nbytes, raw bytes. bfloat16 is stored as code 13."""
    import struct
    with open(path, "wb") as f:
        f.write(b"PTPB")
        f.write(struct.pack("<II", 1, len(flat)))
        for x in flat:
            a = np.asarray(x)
            if a.dtype.name == "bfloat16":
                code = 13  # PJRT_Buffer_Type_BF16
                raw = a.tobytes()
            else:
                code = _PJRT_DTYPE.get(a.dtype)
                if code is None:
                    a = a.astype(np.float32)
                    code = 11
                raw = a.tobytes()
            f.write(struct.pack("<II", code, a.ndim))
            f.write(struct.pack(f"<{a.ndim}q", *a.shape))
            f.write(struct.pack("<Q", len(raw)))
            f.write(raw)


def _write_jax_export(path, fn, example_args):
    """Serialize fn as a jax.export artifact lowered for BOTH cpu and tpu,
    so the same file loads on the serving chip and in CPU CI. This is the
    parse_from_string side of the ProgramDesc round-trip
    (ref framework.py:3459): Python can load the artifact back into a
    runnable program with no access to the original model code."""
    from jax import export as jexport
    # export over FLAT leaves so the loader needs no pytree structure
    flat, treedef = jax.tree_util.tree_flatten(tuple(example_args))

    def flat_fn(*leaves):
        args = jax.tree_util.tree_unflatten(treedef, leaves)
        out = fn(*args)
        return tuple(jax.tree_util.tree_leaves(out)) if not hasattr(
            out, "shape") else out

    exp = jexport.export(jax.jit(flat_fn), platforms=("cpu", "tpu"))(*flat)
    with open(path, "wb") as f:
        f.write(exp.serialize())


def load_program(path):
    """Load a serialized program (model.jaxexport) back into a runnable
    callable — save→load→run round-trip with no original Python code.

    Ref: framework.py:3459 Program.parse_from_string + io.py:1201
    load_inference_model. Returns a function of the program's flat inputs
    (for inference exports: (params_pytree_flattened..., *inputs))."""
    from jax import export as jexport
    fp = path if path.endswith(".jaxexport") else os.path.join(
        path, "model.jaxexport")
    with open(fp, "rb") as f:
        exp = jexport.deserialize(f.read())

    def run(*args):
        return exp.call(*args)

    run.in_avals = exp.in_avals
    run.out_avals = exp.out_avals
    return run


def load_inference_model(path, raw=False):
    """Load an exported model. Default: a runnable predictor that executes
    the serialized program itself via jax.export — the true ProgramDesc
    round-trip (ref io.py:1201 load_inference_model returns a runnable
    program, not just bytes; framework.py:3459 parse_from_string). With
    raw=True: the (stablehlo_text, params_list, signature) triple for
    external runtimes (the C++ predictor consumes the same artifacts)."""
    with open(os.path.join(path, "signature.json")) as f:
        sig = json.load(f)
    data = np.load(os.path.join(path, "params.npz"))
    flat = [jnp.asarray(data[f"p{i}"]) for i in range(sig["num_params"])]
    if raw:
        with open(os.path.join(path, "model.stablehlo")) as f:
            hlo = f.read()
        return hlo, flat, sig
    if not os.path.exists(os.path.join(path, "model.jaxexport")):
        from paddle_tpu.core.enforce import EnforceError
        raise EnforceError(
            f"{path} has no model.jaxexport (exported by an older version?) "
            "— re-export with save_inference_model, or pass raw=True for "
            "the (stablehlo, params, signature) triple")
    prog = load_program(path)

    def predictor(*inputs):
        return prog(*flat, *inputs)

    predictor.signature = sig
    predictor.params = flat
    return predictor


class Predictor:
    """In-process predictor (ref: AnalysisPredictor api surface —
    analysis_predictor.h:47). Wraps fn+params, jits on first run, caches the
    executable per input shape."""

    def __init__(self, fn, params):
        self.fn = fn
        self.params = params
        self._jit = jax.jit(fn)

    def run(self, *inputs):
        return self._jit(self.params, *inputs)

    __call__ = run


_PJRT_DTYPE_INV = {v: k for k, v in _PJRT_DTYPE.items()}


def read_params_bin(path):
    """Parse a PTPB tensor archive (params.bin / predictor --dump_outputs)
    back into numpy arrays — the Python side of the C++ serving contract."""
    import struct
    out = []
    with open(path, "rb") as f:
        blob = f.read()
    if blob[:4] != b"PTPB":
        raise ValueError(f"{path}: bad magic")
    version, n = struct.unpack_from("<II", blob, 4)
    if version != 1:
        raise ValueError(f"{path}: unsupported version {version}")
    off = 12
    for _ in range(n):
        code, ndim = struct.unpack_from("<II", blob, off)
        off += 8
        dims = struct.unpack_from(f"<{ndim}q", blob, off)
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", blob, off)
        off += 8
        raw = blob[off:off + nbytes]
        off += nbytes
        if code == 13:  # bf16: widen via uint16 -> float32
            u = np.frombuffer(raw, np.uint16).astype(np.uint32) << 16
            arr = u.view(np.float32).reshape(dims)
        else:
            dt = _PJRT_DTYPE_INV.get(code)
            if dt is None:
                raise ValueError(f"{path}: unknown dtype code {code}")
            arr = np.frombuffer(raw, dt).reshape(dims)
        out.append(arr)
    return out
