"""I/O: checkpointing + inference export (ref: python/paddle/fluid/io.py)
+ the pluggable remote filesystem layer (ref: framework/io/fs.cc)."""

from paddle_tpu.io import fs
from paddle_tpu.io.fs import (
    MemFS,
    ensure_local,
    fs_exists,
    fs_open,
    get_tree,
    put_tree,
    register_filesystem,
    remove_tree,
)
from paddle_tpu.io.checkpoint import (
    CheckpointManager,
    latest_step,
    load_persistables,
    save_persistables,
    stack_layer_tree,
    unstack_layer_tree,
)
from paddle_tpu.io.inference import (
    Predictor,
    load_inference_model,
    load_program,
    save_inference_model,
    save_train_program,
)
