"""Pluggable filesystem layer — remote/object-store IO for datasets and
checkpoints.

Ref: /root/reference/paddle/fluid/framework/io/fs.cc (localfs_* + hdfs_*
shell commands behind one open/exists/list surface) and
python/paddle/fluid/incubate/fleet/utils/hdfs.py (HDFSClient). The
reference shells out to `hadoop fs`; production TPU pods read from object
stores (gs://, s3://) instead — same need, different fabric.

TPU-first shape: ONE registry keyed by URL scheme. `LocalFS` ships;
`MemFS` is the in-process reference implementation (used by tests and as
the template for real gs/hdfs adapters — a real adapter only implements
the same 6 primitives). Consumers never dispatch on scheme themselves:

    from paddle_tpu.io import fs
    with fs.fs_open("gs://bucket/part-0000", "rb") as f: ...
    local = fs.ensure_local("gs://bucket/part-0000")  # for native readers

`register_filesystem("gs", MyGcsFS())` plugs in a real backend; nothing
else in the framework changes (FileDataset and CheckpointManager go
through this module).
"""

import os
import shutil
import tempfile
import threading

_REGISTRY = {}
_LOCK = threading.Lock()


def split_scheme(path):
    """'gs://b/k' -> ('gs', 'b/k'); '/local/p' -> (None, '/local/p').

    Windows drive letters ('C:/x') and bare relative paths have no '://'
    and fall through to local."""
    if "://" in str(path):
        scheme, _, rest = str(path).partition("://")
        return scheme, rest
    return None, str(path)


def register_filesystem(scheme, fs):
    """Plug a FileSystem implementation in for a URL scheme."""
    with _LOCK:
        _REGISTRY[scheme] = fs


def get_filesystem(path):
    """(FileSystem, path) for a possibly scheme-prefixed path."""
    scheme, _ = split_scheme(path)
    if scheme is None:
        return _LOCAL, path
    with _LOCK:
        fs = _REGISTRY.get(scheme)
    if fs is None:
        from paddle_tpu.core.enforce import EnforceError
        raise EnforceError(
            f"no filesystem registered for scheme '{scheme}://' — call "
            f"paddle_tpu.io.fs.register_filesystem({scheme!r}, impl) "
            "(see MemFS for the 6-primitive template)")
    return fs, path


class LocalFS:
    """POSIX passthrough (ref fs.cc localfs_*)."""

    def open(self, path, mode="rb"):
        if "w" in mode or "a" in mode:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
        return open(path, mode)

    def exists(self, path):
        return os.path.exists(path)

    def isdir(self, path):
        return os.path.isdir(path)

    def listdir(self, path):
        return sorted(os.listdir(path))

    def makedirs(self, path):
        os.makedirs(path, exist_ok=True)

    def remove(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)


_LOCAL = LocalFS()


class MemFS:
    """In-process object store: the test double AND the reference
    implementation a real remote adapter copies (same 6 primitives over a
    flat key space with implicit directories — object-store semantics)."""

    def __init__(self):
        self._blobs = {}
        self._lock = threading.Lock()

    def _key(self, path):
        return split_scheme(path)[1].rstrip("/")

    def open(self, path, mode="rb"):
        import io
        k = self._key(path)
        if "r" in mode and "w" not in mode:
            with self._lock:
                if k not in self._blobs:
                    raise FileNotFoundError(path)
                data = self._blobs[k]
            return io.BytesIO(data) if "b" in mode else \
                io.StringIO(data.decode())
        fsref = self

        class _Writer(io.BytesIO):
            def close(self2):
                with fsref._lock:
                    fsref._blobs[k] = self2.getvalue()
                super(_Writer, self2).close()

            def __exit__(self2, *a):
                self2.close()

        if "b" not in mode:
            class _TextWriter(io.StringIO):
                def close(self2):
                    with fsref._lock:
                        fsref._blobs[k] = self2.getvalue().encode()
                    super(_TextWriter, self2).close()

                def __exit__(self2, *a):
                    self2.close()
            return _TextWriter()
        return _Writer()

    def exists(self, path):
        k = self._key(path)
        with self._lock:
            return k in self._blobs or any(
                b.startswith(k + "/") for b in self._blobs)

    def isdir(self, path):
        k = self._key(path)
        with self._lock:
            return any(b.startswith(k + "/") for b in self._blobs)

    def listdir(self, path):
        k = self._key(path)
        pre = k + "/" if k else ""
        with self._lock:
            names = {b[len(pre):].split("/", 1)[0]
                     for b in self._blobs if b.startswith(pre)}
        return sorted(names)

    def makedirs(self, path):
        pass  # directories are implicit (object-store semantics)

    def remove(self, path):
        k = self._key(path)
        with self._lock:
            for b in [b for b in self._blobs
                      if b == k or b.startswith(k + "/")]:
                del self._blobs[b]


def fs_open(path, mode="rb"):
    """Open a local or scheme-prefixed path through the registry."""
    fs, p = get_filesystem(path)
    return fs.open(p, mode)


def fs_exists(path):
    fs, p = get_filesystem(path)
    return fs.exists(p)


_CACHE_DIR = None


def _cache_dir():
    global _CACHE_DIR
    if _CACHE_DIR is None:
        _CACHE_DIR = tempfile.mkdtemp(prefix="pt_fs_cache_")
    return _CACHE_DIR


def ensure_local(path, cache_dir=None):
    """A REAL local path for `path`: identity for local paths; for remote
    ones, download into the cache (once per path) and return the copy —
    what the C++ native reader / orbax need. (Ref fs.cc's download-to-tmp
    pattern in fleet utils.)

    The cache is per-process by default (a mkdtemp dir; pass `cache_dir`
    to share/persist it) and never evicts — callers staging large corpora
    should point cache_dir at managed scratch space and `clear_cache()`
    between epochs/datasets if disk is tight."""
    import hashlib
    scheme, rest = split_scheme(path)
    if scheme is None:
        return path
    # collision-free key: basename for humans + full-path hash for truth
    # ('a/b__c' and 'a/b/c' must not share a cache slot)
    digest = hashlib.sha1(str(path).encode()).hexdigest()[:16]
    name = os.path.basename(rest.rstrip("/")) or "blob"
    base = os.path.join(cache_dir or _cache_dir(), scheme,
                        f"{digest}_{name}")
    if not os.path.exists(base):
        fs, _ = get_filesystem(path)
        os.makedirs(os.path.dirname(base), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(base),
                                   prefix=name + ".")
        try:
            with fs.open(path, "rb") as src, os.fdopen(fd, "wb") as dst:
                shutil.copyfileobj(src, dst)
            os.replace(tmp, base)  # atomic publish; unique tmp per caller
        except BaseException:
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
    return base


def clear_cache():
    """Drop the process-wide ensure_local cache directory."""
    global _CACHE_DIR
    if _CACHE_DIR is not None and os.path.isdir(_CACHE_DIR):
        shutil.rmtree(_CACHE_DIR, ignore_errors=True)
    _CACHE_DIR = None


def put_tree(local_dir, remote_dir):
    """Mirror a local directory tree to a (remote) destination."""
    fs, _ = get_filesystem(remote_dir)
    for root, _dirs, files in os.walk(local_dir):
        rel = os.path.relpath(root, local_dir)
        for name in files:
            dst = remote_dir.rstrip("/") + (
                "/" if rel == "." else f"/{rel}/") + name
            with open(os.path.join(root, name), "rb") as src, \
                    fs.open(dst, "wb") as out:
                shutil.copyfileobj(src, out)


def get_tree(remote_dir, local_dir):
    """Mirror a (remote) directory tree into a local directory. Raises
    FileNotFoundError when the source does not exist — a silent empty
    mirror would poison downstream latest-step discovery."""
    fs, p = get_filesystem(remote_dir)
    if not fs.exists(p):
        raise FileNotFoundError(remote_dir)

    def walk(rdir, ldir):
        os.makedirs(ldir, exist_ok=True)
        for name in fs.listdir(rdir):
            rpath = rdir.rstrip("/") + "/" + name
            lpath = os.path.join(ldir, name)
            if fs.isdir(rpath):
                walk(rpath, lpath)
            else:
                with fs.open(rpath, "rb") as src, open(lpath, "wb") as dst:
                    shutil.copyfileobj(src, dst)

    walk(remote_dir, local_dir)


def remove_tree(path):
    fs, p = get_filesystem(path)
    fs.remove(p)


def listdir(path):
    fs, p = get_filesystem(path)
    return fs.listdir(p)
