"""Pluggable filesystem layer — remote/object-store IO for datasets and
checkpoints.

Ref: /root/reference/paddle/fluid/framework/io/fs.cc (localfs_* + hdfs_*
shell commands behind one open/exists/list surface) and
python/paddle/fluid/incubate/fleet/utils/hdfs.py (HDFSClient). The
reference shells out to `hadoop fs`; production TPU pods read from object
stores (gs://, s3://) instead — same need, different fabric.

TPU-first shape: ONE registry keyed by URL scheme. `LocalFS` ships;
`MemFS` is the in-process reference implementation (used by tests and as
the template for real gs/hdfs adapters — a real adapter only implements
the same 6 primitives). Consumers never dispatch on scheme themselves:

    from paddle_tpu.io import fs
    with fs.fs_open("gs://bucket/part-0000", "rb") as f: ...
    local = fs.ensure_local("gs://bucket/part-0000")  # for native readers

`register_filesystem("gs", MyGcsFS())` plugs in a real backend; nothing
else in the framework changes (FileDataset and CheckpointManager go
through this module).
"""

import os
import shutil
import tempfile
import threading

from paddle_tpu.core import retry as _retry

_REGISTRY = {}
_LOCK = threading.Lock()


def _policy_for(path):
    """RetryPolicy for remote (scheme-prefixed) paths; None for local ones.
    Local POSIX ops don't retry — a local failure is a bug or a full disk,
    and masking it with backoff would only slow the report down."""
    scheme, _ = split_scheme(path)
    return _retry.default_policy() if scheme is not None else None


def _call(policy, fn, *args, **kwargs):
    if policy is None:
        return fn(*args, **kwargs)
    return policy.call(fn, *args, **kwargs)


def split_scheme(path):
    """'gs://b/k' -> ('gs', 'b/k'); '/local/p' -> (None, '/local/p').

    Windows drive letters ('C:/x') and bare relative paths have no '://'
    and fall through to local."""
    if "://" in str(path):
        scheme, _, rest = str(path).partition("://")
        return scheme, rest
    return None, str(path)


def register_filesystem(scheme, fs):
    """Plug a FileSystem implementation in for a URL scheme."""
    with _LOCK:
        _REGISTRY[scheme] = fs


def get_filesystem(path):
    """(FileSystem, path) for a possibly scheme-prefixed path."""
    scheme, _ = split_scheme(path)
    if scheme is None:
        return _LOCAL, path
    with _LOCK:
        fs = _REGISTRY.get(scheme)
    if fs is None:
        from paddle_tpu.core.enforce import EnforceError
        raise EnforceError(
            f"no filesystem registered for scheme '{scheme}://' — call "
            f"paddle_tpu.io.fs.register_filesystem({scheme!r}, impl) "
            "(see MemFS for the 6-primitive template)")
    return fs, path


class LocalFS:
    """POSIX passthrough (ref fs.cc localfs_*)."""

    def open(self, path, mode="rb"):
        if "w" in mode or "a" in mode:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
        return open(path, mode)

    def exists(self, path):
        return os.path.exists(path)

    def isdir(self, path):
        return os.path.isdir(path)

    def listdir(self, path):
        if not os.path.isdir(path):
            # normalize to FileNotFoundError (MemFS.open semantics) so
            # callers can branch on "not there yet" without catching the
            # whole OSError family (which the retry layer treats as
            # transient)
            raise FileNotFoundError(path)
        return sorted(os.listdir(path))

    def makedirs(self, path):
        os.makedirs(path, exist_ok=True)

    def remove(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)


_LOCAL = LocalFS()


class MemFS:
    """In-process object store: the test double AND the reference
    implementation a real remote adapter copies (same 6 primitives over a
    flat key space with implicit directories — object-store semantics)."""

    def __init__(self):
        self._blobs = {}
        self._lock = threading.Lock()

    def _key(self, path):
        return split_scheme(path)[1].rstrip("/")

    def open(self, path, mode="rb"):
        import io
        k = self._key(path)
        if "r" in mode and "w" not in mode:
            with self._lock:
                if k not in self._blobs:
                    raise FileNotFoundError(path)
                data = self._blobs[k]
            return io.BytesIO(data) if "b" in mode else \
                io.StringIO(data.decode())
        fsref = self

        class _Writer(io.BytesIO):
            def close(self2):
                with fsref._lock:
                    fsref._blobs[k] = self2.getvalue()
                super(_Writer, self2).close()

            def __exit__(self2, *a):
                self2.close()

        if "b" not in mode:
            class _TextWriter(io.StringIO):
                def close(self2):
                    with fsref._lock:
                        fsref._blobs[k] = self2.getvalue().encode()
                    super(_TextWriter, self2).close()

                def __exit__(self2, *a):
                    self2.close()
            return _TextWriter()
        return _Writer()

    def exists(self, path):
        k = self._key(path)
        with self._lock:
            return k in self._blobs or any(
                b.startswith(k + "/") for b in self._blobs)

    def isdir(self, path):
        k = self._key(path)
        with self._lock:
            return any(b.startswith(k + "/") for b in self._blobs)

    def listdir(self, path):
        k = self._key(path)
        pre = k + "/" if k else ""
        with self._lock:
            names = {b[len(pre):].split("/", 1)[0]
                     for b in self._blobs if b.startswith(pre)}
        return sorted(names)

    def makedirs(self, path):
        pass  # directories are implicit (object-store semantics)

    def remove(self, path):
        k = self._key(path)
        with self._lock:
            for b in [b for b in self._blobs
                      if b == k or b.startswith(k + "/")]:
                del self._blobs[b]


def fs_open(path, mode="rb"):
    """Open a local or scheme-prefixed path through the registry. Remote
    opens retry transient failures per the ``retry_*`` flags."""
    fs, p = get_filesystem(path)
    return _call(_policy_for(path), fs.open, p, mode)


def fs_exists(path):
    fs, p = get_filesystem(path)
    return fs.exists(p)


_CACHE_DIR = None


def _cache_dir():
    global _CACHE_DIR
    if _CACHE_DIR is None:
        _CACHE_DIR = tempfile.mkdtemp(prefix="pt_fs_cache_")
    return _CACHE_DIR


def ensure_local(path, cache_dir=None):
    """A REAL local path for `path`: identity for local paths; for remote
    ones, download into the cache (once per path) and return the copy —
    what the C++ native reader / orbax need. (Ref fs.cc's download-to-tmp
    pattern in fleet utils.)

    The cache is per-process by default (a mkdtemp dir; pass `cache_dir`
    to share/persist it) and never evicts — callers staging large corpora
    should point cache_dir at managed scratch space and `clear_cache()`
    between epochs/datasets if disk is tight."""
    import hashlib
    scheme, rest = split_scheme(path)
    if scheme is None:
        return path
    # collision-free key: basename for humans + full-path hash for truth
    # ('a/b__c' and 'a/b/c' must not share a cache slot)
    digest = hashlib.sha1(str(path).encode()).hexdigest()[:16]
    name = os.path.basename(rest.rstrip("/")) or "blob"
    base = os.path.join(cache_dir or _cache_dir(), scheme,
                        f"{digest}_{name}")
    if not os.path.exists(base):
        fs, _ = get_filesystem(path)
        os.makedirs(os.path.dirname(base), exist_ok=True)

        def attempt():
            # fresh tmp + reopened source per attempt: a failed transfer
            # leaves nothing behind to poison the retry or the cache
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(base),
                                       prefix=name + ".")
            try:
                with fs.open(path, "rb") as src, \
                        os.fdopen(fd, "wb") as dst:
                    shutil.copyfileobj(src, dst)
                os.replace(tmp, base)  # atomic publish; unique tmp each
            except BaseException:
                if os.path.exists(tmp):
                    os.remove(tmp)
                raise

        _call(_policy_for(path), attempt)
    return base


def clear_cache():
    """Drop the process-wide ensure_local cache directory."""
    global _CACHE_DIR
    if _CACHE_DIR is not None and os.path.isdir(_CACHE_DIR):
        shutil.rmtree(_CACHE_DIR, ignore_errors=True)
    _CACHE_DIR = None


def put_tree(local_dir, remote_dir):
    """Mirror a local directory tree to a (remote) destination. Each file
    transfer retries independently (one flaky object doesn't restart the
    whole tree)."""
    fs, _ = get_filesystem(remote_dir)
    policy = _policy_for(remote_dir)

    def copy_one(srcp, dst):
        # whole-file unit of retry: reopen both ends on each attempt so
        # a mid-stream failure never leaves a half-written object ACTIVE
        # as the final content
        with open(srcp, "rb") as src, fs.open(dst, "wb") as out:
            shutil.copyfileobj(src, out)

    for root, _dirs, files in os.walk(local_dir):
        rel = os.path.relpath(root, local_dir)
        for name in files:
            dst = remote_dir.rstrip("/") + (
                "/" if rel == "." else f"/{rel}/") + name
            _call(policy, copy_one, os.path.join(root, name), dst)


def get_tree(remote_dir, local_dir):
    """Mirror a (remote) directory tree into a local directory,
    atomically: the download lands in a temp dir that is os.replace'd
    into place only when complete — a failure mid-walk leaves no partial
    local tree to poison latest-step discovery (same atomic-publish
    discipline as ensure_local). An existing local_dir is replaced
    wholesale. Raises FileNotFoundError when the source does not exist —
    a silent empty mirror would be just as poisonous."""
    fs, p = get_filesystem(remote_dir)
    if not fs.exists(p):
        raise FileNotFoundError(remote_dir)
    policy = _policy_for(remote_dir)

    def fetch_one(rpath, lpath):
        with fs.open(rpath, "rb") as src, open(lpath, "wb") as dst:
            shutil.copyfileobj(src, dst)

    def walk(rdir, ldir):
        os.makedirs(ldir, exist_ok=True)
        for name in _call(policy, fs.listdir, rdir):
            rpath = rdir.rstrip("/") + "/" + name
            lpath = os.path.join(ldir, name)
            if _call(policy, fs.isdir, rpath):
                walk(rpath, lpath)
            else:
                _call(policy, fetch_one, rpath, lpath)

    local_dir = os.path.abspath(local_dir)
    parent = os.path.dirname(local_dir)
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".pt_get_tree_", dir=parent)
    try:
        walk(remote_dir, tmp)
        try:
            os.replace(tmp, local_dir)      # atomic when dst absent/empty
        except OSError:
            shutil.rmtree(local_dir, ignore_errors=True)
            os.replace(tmp, local_dir)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def remove_tree(path):
    fs, p = get_filesystem(path)
    _call(_policy_for(path), fs.remove, p)


def listdir(path):
    fs, p = get_filesystem(path)
    return _call(_policy_for(path), fs.listdir, p)
