"""Checkpointing — save/load training state.

Ref: /root/reference/python/paddle/fluid/io.py — save_persistables :509 /
load_persistables :787 (training checkpoint incl. optimizer moments),
save/load_inference_model :997/1201, and the save/load *ops*
(operators/save_op.cc, load_combine_op.cc). Distributed: checkpoint_notify
RPC per pserver shard (distributed_ops/checkpoint_notify_op.cc).

TPU-first: orbax async checkpointing — atomic-rename discipline, per-shard
parallel writes on multi-host (each host saves its addressable shards;
restore re-shards to the current mesh), which the reference lacked
(SURVEY.md §5 "No async/atomic-rename discipline").
"""

import os

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def save_persistables(state, path, step=None, async_=False):
    """Save a pytree of params + optimizer state (ref: io.py:509).

    state: arbitrary pytree (params, opt moments, step, BN stats...).
    """
    path = os.path.abspath(path)
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        target = os.path.join(path, str(step)) if step is not None else path
        if os.path.exists(target):
            import shutil
            shutil.rmtree(target)
        ckptr.save(target, state)
        if not async_:
            ckptr.wait_until_finished()
        return target
    # numpy fallback
    target = os.path.join(path, str(step)) if step is not None else path
    os.makedirs(target, exist_ok=True)
    flat, treedef = jax.tree_util.tree_flatten(state)
    np.savez(os.path.join(target, "state.npz"),
             **{str(i): np.asarray(x) for i, x in enumerate(flat)})
    return target


def load_persistables(path, template, step=None):
    """Restore into the structure of `template` (ref: io.py:787). Template
    supplies dtypes/shapes/shardings — restored arrays land on the
    template's sharding (re-shard on restore)."""
    target = os.path.join(path, str(step)) if step is not None else path
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x, template)
        return ckptr.restore(target, abstract)
    flat, treedef = jax.tree_util.tree_flatten(template)
    data = np.load(os.path.join(target, "state.npz"))
    restored = [jax.numpy.asarray(data[str(i)]) for i in range(len(flat))]
    return jax.tree_util.tree_unflatten(treedef, restored)


def latest_step(path):
    """Find newest step dir for resume (ref: the reference had no resume
    discovery; fleet_util picked paths manually)."""
    if not os.path.isdir(path):
        return None
    steps = [int(d) for d in os.listdir(path) if d.isdigit()]
    return max(steps) if steps else None


class CheckpointManager:
    """Keep-last-N rotation + resume (orbax CheckpointManager when
    available)."""

    def __init__(self, path, max_to_keep=3, save_interval_steps=1):
        from paddle_tpu.io import fs as _fs
        scheme, _rest = _fs.split_scheme(path)
        if scheme is not None:
            # remote checkpointing (ref fs.cc hdfs_*, hdfs.py): orbax runs
            # against a deterministic local staging dir (same path ->
            # same staging across processes on a host, so a restarted
            # worker restores what it staged) and every saved step is
            # mirrored to the remote tree; restore pulls missing steps.
            import hashlib
            import tempfile
            self._remote = str(path).rstrip("/")
            self._fs = _fs
            tag = hashlib.sha1(self._remote.encode()).hexdigest()[:16]
            self.path = os.path.join(tempfile.gettempdir(),
                                     "pt_ckpt_staging", tag)
            os.makedirs(self.path, exist_ok=True)
        else:
            self._remote = None
            self.path = os.path.abspath(path)
        self.max_to_keep = max_to_keep
        self.save_interval = save_interval_steps
        if _HAS_ORBAX:
            self._mgr = ocp.CheckpointManager(
                self.path,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep,
                    save_interval_steps=save_interval_steps))
        else:
            self._mgr = None

    def _mirror_save(self, step):
        """Push the completed step dir to the remote tree and prune remote
        steps past the keep window — by STEP-NUMBER retention, never by
        mirroring the local dir listing: a fresh host stages only the
        steps it touched, and pruning 'whatever is not local' would wipe
        valid remote history (or, before any restore, ALL of it)."""
        if self._remote is None:
            return
        self.wait()  # the async save must be durable before mirroring
        self._fs.put_tree(os.path.join(self.path, str(step)),
                          f"{self._remote}/{step}")
        remote_steps = sorted(self._remote_steps())
        for old in remote_steps[:-self.max_to_keep]:
            self._fs.remove_tree(f"{self._remote}/{old}")

    def _remote_steps(self):
        if self._remote is None or not self._fs.fs_exists(self._remote):
            return []
        return [int(n) for n in self._fs.listdir(self._remote)
                if n.isdigit()]

    def _fetch_remote(self, step):
        """Pull a step dir from the remote tree into staging if absent
        locally (fresh host resuming someone else's checkpoint)."""
        if self._remote is None:
            return
        local = os.path.join(self.path, str(step))
        if not os.path.isdir(local):
            self._fs.get_tree(f"{self._remote}/{step}", local)
            if self._mgr is not None:
                # orbax scanned the staging dir at construction; rebuild so
                # it sees the newly fetched step
                self._mgr.close()
                self._mgr = ocp.CheckpointManager(
                    self.path,
                    options=ocp.CheckpointManagerOptions(
                        max_to_keep=self.max_to_keep,
                        save_interval_steps=self.save_interval))

    def save(self, step, state):
        if self._mgr is not None:
            saved = self._mgr.save(step, args=ocp.args.StandardSave(state))
            if saved:
                self._mirror_save(step)
            return saved
        if step % self.save_interval == 0:
            save_persistables(state, self.path, step)
            steps = sorted(int(d) for d in os.listdir(self.path)
                           if d.isdigit())
            for old in steps[:-self.max_to_keep]:
                import shutil
                shutil.rmtree(os.path.join(self.path, str(old)))
            self._mirror_save(step)
            return True
        return False

    def restore(self, template, step=None):
        if step is None and self._remote is not None:
            # the REMOTE tree is authoritative: the deterministic staging
            # dir survives across experiments on a host, and a stale local
            # step outranking a reset remote would silently resume the
            # wrong run's weights
            cand = self._remote_steps()
            step = max(cand) if cand else None
            if step is None:
                return None, None
        if step is not None:
            self._fetch_remote(step)
        if self._mgr is not None:
            step = step if step is not None else self._mgr.latest_step()
            if step is None:
                return None, None
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                if hasattr(x, "shape") else x, template)
            state = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
            return state, step
        step = step if step is not None else latest_step(self.path)
        if step is None:
            return None, None
        return load_persistables(self.path, template, step), step

    def wait(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def close(self):
        """Release orbax's async machinery (background checkpoint threads
        can otherwise outlive the manager and stall interpreter shutdown).
        The manager is unusable afterwards."""
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
