"""Checkpointing — save/load training state.

Ref: /root/reference/python/paddle/fluid/io.py — save_persistables :509 /
load_persistables :787 (training checkpoint incl. optimizer moments),
save/load_inference_model :997/1201, and the save/load *ops*
(operators/save_op.cc, load_combine_op.cc). Distributed: checkpoint_notify
RPC per pserver shard (distributed_ops/checkpoint_notify_op.cc).

TPU-first: orbax async checkpointing — atomic-rename discipline, per-shard
parallel writes on multi-host (each host saves its addressable shards;
restore re-shards to the current mesh), which the reference lacked
(SURVEY.md §5 "No async/atomic-rename discipline").

Fault tolerance (no reference counterpart — checkpoint_notify_op.cc fires
one RPC and hopes): remote mirrors are torn-write protected — a COMMIT
marker is the LAST object pushed per step, and discovery/restore ignore
steps without it, so a crash mid-mirror can never be resumed from. A
mirror push that still fails after retries (io/fs.py RetryPolicy)
degrades: the step is queued and re-pushed on the next save while
training continues on the durable local copy (``strict_mirror`` flag or
ctor arg restores fail-fast).
"""

import binascii
import json
import os

import jax
import numpy as np

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.testing.chaos import fault_point

# pushed last into each mirrored step dir; its presence IS the commit
COMMIT_MARKER = "COMMIT"
# Integrity manifest: per-leaf crc32 checksums plus caller meta (RNG key,
# data cursor, guardian state). Locally it is a "<step>.meta.json" sidecar
# BESIDE the step dirs — a name that never parses as a step number, so
# every retention loop skips it and orbax never sees a foreign file inside
# its step dir. In the remote mirror it rides INSIDE the step dir beside
# the COMMIT marker (pruned with the step, fetched with the step).
META_SUFFIX = ".meta.json"
META_NAME = "INTEGRITY.json"

try:
    import orbax.checkpoint as ocp
    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False


def crc_manifest(state):
    """Per-leaf crc32 of a pytree's raw bytes, keyed by pytree key path
    (with dtype/shape so a reshaped corruption can't collide). Computed
    from the in-memory state at save time and from the restored state at
    verify time — equality means the bytes round-tripped."""
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    man = {}
    for kp, leaf in flat:
        a = np.asarray(leaf)
        man[jax.tree_util.keystr(kp)] = {
            "crc32": int(binascii.crc32(np.ascontiguousarray(a).tobytes())),
            "dtype": str(a.dtype), "shape": list(a.shape)}
    return man


def save_persistables(state, path, step=None, async_=False):
    """Save a pytree of params + optimizer state (ref: io.py:509).

    state: arbitrary pytree (params, opt moments, step, BN stats...).
    """
    path = os.path.abspath(path)
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        target = os.path.join(path, str(step)) if step is not None else path
        if os.path.exists(target):
            import shutil
            shutil.rmtree(target)
        ckptr.save(target, state)
        if not async_:
            ckptr.wait_until_finished()
        return target
    # numpy fallback
    target = os.path.join(path, str(step)) if step is not None else path
    os.makedirs(target, exist_ok=True)
    flat, treedef = jax.tree_util.tree_flatten(state)
    np.savez(os.path.join(target, "state.npz"),
             **{str(i): np.asarray(x) for i, x in enumerate(flat)})
    return target


def load_persistables(path, template, step=None):
    """Restore into the structure of `template` (ref: io.py:787). Template
    supplies dtypes/shapes/shardings — restored arrays land on the
    template's sharding (re-shard on restore)."""
    target = os.path.join(path, str(step)) if step is not None else path
    if _HAS_ORBAX:
        ckptr = ocp.StandardCheckpointer()
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x, template)
        return ckptr.restore(target, abstract)
    flat, treedef = jax.tree_util.tree_flatten(template)
    data = np.load(os.path.join(target, "state.npz"))
    restored = [jax.numpy.asarray(data[str(i)]) for i in range(len(flat))]
    return jax.tree_util.tree_unflatten(treedef, restored)


def stack_layer_tree(tree):
    """Up-convert a per-layer param tree to the scan-over-layers layout.

    Wherever a dict's keys are exactly "0".."n-1" (the ModuleList layout of
    the unrolled encoders) and the per-index subtrees share a structure,
    the subtrees are stacked leaf-wise along a new leading layer axis and
    the dict collapses to {"layer": stacked} — the nn.ScanLayers layout.
    Checkpoints saved before scan-over-layers load with their old template
    and convert through this (see README "Performance": checkpoint
    migration)."""
    if not isinstance(tree, dict) or not tree:
        return tree
    idx = [str(i) for i in range(len(tree))]
    if sorted(tree.keys()) == sorted(idx) and all(
            isinstance(tree[i], dict) for i in idx):
        subs = [stack_layer_tree(tree[i]) for i in idx]
        import jax.numpy as jnp
        return {"layer": jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *subs)}
    return {k: stack_layer_tree(v) for k, v in tree.items()}


def unstack_layer_tree(tree):
    """Inverse of stack_layer_tree: every {"layer": stacked} subtree (the
    nn.ScanLayers layout — a single-key dict whose leaves carry the layer
    axis) splits back into {"0": ..., "n-1": ...} per-layer subtrees, for
    serving paths that step layers individually (GPTDecoder KV caches)."""
    import jax
    if not isinstance(tree, dict):
        return tree
    if set(tree.keys()) == {"layer"} and isinstance(tree["layer"], dict):
        stacked = tree["layer"]
        leaves = jax.tree_util.tree_leaves(stacked)
        if leaves:
            n = leaves[0].shape[0]
            return {str(i): unstack_layer_tree(jax.tree_util.tree_map(
                lambda x: x[i], stacked)) for i in range(n)}
    return {k: unstack_layer_tree(v) for k, v in tree.items()}


def latest_step(path):
    """Find newest step dir for resume (ref: the reference had no resume
    discovery; fleet_util picked paths manually)."""
    try:
        steps = [int(d) for d in os.listdir(path) if d.isdigit()]
    except (FileNotFoundError, NotADirectoryError):
        return None
    return max(steps) if steps else None


class CheckpointManager:
    """Keep-last-N rotation + resume (orbax CheckpointManager when
    available)."""

    def __init__(self, path, max_to_keep=3, save_interval_steps=1,
                 strict_mirror=None):
        from paddle_tpu.core import flags as F
        from paddle_tpu.io import fs as _fs
        self.strict_mirror = (F.get_flag("strict_mirror")
                              if strict_mirror is None else strict_mirror)
        self._mirror_pending = []      # steps whose remote push failed
        scheme, _rest = _fs.split_scheme(path)
        if scheme is not None:
            # remote checkpointing (ref fs.cc hdfs_*, hdfs.py): orbax runs
            # against a deterministic local staging dir (same path ->
            # same staging across processes on a host, so a restarted
            # worker restores what it staged) and every saved step is
            # mirrored to the remote tree; restore pulls missing steps.
            import hashlib
            import tempfile
            self._remote = str(path).rstrip("/")
            self._fs = _fs
            tag = hashlib.sha1(self._remote.encode()).hexdigest()[:16]
            self.path = os.path.join(tempfile.gettempdir(),
                                     "pt_ckpt_staging", tag)
            os.makedirs(self.path, exist_ok=True)
        else:
            self._remote = None
            self.path = os.path.abspath(path)
        self.max_to_keep = max_to_keep
        self.save_interval = save_interval_steps
        self._mgr = self._make_mgr() if _HAS_ORBAX else None

    def _make_mgr(self):
        return ocp.CheckpointManager(
            self.path,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self.max_to_keep,
                save_interval_steps=self.save_interval))

    def _mirror_one(self, step):
        """Atomically publish ONE staged step to the remote tree: clear
        any torn remnant of a previous attempt, push the files, then the
        COMMIT marker as the final object — a reader that doesn't see
        COMMIT sees nothing."""
        dst = f"{self._remote}/{step}"
        if self._fs.fs_exists(dst):
            self._fs.remove_tree(dst)
        self._fs.put_tree(os.path.join(self.path, str(step)), dst)
        meta = self._meta_path(step)
        if os.path.exists(meta):
            # the integrity manifest lands beside the COMMIT marker,
            # before it — commit covers the manifest too
            with open(meta, "rb") as src:
                payload = src.read()
            with self._fs.fs_open(f"{dst}/{META_NAME}", "wb") as f:
                f.write(payload)
        with self._fs.fs_open(f"{dst}/{COMMIT_MARKER}", "wb") as f:
            f.write(b"committed")

    def _mirror_save(self, step):
        """Push the completed step (plus any previously-failed queued
        steps) to the remote tree and prune past the keep window — by
        COMMITTED-STEP retention, never by mirroring the local dir
        listing: a fresh host stages only the steps it touched, and
        pruning 'whatever is not local' would wipe valid remote history
        (or, before any restore, ALL of it).

        Per-object transfers retry (io/fs.py RetryPolicy); a step that
        still fails is queued for the next save instead of raising into
        the train loop, unless strict_mirror."""
        if self._remote is None:
            return
        fault_point("checkpoint.mirror")
        self.wait()  # the async save must be durable before mirroring
        todo = [s for s in self._mirror_pending
                if os.path.isdir(os.path.join(self.path, str(s)))]
        if step not in todo:
            todo.append(step)
        failed = []
        for s in sorted(todo):
            try:
                self._mirror_one(s)
            except Exception as e:
                _metrics.counter("checkpoint.mirror_degraded").inc()
                if self.strict_mirror:
                    # everything from the failed step on is still owed
                    self._mirror_pending = [x for x in sorted(todo)
                                            if x >= s]
                    raise
                failed.append(s)
                print(f"[checkpoint] WARNING: mirror of step {s} to "
                      f"{self._remote} failed after retries ({e!r}); "
                      f"queued for next save")
        self._mirror_pending = failed
        committed = sorted(self._remote_steps())
        if committed and not failed:
            # prune anything older than the keep window's floor — torn
            # junk included; torn dirs >= the floor are republished by
            # _mirror_one's clear-then-push
            cutoff = committed[-self.max_to_keep:][0]
            for name in self._fs.listdir(self._remote):
                if name.isdigit() and int(name) < cutoff:
                    self._fs.remove_tree(f"{self._remote}/{name}")

    def _remote_steps(self, committed_only=True):
        """Step numbers present in the remote tree; by default only steps
        whose COMMIT marker landed — an uncommitted (torn) step must be
        invisible to discovery/restore."""
        if self._remote is None or not self._fs.fs_exists(self._remote):
            return []
        steps = []
        for n in self._fs.listdir(self._remote):
            if not n.isdigit():
                continue
            if committed_only and not self._fs.fs_exists(
                    f"{self._remote}/{n}/{COMMIT_MARKER}"):
                # torn mirror from a crashed writer: invisible to
                # restore, but counted — a run that keeps resuming past
                # torn steps is losing work and should say so
                _metrics.counter("checkpoint.torn_skips").inc()
                continue
            steps.append(int(n))
        return steps

    def _fetch_remote(self, step):
        """Pull a step dir from the remote tree into staging if absent
        locally (fresh host resuming someone else's checkpoint). Refuses
        torn (uncommitted) remote steps."""
        if self._remote is None:
            return
        local = os.path.join(self.path, str(step))
        if not os.path.isdir(local):
            fault_point("checkpoint.fetch")
            from paddle_tpu.core.enforce import enforce
            enforce(self._fs.fs_exists(
                f"{self._remote}/{step}/{COMMIT_MARKER}"),
                f"remote checkpoint step {step} at {self._remote} has no "
                f"{COMMIT_MARKER} marker (torn mirror from a crashed "
                "writer?) — refusing to restore from it")
            self._fs.get_tree(f"{self._remote}/{step}", local)
            marker = os.path.join(local, COMMIT_MARKER)
            if os.path.exists(marker):
                os.remove(marker)      # staging holds orbax files only
            fetched_meta = os.path.join(local, META_NAME)
            if os.path.exists(fetched_meta):
                # back to its local sidecar home beside the step dirs
                os.replace(fetched_meta, self._meta_path(step))
            if self._mgr is not None:
                # orbax scanned the staging dir at construction; rebuild so
                # it sees the newly fetched step
                self._mgr.close()
                self._mgr = self._make_mgr()

    # -- integrity manifest + caller meta ----------------------------------
    def _meta_path(self, step):
        return os.path.join(self.path, f"{int(step)}{META_SUFFIX}")

    def _write_meta(self, step, state, meta):
        payload = {"step": int(step), "crc32": crc_manifest(state),
                   "meta": meta or {}}
        tmp = self._meta_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._meta_path(step))

    def _prune_meta(self, keep_steps):
        """Drop sidecars whose step dir is gone (retention or
        reconciliation removed it)."""
        keep = {int(s) for s in keep_steps}
        try:
            names = os.listdir(self.path)
        except (FileNotFoundError, NotADirectoryError):
            return
        for name in names:
            if not name.endswith(META_SUFFIX):
                continue
            stem = name[:-len(META_SUFFIX)]
            if stem.isdigit() and int(stem) not in keep:
                try:
                    os.remove(os.path.join(self.path, name))
                except OSError:
                    pass

    def read_meta(self, step):
        """The caller-supplied meta dict saved with `step` (the Trainer
        stores RNG key, data cursor, and guardian state there); {} when
        the step predates integrity manifests."""
        try:
            with open(self._meta_path(step)) as f:
                return json.load(f).get("meta") or {}
        except (OSError, ValueError):
            return {}

    def _manifest_mismatches(self, step, state):
        """Leaf key paths whose crc32 disagrees with the step's saved
        manifest; [] means clean — or unverifiable (no manifest: the
        step predates integrity manifests)."""
        try:
            with open(self._meta_path(step)) as f:
                manifest = json.load(f).get("crc32") or {}
        except (OSError, ValueError):
            return []
        actual = crc_manifest(state)
        return [key for key, spec in manifest.items()
                if (actual.get(key) is None
                    or actual[key]["crc32"] != spec["crc32"]
                    or actual[key]["dtype"] != spec["dtype"]
                    or actual[key]["shape"] != spec["shape"])]

    def save(self, step, state, force=False, meta=None, version=None):
        """Save when the step hits the save interval; `force=True`
        bypasses the interval gate (preemption: flush the current step at
        the boundary before exiting). `meta` is an arbitrary
        JSON-serializable dict stored in the step's integrity sidecar and
        returned by read_meta(). `version` rides the sidecar as
        meta["model_version"] — FleetRouter.deploy() reads it to tag the
        rollout when no explicit version is given."""
        if version is not None:
            meta = dict(meta or {})
            meta["model_version"] = str(version)
        if self._mgr is not None:
            if force and self._mgr.latest_step() == step:
                saved = True           # boundary save already landed
            else:
                saved = self._mgr.save(
                    step, args=ocp.args.StandardSave(state), force=force)
            if saved:
                _metrics.counter("checkpoint.saves").inc()
                self._write_meta(step, state, meta)
                self._mirror_save(step)
                self._prune_meta(self._mgr.all_steps())
            return saved
        if force or step % self.save_interval == 0:
            save_persistables(state, self.path, step)
            steps = sorted(int(d) for d in os.listdir(self.path)
                           if d.isdigit())
            for old in steps[:-self.max_to_keep]:
                import shutil
                shutil.rmtree(os.path.join(self.path, str(old)))
            _metrics.counter("checkpoint.saves").inc()
            self._write_meta(step, state, meta)
            self._mirror_save(step)
            self._prune_meta(steps[-self.max_to_keep:])
            return True
        return False

    def steps(self):
        """Restorable step numbers, ascending: committed remote steps
        when mirrored (the remote tree is authoritative), else the local
        step dirs."""
        if self._remote is not None:
            return sorted(self._remote_steps())
        if self._mgr is not None:
            return sorted(int(s) for s in self._mgr.all_steps())
        try:
            return sorted(int(d) for d in os.listdir(self.path)
                          if d.isdigit())
        except (FileNotFoundError, NotADirectoryError):
            return []

    def _reconcile_staging(self, committed):
        """Drop staged steps the authoritative remote doesn't know about —
        leftovers of an older experiment on this host (the staging dir is
        deterministic per remote path), or of a crashed run whose mirror
        push never landed. Left in place they'd collide with this run's
        saves at the same step numbers (orbax StepAlreadyExistsError mid
        train loop)."""
        import shutil
        stale = [d for d in os.listdir(self.path)
                 if d.isdigit() and int(d) not in committed]
        for d in stale:
            shutil.rmtree(os.path.join(self.path, d), ignore_errors=True)
        if stale:
            self._prune_meta(committed)
        if stale and self._mgr is not None:
            self._mgr.close()
            self._mgr = self._make_mgr()

    def _restore_one(self, step, template):
        """Load one step (fetching from the mirror when staged-out) with
        no integrity judgment — exceptions propagate to the verified
        wrapper."""
        self._fetch_remote(step)
        if self._mgr is not None:
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
                if hasattr(x, "shape") else x, template)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        return load_persistables(self.path, template, step)

    def _restore_verified(self, step, template, verify):
        """Load `step` and (when `verify`) check it against its crc32
        manifest. A mismatch or load failure wipes the local copy and
        re-fetches the mirror's once; if the step is still bad it is
        abandoned (checkpoint.integrity_fallbacks) and the caller
        degrades to the previous committed step. Returns the state or
        None."""
        import shutil
        for attempt in ("local", "refetch"):
            if attempt == "refetch":
                if self._remote is None:
                    break              # nowhere cleaner to re-fetch from
                shutil.rmtree(os.path.join(self.path, str(step)),
                              ignore_errors=True)
                try:
                    os.remove(self._meta_path(step))
                except OSError:
                    pass
                if self._mgr is not None:
                    self._mgr.close()
                    self._mgr = self._make_mgr()
            try:
                if verify:
                    fault_point("checkpoint.verify")
                state = self._restore_one(step, template)
                bad = (self._manifest_mismatches(step, state)
                       if verify else [])
            except Exception as e:
                self._last_restore_exc = e
                print(f"[checkpoint] WARNING: restore of step {step} "
                      f"failed ({type(e).__name__}: {e})")
                continue
            if not bad:
                return state
            _metrics.counter("checkpoint.corrupt_leaves").inc(len(bad))
            print(f"[checkpoint] WARNING: step {step} failed integrity "
                  f"verification on {len(bad)} leaves "
                  f"(e.g. {bad[0]!r})")
        _metrics.counter("checkpoint.integrity_fallbacks").inc()
        return None

    def restore(self, template, step=None, verify=None):
        """Restore the newest healthy step (or exactly `step` when
        given). With `verify` (default: the checkpoint_verify flag) each
        candidate is checked against its crc32 manifest; a corrupt or
        unreadable step degrades to a clean mirror re-fetch, then to the
        previous committed step, instead of loading garbage."""
        if verify is None:
            from paddle_tpu.core import flags as F
            verify = bool(F.get_flag("checkpoint_verify"))
        explicit = step is not None
        self._last_restore_exc = None
        if explicit:
            cand = [int(step)]
        elif self._remote is not None:
            # the REMOTE tree is authoritative: the deterministic staging
            # dir survives across experiments on a host, and a stale local
            # step outranking a reset remote would silently resume the
            # wrong run's weights
            cand = sorted(self._remote_steps(), reverse=True)
            self._reconcile_staging(set(cand))
        elif self._mgr is not None:
            cand = sorted((int(s) for s in self._mgr.all_steps()),
                          reverse=True)
        else:
            last = latest_step(self.path)
            cand = (sorted((int(d) for d in os.listdir(self.path)
                            if d.isdigit()), reverse=True)
                    if last is not None else [])
        for s in cand:
            state = self._restore_verified(s, template, verify)
            if state is not None:
                _metrics.counter("checkpoint.restores").inc()
                return state, s
        if cand:
            if explicit and self._last_restore_exc is not None:
                # the caller named this exact step: surface WHY it is
                # unloadable (torn mirror, missing files) rather than a
                # generic verification verdict
                raise self._last_restore_exc
            raise RuntimeError(
                f"no checkpoint step under {self._remote or self.path} "
                f"survived integrity verification (tried {cand})")
        return None, None

    def wait(self):
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def close(self):
        """Release orbax's async machinery (background checkpoint threads
        can otherwise outlive the manager and stall interpreter shutdown).
        The manager is unusable afterwards. Queued mirror pushes get one
        last best-effort flush (a clean shutdown shouldn't strand a
        recovered remote one save behind)."""
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
        if self._remote is not None and self._mirror_pending:
            try:
                self._mirror_save(self._mirror_pending[-1])
            except Exception as e:      # already logged per-step
                print(f"[checkpoint] WARNING: final mirror flush failed "
                      f"({e!r}); steps {self._mirror_pending} remain "
                      "local-only")
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
