"""Distribution: mesh/sharding, collectives, DP/FSDP/TP/PP/SP, compressed
gradients, sharded embeddings, multi-host launch.

Ref map (reference → here):
  ParallelExecutor + multi_devices_graph_pass  → api.DataParallel (pjit/GSPMD)
  operators/collective/ c_* ops                → collective.* (lax collectives)
  nccl_helper.h rings + gen_nccl_id            → mesh.make_mesh + jax.distributed
  DGC sparse allreduce                         → dgc.sparse_all_reduce
  pserver / distributed_lookup_table           → embedding.ShardedEmbedding
  SelectedRows grads + PSLib pull/push         → sparse.SparseTable/HostTable
  PipelineTrainer/SectionWorker                → pipeline.make_pipeline_fn
  distributed launch.py                        → launch.py
  LocalSGD (transpiler/collective.py)          → api.local_sgd_sync
  (new) ring attention / Ulysses SP            → ring_attention.py
"""

from paddle_tpu.parallel import (
    api,
    autoplan,
    collective,
    communicator,
    dgc,
    embedding,
    fleet as fleet_mod,
    heartbeat,
    launch,
    mesh,
    pipeline,
    planner,
    ring_attention,
    sparse,
)
from paddle_tpu.parallel.planner import DistributionPlan, DistributionPlanner
from paddle_tpu.parallel.autoplan import (MeshPlan, ModelSpec, Topology,
                                          plan as auto_plan)
from paddle_tpu.parallel.sparse import HostTable, SparseTable
from paddle_tpu.parallel.elastic import ElasticRunner
from paddle_tpu.parallel.fleet import DistributedStrategy, Fleet, fleet
from paddle_tpu.parallel.communicator import (DCASGD, GeoSGD, GradientMerge,
                                              LocalSGD, stack_replicas,
                                              unstack_replica)
from paddle_tpu.parallel.heartbeat import (FileHeartbeat, HeartBeatMonitor,
                                           KVHeartbeat, KVMonitor,
                                           PeerFailureError,
                                           barrier_with_timeout, kv_barrier)
from paddle_tpu.parallel.mesh import (
    DP, EP, FSDP, PP, SP, TP,
    current_mesh,
    data_parallel_mesh,
    make_hybrid_mesh,
    make_mesh,
    named_sharding,
    replicated,
)
from paddle_tpu.parallel.api import (
    DataParallel,
    fsdp_sharding,
    infer_vocab_axis,
    local_sgd_sync,
    replicate,
    shard_batch,
    tp_lm_sharding,
    tp_lm_specs,
)
