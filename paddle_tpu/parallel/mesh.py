"""Device mesh construction and axis conventions.

Ref: the reference's device topology handling — NCCLContextMap per-device
rings (/root/reference/paddle/fluid/platform/nccl_helper.h:90), hierarchical
inter/intra-node communicators (:179), and launch-time env wiring
(python/paddle/distributed/launch.py).

TPU-first: one `jax.sharding.Mesh` over all devices replaces communicator
rings — XLA lowers collectives onto ICI/DCN topology-aware, no id bootstrap.
Canonical axis names:
  "dp"   data parallel            (ref: ParallelExecutor allreduce mode)
  "fsdp" fully-sharded data par.  (ref: absent — modern addition)
  "tp"   tensor/model parallel    (ref: absent — DistFCConfig stub only)
  "pp"   pipeline stages          (ref: PipelineOptimizer sections)
  "sp"   sequence/context par.    (ref: absent — long-context addition)
  "ep"   expert/embedding shards  (ref: pserver param shards)
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP, FSDP, TP, PP, SP, EP = "dp", "fsdp", "tp", "pp", "sp", "ep"


def make_mesh(axes=None, devices=None):
    """Build a Mesh from {axis_name: size}. Sizes must multiply to the device
    count; a size of -1 is inferred."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    axes = dict(axes or {DP: n})
    names = list(axes)
    sizes = [axes[a] for a in names]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    assert int(np.prod(sizes)) == n, (sizes, n)
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def data_parallel_mesh(devices=None):
    return make_mesh({DP: -1}, devices)


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, P(*spec))


def replicated(mesh):
    return NamedSharding(mesh, P())


def local_mesh_info():
    """Process-local view for multi-host (ref: trainer env
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM, launch.py:78-81)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
