"""Device mesh construction and axis conventions.

Ref: the reference's device topology handling — NCCLContextMap per-device
rings (/root/reference/paddle/fluid/platform/nccl_helper.h:90), hierarchical
inter/intra-node communicators (:179), and launch-time env wiring
(python/paddle/distributed/launch.py).

TPU-first: one `jax.sharding.Mesh` over all devices replaces communicator
rings — XLA lowers collectives onto ICI/DCN topology-aware, no id bootstrap.
Canonical axis names:
  "dp"   data parallel            (ref: ParallelExecutor allreduce mode)
  "fsdp" fully-sharded data par.  (ref: absent — modern addition)
  "tp"   tensor/model parallel    (ref: absent — DistFCConfig stub only)
  "pp"   pipeline stages          (ref: PipelineOptimizer sections)
  "sp"   sequence/context par.    (ref: absent — long-context addition)
  "ep"   expert/embedding shards  (ref: pserver param shards)
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP, FSDP, TP, PP, SP, EP = "dp", "fsdp", "tp", "pp", "sp", "ep"


def make_mesh(axes=None, devices=None):
    """Build a Mesh from {axis_name: size}. Sizes must multiply to the device
    count; a size of -1 is inferred."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    axes = dict(axes or {DP: n})
    names = list(axes)
    sizes = [axes[a] for a in names]
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n // known
    assert int(np.prod(sizes)) == n, (sizes, n)
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def data_parallel_mesh(devices=None):
    return make_mesh({DP: -1}, devices)


def make_hybrid_mesh(ici_axes, dcn_axes, devices=None):
    """Multi-slice mesh: DCN-connected slices on the OUTER axes, ICI
    within a slice on the inner axes (ref: the reference's hierarchical
    inter/intra-node communicators, nccl_helper.h:179 — rebuilt as mesh
    geometry so XLA routes collectives onto the right fabric).

    ici_axes / dcn_axes: {axis_name: size} (sizes of -1 inferred; DCN
    sizes must multiply to the slice count). On real multi-slice TPU,
    uses mesh_utils.create_hybrid_device_mesh (which reads slice_index);
    on homogeneous single-slice platforms (CPU testing), falls back to a
    reshape with the DCN axes outermost — the same axis ORDER contract,
    so shardings written against it transfer unchanged.

    Rule of thumb the axis order encodes: put dp (gradient allreduce,
    latency-tolerant) on DCN axes; keep tp/sp/pp (activation-sized,
    latency-sensitive) on ICI axes.
    """
    devices = devices if devices is not None else jax.devices()
    dcn = dict(dcn_axes)
    ici = dict(ici_axes)
    n = len(devices)
    slices = {getattr(d, "slice_index", 0) for d in devices}
    per_slice = n // max(len(slices), 1)

    def resolve(axes, total):
        names = list(axes)
        sizes = [axes[a] for a in names]
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1]))
            sizes[sizes.index(-1)] = total // known
        assert int(np.prod(sizes)) == total, (axes, total)
        return names, sizes

    if len(slices) > 1:
        from jax.experimental import mesh_utils
        dcn_names, dcn_sizes = resolve(dcn, len(slices))
        ici_names, ici_sizes = resolve(ici, per_slice)
        # returns shape (*dcn_sizes, *ici_sizes): DCN axes outermost
        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici_sizes, dcn_sizes, devices=devices)
        return Mesh(dev_array, tuple(dcn_names) + tuple(ici_names))
    # single-slice / CPU testing: same axis-order contract, plain reshape
    # (explicit DCN sizes required — there is no slice topology to infer)
    dcn_names = list(dcn)
    dcn_sizes = [dcn[a] for a in dcn_names]
    assert -1 not in dcn_sizes, \
        "single-slice make_hybrid_mesh needs explicit dcn sizes"
    total_dcn = int(np.prod(dcn_sizes))
    ici_names, ici_sizes = resolve(ici, n // total_dcn)
    dev_array = np.asarray(devices).reshape(dcn_sizes + ici_sizes)
    return Mesh(dev_array, tuple(dcn_names) + tuple(ici_names))


def current_mesh():
    """The Mesh installed by an enclosing ``with mesh:`` block, or None.

    Lets mesh-aware ops (fused_xent's vocab-sharded path) resolve the mesh
    at trace time without threading it through every model signature —
    the same contract GSPMD's own `with mesh` constraint APIs use."""
    try:
        from jax._src import mesh as _mesh_lib
        m = _mesh_lib.thread_resources.env.physical_mesh
        if m is not None and not m.empty:
            return m
    except Exception:
        pass
    return None


def named_sharding(mesh, *spec):
    return NamedSharding(mesh, P(*spec))


def replicated(mesh):
    return NamedSharding(mesh, P())


def local_mesh_info():
    """Process-local view for multi-host (ref: trainer env
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM, launch.py:78-81)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
    }
