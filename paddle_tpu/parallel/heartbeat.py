"""Worker liveness monitoring — failure detection on the DCN fabric.

Ref: /root/reference/paddle/fluid/operators/distributed/heart_beat_monitor.h:38
(HeartBeatMonitor on the pserver: per-trainer UNINITED/RUNNING/COMPLETED
states, a monitor thread warning when a RUNNING trainer stops sending grads)
and rpc retry/deadline flags (operators/distributed/: FLAGS_rpc_deadline,
FLAGS_rpc_retry_times).

TPU-first: XLA collectives have no per-message deadline — liveness is
tracked out-of-band. `HeartBeatMonitor` is in-process (thread) fed by worker
pings; `KVHeartbeat`/`KVMonitor` ride the jax.distributed coordination
service (the DCN control fabric every multi-host job already has — no
shared filesystem needed, skew-free sequence-change ages, bounded set
retries); `FileHeartbeat` remains for single-host shared-dir setups.
`kv_barrier`/`barrier_with_timeout` are the bounded-wait barriers the RPC
layer's batch barriers provided.
"""

import os
import threading
import time

from paddle_tpu.core import flags as F
from paddle_tpu.observability import metrics as _metrics

UNINITED = "UNINITED"
RUNNING = "RUNNING"
COMPLETED = "COMPLETED"
STALLED = "STALLED"


class HeartBeatMonitor:
    """Track worker liveness from pings; invoke `on_stall(worker, age)` when
    a RUNNING worker goes silent past the timeout."""

    def __init__(self, num_workers, timeout_s=None, interval_s=None,
                 on_stall=None, clock=time.monotonic):
        self.num_workers = num_workers
        self.timeout_s = (timeout_s if timeout_s is not None
                          else F.get_flag("dist_heartbeat_timeout_s"))
        self.interval_s = (interval_s if interval_s is not None
                           else F.get_flag("dist_heartbeat_interval_s"))
        self.on_stall = on_stall
        self._clock = clock
        self._lock = threading.Lock()
        # worker -> last ping time; graft-guard: self._lock
        self._last = {}
        self._state = {
            i: UNINITED
            for i in range(num_workers)}  # graft-guard: self._lock
        self._thread = None
        self._stop = threading.Event()

    def update(self, worker, state=RUNNING):
        """Record a ping (ref: HeartBeatMonitor::Update)."""
        with self._lock:
            self._last[worker] = self._clock()
            if self._state.get(worker) != COMPLETED or state == COMPLETED:
                self._state[worker] = state

    def complete(self, worker):
        self.update(worker, COMPLETED)

    def add_worker(self, worker=None):
        """Grow the monitored set by one (elastic scale-up: the fleet
        autoscaler spawning a replica). Returns the new worker index."""
        with self._lock:
            if worker is None:
                worker = self.num_workers
            self.num_workers = max(self.num_workers, worker + 1)
            self._state.setdefault(worker, UNINITED)
            return worker

    def check(self):
        """One scan; returns {worker: (state, age_s)}. RUNNING workers past
        the timeout flip to STALLED and fire on_stall."""
        now = self._clock()
        out = {}
        stalls = []
        with self._lock:
            for w in range(self.num_workers):
                age = now - self._last.get(w, now)
                st = self._state.get(w, UNINITED)
                if st == RUNNING and age > self.timeout_s:
                    st = self._state[w] = STALLED
                    stalls.append((w, age))
                out[w] = (st, age)
        # the stall callback runs outside the lock: it may call back
        # into an engine/controller holding its own lock, and update()
        # from worker threads must never wait on it
        for w, age in stalls:
            _metrics.counter("heartbeat.missed").inc(worker=w)
            if self.on_stall is not None:
                self.on_stall(w, age)
        return out

    def start(self):
        """Background monitor thread (ref: LostWorkerMonitor loop)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.check()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="heartbeat-monitor")
        self._thread.start()

    def stop(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None

    def all_completed(self):
        with self._lock:
            return all(s == COMPLETED for s in self._state.values())


class FileHeartbeat:
    """Cross-process heartbeat over a shared directory: each worker touches
    `<dir>/worker_<i>.hb`; any process can monitor mtimes."""

    def __init__(self, directory, worker):
        self.dir = directory
        self.worker = worker
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"worker_{worker}.hb")

    def ping(self):
        with open(self.path, "a"):
            os.utime(self.path, None)

    def complete(self):
        with open(self.path + ".done", "w") as f:
            f.write("done")

    @staticmethod
    def scan(directory, num_workers, timeout_s):
        """Returns {worker: (state, age_s)} from file mtimes."""
        now = time.time()
        out = {}
        for w in range(num_workers):
            p = os.path.join(directory, f"worker_{w}.hb")
            if os.path.exists(p + ".done"):
                out[w] = (COMPLETED, 0.0)
            elif not os.path.exists(p):
                out[w] = (UNINITED, 0.0)
            else:
                age = now - os.path.getmtime(p)
                out[w] = (STALLED if age > timeout_s else RUNNING, age)
        return out


def _kv_client():
    """The jax.distributed coordination-service client — the DCN control
    fabric every multi-host job already has (launch.init_distributed).
    This is the transport the reference's HeartBeatMonitor rode the RPC
    layer for; no shared filesystem is required."""
    from jax._src import distributed
    client = getattr(distributed.global_state, "client", None)
    if client is None:
        from paddle_tpu.core.enforce import EnforceError
        raise EnforceError(
            "jax.distributed is not initialized — call "
            "paddle_tpu.parallel.launch.init_distributed() first "
            "(KV heartbeat rides the coordination service)")
    return client


def _kv_set(client, key, value, retries=3, backoff_s=0.1):
    """Set with bounded retries (ref FLAGS_rpc_retry_times semantics)."""
    last = None
    for attempt in range(retries):
        try:
            try:
                client.key_value_set(key, value, allow_overwrite=True)
            except TypeError:  # older jaxlib: no allow_overwrite kwarg
                try:
                    client.key_value_delete(key)
                except Exception:
                    pass
                client.key_value_set(key, value)
            return
        except Exception as e:  # transient coordination-service failure
            last = e
            time.sleep(backoff_s * (2 ** attempt))
    raise last


class PeerFailureError(RuntimeError):
    """The coordination service itself reported a dead/crashed task — the
    transport's connection-level liveness fired before any heartbeat
    timeout. This IS failure detection (just without per-worker
    attribution); elastic controllers treat it like a stall of unknown
    rank."""


def _kv_try_get(client, key):
    try:
        return client.key_value_try_get(key)
    except Exception as e:
        if "NOT_FOUND" in str(e) or isinstance(e, KeyError):
            return None  # key absent: worker not started yet
        raise PeerFailureError(
            f"coordination service error while reading '{key}' — a peer "
            f"task likely died (connection-level detection): {e}") from e


class KVHeartbeat:
    """Worker side of the DCN heartbeat: ping() bumps a sequence number in
    the jax.distributed KV store under `<tag>/worker_<i>`.

    The monitor (KVMonitor) tracks when it FIRST SAW each sequence change
    with its own clock, so cross-host clock skew never enters the age
    computation — the skew-free analog of the reference pserver observing
    grad arrival times (heart_beat_monitor.h:38 Update on recv)."""

    def __init__(self, worker, tag="hb", client=None, retries=3):
        self.worker = worker
        self.key = f"{tag}/worker_{worker}"
        self.client = client if client is not None else _kv_client()
        self.retries = retries
        self._seq = 0

    def ping(self):
        self._seq += 1
        _kv_set(self.client, self.key, f"{self._seq}:{RUNNING}",
                retries=self.retries)

    def complete(self):
        self._seq += 1
        _kv_set(self.client, self.key, f"{self._seq}:{COMPLETED}",
                retries=self.retries)


class KVMonitor:
    """Monitor side: scan() reads every worker's key and flags RUNNING
    workers whose sequence number has not advanced within `timeout_s` as
    STALLED (on_stall(worker, age) fires once per stall). Works from any
    process in the job — typically rank 0, the pserver successor."""

    def __init__(self, num_workers, timeout_s=None, tag="hb", client=None,
                 on_stall=None, clock=time.monotonic):
        self.num_workers = num_workers
        self.timeout_s = (timeout_s if timeout_s is not None
                          else F.get_flag("dist_heartbeat_timeout_s"))
        self.tag = tag
        self.client = client if client is not None else _kv_client()
        self.on_stall = on_stall
        self._clock = clock
        self._seen = {}     # worker -> (seq, first_seen_time)
        self._stalled = set()

    def scan(self):
        """Returns {worker: (state, age_s)}."""
        now = self._clock()
        out = {}
        for w in range(self.num_workers):
            raw = _kv_try_get(self.client, f"{self.tag}/worker_{w}")
            if raw is None:
                out[w] = (UNINITED, 0.0)
                continue
            if isinstance(raw, bytes):
                raw = raw.decode()
            seq_s, _, state = raw.partition(":")
            seq = int(seq_s)
            prev = self._seen.get(w)
            if prev is None or prev[0] != seq:
                self._seen[w] = (seq, now)
                self._stalled.discard(w)
            if state == COMPLETED:
                out[w] = (COMPLETED, 0.0)
                continue
            age = now - self._seen[w][1]
            if age > self.timeout_s:
                if w not in self._stalled:
                    self._stalled.add(w)
                    _metrics.counter("heartbeat.missed").inc(worker=w)
                    if self.on_stall is not None:
                        self.on_stall(w, age)
                out[w] = (STALLED, age)
            else:
                out[w] = (RUNNING, age)
        return out


def kv_barrier(name, timeout_s=300.0, client=None):
    """Deadline-bounded barrier on the coordination service (the RPC
    batch_barrier with FLAGS_rpc_deadline, minus the RPC layer). Raises
    TimeoutError for slow peers and PeerFailureError for dead ones — an
    elastic controller keeps waiting on the former and evicts/restarts on
    the latter (same classification as KVMonitor.scan)."""
    client = client if client is not None else _kv_client()
    t0 = time.monotonic()
    try:
        client.wait_at_barrier(name, int(timeout_s * 1000))
    except Exception as e:
        msg = str(e)
        if "DEADLINE_EXCEEDED" in msg or "timed out" in msg.lower():
            raise TimeoutError(
                f"kv_barrier '{name}' timed out after {timeout_s}s: "
                f"{msg}") from e
        raise PeerFailureError(
            f"kv_barrier '{name}': coordination service error — a peer "
            f"task likely died: {msg}") from e
    finally:
        _metrics.counter("heartbeat.barrier_wait_s").inc(
            time.monotonic() - t0, barrier=name)


def barrier_with_timeout(directory, worker, num_workers, timeout_s=300.0,
                         tag="barrier", poll_s=0.05):
    """File-based N-way barrier with a deadline (ref: the RPC layer's
    batch_barrier/fetch_barrier with FLAGS_rpc_deadline). Raises TimeoutError
    listing the missing workers.

    One-shot per (directory, tag): marker files persist, so reuse a tag only
    for the same sync point (Fleet.barrier stamps a generation counter)."""
    os.makedirs(directory, exist_ok=True)
    mine = os.path.join(directory, f"{tag}.{worker}")
    with open(mine, "w") as f:
        f.write(str(worker))
    deadline = time.time() + timeout_s
    t0 = time.monotonic()
    try:
        while True:
            present = {i for i in range(num_workers) if os.path.exists(
                os.path.join(directory, f"{tag}.{i}"))}
            if len(present) == num_workers:
                return
            if time.time() > deadline:
                missing = sorted(set(range(num_workers)) - present)
                raise TimeoutError(
                    f"barrier '{tag}' timed out after {timeout_s}s; "
                    f"missing workers {missing}")
            time.sleep(poll_s)
    finally:
        _metrics.counter("heartbeat.barrier_wait_s").inc(
            time.monotonic() - t0, barrier=tag)
