"""Worker liveness monitoring — failure detection on the DCN fabric.

Ref: /root/reference/paddle/fluid/operators/distributed/heart_beat_monitor.h:38
(HeartBeatMonitor on the pserver: per-trainer UNINITED/RUNNING/COMPLETED
states, a monitor thread warning when a RUNNING trainer stops sending grads)
and rpc retry/deadline flags (operators/distributed/: FLAGS_rpc_deadline,
FLAGS_rpc_retry_times).

TPU-first: XLA collectives have no per-message deadline — liveness is
tracked out-of-band. `HeartBeatMonitor` is in-process (thread) fed by worker
pings; `FileHeartbeat` extends it across processes via mtime files on a
shared dir (the typical multi-host TPU pod setup), replacing the reference's
grad-arrival sniffing. `barrier_with_timeout` is the bounded-wait barrier
the RPC layer's batch barriers provided.
"""

import os
import threading
import time

from paddle_tpu.core import flags as F

UNINITED = "UNINITED"
RUNNING = "RUNNING"
COMPLETED = "COMPLETED"
STALLED = "STALLED"


class HeartBeatMonitor:
    """Track worker liveness from pings; invoke `on_stall(worker, age)` when
    a RUNNING worker goes silent past the timeout."""

    def __init__(self, num_workers, timeout_s=None, interval_s=None,
                 on_stall=None, clock=time.monotonic):
        self.num_workers = num_workers
        self.timeout_s = (timeout_s if timeout_s is not None
                          else F.get_flag("dist_heartbeat_timeout_s"))
        self.interval_s = (interval_s if interval_s is not None
                           else F.get_flag("dist_heartbeat_interval_s"))
        self.on_stall = on_stall
        self._clock = clock
        self._lock = threading.Lock()
        self._last = {}          # worker -> last ping time
        self._state = {i: UNINITED for i in range(num_workers)}
        self._thread = None
        self._stop = threading.Event()

    def update(self, worker, state=RUNNING):
        """Record a ping (ref: HeartBeatMonitor::Update)."""
        with self._lock:
            self._last[worker] = self._clock()
            if self._state.get(worker) != COMPLETED or state == COMPLETED:
                self._state[worker] = state

    def complete(self, worker):
        self.update(worker, COMPLETED)

    def check(self):
        """One scan; returns {worker: (state, age_s)}. RUNNING workers past
        the timeout flip to STALLED and fire on_stall."""
        now = self._clock()
        out = {}
        with self._lock:
            for w in range(self.num_workers):
                age = now - self._last.get(w, now)
                st = self._state.get(w, UNINITED)
                if st == RUNNING and age > self.timeout_s:
                    st = self._state[w] = STALLED
                    if self.on_stall is not None:
                        self.on_stall(w, age)
                out[w] = (st, age)
        return out

    def start(self):
        """Background monitor thread (ref: LostWorkerMonitor loop)."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.check()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="heartbeat-monitor")
        self._thread.start()

    def stop(self):
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None

    def all_completed(self):
        with self._lock:
            return all(s == COMPLETED for s in self._state.values())


class FileHeartbeat:
    """Cross-process heartbeat over a shared directory: each worker touches
    `<dir>/worker_<i>.hb`; any process can monitor mtimes."""

    def __init__(self, directory, worker):
        self.dir = directory
        self.worker = worker
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"worker_{worker}.hb")

    def ping(self):
        with open(self.path, "a"):
            os.utime(self.path, None)

    def complete(self):
        with open(self.path + ".done", "w") as f:
            f.write("done")

    @staticmethod
    def scan(directory, num_workers, timeout_s):
        """Returns {worker: (state, age_s)} from file mtimes."""
        now = time.time()
        out = {}
        for w in range(num_workers):
            p = os.path.join(directory, f"worker_{w}.hb")
            if os.path.exists(p + ".done"):
                out[w] = (COMPLETED, 0.0)
            elif not os.path.exists(p):
                out[w] = (UNINITED, 0.0)
            else:
                age = now - os.path.getmtime(p)
                out[w] = (STALLED if age > timeout_s else RUNNING, age)
        return out


def barrier_with_timeout(directory, worker, num_workers, timeout_s=300.0,
                         tag="barrier", poll_s=0.05):
    """File-based N-way barrier with a deadline (ref: the RPC layer's
    batch_barrier/fetch_barrier with FLAGS_rpc_deadline). Raises TimeoutError
    listing the missing workers.

    One-shot per (directory, tag): marker files persist, so reuse a tag only
    for the same sync point (Fleet.barrier stamps a generation counter)."""
    os.makedirs(directory, exist_ok=True)
    mine = os.path.join(directory, f"{tag}.{worker}")
    with open(mine, "w") as f:
        f.write(str(worker))
    deadline = time.time() + timeout_s
    while True:
        present = {i for i in range(num_workers)
                   if os.path.exists(os.path.join(directory, f"{tag}.{i}"))}
        if len(present) == num_workers:
            return
        if time.time() > deadline:
            missing = sorted(set(range(num_workers)) - present)
            raise TimeoutError(
                f"barrier '{tag}' timed out after {timeout_s}s; "
                f"missing workers {missing}")
        time.sleep(poll_s)
