"""Hardware topology descriptions for the auto-parallelism planner.

A :class:`Topology` is the planner's entire view of the machine: chip
count, per-chip HBM and peak flops, and the two link classes that price
collectives — intra-slice ICI and inter-slice/host DCN (the hierarchical
topology of arxiv 2110.10548: placement cost depends on which links a
collective crosses, not just payload bytes).

Built-ins cover the CPU host (``cpuN`` — the tier-1/dev environment,
matching conftest's forced virtual devices) and common TPU slice shapes
(``v5e-8``, ``2xv5e-8`` for two slices, ...). When running live,
:func:`detect` derives a Topology from ``jax.devices()`` instead.

Numbers are *planning estimates* (peak specs, not measured), good for
ranking candidate meshes; they are not a performance model of record.
Stdlib-only at import — jax is pulled in lazily by :func:`detect`.
"""

import dataclasses
import re

GIB = 1024 ** 3


@dataclasses.dataclass(frozen=True)
class Topology:
    """One machine the planner can place a mesh on."""
    name: str
    num_chips: int            # total chips (all slices)
    hbm_bytes: int            # per-chip accelerator memory
    peak_flops: float         # per-chip peak (bf16 matmul units)
    intra_bw: float           # bytes/s per chip over in-slice links (ICI)
    inter_bw: float           # bytes/s per chip across slices/hosts (DCN)
    cores_per_chip: int = 1
    num_slices: int = 1
    hbm_bw: float = 0.0       # bytes/s per chip HBM (0 = unknown; the
    #                           roofline term serving decode is bound by
    #                           — speculation break-even depends on it)

    @property
    def chips_per_slice(self):
        return max(1, self.num_chips // max(1, self.num_slices))

    def axis_bandwidth(self, crosses_slices):
        """Per-chip bandwidth a collective sees on this axis."""
        return self.inter_bw if crosses_slices else self.intra_bw

    def to_json(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d):
        return cls(**d)


# per-chip characteristics by device kind: (hbm, peak bf16 flops, ici
# bytes/s per chip, dcn bytes/s per chip, hbm bytes/s per chip). Peaks
# mirror observability/perf.py peak_flops(); link numbers are spec-sheet
# order of magnitude, enough to rank dp-over-DCN vs tp-over-ICI
# correctly; HBM bandwidth is the roofline term batch-1 decode (and so
# the speculation break-even) is bound by.
_CHIPS = {
    "cpu": (4 * GIB, 5.0e10, 2.0e10, 2.0e10, 3.0e10),
    "v4": (32 * GIB, 275e12, 2.4e11, 2.5e10, 1.2e12),
    "v5e": (16 * GIB, 197e12, 1.0e11, 2.5e10, 8.2e11),
    "v5p": (95 * GIB, 459e12, 4.8e11, 2.5e10, 2.77e12),
    "v6e": (32 * GIB, 918e12, 1.8e11, 2.5e10, 1.64e12),
}

# "kind-N" (one slice of N chips) or "MxKIND-N" (M slices). cpuN means N
# virtual host devices (XLA_FLAGS --xla_force_host_platform_device_count).
_NAME_RE = re.compile(r"(?:(\d+)x)?([a-z0-9]+?)-?(\d+)$")

# presets listed by the CLI; any "(Mx)kind-N" spelling parses too
PRESETS = ("cpu1", "cpu4", "cpu8", "v5e-4", "v5e-8", "v5e-16", "v5e-64",
           "2xv5e-16", "v4-8", "v4-32", "v5p-8", "v5p-16", "v6e-8",
           "v6e-16")


def get_topology(name=None, devices=None):
    """Resolve a Topology: explicit name, else the ``autoplan_topology``
    flag, else auto-detection from the live jax devices."""
    if name is None:
        from paddle_tpu.core.flags import get_flag
        name = get_flag("autoplan_topology")
    if not name or name == "auto":
        return detect(devices)
    m = _NAME_RE.match(name.strip().lower())
    if not m or m.group(2) not in _CHIPS:
        raise KeyError(
            f"unknown topology {name!r} (want e.g. {', '.join(PRESETS)}, "
            "or 'auto' to detect from jax.devices())")
    slices = int(m.group(1)) if m.group(1) else 1
    kind, per_slice = m.group(2), int(m.group(3))
    hbm, peak, ici, dcn, mem_bw = _CHIPS[kind]
    return Topology(name=name, num_chips=slices * per_slice,
                    hbm_bytes=hbm, peak_flops=peak, intra_bw=ici,
                    inter_bw=dcn, num_slices=slices, hbm_bw=mem_bw)


def detect(devices=None):
    """Derive a Topology from the live ``jax.devices()``."""
    import jax
    devices = list(devices) if devices is not None else jax.devices()
    kind = (getattr(devices[0], "device_kind", "") or "cpu").lower()
    key = "cpu"
    for k in ("v6e", "v5p", "v5e", "v4"):
        if k in kind:
            key = k
            break
    hbm, peak, ici, dcn, mem_bw = _CHIPS[key]
    stats = getattr(devices[0], "memory_stats", None)
    if callable(stats):
        try:
            limit = (stats() or {}).get("bytes_limit")
            if limit:
                hbm = int(limit)
        except Exception:
            pass  # CPU backends often have no memory_stats
    slices = {getattr(d, "slice_index", 0) or 0 for d in devices}
    return Topology(name=f"detected:{key}{len(devices)}",
                    num_chips=len(devices), hbm_bytes=hbm, peak_flops=peak,
                    intra_bw=ici, inter_bw=dcn,
                    num_slices=max(1, len(slices)), hbm_bw=mem_bw)
