"""Factorization search: enumerate dp x tp x pp candidates, prune with
recorded reasons, score the rest, emit a :class:`MeshPlan`.

The search is exhaustive over divisor triples of the device count (the
space is tiny — O(d(n)^2) for n devices) per arxiv 2110.10548: legal
placements are enumerated against the hierarchical topology, each is
priced by the analytic cost model, and the argmin wins. Every pruned
candidate carries a `reasons` list (the `PlanEntry.reason` discipline
lifted to whole factorizations) so an operator can see *why* the
planner refused a mesh, not just that it did.

The winning MeshPlan is the one object the rest of the framework
consumes: `fleet.distributed_optimizer(strategy="auto")`,
`Trainer(mesh_plan=...)`, model `.loss(mesh_plan=...)`, and
`bench.py --mesh auto` all resolve mesh axes, per-param PartitionSpecs
(via the DistributionPlanner emission layer -> autoplan/layouts.py),
and loss sharding kwargs from it. JSON-serializable end to end.

Stdlib-only at import; jax enters lazily through build_mesh()/place().
"""

import dataclasses
import json
import time

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.parallel.autoplan import costmodel
from paddle_tpu.parallel.autoplan import topology as topo_lib

PP_SCHEDULES = ("gpipe", "1f1b", "interleaved")


@dataclasses.dataclass
class Candidate:
    """One (dp, tp, pp) factorization, scored or pruned-with-reasons.
    ``dp_collective`` records the gradient-exchange strategy the cost
    model chose for the dp axis ("f32" | "int8"; defaulted for JSON
    records written before quantized collectives existed)."""
    dp: int
    tp: int
    pp: int
    schedule: str = "1f1b"
    microbatches: int = 1
    feasible: bool = True
    dp_collective: str = "f32"
    reasons: list = dataclasses.field(default_factory=list)
    predicted: dict = dataclasses.field(default_factory=dict)

    @property
    def step_s(self):
        return self.predicted.get("step_s", float("inf"))

    def mesh_axes(self):
        axes = {n: s for n, s in
                (("dp", self.dp), ("tp", self.tp), ("pp", self.pp))
                if s > 1}
        return axes or {"dp": self.dp}

    def label(self):
        return ",".join(f"{n}{s}" for n, s in self.mesh_axes().items())

    def to_json(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d):
        return cls(**d)


def factorizations(n):
    """Every (dp, tp, pp) with dp*tp*pp == n, dp outermost."""
    out = []
    for tp in range(1, n + 1):
        if n % tp:
            continue
        rest = n // tp
        for pp in range(1, rest + 1):
            if rest % pp:
                continue
            out.append((rest // pp, tp, pp))
    return sorted(out)


def _pick_microbatches(local_batch, pp):
    """Smallest divisor of the per-replica batch >= 2*pp (bubble
    fraction <= 1/2), else the largest divisor; 0 when no split at all
    can feed pp stages."""
    if local_batch < pp:
        return 0
    divs = [m for m in range(1, local_batch + 1) if local_batch % m == 0]
    for m in divs:
        if m >= 2 * pp:
            return m
    return divs[-1]


def _check(spec, topology, dp, tp, pp, allow_pp, schedule, usable_hbm,
           quant_allreduce="auto"):
    """Feasibility of one candidate -> (Candidate). Never raises: every
    infeasibility is a recorded reason. ``quant_allreduce`` ("auto" |
    "on" | "off") governs the dp gradient-exchange strategy: "auto"
    prices BOTH the f32 and the chunked-int8 collective and keeps the
    cheaper (the EQuARX decision — quantized bytes change which mesh
    wins), recording why in the candidate's decision record."""
    cand = Candidate(dp=dp, tp=tp, pp=pp, schedule=schedule)
    reasons = cand.reasons
    if spec.batch % dp:
        reasons.append(f"dp={dp}: global batch {spec.batch} not divisible")
    if tp > 1:
        for dim, val in (("hidden", spec.hidden), ("heads", spec.heads),
                         ("intermediate", spec.intermediate),
                         ("vocab", spec.vocab)):
            if val % tp:
                reasons.append(f"tp={tp}: {dim} {val} not divisible")
    if pp > 1:
        if not allow_pp:
            reasons.append(
                f"pp={pp}: pipeline execution disabled for this run "
                "(caller has no pipeline train-step executor)")
        if spec.layers < pp:
            reasons.append(f"pp={pp}: only {spec.layers} layers "
                           "(< stages)")
        elif spec.layers % pp:
            reasons.append(f"pp={pp}: {spec.layers} layers not divisible "
                           "into equal stages")
        if not reasons:
            m = _pick_microbatches(max(1, spec.batch // dp), pp)
            if m == 0:
                reasons.append(
                    f"pp={pp}: per-replica batch {spec.batch // dp} too "
                    "small to microbatch across stages")
            else:
                cand.microbatches = m
    if reasons:
        cand.feasible = False
        return cand
    strategies = {"auto": ("f32", "int8"), "on": ("int8",),
                  "off": ("f32",)}.get(quant_allreduce, ("f32",))
    if dp == 1:
        strategies = ("f32",)       # no dp exchange to quantize
    preds = {s: costmodel.predict(spec, topology, dp, tp, pp,
                                  cand.microbatches, cand.schedule,
                                  dp_collective=s)
             for s in strategies}
    strat = min(preds, key=lambda s: preds[s]["step_s"])
    pred = preds[strat]
    cand.dp_collective = strat
    if pred["mem_bytes"] > usable_hbm:
        cand.feasible = False
        reasons.append(
            f"memory {pred['mem_bytes'] / topo_lib.GIB:.2f} GiB/chip > "
            f"{usable_hbm / topo_lib.GIB:.2f} GiB usable HBM")
    cand.predicted = {k: v for k, v in pred.items()
                      if k not in ("mem", "collective_bytes")}
    cand.predicted["collective_bytes"] = pred["collective_bytes"]
    if dp > 1 and len(preds) > 1:
        other = next(s for s in preds if s != strat)
        cand.predicted["dp_collective_reason"] = (
            f"{strat} all-reduce predicted "
            f"{preds[strat]['step_s'] * 1e3:.3f} ms/step vs "
            f"{preds[other]['step_s'] * 1e3:.3f} for {other} "
            f"(dp wire bytes {preds[strat]['collective_bytes']['dp']:.3g}"
            f" vs {preds[other]['collective_bytes']['dp']:.3g}, quantize "
            f"overhead {preds['int8']['quant_s'] * 1e3:.3f} ms)")
    elif dp > 1:
        cand.predicted["dp_collective_reason"] = (
            f"{strat} forced by quant_allreduce={quant_allreduce}")
    return cand


class MeshPlan:
    """The planner's output: mesh axes + layout + schedule + forecast.

    Mirrors DistributionPlan's inspectability contract — `describe()`
    is a stable human table, `to_json()`/`from_json()` round-trip the
    whole decision record including every pruned candidate's reasons.
    """

    def __init__(self, model, topology, axes, schedule, microbatches,
                 predicted, reason, candidates, entries=None):
        self.model = model
        self.topology = topology
        self.axes = dict(axes)
        self.schedule = schedule
        self.microbatches = microbatches
        self.predicted = dict(predicted)
        self.reason = reason
        self.candidates = list(candidates)
        # param path -> PlanEntry, filled by place()/shardings()
        self.entries = dict(entries or {})
        self._mesh = None

    # -- factorization views ------------------------------------------
    @property
    def dp(self):
        return self.axes.get("dp", 1)

    @property
    def tp(self):
        return self.axes.get("tp", 1)

    @property
    def pp(self):
        return self.axes.get("pp", 1)

    def label(self):
        return ",".join(f"{n}{s}" for n, s in self.axes.items())

    # -- consumption --------------------------------------------------
    def build_mesh(self, devices=None):
        """The jax Mesh for the winning axes (cached)."""
        from paddle_tpu.parallel.mesh import make_mesh
        if self._mesh is None:
            self._mesh = make_mesh(dict(self.axes), devices)
        return self._mesh

    def planner(self, mesh=None):
        """The sharding-emission layer: a DistributionPlanner in LM
        mode (autoplan/layouts.py rules, divisibility-downgrade)."""
        from paddle_tpu.parallel.planner import DistributionPlanner
        return DistributionPlanner(mesh or self.build_mesh(),
                                   lm_rules=True)

    def shardings(self, params, mesh=None):
        """NamedSharding pytree for `params`; records the per-param
        PlanEntry decisions on self.entries."""
        dplan = self.planner(mesh).plan(params)
        self.entries.update(dplan.entries)
        return dplan.param_shardings(params)

    def place(self, params, mesh=None):
        """device_put params per the plan (and record the entries)."""
        dplan = self.planner(mesh).plan(params)
        self.entries.update(dplan.entries)
        return dplan.place(params)

    def loss_kwargs(self):
        """Sharding kwargs for the model `.loss()` entry points."""
        return {"vocab_axis": "tp" if self.tp > 1 else None,
                "batch_axis": "dp" if self.dp > 1 else None,
                "mesh": self._mesh}

    def resolve_loss_axes(self, vocab_axis=None, batch_axis=None,
                          mesh=None):
        """Fill unset loss-sharding kwargs from the plan (the
        `mesh_plan=` path of the model `.loss()` entry points);
        explicitly-passed values win."""
        kw = self.loss_kwargs()
        return (vocab_axis or kw["vocab_axis"],
                batch_axis or kw["batch_axis"],
                mesh if mesh is not None else kw["mesh"])

    def strategy(self):
        """The equivalent fleet.DistributedStrategy."""
        from paddle_tpu.parallel.fleet import DistributedStrategy
        return DistributedStrategy.from_plan(self)

    # -- inspection ---------------------------------------------------
    def summary(self):
        """Compact record for bench rows / run logs."""
        out = {"axes": dict(self.axes), "schedule": self.schedule,
               "microbatches": self.microbatches,
               "topology": self.topology.name,
               "step_s": round(self.predicted.get("step_s", 0.0), 6),
               "mem_gib": round(
                   self.predicted.get("mem_bytes", 0) / topo_lib.GIB, 3),
               "reason": self.reason}
        if self.dp > 1:
            out["dp_collective"] = self.predicted.get("dp_collective",
                                                      "f32")
            out["dp_wire_bytes"] = self.predicted.get(
                "collective_bytes", {}).get("dp")
        return out

    def describe(self, top=None):
        """Human-readable ranked candidate table."""
        rows = sorted(self.candidates,
                      key=lambda c: (not c.feasible, c.step_s))
        if top:
            rows = rows[:top]
        lines = [f"autoplan: {self.model} on {self.topology.name} "
                 f"({self.topology.num_chips} chips) -> {self.label()}",
                 f"  {self.reason}",
                 f"  {'mesh':<14}{'sched':<8}{'ubs':>4}{'step_ms':>10}"
                 f"{'mem GiB':>9}  note"]
        for c in rows:
            if c.feasible:
                note = "<- winner" if c.mesh_axes() == self.axes else ""
                lines.append(
                    f"  {c.label():<14}"
                    f"{(c.schedule if c.pp > 1 else '-'):<8}"
                    f"{c.microbatches:>4}{c.step_s * 1e3:>10.2f}"
                    f"{c.predicted.get('mem_bytes', 0) / topo_lib.GIB:>9.2f}"
                    f"  {note}")
            else:
                lines.append(f"  {c.label():<14}{'-':<8}{'-':>4}"
                             f"{'-':>10}{'-':>9}  PRUNED: "
                             + "; ".join(c.reasons))
        return "\n".join(lines)

    def to_json(self):
        return {"model": self.model, "topology": self.topology.to_json(),
                "axes": dict(self.axes), "schedule": self.schedule,
                "microbatches": self.microbatches,
                "predicted": self.predicted, "reason": self.reason,
                "candidates": [c.to_json() for c in self.candidates],
                "entries": {name: {"spec": list(e.spec),
                                   "reason": e.reason}
                            for name, e in sorted(self.entries.items())}}

    def dumps(self, **kw):
        return json.dumps(self.to_json(), **kw)

    @classmethod
    def from_json(cls, d):
        from paddle_tpu.parallel.planner import PlanEntry
        entries = {
            name: PlanEntry(name, tuple(e["spec"]), e["reason"])
            for name, e in d.get("entries", {}).items()}
        return cls(model=d["model"],
                   topology=topo_lib.Topology.from_json(d["topology"]),
                   axes=d["axes"], schedule=d["schedule"],
                   microbatches=d["microbatches"],
                   predicted=d["predicted"], reason=d["reason"],
                   candidates=[Candidate.from_json(c)
                               for c in d["candidates"]],
                   entries=entries)


class NoFeasiblePlanError(ValueError):
    """Raised only when *every* factorization is infeasible; the message
    carries each candidate's recorded reasons."""


def plan(spec, topology=None, devices=None, allow_pp=True,
         schedule="1f1b", hbm_fraction=None, quant_allreduce=None):
    """Search dp x tp x pp factorizations of the device count and return
    the argmin-predicted-step-time :class:`MeshPlan`.

    `devices` overrides the topology's chip count (e.g. bench planning
    over the live `jax.devices()` while a preset supplies per-chip
    characteristics). `allow_pp=False` prunes pipeline candidates with
    a recorded reason — for callers whose train step has no pipeline
    executor. `quant_allreduce` (default: the flag) governs the dp
    gradient-exchange strategy per :func:`_check`.
    """
    t0 = time.perf_counter()
    if topology is None or isinstance(topology, str):
        topology = topo_lib.get_topology(topology)
    if hbm_fraction is None or quant_allreduce is None:
        from paddle_tpu.core.flags import get_flag
        if hbm_fraction is None:
            hbm_fraction = get_flag("autoplan_hbm_fraction")
        if quant_allreduce is None:
            quant_allreduce = get_flag("quant_allreduce")
    n = int(devices) if devices else topology.num_chips
    usable = topology.hbm_bytes * hbm_fraction
    cands = []
    for dp, tp, pp in factorizations(n):
        c = _check(spec, topology, dp, tp, pp, allow_pp, schedule, usable,
                   quant_allreduce=quant_allreduce)
        _metrics.counter("autoplan.candidates").inc(
            outcome="scored" if c.feasible else "pruned")
        cands.append(c)
    feasible = [c for c in cands if c.feasible]
    if not feasible:
        detail = "; ".join(
            f"{c.label()}: {' / '.join(c.reasons)}" for c in cands)
        raise NoFeasiblePlanError(
            f"autoplan: no feasible mesh for {spec.name} on "
            f"{topology.name} ({n} devices) — {detail}")
    # ties break toward the simplest mesh (fewest parallel modes)
    win = min(feasible,
              key=lambda c: (c.step_s, len(c.mesh_axes()), c.tp, c.pp))
    reason = (
        f"argmin predicted step time over {len(feasible)} feasible of "
        f"{len(cands)} candidates: {win.label()} "
        f"(~{win.step_s * 1e3:.2f} ms/step, "
        f"{win.predicted.get('mem_bytes', 0) / topo_lib.GIB:.2f} GiB/chip"
        + (f", {win.schedule} x{win.microbatches} microbatches"
           if win.pp > 1 else "")
        + (f", {win.dp_collective} dp all-reduce" if win.dp > 1 else "")
        + ")")
    out = MeshPlan(model=spec.name, topology=topology,
                   axes=win.mesh_axes(), schedule=win.schedule,
                   microbatches=win.microbatches, predicted=win.predicted,
                   reason=reason, candidates=cands)
    _metrics.histogram("autoplan.plan_s").observe(
        time.perf_counter() - t0)
    return out
