"""autoplan — cost-model-driven auto-parallelism.

Model + topology in, dp x tp x pp mesh + shardings + collective
strategy out (arxiv 2110.10548 style: enumerate legal placements over
the hierarchical topology, score with an analytic compute/memory/
collective cost model, pick the argmin):

    from paddle_tpu.parallel import autoplan

    spec = autoplan.ModelSpec.from_config(GPTConfig.small(),
                                          batch=32, seq=1024)
    mp = autoplan.plan(spec, topology="v5e-8")
    print(mp.describe())            # ranked candidate table + reasons
    mesh = mp.build_mesh()
    params = mp.place(params)       # LM layout via DistributionPlanner
    loss = model.loss(ids, mesh_plan=mp)

Entry points elsewhere: ``fleet.auto_plan(...)`` +
``distributed_optimizer(strategy="auto")``, ``Trainer(mesh_plan=...)``,
``bench.py --mesh auto``, and the ``tools/autoplan.py`` CLI.
"""

from paddle_tpu.parallel.autoplan.costmodel import (  # noqa: F401
    ModelSpec, calibration_report, chip_memory, collective_bytes,
    train_flops)
from paddle_tpu.parallel.autoplan.layouts import lm_layout  # noqa: F401
from paddle_tpu.parallel.autoplan.search import (  # noqa: F401
    Candidate, MeshPlan, NoFeasiblePlanError, factorizations, plan)
from paddle_tpu.parallel.autoplan.topology import (  # noqa: F401
    Topology, detect, get_topology)

__all__ = [
    "Candidate", "MeshPlan", "ModelSpec", "NoFeasiblePlanError",
    "Topology", "calibration_report", "chip_memory", "collective_bytes",
    "detect", "factorizations", "get_topology", "lm_layout", "plan",
    "train_flops",
]
