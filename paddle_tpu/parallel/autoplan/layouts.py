"""One source of truth for the Megatron-flavored LM sharding layout.

Both spec emitters in this package — `parallel.api.tp_lm_specs` (the
hand-driven path) and `parallel.planner.DistributionPlanner` (autoplan's
sharding-emission layer) — resolve a param's PartitionSpec through
:func:`lm_layout`, so the [V, H] vocab-table / [H, V] out_proj /
column-sharded-FFN rules live in exactly one place. Before this module
the same rules were duplicated in api.py and approximated by the
planner's generic largest-divisible-dim rule, and the two could drift.

Stdlib-only on purpose: specs are plain tuples of axis-name-or-None
(`PlanEntry.spec` convention); callers build `PartitionSpec(*spec)`.
The planner and the cost model can therefore reason about layouts
without importing jax.

Divisibility is a *downgrade*, never an error: with `tp_size` given, a
rule whose named dim does not divide evenly falls back to replicated
and the returned reason records the skip (`"tp SKIPPED: ..."`) — the
per-decision inspectability contract of `PlanEntry.reason`.
"""

# the tied-embedding tables across the LM families (GPT/BERT/ERNIE tok_emb,
# NMT src/tgt) — [V, H] "vh" layout, vocab dim 0 shards over tp so the
# fused cross-entropy runs per vocab shard with no weight gather
LM_VOCAB_TABLES = frozenset({"tok_emb", "src_emb", "tgt_emb"})

# default: 2-D weights smaller than this many elements stay replicated
LM_MIN_SIZE = 2 ** 11


def _downgrade(spec, shape, tp_size, reason):
    """Replicate any dim whose size does not divide tp_size; explain.

    tp_size=None skips the divisibility check (spec-emission callers
    like tp_lm_specs, where the mesh is unknown); tp_size=1 means the
    mesh has NO tp axis, so every named axis must be stripped or
    NamedSharding rejects the spec on a pure-dp mesh."""
    if tp_size is None:
        return tuple(spec), reason
    if tp_size <= 1:
        return ((None,) * len(spec),
                f"replicated (tp=1 — no tp axis in mesh; rule was: "
                f"{reason})")
    out = list(spec)
    for i, axis in enumerate(spec):
        if axis is not None and shape[i] % tp_size != 0:
            out[i] = None
            reason = (f"tp SKIPPED: dim {i} ({shape[i]}) not divisible "
                      f"by tp={tp_size} — replicated (was: {reason})")
    return tuple(out), reason


def lm_layout(names, shape, tp="tp", min_size=LM_MIN_SIZE, tp_size=None):
    """The LM tensor-parallel layout rule for one param.

    Args:
      names: path components of the param (e.g. ["tok_emb", "weight"]).
      shape: the param's shape tuple.
      tp: mesh axis name to shard over.
      min_size: 2-D weights below this many elements replicate.
      tp_size: when given (the axis size), non-divisible dims are
        downgraded to replicated with a recorded reason instead of
        emitting a spec that would fail at placement.

    Returns (spec, reason): spec is a tuple of axis-name-or-None per
    dim; reason is the human-readable decision record. Never raises.
    """
    names = [str(n) for n in names]
    leaf = names[-1] if names else ""
    ndim = len(shape)
    size = 1
    for d in shape:
        size *= d
    if leaf == "weight" and ndim == 2 and LM_VOCAB_TABLES & set(names):
        return _downgrade(
            (tp, None), shape, tp_size,
            "tp: vocab dim 0 of embedding table ([V, H] vh layout; fused "
            "xent runs per shard)")
    if leaf == "weight" and ndim == 2 and "out_proj" in names:
        return _downgrade(
            (None, tp), shape, tp_size,
            "tp: vocab dim 1 of output projection ([H, V] hv layout)")
    if leaf == "mlm_bias" and ndim == 1:
        return _downgrade(
            (tp,), shape, tp_size,
            "tp: vocab-length bias follows the table shard")
    if ndim == 2 and size >= min_size:
        return _downgrade(
            (None, tp), shape, tp_size,
            f"tp: column-shard 2-D weight (size {size} >= {min_size})")
    return (None,) * ndim, (
        "replicated (not an LM tp target: "
        f"{'scalar' if ndim == 0 else f'{ndim}-D, size {size}'})")
