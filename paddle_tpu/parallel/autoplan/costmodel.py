"""Analytic cost model for candidate (dp, tp, pp) factorizations.

Per arxiv 2110.10548's framing, a candidate placement is scored with
closed-form estimates of three resources:

  * compute flops — transformer matmul flops (QKV/out projections, FFN,
    S^2 attention scores, the vocab logits matmul), trained = 3x forward
    (each matmul's backward is two matmuls). Calibrated against
    ``jit(step).lower().compile().cost_analysis()`` on CPU by
    :func:`calibration_report` / tests/test_autoplan.py.
  * per-chip memory — params + Adam moments + grads (f32), sharded over
    tp (and layers over pp), plus remat-policy-aware activation
    residents and the fused-xent chunk temporary. Candidates whose
    total exceeds usable HBM are pruned by the search.
  * collective bytes — ring all-reduce of grads over dp
    (2(n-1)/n x payload), the per-layer activation all-reduces of
    Megatron tp (2 fwd + 4 bwd-equivalent, folded to 3x fwd here), and
    p2p microbatch boundary sends for pp. The per-axis byte account is
    the hook where quantized collectives (EQuARX, arxiv 2506.17615)
    would later discount an axis.

Everything here is an *estimate for ranking*: absolute step times are
not promised, but the ordering of candidates on a given topology is
what the search needs. Stdlib-only at import; jax is pulled in lazily
by :func:`calibration_report`.
"""

import dataclasses

# assumed fraction of peak the matmuls sustain — cancels out when
# ranking candidates on one topology, kept explicit for step_s realism
MFU_ASSUMED = 0.4

# activation elements saved per token per layer, in units of H and I:
# qkv + attn-out + 2 residual streams + ln stats ~= 8H; ffn hidden ~= 2I
_ACT_H, _ACT_I = 8, 2

# fraction of saved activations that survive each remat policy
# (nn/encoder scan-over-layers checkpoint policies)
REMAT_KEEP = {"nothing": 1.0, "dots_saveable": 0.6, "full": 0.15}


@dataclasses.dataclass
class ModelSpec:
    """The cost model's view of one training job (model x batch x seq)."""
    name: str
    vocab: int
    hidden: int
    layers: int
    heads: int
    intermediate: int
    seq: int
    batch: int                 # global batch
    mask_fraction: float = 1.0  # fraction of tokens entering the loss (MLM)
    extra_vocab: int = 0       # second embedding table (NMT src_emb)
    max_position: int = 0      # position-table rows (0 -> seq)
    remat: str = "nothing"
    param_bytes: int = 4       # f32 master params
    act_bytes: int = 2         # bf16 activations (amp policy)

    @property
    def tokens(self):
        return self.batch * self.seq

    @property
    def loss_rows(self):
        """Rows entering the vocab-projection loss per step (matches
        analysis/contracts.py ShardedCase.loss_rows)."""
        if self.mask_fraction >= 1.0:
            return self.batch * self.seq
        return self.batch * max(1, int(self.mask_fraction * self.seq))

    def to_json(self):
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d):
        return cls(**d)

    @classmethod
    def from_config(cls, cfg, batch, seq, name=None):
        """Build a spec from a model config dataclass (GPTConfig /
        BertConfig / ErnieConfig / TransformerConfig)."""
        cname = type(cfg).__name__.lower()
        name = name or cname.replace("config", "")
        if hasattr(cfg, "d_model"):       # NMT encoder-decoder
            return cls(name=name, vocab=cfg.tgt_vocab, hidden=cfg.d_model,
                       layers=cfg.enc_layers + cfg.dec_layers,
                       heads=cfg.num_heads, intermediate=cfg.ffn_dim,
                       seq=seq, batch=batch, extra_vocab=cfg.src_vocab,
                       max_position=getattr(cfg, "max_len", 0))
        mlm = "bert" in cname or "ernie" in cname
        return cls(name=name, vocab=cfg.vocab_size, hidden=cfg.hidden_size,
                   layers=cfg.num_layers, heads=cfg.num_heads,
                   intermediate=cfg.intermediate_size, seq=seq, batch=batch,
                   mask_fraction=0.15 if mlm else 1.0,
                   max_position=getattr(cfg, "max_position", 0),
                   remat=getattr(cfg, "remat", None) or "nothing")


# ---------------------------------------------------------------- flops

def fwd_flops(spec):
    """Forward matmul flops for one step (2*M*N*K per matmul, XLA's
    counting convention)."""
    H, I = spec.hidden, spec.intermediate
    T = spec.tokens
    proj = 2 * T * (4 * H * H + 2 * H * I)          # qkv+out, ffn up+down
    attn = 4 * spec.batch * spec.seq ** 2 * H       # QK^T and PV
    loss = 2 * spec.loss_rows * H * spec.vocab      # (chunked) logits
    return spec.layers * (proj + attn) + loss


def train_flops(spec):
    """Forward + backward: each matmul's grad is two matmuls -> 3x fwd.
    Remat recompute (policy 'full') re-runs the forward once more."""
    mult = 4.0 if spec.remat == "full" else 3.0
    return mult * fwd_flops(spec)


# --------------------------------------------------------------- memory

def param_counts(spec):
    """{embedding, per_layer, head} param counts. The embedding group is
    the vocab-dim-shardable [V, H] mass (+ position table, replicated in
    the count's 'head' bucket for simplicity)."""
    H, I = spec.hidden, spec.intermediate
    emb = (spec.vocab + spec.extra_vocab) * H
    per_layer = 4 * H * H + 2 * H * I + 13 * H      # weights + biases + ln
    pos = max(spec.max_position, spec.seq) * H
    head = pos + 2 * H                               # pos table + final ln
    if spec.mask_fraction < 1.0:                     # MLM transform head
        head += H * H + H + spec.vocab               # dense + ln + mlm_bias
    return {"embedding": emb, "per_layer": per_layer, "head": head}


def chip_memory(spec, dp, tp, pp, microbatches=1, schedule="1f1b"):
    """Per-chip memory estimate (bytes) for a candidate factorization.

    Params follow the LM layout (autoplan/layouts.py): embedding tables
    and 2-D weights shard over tp; layers split across pp stages; dp
    replicates (no ZeRO here). Optimizer state = 2 Adam moments (f32).
    """
    counts = param_counts(spec)
    layers_local = -(-spec.layers // pp)            # ceil: worst stage
    params_c = (counts["embedding"] / tp
                + layers_local * counts["per_layer"] / tp
                + counts["head"])                   # head mostly replicated
    state = params_c * spec.param_bytes * 3         # master + 2 moments
    grads = params_c * spec.param_bytes
    # activation residents between forward and backward
    local_b = max(1, spec.batch // dp)
    micro_b = max(1, local_b // microbatches) if pp > 1 else local_b
    keep = REMAT_KEEP.get(spec.remat, 1.0)
    act_layer = (micro_b * spec.seq
                 * (_ACT_H * spec.hidden + _ACT_I * spec.intermediate)
                 / tp * spec.act_bytes)
    if pp > 1:
        in_flight = microbatches if schedule == "gpipe" \
            else min(pp, microbatches)
    else:
        in_flight = 1
    acts = layers_local * act_layer * keep * in_flight
    # fused-xent chunk temporary: [local rows, min(V/tp, chunk)] f32
    rows_local = max(1, spec.loss_rows // dp)
    loss_tmp = rows_local * min(spec.vocab / tp, 8192) * 4
    total = state + grads + acts + loss_tmp
    return {"params_state": state, "grads": grads, "activations": acts,
            "loss_tmp": loss_tmp, "total": total}


def train_hlo_bytes(spec, dp, tp, pp=1):
    """Per-chip estimate of XLA cost_analysis's "bytes accessed" for one
    train step — a TRAFFIC estimate, unlike :func:`chip_memory`'s
    residency. Each forward intermediate is written once and re-read by
    its consumer and its backward (~3 touches), the backward writes and
    re-reads matching gradients (~3 more), params and Adam moments sweep
    once in each direction, and the chunked loss touches its
    [rows, chunk] tile on the forward and backward. Order-of-magnitude
    only: the MaxHloBytes budget contract multiplies it by a calibrated
    tolerance."""
    H, I = spec.hidden, spec.intermediate
    f32 = 4
    local_t = max(1, spec.tokens // dp)
    local_b = max(1, spec.batch // dp)
    act = spec.layers * local_t * (_ACT_H * H + _ACT_I * I) / tp * f32
    scores = spec.layers * local_b * spec.heads * spec.seq ** 2 / tp * f32
    counts = param_counts(spec)
    params = (counts["embedding"] / tp
              + -(-spec.layers // pp) * counts["per_layer"] / tp
              + counts["head"]) * spec.param_bytes
    logits = (max(1, spec.loss_rows // dp)
              * min(spec.vocab / tp, 8192) * f32)
    return 6.0 * (act + scores) + 8.0 * params + 4.0 * logits


# ------------------------------------------------------ serving (decode)

def decode_flops(spec, slots, context):
    """Matmul flops for ONE serving decode step: each live slot pushes a
    single token through every layer (projections + FFN), attends over
    ``context`` cached positions, and scores the full vocab."""
    H, I = spec.hidden, spec.intermediate
    proj = 2 * slots * (4 * H * H + 2 * H * I)
    attn = 4 * slots * context * H
    logits = 2 * slots * H * spec.vocab
    return spec.layers * (proj + attn) + logits


def decode_hlo_bytes(spec, slots, context, kv_dtype=None):
    """Traffic estimate for one decode step: every parameter is read
    once (batch=slots is too small to amortize below one sweep) and the
    K/V cache pages for ``context`` positions are read and written
    back. ``kv_dtype="int8"`` prices the quantized pool: 1 byte/element
    plus one f32 per-row scale per cached token (the ops/attention.py
    layout) in place of ``param_bytes`` per element — the ~4x KV-traffic
    cut the re-derived MaxHloBytes serve budget encodes. The budget
    contract multiplies by a tolerance."""
    counts = param_counts(spec)
    params = (counts["embedding"] + spec.layers * counts["per_layer"]
              + counts["head"]) * spec.param_bytes
    if str(kv_dtype or "") == "int8":
        row_bytes = spec.hidden * 1 + 4          # int8 values + f32 scale
    else:
        row_bytes = spec.hidden * spec.param_bytes
    kv = 2 * spec.layers * slots * context * row_bytes * 2
    return params + kv


def verify_flops(spec, slots, window, context):
    """Matmul flops for ONE speculative verify step: each live slot
    pushes a ``window``-token window (pending token + spec_k proposals)
    through every layer, every window position attends the slot's whole
    cached prefix, and the head scores each position — a decode step
    amortized over up to ``window`` emitted tokens."""
    H, I = spec.hidden, spec.intermediate
    proj = 2 * slots * window * (4 * H * H + 2 * H * I)
    attn = 4 * slots * window * context * H
    logits = 2 * slots * window * H * spec.vocab
    return spec.layers * (proj + attn) + logits


def verify_hlo_bytes(spec, slots, window, context, kv_dtype=None,
                     pool_rows=None):
    """Traffic estimate for one verify step: one parameter sweep (the
    window batch is still far too small to amortize below it), the
    slot's cached prefix + window rows gathered for attention, and the
    window's fresh K/V rows written back. ``pool_rows`` (total page-pool
    token rows = num_pages * page_size) additionally prices the donated
    pool pass-through: the verify module's page-gather/scatter touches
    every pool row once in and once out, which dominates when the pool
    dwarfs the live context."""
    counts = param_counts(spec)
    params = (counts["embedding"] + spec.layers * counts["per_layer"]
              + counts["head"]) * spec.param_bytes
    if str(kv_dtype or "") == "int8":
        row_bytes = spec.hidden * 1 + 4          # int8 values + f32 scale
    else:
        row_bytes = spec.hidden * spec.param_bytes
    kv = 2 * spec.layers * slots * (context + 2 * window) * row_bytes
    if pool_rows:
        kv += 2 * spec.layers * pool_rows * row_bytes * 2
    return params + kv


def predict_decode(spec, topology, slots, context, rate=None,
                   kv_dtype=None, draft_spec=None, spec_k=None,
                   accept_rate=None, pool_rows=None):
    """Score one serving decode step the way :func:`predict` scores a
    train step: flops + traffic estimates and a step-seconds figure.
    ``rate=None`` prices compute at the autotune-measured achieved rate
    (falling back to analytic); passing an explicit rate keeps the call
    stdlib-pure — what the budget contracts do. ``kv_dtype`` prices the
    KV pool per :func:`decode_hlo_bytes`.

    ``spec_k`` switches on speculative-decoding pricing: one round =
    spec_k draft steps (``draft_spec``; None = self-draft at the target
    spec) plus ONE target verify over a spec_k+1 window, emitting
    1 + accept_rate * spec_k tokens per slot. The verify_* keys are
    what the serve.verify budget contracts consume.

    Two break-even figures come out, and they tell different stories:
    ``break_even_accept_rate`` is the FLOPS break-even — verifying a
    W-token window costs ~W tokens of compute, so on pure flops
    speculation never pays (the figure sits at or above 1.0; that is a
    statement about energy, not latency). ``break_even_accept_rate_s``
    is the ROOFLINE (wall-clock) break-even: each step is priced at
    max(flops/rate, bytes/hbm_bw), and because batch-1 decode is
    memory-bound (one weight+KV stream per step), the verify window
    amortizes the stream over W tokens — this is the figure
    tools/autoplan.py reports per topology, and it needs the
    topology's ``hbm_bw`` (absent -> the time keys are omitted)."""
    flops = float(decode_flops(spec, slots, context))
    dec_bytes = float(decode_hlo_bytes(spec, slots, context,
                                       kv_dtype=kv_dtype))
    if rate is None:
        rate, rate_source = achieved_rate(topology)
    else:
        rate_source = "fixed"
    hbm_bw = float(getattr(topology, "hbm_bw", 0.0) or 0.0)

    def roofline(f, b):
        return max(f / rate, b / hbm_bw) if hbm_bw > 0 else None

    out = {
        "step_s": flops / rate,
        "flops_per_chip": flops,
        "hlo_bytes": dec_bytes,
        "kv_dtype": str(kv_dtype or "f32"),
        "rate_source": rate_source,
        "rate_flops_s": rate,
    }
    step_rl = roofline(flops, dec_bytes)
    if step_rl is not None:
        out["step_roofline_s"] = step_rl
    if spec_k:
        window = spec_k + 1
        dspec = draft_spec if draft_spec is not None else spec
        vf = float(verify_flops(spec, slots, window, context))
        vb = float(verify_hlo_bytes(spec, slots, window, context,
                                    kv_dtype=kv_dtype,
                                    pool_rows=pool_rows))
        df = float(spec_k * decode_flops(dspec, slots, context))
        db = float(spec_k * decode_hlo_bytes(dspec, slots, context,
                                             kv_dtype=kv_dtype))
        out.update({
            "spec_k": int(spec_k),
            "draft": "self" if draft_spec is None else
                     (dspec.name or "draft"),
            "verify_flops_per_chip": vf,
            "verify_hlo_bytes": vb,
            "draft_flops_per_chip": df,
            "round_flops_per_chip": df + vf,
            "round_s": (df + vf) / rate,
            "draft_overhead": df / flops,
            "break_even_accept_rate":
                max(0.0, ((df + vf) / flops - 1.0) / spec_k),
        })
        round_rl = None
        if step_rl is not None:
            # one draft step prices at 1/spec_k of the k-step totals
            round_rl = (roofline(vf, vb)
                        + spec_k * roofline(df / spec_k, db / spec_k))
            out.update({
                "round_roofline_s": round_rl,
                "break_even_accept_rate_s":
                    max(0.0, (round_rl / step_rl - 1.0) / spec_k),
            })
        if accept_rate is not None:
            tps = 1.0 + float(accept_rate) * spec_k
            out.update({
                "accept_rate": float(accept_rate),
                "tokens_per_target_step": tps,
                "flops_per_token": (df + vf) / (slots * tps),
                "speedup_vs_plain": flops * tps / (df + vf),
            })
            if round_rl is not None:
                out["speedup_vs_plain_s"] = step_rl * tps / round_rl
    return out


# ----------------------------------------------------------- collectives

# compute overhead of the chunked int8 collective, in simple ops per
# gradient element: abs/max + divide + round + clip + cast on the way
# out, int32 accumulate + scale-multiply back — ~8 elementwise ops
QUANT_ALLREDUCE_OPS_PER_ELEM = 8.0
QUANT_CHUNK_DEFAULT = 65536


def dp_grad_elements(spec, tp, pp):
    """Gradient elements one dp all-reduce exchanges per chip (the
    tp/pp-sharded parameter count) — what both collective strategies
    quantify over."""
    counts = param_counts(spec)
    layers_local = -(-spec.layers // pp)
    return (counts["embedding"] / tp
            + layers_local * counts["per_layer"] / tp
            + counts["head"])


def collective_bytes(spec, dp, tp, pp, microbatches=1,
                     dp_collective="f32", quant_chunk=QUANT_CHUNK_DEFAULT):
    """Per-chip bytes moved per step, by mesh axis. Ring all-reduce of N
    payload bytes moves 2(n-1)/n x N per chip; all-gather/reduce-scatter
    halves (n-1)/n x N each — the dp grad sync is priced as the full
    all-reduce, tp as the Megatron per-layer activation all-reduces, pp
    as p2p boundary sends.

    ``dp_collective`` picks the dp strategy the EQuARX way
    (arxiv 2506.17615 — quantized all-reduce is a planner decision):
    "f32" moves param_bytes per gradient element; "int8" moves 1 byte
    per element plus one f32 scale per ``quant_chunk`` elements (the
    parallel/communicator.py quantized_psum wire layout)."""
    out = {}
    counts = param_counts(spec)
    layers_local = -(-spec.layers // pp)
    local_b = max(1, spec.batch // dp)
    if dp > 1:
        elems = dp_grad_elements(spec, tp, pp)
        if dp_collective == "int8":
            chunk = max(int(quant_chunk), 1)
            grad_payload = elems + (-(-elems // chunk)) * 4
        else:
            grad_payload = elems * spec.param_bytes
        out["dp"] = 2.0 * (dp - 1) / dp * grad_payload
    if tp > 1:
        act = local_b * spec.seq * spec.hidden * spec.act_bytes
        # 2 all-reduces/layer fwd (attn out + ffn out), ~3x for train
        out["tp"] = (layers_local * 6 * act * 2.0 * (tp - 1) / tp
                     + 4 * max(1, spec.loss_rows // dp) * 4)  # xent stats
    if pp > 1:
        micro_b = max(1, local_b // max(1, microbatches))
        act = micro_b * spec.seq * spec.hidden * spec.act_bytes
        out["pp"] = 2 * max(1, microbatches) * act   # fwd act + bwd grad
    return out


# -------------------------------------------------------------- predict

def _topology_chip(topology):
    """The chip-family key of a topology name ("v5e-16" -> "v5e",
    "detected:cpu4" -> "cpu") — what the autotune cache keys rates by."""
    name = (topology.name or "").split(":")[-1].lower()
    for key in ("v6e", "v5p", "v5e", "v4"):
        if key in name:
            return key
    return "cpu"


def achieved_rate(topology):
    """(achieved flops/s, source) for pricing compute: the harmonic-mean
    measured rate from the autotune cache when this chip family has
    entries (source "measured"), else the analytic ``peak * MFU_ASSUMED``
    constant (source "analytic"). Import is lazy and failure-tolerant —
    this module stays stdlib-importable and a broken cache must never
    take down a plan."""
    try:
        from paddle_tpu.ops.pallas import autotune
        rate = autotune.measured_rate(_topology_chip(topology))
    except Exception:
        rate = None
    if rate is not None:
        return rate[0], "measured"
    return topology.peak_flops * MFU_ASSUMED, "analytic"


def predict(spec, topology, dp, tp, pp, microbatches=1, schedule="1f1b",
            rate=None, dp_collective="f32",
            quant_chunk=QUANT_CHUNK_DEFAULT):
    """Score one candidate: predicted step seconds + the estimates that
    produced it. dp is the outermost axis — it crosses slice boundaries
    first on a multi-slice topology, so it prices at DCN bandwidth.

    Compute is priced at the achieved-flops/s rate measured by the tile
    autotuner when its cache has entries for this chip family (the
    ``rate_source`` field says which constant priced the candidate);
    passing ``rate`` explicitly skips that lookup and keeps the call
    stdlib-pure (what the budget contracts do).

    ``dp_collective="int8"`` prices the chunked quantized all-reduce:
    ~4x fewer dp wire bytes, paid for with
    QUANT_ALLREDUCE_OPS_PER_ELEM elementwise ops per gradient element of
    quantize/dequant compute — the trade that makes quantization win on
    DCN-bandwidth dp axes and lose on ICI ones."""
    flops_c = train_flops(spec) / (dp * tp * pp)
    if rate is None:
        rate, rate_source = achieved_rate(topology)
    else:
        rate_source = "fixed"
    compute_s = flops_c / rate
    bubble = (pp - 1) / max(1, microbatches) if pp > 1 else 0.0
    coll = collective_bytes(spec, dp, tp, pp, microbatches,
                            dp_collective=dp_collective,
                            quant_chunk=quant_chunk)
    multi = topology.num_slices > 1
    coll_s = sum(
        b / topology.axis_bandwidth(crosses_slices=(ax == "dp" and multi))
        for ax, b in coll.items())
    quant_s = 0.0
    if dp > 1 and dp_collective == "int8":
        quant_s = (QUANT_ALLREDUCE_OPS_PER_ELEM
                   * dp_grad_elements(spec, tp, pp) / rate)
    mem = chip_memory(spec, dp, tp, pp, microbatches, schedule)
    return {
        "step_s": compute_s * (1.0 + bubble) + coll_s + quant_s,
        "compute_s": compute_s,
        "collective_s": coll_s,
        "quant_s": quant_s,
        "dp_collective": dp_collective if dp > 1 else "none",
        "bubble_fraction": bubble,
        "flops_per_chip": flops_c,
        "hlo_bytes": float(train_hlo_bytes(spec, dp, tp, pp)),
        "mem_bytes": mem["total"],
        "mem": mem,
        "collective_bytes": coll,
        "rate_source": rate_source,
        "rate_flops_s": rate,
    }


# ----------------------------------------------------------- calibration

def calibration_report(spec, jitted, *args, topology=None):
    """Compare the analytic flop count against XLA's own
    ``compile().cost_analysis()`` for a jitted train step — the
    cost-model's ground-truth hook (runs on CPU; tests assert the ratio
    stays inside a tolerance band).

    The ``constants`` block labels which source prices compute on this
    chip family: "measured" (autotune-cache achieved-flops/s, with the
    rate and how many cache entries back it) vs "analytic"
    (``peak * MFU_ASSUMED``)."""
    from paddle_tpu.observability.perf import cost_flops
    measured = cost_flops(jitted, *args)
    predicted = train_flops(spec)
    if topology is None:
        from paddle_tpu.parallel.autoplan import topology as _topo
        topology = _topo.detect()
    rate, rate_source = achieved_rate(topology)
    try:
        from paddle_tpu.ops.pallas import autotune
        chip = _topology_chip(topology)
        entries = len(autotune.measured_rates().get(chip, ()))
    except Exception:
        chip, entries = _topology_chip(topology), 0
    return {
        "model": spec.name,
        "predicted_flops": float(predicted),
        "measured_flops": float(measured),
        "ratio": float(predicted / measured) if measured else None,
        "constants": {
            "chip": chip,
            "rate_source": rate_source,
            "rate_flops_s": float(rate),
            "measured_entries": entries,
        },
    }
