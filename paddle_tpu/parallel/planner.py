"""Distribution planner — the DistributeTranspiler successor.

Ref: /root/reference/python/paddle/fluid/transpiler/distribute_transpiler.py
:230 — the reference REWRITES a built Program per mode (pserver slicing
:137-173, nccl2 :308, collective :360), inserting send/recv/allreduce ops
and splitting variables. On TPU the program never needs op-level surgery:
GSPMD propagates shardings from annotations, so "transpiling" a captured
program = choosing a mesh and a PartitionSpec for every param and input.
This planner makes that choice *for an arbitrary captured program* from a
DistributedStrategy — the transpiler's planning role without its rewrite
machinery — and returns the pjit-wrapped step plus a materialized plan
(inspectable/serializable, the counterpart of test_dist_transpiler.py's
asserts on rewritten program text).

Planning rules (applied per-param, in order):
  * tp: params whose name matches `tp_patterns` (or, with
    tp_auto=True, any >=2-D param) shard their largest tp-divisible dim
    over the "tp" axis — reference DistFCConfig's intent, generalized.
  * ep (FIRST, wins over tp/fsdp): params matching ep_patterns shard
    their leading [E, ...] expert-stack dim over the "ep" axis (the
    pserver table-shard successor; nn/moe.py convention).
  * fsdp: remaining params above `fsdp_min_size` shard their largest
    divisible dim over the "fsdp" axis (ZeRO-3).
  * otherwise replicated (pure DP; grads all-reduce over "dp" like the
    multi_devices_graph_pass AllReduce mode).
Inputs shard dim 0 over "dp"; sparse-table params use P("ep", None).
"""

import dataclasses
import json
import re

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.core.enforce import enforce
from paddle_tpu.parallel.autoplan import layouts


@dataclasses.dataclass
class PlanEntry:
    path: str
    spec: tuple          # PartitionSpec as a tuple of axis-or-None
    reason: str

    def partition_spec(self):
        return P(*self.spec)


class DistributionPlan:
    """Materialized plan: {param path: PlanEntry} + input specs."""

    def __init__(self, entries, input_specs, mesh):
        self.entries = entries
        self.input_specs = input_specs
        self.mesh = mesh

    def param_shardings(self, params):
        """NamedSharding pytree matching `params`."""
        flat = jax.tree_util.tree_leaves_with_path(params)
        out = []
        for path, leaf in flat:
            name = _path_name(path)
            spec = self.entries[name].partition_spec()
            out.append(NamedSharding(self.mesh, spec))
        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(params), out)

    def place(self, params):
        """device_put params per the plan."""
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), params,
            self.param_shardings(params))

    def describe(self):
        """Transpiler-test-style textual form (assertable/serializable)."""
        return json.dumps(
            {name: {"spec": [str(s) for s in e.spec], "reason": e.reason}
             for name, e in sorted(self.entries.items())}, indent=2)


def _path_name(path):
    return "/".join(str(getattr(k, "key", k)) for k in path)


class DistributionPlanner:
    """Plan shardings for an arbitrary captured program's params/inputs."""

    def __init__(self, mesh, tp_patterns=(), tp_auto=False,
                 fsdp_min_size=None, ep_patterns=(), lm_rules=False,
                 lm_min_size=layouts.LM_MIN_SIZE):
        self.mesh = mesh
        self.axes = dict(mesh.shape)
        self.tp_patterns = [re.compile(p) for p in tp_patterns]
        self.tp_auto = tp_auto
        self.fsdp_min_size = fsdp_min_size
        # lm_rules: resolve tp specs through the shared LM layout table
        # (autoplan/layouts.py — the same source of truth as
        # api.tp_lm_specs) before the generic pattern rules. This is the
        # mode autoplan's MeshPlan emits shardings through.
        self.lm_rules = lm_rules
        self.lm_min_size = lm_min_size
        # expert-parallel: params matching these patterns shard their
        # LEADING dim (the [E, ...] expert stack convention, nn/moe.py)
        # over the "ep" axis — the pserver table-shard successor rule
        self.ep_patterns = [re.compile(p) for p in ep_patterns]

    def _largest_divisible_dim(self, shape, n):
        cands = [(d, i) for i, d in enumerate(shape) if d % n == 0 and d > 1]
        if not cands:
            return None
        return max(cands)[1]

    def plan_params(self, params):
        entries = {}
        tp = self.axes.get("tp", 1)
        fsdp = self.axes.get("fsdp", 1)
        ep = self.axes.get("ep", 1)
        for path, leaf in jax.tree_util.tree_leaves_with_path(params):
            name = _path_name(path)
            shape = tuple(getattr(leaf, "shape", ()))
            if self.lm_rules and tp > 1:
                t, lm_reason = layouts.lm_layout(
                    name.split("/"), shape, min_size=self.lm_min_size,
                    tp_size=tp)
                if "tp" in t or lm_reason.startswith("tp SKIPPED"):
                    # an LM rule decided (sharded, or downgraded with its
                    # skip recorded); non-targets fall through to the
                    # generic ep/fsdp/dp rules below
                    entries[name] = PlanEntry(name, t, lm_reason)
                    continue
            spec = [None] * len(shape)
            reason = "replicated (dp)"
            if ep > 1 and shape and any(
                    rx.search(name) for rx in self.ep_patterns):
                if shape[0] % ep == 0:
                    spec[0] = "ep"
                    reason = f"ep: expert dim 0 over {ep}"
                else:
                    # explicit match that cannot shard: make the skip
                    # inspectable (every planner decision is)
                    reason = (f"ep SKIPPED: dim 0 ({shape[0]}) not "
                              f"divisible by ep={ep}")
            if "ep" not in spec and tp > 1 and len(shape) >= 2 and (
                    self.tp_auto
                    or any(rx.search(name) for rx in self.tp_patterns)):
                dim = self._largest_divisible_dim(shape, tp)
                suffix = ("; " + reason
                          if reason.startswith("ep SKIPPED") else "")
                if dim is not None:
                    spec[dim] = "tp"
                    reason = f"tp: dim {dim} over {tp}" + suffix
                else:
                    # tp matched but no dim divides: skip with the
                    # decision recorded (never raise mid-plan) — the
                    # param stays replicated and may still pick up fsdp
                    reason = (f"tp SKIPPED: no dim of {shape} divisible "
                              f"by tp={tp}" + suffix)
            min_size = (self.fsdp_min_size if self.fsdp_min_size is not None
                        else 0)  # None = shard everything over fsdp
            if "tp" not in spec and "ep" not in spec and fsdp > 1 \
                    and shape and \
                    _size(shape) >= min_size:
                dim = self._largest_divisible_dim(shape, fsdp)
                if dim is not None:
                    spec[dim] = "fsdp"
                    suffix = ("; " + reason if "SKIPPED" in reason
                              else "")
                    reason = f"fsdp: dim {dim} over {fsdp}" + suffix
            entries[name] = PlanEntry(name, tuple(spec), reason)
        return entries

    def plan(self, params, example_batch=()):
        input_specs = []
        for x in example_batch:
            nd = getattr(x, "ndim", 0)
            input_specs.append(P("dp", *([None] * (nd - 1))) if nd >= 1
                               and "dp" in self.axes and self.axes["dp"] > 1
                               else P())
        return DistributionPlan(self.plan_params(params), input_specs,
                                self.mesh)

    def compile_step(self, step_fn, params, opt_state, example_batch,
                     donate=True):
        """pjit the train step under the plan: the 'transpiled program'.

        step_fn(params, opt_state, *batch) -> (loss, params, opt_state).
        Returns (jitted_step, placed_params, placed_opt_state, plan)."""
        plan = self.plan(params, example_batch)
        pshard = plan.param_shardings(params)
        oshard = jax.tree_util.tree_map(
            lambda x: NamedSharding(self.mesh, P()), opt_state)
        # optimizer slots shard like their params (moments are per-weight)
        if isinstance(opt_state, dict) and "slots" in opt_state:
            oshard = dict(oshard)
            oshard["slots"] = _broadcast_shardings(
                pshard, opt_state["slots"])
        in_shard = (pshard, oshard) + tuple(
            NamedSharding(self.mesh, s) for s in plan.input_specs)
        # pin outputs to the same layout so step t+1 accepts step t's state
        out_shard = (NamedSharding(self.mesh, P()), pshard, oshard)
        jitted = jax.jit(step_fn, in_shardings=in_shard,
                         out_shardings=out_shard,
                         donate_argnums=(0, 1) if donate else ())
        placed_p = plan.place(params)
        placed_o = jax.device_put(opt_state, oshard)
        return jitted, placed_p, placed_o, plan


def _size(shape):
    n = 1
    for d in shape:
        n *= d
    return n


def _broadcast_shardings(pshard, slots):
    """Broadcast each param's sharding onto its (possibly deeper) slot
    subtree: slots = params-structure with each leaf replaced by a dict of
    moment arrays shaped like the param."""
    flat_shard, treedef = jax.tree_util.tree_flatten(
        pshard, is_leaf=lambda x: isinstance(x, NamedSharding))
    subtrees = treedef.flatten_up_to(slots)

    def slot_sharding(arr, s):
        # param-shaped moments inherit the param sharding; odd-shaped slots
        # (scalars etc.) stay replicated
        if getattr(arr, "ndim", 0) == len(s.spec):
            return s
        return NamedSharding(s.mesh, P())

    mapped = [jax.tree_util.tree_map(lambda a, s=s: slot_sharding(a, s), sub)
              for s, sub in zip(flat_shard, subtrees)]
    return jax.tree_util.tree_unflatten(treedef, mapped)
