"""Gradient-communication schedules: gradient merge, Local SGD, Geo-SGD,
DC-ASGD.

Ref: /root/reference/paddle/fluid/operators/distributed/communicator.h:276
(AsyncCommunicator — background threads merging grads before send) and :323
(GeoSgdCommunicator — train locally, periodically sync parameter deltas);
transpiler/collective.py:269 (LocalSGD — averaged params every k steps).

TPU-first: there are no background send threads — the schedules become
*functional wrappers* compiled into the train step:

- `GradientMerge` accumulates k micro-grads before one optimizer apply
  (the async communicator's merge, made deterministic).
- Local SGD / Geo-SGD need *divergent* per-group replicas, which GSPMD's
  replicated params can't express; they run under `shard_map` with params
  stacked over the dp axis (each group owns a copy) and sync by `pmean`
  every k steps — the delta ride over ICI replaces the pserver delta RPC.
"""

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.testing.chaos import fault_point


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _pmean_varying(x, axis_name):
    """pmean whose output is typed varying-over-axis where the type system
    exists: jax >= 0.6 shard_map (check_vma) needs the explicit pcast so
    both lax.cond branches carry the same type; jax 0.4.x (check_rep) has
    no lax.pcast and needs no cast back."""
    out = lax.pmean(x, axis_name)
    if hasattr(lax, "pcast"):
        out = lax.pcast(out, axis_name, to="varying")
    return out


class GradientMerge:
    """Accumulate `merge_steps` gradients, then apply their mean once.

    Wraps any paddle_tpu Optimizer; state layout:
      {"inner": opt_state, "acc": grads-like, "count": i32}
    Equivalent to `merge_steps`-times larger batch (ref: communicator
    merged-send; also fluid's GradientMergeOptimizer in later versions).
    """

    def __init__(self, optimizer, merge_steps):
        assert merge_steps >= 1
        self.inner = optimizer
        self.merge_steps = merge_steps

    def init(self, params):
        return {
            "inner": self.inner.init(params),
            "acc": _tmap(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32),
        }

    def apply_gradients(self, params, grads, state):
        acc = _tmap(lambda a, g: a + g, state["acc"], grads)
        count = state["count"] + 1
        do_apply = count >= self.merge_steps

        def apply_branch(operand):
            params, acc, inner = operand
            mean = _tmap(lambda a: a / self.merge_steps, acc)
            p2, s2 = self.inner.apply_gradients(params, mean, inner)
            return p2, s2, _tmap(jnp.zeros_like, acc), jnp.zeros((), jnp.int32)

        def skip_branch(operand):
            params, acc, inner = operand
            return params, inner, acc, count

        params, inner, acc, count = lax.cond(
            do_apply, apply_branch, skip_branch,
            (params, acc, state["inner"]))
        return params, {"inner": inner, "acc": acc, "count": count}

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, *args, **kwargs)
        params, state = self.apply_gradients(params, grads, state)
        return loss, params, state, aux


def stack_replicas(params, n):
    """Stack n copies of params along a new leading axis (to be sharded over
    the dp/ep axis inside shard_map for divergent-replica schedules)."""
    return _tmap(lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), params)


def unstack_replica(params, i=0):
    return _tmap(lambda p: p[i], params)


class LocalSGD:
    """Local SGD: k local optimizer steps per group, then param averaging.

    Ref: transpiler/collective.py:269 (LocalSGD transpiler inserts periodic
    broadcast-averaged params instead of per-step allreduce).

    Use inside shard_map with params carrying a leading sharded dp axis of
    size 1 per shard (see tests / fleet.localized_train_step): `step()` is the
    per-group local update; `sync()` is the periodic pmean.
    """

    def __init__(self, optimizer, sync_steps, axis_name="dp"):
        self.inner = optimizer
        self.sync_steps = sync_steps
        self.axis_name = axis_name

    def init(self, params):
        return {"inner": self.inner.init(params),
                "since_sync": jnp.zeros((), jnp.int32)}

    def step(self, loss_fn, params, state, *args, **kwargs):
        """One local step + conditional sync (call under shard_map).
        Delegates to inner.minimize so AMP/recompute wrappers compose."""
        loss, params, inner, aux = self.inner.minimize(
            loss_fn, params, state["inner"], *args, **kwargs)
        since = state["since_sync"] + 1
        do_sync = since >= self.sync_steps
        params = lax.cond(
            do_sync,
            # pmean output is unvarying over the axis; pcast back to varying
            # so both cond branches carry the same shard_map type
            lambda p: _tmap(
                lambda x: _pmean_varying(x, self.axis_name), p),
            lambda p: p, params)
        since = jnp.where(do_sync, 0, since)
        return loss, params, {"inner": inner, "since_sync": since}, aux


class DCASGD:
    """Delay-compensated async SGD (ref: transpiler/distribute_transpiler.py:174
    — the `dc_asgd` transpiler mode where the pserver applies each late
    gradient compensated for its staleness; Zheng et al. 2017). The
    compensation is the diagonal curvature surrogate:

        g_comp = g + lambda * g ⊙ g ⊙ (w_server − w_stale)

    i.e. a first-order correction of the stale gradient toward the value
    it would have had at the server's CURRENT weights.

    TPU-first redesign: no pserver thread — staleness is modeled
    functionally under `shard_map` with divergent dp replicas (like
    LocalSGD/GeoSGD): each group trains on its last PULLED copy (stale for
    up to `pull_steps` steps) while the shared anchor (= the pserver copy)
    integrates every group's compensated gradient each step; groups re-pull
    the anchor every `pull_steps` steps. `lambda_=0` degrades to plain
    async SGD — the convergence tests compare against exactly that.
    """

    def __init__(self, lr, pull_steps, lambda_=1.0, axis_name="dp"):
        self.lr = lr
        self.pull_steps = pull_steps
        self.lambda_ = lambda_
        self.axis_name = axis_name

    def init(self, params):
        return {"anchor": params,
                "since_pull": jnp.zeros((), jnp.int32)}

    def step(self, loss_fn, params, state, *args, **kwargs):
        """One async round under shard_map: gradient at the stale local
        copy, compensated server update, periodic pull."""
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, *args, **kwargs)
        anchor = state["anchor"]
        comp = _tmap(
            lambda g, a, p: g + self.lambda_ * g * g * (a - p),
            grads, anchor, params)
        mean_comp = _tmap(
            lambda d: _pmean_varying(d, self.axis_name), comp)
        anchor = _tmap(lambda a, d: a - self.lr * d, anchor, mean_comp)
        since = state["since_pull"] + 1
        do_pull = since >= self.pull_steps
        params = lax.cond(do_pull, lambda o: o[1], lambda o: o[0],
                          (params, anchor))
        since = jnp.where(do_pull, 0, since)
        return loss, params, {"anchor": anchor, "since_pull": since}, aux


class GeoSGD:
    """Geo-SGD: k local steps, then communicate the *delta* vs the last
    synced anchor and apply everyone's average delta to the anchor.

    Ref: operators/distributed/communicator.h:323 GeoSgdCommunicator +
    geo_sgd_transpiler.py — local training with periodic delta push/pull
    against the pserver copy; here the anchor is the pserver copy and the
    delta allreduce rides ICI/DCN.
    """

    def __init__(self, optimizer, sync_steps, axis_name="dp"):
        self.inner = optimizer
        self.sync_steps = sync_steps
        self.axis_name = axis_name

    def init(self, params):
        return {"inner": self.inner.init(params),
                "anchor": params,
                "since_sync": jnp.zeros((), jnp.int32)}

    def step(self, loss_fn, params, state, *args, **kwargs):
        loss, params, inner, aux = self.inner.minimize(
            loss_fn, params, state["inner"], *args, **kwargs)
        since = state["since_sync"] + 1
        do_sync = since >= self.sync_steps

        def sync_branch(operand):
            params, anchor = operand
            delta = _tmap(lambda p, a: p - a, params, anchor)
            mean_delta = _tmap(
                lambda d: _pmean_varying(d, self.axis_name), delta)
            new_anchor = _tmap(lambda a, d: a + d, anchor, mean_delta)
            return new_anchor, new_anchor

        params, anchor = lax.cond(
            do_sync, sync_branch, lambda o: o, (params, state["anchor"]))
        since = jnp.where(do_sync, 0, since)
        return loss, params, {"inner": inner, "anchor": anchor,
                              "since_sync": since}, aux


# --- quantized dp all-reduce (the EQuARX direction, arXiv:2506.17615) ----
#
# collective.compressed_psum's int8 variant carries ONE per-tensor scale
# (a pmax round-trip per tensor, and one outlier ruins the whole tensor's
# resolution). The chunked collective below is the planner-visible
# strategy: the flattened gradient is cut into fixed-size chunks, each
# chunk carries its own shared f32 scale (4 bytes of overhead per chunk
# on the wire), values travel as int8 and are summed in int32. The
# autoplan cost model prices exactly this layout (elems x 1B + chunks x
# 4B) so search.py can CHOOSE it where the dp axis crosses slices (DCN
# bandwidth) and reject it on ICI, where the quantize/dequant compute
# overhead exceeds the wire saving. Same stock-XLA caveat as
# compressed_psum: the int32 psum means semantic parity, not true int8
# wire traffic, off EQuARX-capable backends.


def _quant_chunked(flat, chunk):
    n = flat.shape[0]
    nch = -(-n // chunk)
    return jnp.pad(flat, (0, nch * chunk - n)).reshape(nch, chunk), n


def quantized_psum(x, axis_name, chunk=None):
    """Chunked int8 quantize->psum->dequant cross-replica sum. Each chunk
    quantizes against the axis-wide absmax of that chunk (lax.pmax), so
    every shard agrees on the scale and integer sums are exact. Returns
    ``(sum_like_x, clamps)`` — `clamps` counts elements that exceeded the
    int8 range pre-clip (zero in healthy operation; non-zero flags a
    scale gone bad, e.g. non-finite gradients — the guardian's skip-apply
    gate catches the resulting non-finite update)."""
    if chunk is None:
        from paddle_tpu.core.flags import get_flag
        chunk = int(get_flag("quant_allreduce_chunk"))
    flat = x.astype(jnp.float32).reshape(-1)
    xc, n = _quant_chunked(flat, max(int(chunk), 1))
    absmax = lax.pmax(jnp.max(jnp.abs(xc), axis=1), axis_name)   # [nch]
    scale = jnp.maximum(absmax, 1e-30) / 127.0
    qf = jnp.round(xc / scale[:, None])
    clamps = jnp.sum((jnp.abs(qf) > 127.0).astype(jnp.int32))
    q = jnp.clip(qf, -127.0, 127.0).astype(jnp.int8)
    s = lax.psum(q.astype(jnp.int32), axis_name)
    out = (s.astype(jnp.float32) * scale[:, None]).reshape(-1)[:n]
    return out.reshape(x.shape).astype(x.dtype), clamps


def quantized_pmean(x, axis_name, chunk=None):
    """Mean-reducing twin of :func:`quantized_psum` (the gradient
    exchange form). Returns ``(mean_like_x, clamps)``."""
    s, clamps = quantized_psum(x, axis_name, chunk=chunk)
    return s / lax.psum(1, axis_name), clamps


def quant_wire_bytes(num_elements, dp, chunk=None):
    """Per-chip wire bytes one quantized all-reduce of `num_elements`
    moves on a dp-way ring: 2(dp-1)/dp passes over int8 payload plus one
    f32 scale per chunk — the same expression autoplan/costmodel.py
    prices, kept here so bench rows and the planner cannot drift."""
    if chunk is None:
        from paddle_tpu.core.flags import get_flag
        chunk = int(get_flag("quant_allreduce_chunk"))
    chunk = max(int(chunk), 1)
    payload = num_elements + (-(-num_elements // chunk)) * 4
    return 2.0 * (dp - 1) / max(dp, 1) * payload


def resolve_quant_allreduce(choice=None, crosses_slices=False):
    """Resolve the `quant_allreduce` flag to a bool for one dp axis:
    'on'/'off' force it; 'auto' quantizes only cross-slice (DCN) dp axes
    — the same rule the autoplan cost model prices, so a forced choice
    and a planned one agree on when quantization pays. The
    ``collective.quant`` fault point sits on this resolution: an
    injected fault degrades the exchange to the exact f32 collective
    (counted, never raised into a step)."""
    if choice is None:
        from paddle_tpu.core.flags import get_flag
        choice = get_flag("quant_allreduce")
    try:
        fault_point("collective.quant")
    except Exception:
        _metrics.counter("collective.quant_degraded").inc()
        return False
    if choice == "on":
        return True
    if choice == "off":
        return False
    return bool(crosses_slices)


def record_quant_traffic(nbytes):
    """Publish one quantized exchange's per-chip wire traffic to the
    ``collective.quant_bytes{direction}`` counter (ring all-reduce moves
    the payload both ways)."""
    c = _metrics.counter("collective.quant_bytes")
    c.inc(nbytes, direction="send")
    c.inc(nbytes, direction="recv")


def publish_clamp_count(state, last=0):
    """Host-side delta publisher for a QuantizedGradSync state's
    cumulative clamp counter -> ``quant.overflow_clamps`` (the
    amp.skipped_steps idiom: the device count lives in the optimizer
    state; the host publishes deltas between reads). Returns the new
    `last` watermark."""
    n = int(state["clamps"])
    if n > last:
        _metrics.counter("quant.overflow_clamps").inc(n - last)
    return n


class QuantizedGradSync:
    """Data-parallel gradient exchange through the chunked int8
    collective. Wraps any paddle_tpu Optimizer; use under shard_map with
    a dp axis (the LocalSGD/GeoSGD discipline): each apply_gradients
    quantize-pmeans every gradient leaf across the axis before the inner
    apply, and accumulates the clamp count in its state
    ({"inner": opt_state, "clamps": i32} — publish_clamp_count turns it
    into the quant.overflow_clamps counter host-side).

    Parity guard: quantization error is bounded (<= scale/2 per element
    pre-mean), but a pathological batch (inf/nan gradients) collapses
    the chunk scale and surfaces as a non-finite update — exactly what
    the guardian's skip-apply gate already rejects, so a quantized step
    can degrade a step to a skip but never corrupt params."""

    def __init__(self, optimizer, axis_name="dp", chunk=None):
        self.inner = optimizer
        self.axis_name = axis_name
        self.chunk = chunk

    def init(self, params):
        return {"inner": self.inner.init(params),
                "clamps": jnp.zeros((), jnp.int32)}

    def apply_gradients(self, params, grads, state):
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        synced, clamps = [], state["clamps"]
        for g in leaves:
            m, c = quantized_pmean(g, self.axis_name, chunk=self.chunk)
            synced.append(m)
            clamps = clamps + c
        mean = jax.tree_util.tree_unflatten(treedef, synced)
        params, inner = self.inner.apply_gradients(params, mean,
                                                   state["inner"])
        return params, {"inner": inner, "clamps": clamps}

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, *args, **kwargs)
        params, state = self.apply_gradients(params, grads, state)
        return loss, params, state, aux
