"""Gradient-communication schedules: gradient merge, Local SGD, Geo-SGD,
DC-ASGD.

Ref: /root/reference/paddle/fluid/operators/distributed/communicator.h:276
(AsyncCommunicator — background threads merging grads before send) and :323
(GeoSgdCommunicator — train locally, periodically sync parameter deltas);
transpiler/collective.py:269 (LocalSGD — averaged params every k steps).

TPU-first: there are no background send threads — the schedules become
*functional wrappers* compiled into the train step:

- `GradientMerge` accumulates k micro-grads before one optimizer apply
  (the async communicator's merge, made deterministic).
- Local SGD / Geo-SGD need *divergent* per-group replicas, which GSPMD's
  replicated params can't express; they run under `shard_map` with params
  stacked over the dp axis (each group owns a copy) and sync by `pmean`
  every k steps — the delta ride over ICI replaces the pserver delta RPC.
"""

import jax
import jax.numpy as jnp
from jax import lax


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _pmean_varying(x, axis_name):
    """pmean whose output is typed varying-over-axis where the type system
    exists: jax >= 0.6 shard_map (check_vma) needs the explicit pcast so
    both lax.cond branches carry the same type; jax 0.4.x (check_rep) has
    no lax.pcast and needs no cast back."""
    out = lax.pmean(x, axis_name)
    if hasattr(lax, "pcast"):
        out = lax.pcast(out, axis_name, to="varying")
    return out


class GradientMerge:
    """Accumulate `merge_steps` gradients, then apply their mean once.

    Wraps any paddle_tpu Optimizer; state layout:
      {"inner": opt_state, "acc": grads-like, "count": i32}
    Equivalent to `merge_steps`-times larger batch (ref: communicator
    merged-send; also fluid's GradientMergeOptimizer in later versions).
    """

    def __init__(self, optimizer, merge_steps):
        assert merge_steps >= 1
        self.inner = optimizer
        self.merge_steps = merge_steps

    def init(self, params):
        return {
            "inner": self.inner.init(params),
            "acc": _tmap(jnp.zeros_like, params),
            "count": jnp.zeros((), jnp.int32),
        }

    def apply_gradients(self, params, grads, state):
        acc = _tmap(lambda a, g: a + g, state["acc"], grads)
        count = state["count"] + 1
        do_apply = count >= self.merge_steps

        def apply_branch(operand):
            params, acc, inner = operand
            mean = _tmap(lambda a: a / self.merge_steps, acc)
            p2, s2 = self.inner.apply_gradients(params, mean, inner)
            return p2, s2, _tmap(jnp.zeros_like, acc), jnp.zeros((), jnp.int32)

        def skip_branch(operand):
            params, acc, inner = operand
            return params, inner, acc, count

        params, inner, acc, count = lax.cond(
            do_apply, apply_branch, skip_branch,
            (params, acc, state["inner"]))
        return params, {"inner": inner, "acc": acc, "count": count}

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, *args, **kwargs)
        params, state = self.apply_gradients(params, grads, state)
        return loss, params, state, aux


def stack_replicas(params, n):
    """Stack n copies of params along a new leading axis (to be sharded over
    the dp/ep axis inside shard_map for divergent-replica schedules)."""
    return _tmap(lambda p: jnp.broadcast_to(p[None], (n,) + p.shape), params)


def unstack_replica(params, i=0):
    return _tmap(lambda p: p[i], params)


class LocalSGD:
    """Local SGD: k local optimizer steps per group, then param averaging.

    Ref: transpiler/collective.py:269 (LocalSGD transpiler inserts periodic
    broadcast-averaged params instead of per-step allreduce).

    Use inside shard_map with params carrying a leading sharded dp axis of
    size 1 per shard (see tests / fleet.localized_train_step): `step()` is the
    per-group local update; `sync()` is the periodic pmean.
    """

    def __init__(self, optimizer, sync_steps, axis_name="dp"):
        self.inner = optimizer
        self.sync_steps = sync_steps
        self.axis_name = axis_name

    def init(self, params):
        return {"inner": self.inner.init(params),
                "since_sync": jnp.zeros((), jnp.int32)}

    def step(self, loss_fn, params, state, *args, **kwargs):
        """One local step + conditional sync (call under shard_map).
        Delegates to inner.minimize so AMP/recompute wrappers compose."""
        loss, params, inner, aux = self.inner.minimize(
            loss_fn, params, state["inner"], *args, **kwargs)
        since = state["since_sync"] + 1
        do_sync = since >= self.sync_steps
        params = lax.cond(
            do_sync,
            # pmean output is unvarying over the axis; pcast back to varying
            # so both cond branches carry the same shard_map type
            lambda p: _tmap(
                lambda x: _pmean_varying(x, self.axis_name), p),
            lambda p: p, params)
        since = jnp.where(do_sync, 0, since)
        return loss, params, {"inner": inner, "since_sync": since}, aux


class DCASGD:
    """Delay-compensated async SGD (ref: transpiler/distribute_transpiler.py:174
    — the `dc_asgd` transpiler mode where the pserver applies each late
    gradient compensated for its staleness; Zheng et al. 2017). The
    compensation is the diagonal curvature surrogate:

        g_comp = g + lambda * g ⊙ g ⊙ (w_server − w_stale)

    i.e. a first-order correction of the stale gradient toward the value
    it would have had at the server's CURRENT weights.

    TPU-first redesign: no pserver thread — staleness is modeled
    functionally under `shard_map` with divergent dp replicas (like
    LocalSGD/GeoSGD): each group trains on its last PULLED copy (stale for
    up to `pull_steps` steps) while the shared anchor (= the pserver copy)
    integrates every group's compensated gradient each step; groups re-pull
    the anchor every `pull_steps` steps. `lambda_=0` degrades to plain
    async SGD — the convergence tests compare against exactly that.
    """

    def __init__(self, lr, pull_steps, lambda_=1.0, axis_name="dp"):
        self.lr = lr
        self.pull_steps = pull_steps
        self.lambda_ = lambda_
        self.axis_name = axis_name

    def init(self, params):
        return {"anchor": params,
                "since_pull": jnp.zeros((), jnp.int32)}

    def step(self, loss_fn, params, state, *args, **kwargs):
        """One async round under shard_map: gradient at the stale local
        copy, compensated server update, periodic pull."""
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, *args, **kwargs)
        anchor = state["anchor"]
        comp = _tmap(
            lambda g, a, p: g + self.lambda_ * g * g * (a - p),
            grads, anchor, params)
        mean_comp = _tmap(
            lambda d: _pmean_varying(d, self.axis_name), comp)
        anchor = _tmap(lambda a, d: a - self.lr * d, anchor, mean_comp)
        since = state["since_pull"] + 1
        do_pull = since >= self.pull_steps
        params = lax.cond(do_pull, lambda o: o[1], lambda o: o[0],
                          (params, anchor))
        since = jnp.where(do_pull, 0, since)
        return loss, params, {"anchor": anchor, "since_pull": since}, aux


class GeoSGD:
    """Geo-SGD: k local steps, then communicate the *delta* vs the last
    synced anchor and apply everyone's average delta to the anchor.

    Ref: operators/distributed/communicator.h:323 GeoSgdCommunicator +
    geo_sgd_transpiler.py — local training with periodic delta push/pull
    against the pserver copy; here the anchor is the pserver copy and the
    delta allreduce rides ICI/DCN.
    """

    def __init__(self, optimizer, sync_steps, axis_name="dp"):
        self.inner = optimizer
        self.sync_steps = sync_steps
        self.axis_name = axis_name

    def init(self, params):
        return {"inner": self.inner.init(params),
                "anchor": params,
                "since_sync": jnp.zeros((), jnp.int32)}

    def step(self, loss_fn, params, state, *args, **kwargs):
        loss, params, inner, aux = self.inner.minimize(
            loss_fn, params, state["inner"], *args, **kwargs)
        since = state["since_sync"] + 1
        do_sync = since >= self.sync_steps

        def sync_branch(operand):
            params, anchor = operand
            delta = _tmap(lambda p, a: p - a, params, anchor)
            mean_delta = _tmap(
                lambda d: _pmean_varying(d, self.axis_name), delta)
            new_anchor = _tmap(lambda a, d: a + d, anchor, mean_delta)
            return new_anchor, new_anchor

        params, anchor = lax.cond(
            do_sync, sync_branch, lambda o: o, (params, state["anchor"]))
        since = jnp.where(do_sync, 0, since)
        return loss, params, {"inner": inner, "anchor": anchor,
                              "since_sync": since}, aux
