"""Deep gradient compression — top-k sparsified gradients.

Ref: /root/reference/paddle/fluid/operators/dgc_op.cc (top-k select +
momentum correction) and framework/details/sparse_all_reduce_op_handle.cc
(RunImplEncoded — NCCL allgather of encoded (idx, val) pairs).

TPU-first: no sparse NCCL allreduce exists on TPU either; we mirror the
reference's *allgather-of-sparse* design with XLA: top-k select per shard
(lax.top_k on |g|), allgather the (indices, values) pairs over the mesh axis,
scatter-add into a dense buffer. Residuals accumulate locally (momentum
correction in optimizer/wrappers.py DGCMomentum).
"""

import jax
import jax.numpy as jnp
from jax import lax


def topk_sparsify(g, sparsity):
    """Keep the top-(1-sparsity) fraction of |g|; returns (sparse_g,
    residual). sparse_g + residual == g."""
    flat = g.reshape(-1)
    k = max(1, int(flat.shape[0] * (1.0 - sparsity)))
    _, idx = lax.top_k(jnp.abs(flat), k)
    mask = jnp.zeros_like(flat, dtype=bool).at[idx].set(True)
    sparse = jnp.where(mask, flat, 0).reshape(g.shape)
    return sparse, g - sparse


def topk_encode(g, k):
    """Encode g as (indices[k], values[k]) of largest-|.| entries."""
    flat = g.reshape(-1)
    _, idx = lax.top_k(jnp.abs(flat), k)
    return idx, flat[idx]


def topk_decode(idx, vals, shape, dtype):
    n = 1
    for d in shape:
        n *= d
    return jnp.zeros((n,), dtype).at[idx].add(vals).reshape(shape)


def sparse_all_reduce(g, axis_name, sparsity=0.999):
    """Compressed allreduce inside shard_map (ref:
    sparse_all_reduce_op_handle.cc RunImplEncoded): encode local top-k,
    allgather pairs, decode+sum. Returns (reduced_dense, local_residual).

    Bandwidth: 2k*(4+4) bytes vs 4n dense — ~250x reduction at 0.1% density,
    same as the reference's DGC premise (arXiv:1712.01887).
    """
    k = max(1, int(g.size * (1.0 - sparsity)))
    idx, vals = topk_encode(g, k)
    mask = jnp.zeros((g.size,), bool).at[idx].set(True)
    residual = jnp.where(mask, 0, g.reshape(-1)).reshape(g.shape)
    all_idx = lax.all_gather(idx, axis_name)      # [n, k]
    all_vals = lax.all_gather(vals, axis_name)    # [n, k]
    dense = jnp.zeros((g.size,), g.dtype).at[all_idx.reshape(-1)].add(
        all_vals.reshape(-1))
    return dense.reshape(g.shape), residual
