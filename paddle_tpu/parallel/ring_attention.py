"""Ring attention — sequence/context parallelism over the ICI ring.

Ref: absent in the reference (2019-era; its sequence story was LoDTensor
batching, /root/reference/paddle/fluid/framework/lod_tensor.h). Required by
BASELINE north star for long-context parity. Design per the ring-attention
pattern: Q stays put, sharded KV blocks rotate around the mesh axis via
ppermute, each step accumulating online-softmax partial results, so a
sequence of length T runs on N chips with T/N local memory and compute
overlapped with neighbor transfers on ICI.

Used inside shard_map with sequences sharded over axis `sp`:
  q, k, v: [B, H, T/N, D] per device.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def ring_attention(q, k, v, axis_name, causal=False, scale=None):
    """Online-softmax attention with KV ring rotation. Per-device shapes
    [B, H, Tlocal, D]; sequence globally sharded over `axis_name`."""
    scale = scale if scale is not None else 1.0 / jnp.sqrt(q.shape[-1])
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, h, tl, d = q.shape
    qf = q.astype(jnp.float32)

    def blockwise(carry, kv_blk, blk_owner):
        m, l, acc = carry
        kb, vb = kv_blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(jnp.float32)) * scale
        if causal:
            q_pos = my * tl + jnp.arange(tl)
            k_pos = blk_owner * tl + jnp.arange(tl)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, -1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, -1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       vb.astype(jnp.float32))
        return m_new, l, acc

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, state):
        m, l, acc, kb, vb = state
        owner = (my - i) % n  # block i arrived from device (my - i)
        m, l, acc = blockwise((m, l, acc), (kb, vb), owner)
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return m, l, acc, kb, vb

    # derive carries from qf so they inherit q's varying-axes type under
    # shard_map (scan requires carry-in/out type equality)
    m0 = jnp.full_like(qf[..., :1], NEG_INF)
    l0 = jnp.zeros_like(qf[..., :1])
    acc0 = jnp.zeros_like(qf)
    m, l, acc, _, _ = lax.fori_loop(0, n, step, (m0, l0, acc0, k, v))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _ring_causal_dispatch(owner, my, blk_fn, zero_fn, kb, vb):
    """Ring causality at BLOCK granularity, shared by the forward and
    backward loops so the visibility rule cannot desynchronize: a device's
    own block runs the causal kernel, blocks from earlier ranks run the
    plain kernel, later ranks contribute nothing."""
    return lax.cond(
        owner == my,
        lambda kv: blk_fn(kv[0], kv[1], True),
        lambda kv: lax.cond(
            owner < my,
            lambda kv2: blk_fn(kv2[0], kv2[1], False),
            lambda kv2: zero_fn(),
            kv),
        (kb, vb))


def _ring_flash_fwd_loop(q, k, v, axis_name, causal, scale, block_q,
                         block_k):
    """Forward ring loop; returns (output in q.dtype, final lse [B,H,Tl])."""
    from paddle_tpu.ops.pallas.flash_attention import \
        _flash_attention_fwd_tpu
    b, h, tl, d = q.shape
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def blk(kb, vb, blk_causal):
        out, lse = _flash_attention_fwd_tpu(
            q, kb, vb, scale, blk_causal, min(block_q, tl), min(block_k, tl),
            return_lse=True)
        return out.astype(jnp.float32), lse

    def step(i, state):
        o, lse, kb, vb = state
        owner = (my - i) % n
        if causal:
            ob, lb = _ring_causal_dispatch(
                owner, my, blk,
                lambda: (jnp.zeros_like(o),
                         jnp.full(lse.shape, NEG_INF, jnp.float32)),
                kb, vb)
        else:
            ob, lb = blk(kb, vb, False)
        # merge normalized partials by logsumexp weight
        new_lse = jnp.logaddexp(lse, lb)
        w_old = jnp.exp(lse - new_lse)[..., None]
        w_new = jnp.exp(lb - new_lse)[..., None]
        o = o * w_old + ob * w_new
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return o, new_lse, kb, vb

    o0 = jnp.zeros(q.shape, jnp.float32)
    lse0 = jnp.full((b, h, tl), NEG_INF, jnp.float32)
    o, lse, _, _ = lax.fori_loop(0, n, step, (o0, lse0, k, v))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_flash_core(q, k, v, axis_name, causal, scale, block_q, block_k):
    return _ring_flash_fwd_loop(q, k, v, axis_name, causal, scale,
                                block_q, block_k)[0]


def _ring_flash_core_fwd(q, k, v, axis_name, causal, scale, block_q,
                         block_k):
    o, lse = _ring_flash_fwd_loop(q, k, v, axis_name, causal, scale,
                                  block_q, block_k)
    return o, (q, k, v, o, lse)


def _ring_flash_core_bwd(axis_name, causal, scale, block_q, block_k, res, g):
    """Ring backward: rotate KV blocks around the ring a second time, this
    time towing their gradient accumulators. Per step the Pallas dq/dkv
    kernels run against the resident block with the device's FINAL
    logsumexp (flash-attention-2 recomputation: p = exp(s − lse_final) is
    exact for any sub-block of keys), so dq accumulates locally and the
    traveling dk/dv arrive back at their owner after the full cycle."""
    from paddle_tpu.ops.pallas.flash_attention import \
        _flash_attention_bwd_tpu
    q, k, v, o, lse = res
    tl = q.shape[2]
    bq, bk = min(block_q, tl), min(block_k, tl)
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def blk_bwd(kb, vb, blk_causal):
        dqc, dkc, dvc = _flash_attention_bwd_tpu(
            q, kb, vb, o, lse, g, scale, blk_causal, bq, bk)
        return (dqc.astype(jnp.float32), dkc.astype(jnp.float32),
                dvc.astype(jnp.float32))

    def step(i, state):
        dq, kb, vb, dkb, dvb = state
        owner = (my - i) % n
        if causal:
            dqc, dkc, dvc = _ring_causal_dispatch(
                owner, my, blk_bwd,
                lambda: (jnp.zeros_like(dq),) * 3,
                kb, vb)
        else:
            dqc, dkc, dvc = blk_bwd(kb, vb, False)
        dq = dq + dqc
        dkb = dkb + dkc
        dvb = dvb + dvc
        # the accumulators travel WITH their block: after the n-step cycle
        # each block (and its gradient) is home again
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        dkb = lax.ppermute(dkb, axis_name, perm)
        dvb = lax.ppermute(dvb, axis_name, perm)
        return dq, kb, vb, dkb, dvb

    zero = jnp.zeros(q.shape, jnp.float32)
    dq, _, _, dk, dv = lax.fori_loop(
        0, n, step, (zero, k, v, jnp.zeros_like(zero), jnp.zeros_like(zero)))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash_core.defvjp(_ring_flash_core_fwd, _ring_flash_core_bwd)


def ring_flash_attention(q, k, v, axis_name, causal=False, scale=None,
                         block_q=512, block_k=512):
    """Ring attention with the Pallas flash kernel as the per-block
    engine: each ring step runs the O(T) online-softmax kernel on the
    resident KV block and partial results merge by logsumexp — so the
    per-device inner loop is MXU-tiled VMEM compute instead of a dense
    [Tl, Tl] XLA einsum, while KV blocks rotate on ICI exactly as in
    `ring_attention`.

    Differentiable: a custom VJP rotates the KV blocks around the ring a
    second time with towed gradient accumulators, running the Pallas
    dq/dkv kernels per resident block against the saved final logsumexp.

    Causality is resolved at BLOCK granularity with lax.cond (the kernel's
    causal flag is compile-time): a device's own block runs the causal
    kernel, blocks from earlier ranks run the plain kernel, later ranks
    contribute nothing. Falls back to `ring_attention` off-TPU or for
    shapes the kernel refuses.

    Call inside shard_map(..., check_vma=False) — pallas_call does not
    declare varying-mesh-axes metadata (same requirement as
    parallel/pipeline.py).
    """
    from paddle_tpu.core.flags import get_flag
    from paddle_tpu.ops.pallas import on_tpu
    b, h, tl, d = q.shape
    if not ((on_tpu() or get_flag("pallas_interpret"))
            and d % 64 == 0 and tl % 8 == 0):
        return ring_attention(q, k, v, axis_name, causal=causal, scale=scale)
    scale = float(scale) if scale is not None else 1.0 / (d ** 0.5)
    return _ring_flash_core(q, k, v, axis_name, causal, scale, block_q,
                            block_k)


def ulysses_attention(q, k, v, axis_name, attention_fn=None, causal=False):
    """Ulysses/DeepSpeed-style sequence parallelism: all_to_all reshards
    [B, H, T/N, D] → [B, H/N, T, D] so each device holds full sequences for a
    head subset, runs normal attention, then reshards back. Complements ring
    attention: better for many-heads models, one collective pair per layer.
    """
    n = lax.axis_size(axis_name)
    if attention_fn is None:
        # flash (Pallas) on TPU / interpret; dense softmax elsewhere —
        # after the all_to_all each device holds FULL sequences for its
        # head subset, exactly the kernel's layout
        from paddle_tpu.ops.pallas.flash_attention import flash_attention
        attention_fn = lambda q_, k_, v_: flash_attention(
            q_, k_, v_, causal=causal)
    # [B, H, Tl, D] -> heads scattered, seq gathered: [B, H/N, T, D]
    reshard = lambda x: lax.all_to_all(x, axis_name, split_axis=1,
                                       concat_axis=2, tiled=True)
    qh, kh, vh = reshard(q), reshard(k), reshard(v)
    out = attention_fn(qh, kh, vh)
    # back: heads gathered, seq scattered
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)
