"""Pipeline parallelism — microbatched stage execution on a mesh axis.

Ref: /root/reference/paddle/fluid/framework/pipeline_trainer.cc +
section_worker.cc:141 (program cut at `cut_list` into sections; Scopes flow
through blocking queues between section threads) and the Python splitter
PipelineOptimizer (/root/reference/python/paddle/fluid/optimizer.py:2985).

TPU-first redesign: no threads or queues — a GPipe-style schedule expressed
as a `lax.scan` over microbatches inside `shard_map` over the "pp" axis.
Each device holds one stage's params; activations hop stage→stage via
`ppermute` (ICI neighbor transfer). The scan pipelines naturally: while
device s processes microbatch m, device s-1 processes m+1 — XLA overlaps
the ppermute with compute. Bubble fraction = (S-1)/(M+S-1), as GPipe.

The reference's SectionWorker sync_steps model-replica averaging is subsumed
by the optimizer running sharded over "pp" (each stage updates its own
params; no cross-replica drift exists).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from paddle_tpu.parallel.mesh import PP


def pipeline_forward(stage_fn, params, x, axis_name=PP, num_microbatches=None):
    """Run a stage-sharded forward inside shard_map.

    stage_fn(stage_params, h) -> h  — same signature every stage.
    params: stage-stacked pytree (leading dim = n_stages, sharded over pp).
    x: [M, mb, ...] microbatched input; only stage 0 consumes it.
    Returns final-stage outputs stacked [M, mb, ...].

    This is the inner per-device function; wrap with `shard_map` via
    `make_pipeline_fn`.
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    m = x.shape[0]
    perm = [(i, (i + 1) % n) for i in range(n)]

    # strip the stage dim (shard_map gives each device its own slice of size 1)
    my_params = jax.tree_util.tree_map(lambda p: p[0], params)

    total_ticks = m + n - 1
    h_shape = jax.eval_shape(lambda p, a: stage_fn(p, a), my_params,
                             jax.ShapeDtypeStruct(x.shape[1:], x.dtype))

    def tick(carry, t):
        inflight, outputs = carry
        # stage 0 injects microbatch t (if any); others use what arrived
        feed = lax.dynamic_index_in_dim(x, jnp.minimum(t, m - 1), 0,
                                        keepdims=False)
        h_in = jnp.where(me == 0, feed, inflight)
        h_out = stage_fn(my_params, h_in)
        # last stage records output for microbatch (t - (n-1))
        out_idx = t - (n - 1)
        valid = (out_idx >= 0) & (out_idx < m)
        outputs = lax.cond(
            valid & (me == n - 1),
            lambda o: lax.dynamic_update_index_in_dim(o, h_out,
                                                      jnp.maximum(out_idx, 0),
                                                      0),
            lambda o: o, outputs)
        inflight = lax.ppermute(h_out, axis_name, perm)
        return (inflight, outputs), None

    inflight0 = jnp.zeros(h_shape.shape, h_shape.dtype)
    outputs0 = jnp.zeros((m,) + h_shape.shape, h_shape.dtype)
    (_, outputs), _ = lax.scan(tick, (inflight0, outputs0),
                               jnp.arange(total_ticks))
    # only the last stage holds real outputs (others zeros) — psum
    # replicates the result across the pp axis
    return lax.psum(outputs, axis_name)


def make_pipeline_fn(mesh, stage_fn, axis_name=PP):
    """Wrap pipeline_forward in shard_map over the pp axis.

    Returns fn(stacked_params, microbatches) -> outputs where stacked_params
    leaves have leading dim n_stages (sharded over pp) and microbatches is
    [M, mb, ...] (replicated input; stage 0 reads it).
    """
    def inner(params, x):
        return pipeline_forward(stage_fn, params, x, axis_name)

    pspec = P(axis_name)
    return shard_map(
        inner, mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        check_vma=False,
    )


def make_pipeline_train_step(mesh, stage_fn, loss_fn, opt, axis_name=PP,
                             remat=False):
    """GPipe-style pipeline-parallel TRAINING step.

    Ref: /root/reference/python/paddle/fluid/optimizer.py:2985
    (PipelineOptimizer: cut program into sections, microbatch, train) and
    section_worker.cc:141 (SectionWorker::TrainFiles runs forward AND
    backward AND optimizer per section).

    TPU-first redesign: the pipelined forward is pure differentiable lax
    (scan over ticks + ppermute hops), so the *backward pipeline schedule
    falls out of autodiff*: JAX transposes each ppermute into the reverse
    hop and the scan into a reverse-tick scan, which is exactly the GPipe
    backward wave; per-stage gradient accumulation across microbatches is
    the scan-transpose's natural cotangent sum. No section threads, no
    queues, no hand-written 1F1B — XLA schedules the waves.

    `remat=True` wraps each stage in jax.checkpoint so activations are
    rebuilt in the backward wave (the memory win 1F1B exists for;
    ref backward.py:576 _append_backward_ops_with_checkpoints_).

    Args:
      mesh: Mesh with `axis_name` of size n_stages.
      stage_fn(stage_params, h) -> h  — same signature every stage.
      loss_fn(outputs, labels) -> scalar, where outputs is [M, mb, ...]
        stacked final-stage activations.
      opt: paddle_tpu Optimizer; state/params are the stage-stacked pytrees
        (leading dim n_stages, sharded over `axis_name`), so each device
        updates its own stage's slice — the reference's per-section
        optimizer ops.

    Returns step(params, opt_state, x, y) -> (loss, params, opt_state)
    where x is [M, mb, ...] microbatches and y the matching labels.
    """
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def inner(params, x):
        return pipeline_forward(fn, params, x, axis_name)

    pspec = P(axis_name)
    fwd = shard_map(inner, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                    check_vma=False)

    def global_loss(params, x, y):
        return loss_fn(fwd(params, x), y)

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(global_loss)(params, x, y)
        params, opt_state = opt.apply_gradients(params, grads, opt_state)
        return loss, params, opt_state

    return step


def stack_stage_params(per_stage_params):
    """[{params of stage i}] -> stacked pytree with leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, 0), *per_stage_params)


def split_microbatches(batch, num_microbatches):
    """[B, ...] -> [M, B/M, ...] (ref: PipelineOptimizer microbatching)."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((num_microbatches, x.shape[0] // num_microbatches)
                            + x.shape[1:]), batch)
