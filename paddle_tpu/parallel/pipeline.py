"""Pipeline parallelism — microbatched stage execution on a mesh axis.

Ref: /root/reference/paddle/fluid/framework/pipeline_trainer.cc +
section_worker.cc:141 (program cut at `cut_list` into sections; Scopes flow
through blocking queues between section threads) and the Python splitter
PipelineOptimizer (/root/reference/python/paddle/fluid/optimizer.py:2985).

TPU-first redesign: no threads or queues — a GPipe-style schedule expressed
as a `lax.scan` over microbatches inside `shard_map` over the "pp" axis.
Each device holds one stage's params; activations hop stage→stage via
`ppermute` (ICI neighbor transfer). The scan pipelines naturally: while
device s processes microbatch m, device s-1 processes m+1 — XLA overlaps
the ppermute with compute. Bubble fraction = (S-1)/(M+S-1), as GPipe.

The reference's SectionWorker sync_steps model-replica averaging is subsumed
by the optimizer running sharded over "pp" (each stage updates its own
params; no cross-replica drift exists).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # jax < 0.6: experimental namespace, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f=None, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        if f is None:
            return functools.partial(shard_map, **kw)
        return _shard_map(f, **kw)

from paddle_tpu.parallel.mesh import PP


def pipeline_forward(stage_fn, params, x, axis_name=PP, num_microbatches=None):
    """Run a stage-sharded forward inside shard_map.

    stage_fn(stage_params, h) -> h  — same signature every stage.
    params: stage-stacked pytree (leading dim = n_stages, sharded over pp).
    x: [M, mb, ...] microbatched input; only stage 0 consumes it.
    Returns final-stage outputs stacked [M, mb, ...].

    This is the inner per-device function; wrap with `shard_map` via
    `make_pipeline_fn`.
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    m = x.shape[0]
    perm = [(i, (i + 1) % n) for i in range(n)]

    # strip the stage dim (shard_map gives each device its own slice of size 1)
    my_params = jax.tree_util.tree_map(lambda p: p[0], params)

    total_ticks = m + n - 1
    h_shape = jax.eval_shape(lambda p, a: stage_fn(p, a), my_params,
                             jax.ShapeDtypeStruct(x.shape[1:], x.dtype))

    def tick(carry, t):
        inflight, outputs = carry
        # stage 0 injects microbatch t (if any); others use what arrived
        feed = lax.dynamic_index_in_dim(x, jnp.minimum(t, m - 1), 0,
                                        keepdims=False)
        h_in = jnp.where(me == 0, feed, inflight)
        h_out = stage_fn(my_params, h_in)
        # last stage records output for microbatch (t - (n-1))
        out_idx = t - (n - 1)
        valid = (out_idx >= 0) & (out_idx < m)
        outputs = lax.cond(
            valid & (me == n - 1),
            lambda o: lax.dynamic_update_index_in_dim(o, h_out,
                                                      jnp.maximum(out_idx, 0),
                                                      0),
            lambda o: o, outputs)
        inflight = lax.ppermute(h_out, axis_name, perm)
        return (inflight, outputs), None

    inflight0 = jnp.zeros(h_shape.shape, h_shape.dtype)
    outputs0 = jnp.zeros((m,) + h_shape.shape, h_shape.dtype)
    (_, outputs), _ = lax.scan(tick, (inflight0, outputs0),
                               jnp.arange(total_ticks))
    # only the last stage holds real outputs (others zeros) — psum
    # replicates the result across the pp axis
    return lax.psum(outputs, axis_name)


def make_pipeline_fn(mesh, stage_fn, axis_name=PP):
    """Wrap pipeline_forward in shard_map over the pp axis.

    Returns fn(stacked_params, microbatches) -> outputs where stacked_params
    leaves have leading dim n_stages (sharded over pp) and microbatches is
    [M, mb, ...] (replicated input; stage 0 reads it).
    """
    def inner(params, x):
        return pipeline_forward(stage_fn, params, x, axis_name)

    pspec = P(axis_name)
    return shard_map(
        inner, mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        check_vma=False,
    )


def _pipeline_1f1b_loss_and_grads(stage_fn, loss_fn, axis_name):
    """1F1B forward+backward schedule as a single tick scan (per device,
    inside shard_map).

    Ref: /root/reference/paddle/fluid/framework/section_worker.cc:141 — the
    reference's section threads run forward AND backward AND optimizer
    concurrently per section, which bounds in-flight activations by the
    section count instead of the microbatch count. This is the same
    property expressed as data flow: every tick runs ONE forward microstep
    (the GPipe wave) and ONE backward microstep (the reverse wave, lagging
    2(S-1) ticks), so stage s's live activations are bounded by a circular
    buffer of 2S-1 stage inputs — O(S), independent of M — while the
    autodiff-transposed GPipe scan keeps all M microbatch residuals alive.
    Backward recomputes the stage from its saved input (implicit remat, the
    1F1B memory contract).

    Timeline (S stages, M microbatches, ticks t = 0 .. M + 2S - 3):
      forward  of microbatch t - s      at stage s   (valid while < M)
      backward of microbatch t - 2(S-1) + s at stage s
    The last stage's backward of microbatch b starts the same tick as its
    forward (one-F-one-B steady state); cotangents hop stage s -> s-1 via
    reverse ppermute.

    loss_fn is applied per microbatch (outputs[None], y[None]) and the
    per-microbatch losses/gradients averaged — identical to the GPipe path
    whenever loss_fn averages over the leading microbatch axis.
    """
    def inner(params, x, y):
        n = lax.axis_size(axis_name)
        me = lax.axis_index(axis_name)
        m = x.shape[0]
        k = 2 * n - 1  # circular buffer: max residual age is 2(S-1) ticks
        perm_f = [(i, (i + 1) % n) for i in range(n)]
        perm_b = [(i, (i - 1) % n) for i in range(n)]
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        h_sds = jax.eval_shape(lambda p, a: stage_fn(p, a), my_params,
                               jax.ShapeDtypeStruct(x.shape[1:], x.dtype))

        def mb_loss(h_out, y_mb):
            return loss_fn(h_out[None], y_mb[None])

        def tick(carry, t):
            h_fly, g_fly, acts, gacc, lacc = carry
            # ---- forward microstep (the GPipe wave) ----
            feed = lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where(me == 0, feed, h_fly)
            acts = lax.dynamic_update_index_in_dim(
                acts, h_in, jnp.mod(t, k), 0)
            h_out = stage_fn(my_params, h_in)
            # ---- loss head: valid only on the last stage, where the
            # backward of microbatch bl = t-(S-1) starts this same tick ----
            bl = t - (n - 1)
            y_b = lax.dynamic_index_in_dim(
                y, jnp.clip(bl, 0, m - 1), 0, keepdims=False)
            loss_v, dh_out = jax.value_and_grad(mb_loss)(h_out, y_b)
            # ---- backward microstep: stage s handles microbatch b ----
            b = t - 2 * (n - 1) + me
            g_in = jnp.where(me == n - 1, dh_out, g_fly)
            h_saved = lax.dynamic_index_in_dim(
                acts, jnp.mod(b + me, k), 0, keepdims=False)
            _, vjp_fn = jax.vjp(stage_fn, my_params, h_saved)
            dp, dh_prev = vjp_fn(g_in)
            valid_b = (b >= 0) & (b < m)
            gacc = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(valid_b, d, 0), gacc, dp)
            lacc = lacc + jnp.where(
                (me == n - 1) & (bl >= 0) & (bl < m),
                loss_v.astype(jnp.float32), 0.0)
            h_fly = lax.ppermute(h_out, axis_name, perm_f)
            g_fly = lax.ppermute(dh_prev, axis_name, perm_b)
            return (h_fly, g_fly, acts, gacc, lacc), None

        zeros_h = jnp.zeros(h_sds.shape, h_sds.dtype)
        carry0 = (zeros_h, zeros_h,
                  jnp.zeros((k,) + h_sds.shape, h_sds.dtype),
                  jax.tree_util.tree_map(jnp.zeros_like, my_params),
                  jnp.float32(0.0))
        carry, _ = lax.scan(tick, carry0, jnp.arange(m + 2 * (n - 1)))
        gacc, lacc = carry[3], carry[4]
        loss = lax.psum(lacc, axis_name) / m
        grads = jax.tree_util.tree_map(lambda g: (g / m)[None], gacc)
        return loss, grads

    return inner


def interleave_stage_params(stacked, num_stages, num_chunks):
    """[N = V*S, ...] global-stage-stacked params -> [S, V, ...] device-major
    layout for schedule='interleaved': device s holds global stages
    s, s+S, ..., s+(V-1)S (the Megatron-style round-robin placement that
    lets the pipeline ramp advance one *chunk* per tick instead of one
    full device-stage)."""
    return jax.tree_util.tree_map(
        lambda a: a.reshape((num_chunks, num_stages) + a.shape[1:])
                   .swapaxes(0, 1), stacked)


def uninterleave_stage_params(inter, num_stages, num_chunks):
    """Inverse of interleave_stage_params: [S, V, ...] -> [V*S, ...]."""
    return jax.tree_util.tree_map(
        lambda a: a.swapaxes(0, 1).reshape((num_chunks * num_stages,)
                                           + a.shape[2:]), inter)


def _pipeline_interleaved_loss_and_grads(stage_fn, loss_fn, num_chunks,
                                         axis_name):
    """Interleaved 1F1B (virtual pipeline chunks) as a single tick scan.

    Ref: /root/reference/paddle/fluid/framework/pipeline_trainer.cc runs
    2k-1 *sections* with per-section concurrency — far more sections than
    devices. The TPU-native analog: each device holds V chunks (global
    stage G = v*S + s on device s, slot v), every forward/backward hop is
    still a ring ppermute (G -> G+1 always crosses to the next device,
    wrapping s=S-1 -> s=0 raises v by one), and the wave advances one
    CHUNK per tick, so the ramp costs ~(S-1) chunk-ticks instead of
    (S-1) full-stage ticks. Tick schedule (m grouped in rounds of S,
    r = m mod S, g = m div S):

      forward  F(s,v,m) = s + v*S + r + g*S*V
      backward B(s,v,m) = N + (S-1-s) + (V-1-v)*S + r + g*S*V   (N = S*V)

    Both are injective per device (one chunk-forward + one chunk-backward
    per tick) and satisfy F(G+1) = F(G)+1 / B(G) = B(G+1)+1, so each
    tick's single ppermute pair delivers exactly on time. Total ticks
    M*V + S*V + S - 1 vs the plain-1f1b equivalent V*(M + 2S - 2) chunk
    pairs — (S-2)(V-1) ticks saved, the interleave ramp win. Activation
    buffer: 2N chunk inputs (live window max 2N-1, +1 slack so a drain
    tick's store can never clobber the slot it is about to read).
    Backward recomputes each chunk from its saved input (remat implied).
    Same per-microbatch loss_fn contract as the 1f1b schedule.
    """
    def inner(params, x, y):
        n = lax.axis_size(axis_name)          # S devices
        me = lax.axis_index(axis_name)
        v_n = num_chunks                      # V chunks per device
        big_n = n * v_n                       # N global stages
        m = x.shape[0]
        k = 2 * big_n
        perm_f = [(i, (i + 1) % n) for i in range(n)]
        perm_b = [(i, (i - 1) % n) for i in range(n)]
        my_params = jax.tree_util.tree_map(lambda a: a[0], params)  # [V,...]
        chunk0 = jax.tree_util.tree_map(lambda a: a[0], my_params)
        h_sds = jax.eval_shape(lambda p, a: stage_fn(p, a), chunk0,
                               jax.ShapeDtypeStruct(x.shape[1:], x.dtype))

        def mb_loss(h_out, y_mb):
            return loss_fn(h_out[None], y_mb[None])

        def fwd_sched(t):
            u = t - me
            g, rem = u // big_n, u % big_n
            v, r = rem // n, rem % n
            mb = g * n + r
            return v, mb, (u >= 0) & (mb >= 0) & (mb < m)

        def bwd_sched(t):
            u = t - big_n - (n - 1 - me)
            g, rem = u // big_n, u % big_n
            v, r = v_n - 1 - rem // n, rem % n
            mb = g * n + r
            return v, mb, (u >= 0) & (mb >= 0) & (mb < m)

        def fwd_tick(v, mb):  # F(me, v, mb)
            return me + v * n + jnp.mod(mb, n) + (mb // n) * big_n

        def pick(tree, idx):
            return jax.tree_util.tree_map(
                lambda a: lax.dynamic_index_in_dim(a, idx, 0,
                                                   keepdims=False), tree)

        def tick(carry, t):
            h_fly, g_fly, pending_lg, acts, gacc, lacc = carry
            # ---- forward chunk-microstep ----
            vf, mf, fvalid = fwd_sched(t)
            feed = lax.dynamic_index_in_dim(
                x, jnp.clip(mf, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where((me == 0) & (vf == 0), feed, h_fly)
            acts = lax.dynamic_update_index_in_dim(
                acts, h_in, jnp.mod(t, k), 0)
            h_out = stage_fn(pick(my_params, vf), h_in)
            # loss head: device S-1 chunk V-1 is the last global stage;
            # its backward fires one tick after this forward, so the
            # cotangent is carried in pending_lg for exactly one tick
            y_b = lax.dynamic_index_in_dim(
                y, jnp.clip(mf, 0, m - 1), 0, keepdims=False)
            loss_v, dh_out = jax.value_and_grad(mb_loss)(h_out, y_b)
            is_last_fwd = (me == n - 1) & (vf == v_n - 1) & fvalid
            lacc = lacc + jnp.where(is_last_fwd,
                                    loss_v.astype(jnp.float32), 0.0)
            new_pending = jnp.where(is_last_fwd, dh_out,
                                    jnp.zeros_like(dh_out))
            # ---- backward chunk-microstep ----
            vb, mbk, bvalid = bwd_sched(t)
            g_in = jnp.where((me == n - 1) & (vb == v_n - 1), pending_lg,
                             g_fly)
            h_saved = lax.dynamic_index_in_dim(
                acts, jnp.mod(fwd_tick(vb, mbk), k), 0, keepdims=False)
            _, vjp_fn = jax.vjp(stage_fn, pick(my_params, vb), h_saved)
            dp, dh_prev = vjp_fn(g_in)
            gacc = jax.tree_util.tree_map(
                lambda a, d: lax.dynamic_update_index_in_dim(
                    a,
                    lax.dynamic_index_in_dim(a, vb, 0, keepdims=False)
                    + jnp.where(bvalid, d, 0), vb, 0),
                gacc, dp)
            h_fly = lax.ppermute(h_out, axis_name, perm_f)
            g_fly = lax.ppermute(dh_prev, axis_name, perm_b)
            return (h_fly, g_fly, new_pending, acts, gacc, lacc), None

        zeros_h = jnp.zeros(h_sds.shape, h_sds.dtype)
        carry0 = (zeros_h, zeros_h, zeros_h,
                  jnp.zeros((k,) + h_sds.shape, h_sds.dtype),
                  jax.tree_util.tree_map(jnp.zeros_like, my_params),
                  jnp.float32(0.0))
        # last tick = B(stage 0, microbatch M-1) = (2N-1) + f(M-1) where
        # f(m) = (m mod S) + (m div S)*N is the round term (NOT (M-1)
        # collapsed — a partial last round still pays a full-round stride)
        total = 2 * big_n + (m - 1) % n + ((m - 1) // n) * big_n
        carry, _ = lax.scan(tick, carry0, jnp.arange(total))
        gacc, lacc = carry[4], carry[5]
        loss = lax.psum(lacc, axis_name) / m
        grads = jax.tree_util.tree_map(lambda g: (g / m)[None], gacc)
        return loss, grads

    return inner


def make_pipeline_train_step(mesh, stage_fn, loss_fn, opt, axis_name=PP,
                             remat=False, schedule="gpipe", num_chunks=1,
                             dp_axis=None):
    """GPipe-style pipeline-parallel TRAINING step.

    Ref: /root/reference/python/paddle/fluid/optimizer.py:2985
    (PipelineOptimizer: cut program into sections, microbatch, train) and
    section_worker.cc:141 (SectionWorker::TrainFiles runs forward AND
    backward AND optimizer per section).

    TPU-first redesign: the pipelined forward is pure differentiable lax
    (scan over ticks + ppermute hops), so the *backward pipeline schedule
    falls out of autodiff*: JAX transposes each ppermute into the reverse
    hop and the scan into a reverse-tick scan, which is exactly the GPipe
    backward wave; per-stage gradient accumulation across microbatches is
    the scan-transpose's natural cotangent sum. No section threads, no
    queues, no hand-written 1F1B — XLA schedules the waves.

    `remat=True` wraps each stage in jax.checkpoint so activations are
    rebuilt in the backward wave (the memory win 1F1B exists for;
    ref backward.py:576 _append_backward_ops_with_checkpoints_).

    Args:
      mesh: Mesh with `axis_name` of size n_stages.
      stage_fn(stage_params, h) -> h  — same signature every stage.
      loss_fn(outputs, labels) -> scalar, where outputs is [M, mb, ...]
        stacked final-stage activations.
      opt: paddle_tpu Optimizer; state/params are the stage-stacked pytrees
        (leading dim n_stages, sharded over `axis_name`), so each device
        updates its own stage's slice — the reference's per-section
        optimizer ops.

    Returns step(params, opt_state, x, y) -> (loss, params, opt_state)
    where x is [M, mb, ...] microbatches and y the matching labels.

    schedule:
      "gpipe" (default) — forward wave then autodiff-transposed backward
        wave; all M microbatch residuals live across the turnaround
        (remat=True shrinks each residual to the stage input).
      "1f1b"  — one forward + one backward microstep per tick
        (_pipeline_1f1b_loss_and_grads): live activations bounded by
        2S-1 stage inputs regardless of M, backward recomputes from the
        saved input (remat implied). Requires loss_fn to average over
        the microbatch axis (the GPipe path then matches exactly).
      "interleaved" — 1f1b over num_chunks virtual chunks per device
        (_pipeline_interleaved_loss_and_grads): params in the
        interleave_stage_params [S, V, ...] layout; the ramp advances one
        chunk per tick (the reference's many-sections-per-device
        concurrency, pipeline_trainer.cc). Same loss_fn contract.

    dp_axis (1f1b/interleaved only): name of a data-parallel mesh axis to
    compose with the pipeline — each dp replica runs the full pipeline on
    its shard of every microbatch (x/y split on the per-microbatch batch
    dim), gradients/loss psum-averaged across replicas (the reference's
    NCCL-DP x pipeline hybrid, multi_devices_graph_pass + pipeline
    sections). Params replicated over dp, sharded over the pipe axis.
    Requires loss_fn to be a uniform MEAN over the batch rows as well as
    the microbatch axis (mean-of-shard-means == global mean only then;
    a sum over batch rows would come back scaled 1/dp_n).
    """
    if num_chunks != 1 and schedule != "interleaved":
        raise ValueError(
            f"num_chunks={num_chunks} only applies to "
            f"schedule='interleaved' (got {schedule!r}) — a silently "
            "ignored chunk count would misrepresent the configured "
            "parallelism")
    if dp_axis is not None and schedule == "gpipe":
        raise ValueError(
            "dp_axis only applies to the '1f1b'/'interleaved' schedules "
            "— gpipe with dp_axis would silently run every replica on "
            "the full batch")
    pspec = P(axis_name)
    if schedule in ("1f1b", "interleaved"):
        if schedule == "interleaved":
            inner = _pipeline_interleaved_loss_and_grads(
                stage_fn, loss_fn, num_chunks, axis_name)
        else:
            inner = _pipeline_1f1b_loss_and_grads(stage_fn, loss_fn,
                                                  axis_name)
        if dp_axis is None:
            data_spec = P()
            pipe_inner = inner
        else:
            # dp replicas each pipeline their shard of every microbatch
            # ([M, mb, ...] split on the mb dim), then average
            data_spec = P(None, dp_axis)

            def pipe_inner(params, x, y, _inner=inner):
                loss, grads = _inner(params, x, y)
                dp_n = lax.axis_size(dp_axis)
                loss = lax.psum(loss, dp_axis) / dp_n
                grads = jax.tree_util.tree_map(
                    lambda g: lax.psum(g, dp_axis) / dp_n, grads)
                return loss, grads

        fwd_bwd = shard_map(pipe_inner, mesh=mesh,
                            in_specs=(pspec, data_spec, data_spec),
                            out_specs=(P(), pspec), check_vma=False)

        def step(params, opt_state, x, y):
            if schedule == "interleaved":
                # dynamic_index clamps, so a chunk-count/layout mismatch
                # would train silently with wrong gradients — fail at
                # trace time instead (shapes are static)
                for leaf in jax.tree_util.tree_leaves(params):
                    if leaf.ndim < 2 or leaf.shape[1] != num_chunks:
                        raise ValueError(
                            f"interleaved params must have shape "
                            f"[n_stages, num_chunks={num_chunks}, ...] "
                            f"(interleave_stage_params); got leaf shape "
                            f"{leaf.shape}")
            loss, grads = fwd_bwd(params, x, y)
            params, opt_state = opt.apply_gradients(params, grads, opt_state)
            return loss, params, opt_state

        return step
    if schedule != "gpipe":
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         "(choices: 'gpipe', '1f1b', 'interleaved')")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def inner(params, x):
        return pipeline_forward(fn, params, x, axis_name)

    fwd = shard_map(inner, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                    check_vma=False)

    def global_loss(params, x, y):
        return loss_fn(fwd(params, x), y)

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(global_loss)(params, x, y)
        params, opt_state = opt.apply_gradients(params, grads, opt_state)
        return loss, params, opt_state

    return step


def stack_stage_params(per_stage_params):
    """[{params of stage i}] -> stacked pytree with leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, 0), *per_stage_params)


def split_microbatches(batch, num_microbatches):
    """[B, ...] -> [M, B/M, ...] (ref: PipelineOptimizer microbatching)."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((num_microbatches, x.shape[0] // num_microbatches)
                            + x.shape[1:]), batch)
