"""Pipeline parallelism — microbatched stage execution on a mesh axis.

Ref: /root/reference/paddle/fluid/framework/pipeline_trainer.cc +
section_worker.cc:141 (program cut at `cut_list` into sections; Scopes flow
through blocking queues between section threads) and the Python splitter
PipelineOptimizer (/root/reference/python/paddle/fluid/optimizer.py:2985).

TPU-first redesign: no threads or queues — a GPipe-style schedule expressed
as a `lax.scan` over microbatches inside `shard_map` over the "pp" axis.
Each device holds one stage's params; activations hop stage→stage via
`ppermute` (ICI neighbor transfer). The scan pipelines naturally: while
device s processes microbatch m, device s-1 processes m+1 — XLA overlaps
the ppermute with compute. Bubble fraction = (S-1)/(M+S-1), as GPipe.

The reference's SectionWorker sync_steps model-replica averaging is subsumed
by the optimizer running sharded over "pp" (each stage updates its own
params; no cross-replica drift exists).
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from paddle_tpu.parallel.mesh import PP


def pipeline_forward(stage_fn, params, x, axis_name=PP, num_microbatches=None):
    """Run a stage-sharded forward inside shard_map.

    stage_fn(stage_params, h) -> h  — same signature every stage.
    params: stage-stacked pytree (leading dim = n_stages, sharded over pp).
    x: [M, mb, ...] microbatched input; only stage 0 consumes it.
    Returns final-stage outputs stacked [M, mb, ...].

    This is the inner per-device function; wrap with `shard_map` via
    `make_pipeline_fn`.
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    m = x.shape[0]
    perm = [(i, (i + 1) % n) for i in range(n)]

    # strip the stage dim (shard_map gives each device its own slice of size 1)
    my_params = jax.tree_util.tree_map(lambda p: p[0], params)

    total_ticks = m + n - 1
    h_shape = jax.eval_shape(lambda p, a: stage_fn(p, a), my_params,
                             jax.ShapeDtypeStruct(x.shape[1:], x.dtype))

    def tick(carry, t):
        inflight, outputs = carry
        # stage 0 injects microbatch t (if any); others use what arrived
        feed = lax.dynamic_index_in_dim(x, jnp.minimum(t, m - 1), 0,
                                        keepdims=False)
        h_in = jnp.where(me == 0, feed, inflight)
        h_out = stage_fn(my_params, h_in)
        # last stage records output for microbatch (t - (n-1))
        out_idx = t - (n - 1)
        valid = (out_idx >= 0) & (out_idx < m)
        outputs = lax.cond(
            valid & (me == n - 1),
            lambda o: lax.dynamic_update_index_in_dim(o, h_out,
                                                      jnp.maximum(out_idx, 0),
                                                      0),
            lambda o: o, outputs)
        inflight = lax.ppermute(h_out, axis_name, perm)
        return (inflight, outputs), None

    inflight0 = jnp.zeros(h_shape.shape, h_shape.dtype)
    outputs0 = jnp.zeros((m,) + h_shape.shape, h_shape.dtype)
    (_, outputs), _ = lax.scan(tick, (inflight0, outputs0),
                               jnp.arange(total_ticks))
    # only the last stage holds real outputs (others zeros) — psum
    # replicates the result across the pp axis
    return lax.psum(outputs, axis_name)


def make_pipeline_fn(mesh, stage_fn, axis_name=PP):
    """Wrap pipeline_forward in shard_map over the pp axis.

    Returns fn(stacked_params, microbatches) -> outputs where stacked_params
    leaves have leading dim n_stages (sharded over pp) and microbatches is
    [M, mb, ...] (replicated input; stage 0 reads it).
    """
    def inner(params, x):
        return pipeline_forward(stage_fn, params, x, axis_name)

    pspec = P(axis_name)
    return shard_map(
        inner, mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=P(),
        check_vma=False,
    )


def _pipeline_1f1b_loss_and_grads(stage_fn, loss_fn, axis_name):
    """1F1B forward+backward schedule as a single tick scan (per device,
    inside shard_map).

    Ref: /root/reference/paddle/fluid/framework/section_worker.cc:141 — the
    reference's section threads run forward AND backward AND optimizer
    concurrently per section, which bounds in-flight activations by the
    section count instead of the microbatch count. This is the same
    property expressed as data flow: every tick runs ONE forward microstep
    (the GPipe wave) and ONE backward microstep (the reverse wave, lagging
    2(S-1) ticks), so stage s's live activations are bounded by a circular
    buffer of 2S-1 stage inputs — O(S), independent of M — while the
    autodiff-transposed GPipe scan keeps all M microbatch residuals alive.
    Backward recomputes the stage from its saved input (implicit remat, the
    1F1B memory contract).

    Timeline (S stages, M microbatches, ticks t = 0 .. M + 2S - 3):
      forward  of microbatch t - s      at stage s   (valid while < M)
      backward of microbatch t - 2(S-1) + s at stage s
    The last stage's backward of microbatch b starts the same tick as its
    forward (one-F-one-B steady state); cotangents hop stage s -> s-1 via
    reverse ppermute.

    loss_fn is applied per microbatch (outputs[None], y[None]) and the
    per-microbatch losses/gradients averaged — identical to the GPipe path
    whenever loss_fn averages over the leading microbatch axis.
    """
    def inner(params, x, y):
        n = lax.axis_size(axis_name)
        me = lax.axis_index(axis_name)
        m = x.shape[0]
        k = 2 * n - 1  # circular buffer: max residual age is 2(S-1) ticks
        perm_f = [(i, (i + 1) % n) for i in range(n)]
        perm_b = [(i, (i - 1) % n) for i in range(n)]
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        h_sds = jax.eval_shape(lambda p, a: stage_fn(p, a), my_params,
                               jax.ShapeDtypeStruct(x.shape[1:], x.dtype))

        def mb_loss(h_out, y_mb):
            return loss_fn(h_out[None], y_mb[None])

        def tick(carry, t):
            h_fly, g_fly, acts, gacc, lacc = carry
            # ---- forward microstep (the GPipe wave) ----
            feed = lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where(me == 0, feed, h_fly)
            acts = lax.dynamic_update_index_in_dim(
                acts, h_in, jnp.mod(t, k), 0)
            h_out = stage_fn(my_params, h_in)
            # ---- loss head: valid only on the last stage, where the
            # backward of microbatch bl = t-(S-1) starts this same tick ----
            bl = t - (n - 1)
            y_b = lax.dynamic_index_in_dim(
                y, jnp.clip(bl, 0, m - 1), 0, keepdims=False)
            loss_v, dh_out = jax.value_and_grad(mb_loss)(h_out, y_b)
            # ---- backward microstep: stage s handles microbatch b ----
            b = t - 2 * (n - 1) + me
            g_in = jnp.where(me == n - 1, dh_out, g_fly)
            h_saved = lax.dynamic_index_in_dim(
                acts, jnp.mod(b + me, k), 0, keepdims=False)
            _, vjp_fn = jax.vjp(stage_fn, my_params, h_saved)
            dp, dh_prev = vjp_fn(g_in)
            valid_b = (b >= 0) & (b < m)
            gacc = jax.tree_util.tree_map(
                lambda a, d: a + jnp.where(valid_b, d, 0), gacc, dp)
            lacc = lacc + jnp.where(
                (me == n - 1) & (bl >= 0) & (bl < m),
                loss_v.astype(jnp.float32), 0.0)
            h_fly = lax.ppermute(h_out, axis_name, perm_f)
            g_fly = lax.ppermute(dh_prev, axis_name, perm_b)
            return (h_fly, g_fly, acts, gacc, lacc), None

        zeros_h = jnp.zeros(h_sds.shape, h_sds.dtype)
        carry0 = (zeros_h, zeros_h,
                  jnp.zeros((k,) + h_sds.shape, h_sds.dtype),
                  jax.tree_util.tree_map(jnp.zeros_like, my_params),
                  jnp.float32(0.0))
        carry, _ = lax.scan(tick, carry0, jnp.arange(m + 2 * (n - 1)))
        gacc, lacc = carry[3], carry[4]
        loss = lax.psum(lacc, axis_name) / m
        grads = jax.tree_util.tree_map(lambda g: (g / m)[None], gacc)
        return loss, grads

    return inner


def make_pipeline_train_step(mesh, stage_fn, loss_fn, opt, axis_name=PP,
                             remat=False, schedule="gpipe"):
    """GPipe-style pipeline-parallel TRAINING step.

    Ref: /root/reference/python/paddle/fluid/optimizer.py:2985
    (PipelineOptimizer: cut program into sections, microbatch, train) and
    section_worker.cc:141 (SectionWorker::TrainFiles runs forward AND
    backward AND optimizer per section).

    TPU-first redesign: the pipelined forward is pure differentiable lax
    (scan over ticks + ppermute hops), so the *backward pipeline schedule
    falls out of autodiff*: JAX transposes each ppermute into the reverse
    hop and the scan into a reverse-tick scan, which is exactly the GPipe
    backward wave; per-stage gradient accumulation across microbatches is
    the scan-transpose's natural cotangent sum. No section threads, no
    queues, no hand-written 1F1B — XLA schedules the waves.

    `remat=True` wraps each stage in jax.checkpoint so activations are
    rebuilt in the backward wave (the memory win 1F1B exists for;
    ref backward.py:576 _append_backward_ops_with_checkpoints_).

    Args:
      mesh: Mesh with `axis_name` of size n_stages.
      stage_fn(stage_params, h) -> h  — same signature every stage.
      loss_fn(outputs, labels) -> scalar, where outputs is [M, mb, ...]
        stacked final-stage activations.
      opt: paddle_tpu Optimizer; state/params are the stage-stacked pytrees
        (leading dim n_stages, sharded over `axis_name`), so each device
        updates its own stage's slice — the reference's per-section
        optimizer ops.

    Returns step(params, opt_state, x, y) -> (loss, params, opt_state)
    where x is [M, mb, ...] microbatches and y the matching labels.

    schedule:
      "gpipe" (default) — forward wave then autodiff-transposed backward
        wave; all M microbatch residuals live across the turnaround
        (remat=True shrinks each residual to the stage input).
      "1f1b"  — one forward + one backward microstep per tick
        (_pipeline_1f1b_loss_and_grads): live activations bounded by
        2S-1 stage inputs regardless of M, backward recomputes from the
        saved input (remat implied). Requires loss_fn to average over
        the microbatch axis (the GPipe path then matches exactly).
    """
    pspec = P(axis_name)
    if schedule == "1f1b":
        fwd_bwd = shard_map(
            _pipeline_1f1b_loss_and_grads(stage_fn, loss_fn, axis_name),
            mesh=mesh, in_specs=(pspec, P(), P()),
            out_specs=(P(), pspec), check_vma=False)

        def step(params, opt_state, x, y):
            loss, grads = fwd_bwd(params, x, y)
            params, opt_state = opt.apply_gradients(params, grads, opt_state)
            return loss, params, opt_state

        return step
    if schedule != "gpipe":
        raise ValueError(f"unknown pipeline schedule {schedule!r} "
                         "(choices: 'gpipe', '1f1b')")
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    def inner(params, x):
        return pipeline_forward(fn, params, x, axis_name)

    fwd = shard_map(inner, mesh=mesh, in_specs=(pspec, P()), out_specs=P(),
                    check_vma=False)

    def global_loss(params, x, y):
        return loss_fn(fwd(params, x), y)

    def step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(global_loss)(params, x, y)
        params, opt_state = opt.apply_gradients(params, grads, opt_state)
        return loss, params, opt_state

    return step


def stack_stage_params(per_stage_params):
    """[{params of stage i}] -> stacked pytree with leading stage dim."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, 0), *per_stage_params)


def split_microbatches(batch, num_microbatches):
    """[B, ...] -> [M, B/M, ...] (ref: PipelineOptimizer microbatching)."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((num_microbatches, x.shape[0] // num_microbatches)
                            + x.shape[1:]), batch)
