"""Elastic local runner — failure detection closed into fault RECOVERY.

Ref: the reference only *detects* (HeartBeatMonitor warns on stalled
trainers, operators/distributed/heart_beat_monitor.h; PSLib workers sleep
through server restarts, fleet_wrapper.h:60) — dead trainers stay dead.
Here the detector drives supervision: a process supervisor relaunches
crashed workers, and workers recover through Trainer's checkpoint/resume
(state + step restored, seekable datasets continue mid-stream).

Restart pacing is fault-tolerance-aware (no reference counterpart):

  * crashes respawn on exponentially backed-off "not before" deadlines
    (core/retry.py RetryPolicy backoff math — the single backoff
    implementation), tracked per worker so one crash-looping rank never
    stalls exit/crash detection for the others;
  * the restart budget is a crash-loop WINDOW: crashes older than
    `crash_window_s` are forgiven, so a job that hits one rough patch a
    day isn't killed by lifetime-total accounting;
  * a worker exiting with `graceful_exit_rc` (static/trainer.py
    PREEMPTED_EXIT_CODE, 75) was preempted AFTER checkpointing — it
    respawns immediately and never burns crash budget.

Single-host scope (process supervision); multi-host pods restart via
their cluster scheduler — the same worker-side resume path applies.
"""

import os
import subprocess
import sys
import time

from paddle_tpu.core.retry import RetryPolicy


class ElasticRunner:
    """Supervise N worker processes; restart any that die with a nonzero
    exit, with exponential backoff, up to max_restarts each within the
    crash window. Workers are expected to be idempotent via
    checkpoint/resume (TrainerConfig.checkpoint_dir + resume)."""

    def __init__(self, nproc, script, script_args=(), max_restarts=3,
                 restart_delay_s=1.0, backoff_multiplier=2.0,
                 max_restart_delay_s=30.0, crash_window_s=None,
                 graceful_exit_rc=75, env_extra=None):
        self.nproc = nproc
        self.script = script
        self.script_args = list(script_args)
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.crash_window_s = crash_window_s   # None = lifetime budget
        self.graceful_exit_rc = graceful_exit_rc
        self.env_extra = dict(env_extra or {})
        self.restarts = [0] * nproc            # crash respawns (lifetime)
        self.preemptions = [0] * nproc         # graceful-rc respawns
        self._crash_times = [[] for _ in range(nproc)]
        # restart pacing = the framework's one backoff implementation
        # (jitter off: supervised respawns don't thundering-herd a store)
        self._backoff = RetryPolicy(backoff_base_s=restart_delay_s,
                                    backoff_multiplier=backoff_multiplier,
                                    backoff_max_s=max_restart_delay_s,
                                    jitter=0.0)

    def _spawn(self, rank):
        env = dict(os.environ)
        env.update(self.env_extra)
        env["PT_ELASTIC_RANK"] = str(rank)
        env["PT_ELASTIC_RESTART"] = str(self.restarts[rank])
        env["PT_ELASTIC_GENERATION"] = str(self.restarts[rank]
                                           + self.preemptions[rank])
        return subprocess.Popen(
            [sys.executable, self.script, *self.script_args], env=env)

    def _recent_crashes(self, rank, now):
        """Crashes charged against the budget: all of them, or only those
        inside the sliding crash window when one is configured."""
        if self.crash_window_s is not None:
            self._crash_times[rank] = [
                t for t in self._crash_times[rank]
                if now - t <= self.crash_window_s]
            return len(self._crash_times[rank])
        return self.restarts[rank]

    def run(self, timeout=600, poll_s=0.2):
        """Run until every worker exits 0. Raises RuntimeError when a
        worker exhausts its restart budget or the deadline passes.

        The poll loop never blocks on a single worker's backoff: crashed
        workers get a per-rank "respawn not before" deadline and the loop
        keeps polling everyone else meanwhile (a blocking sleep here
        would stall exit/crash detection for all other ranks)."""
        procs = {r: self._spawn(r) for r in range(self.nproc)}
        respawn_at = {}                # rank -> monotonic deadline
        done = set()
        deadline = time.monotonic() + timeout
        try:
            while len(done) < self.nproc:
                now = time.monotonic()
                if now > deadline:
                    raise RuntimeError(
                        f"elastic run timed out; completed={sorted(done)}")
                for r in [r for r, t in respawn_at.items() if now >= t]:
                    del respawn_at[r]
                    procs[r] = self._spawn(r)
                for r, p in list(procs.items()):
                    if r in done or r in respawn_at:
                        continue
                    rc = p.poll()
                    if rc is None:
                        continue
                    if rc == 0:
                        done.add(r)
                        continue
                    if rc == self.graceful_exit_rc:
                        # preemption after checkpoint: resume right away,
                        # no crash budget charged
                        self.preemptions[r] += 1
                        respawn_at[r] = now
                        continue
                    self.restarts[r] += 1
                    self._crash_times[r].append(now)
                    recent = self._recent_crashes(r, now)
                    if recent > self.max_restarts:
                        window = ("" if self.crash_window_s is None else
                                  f" within {self.crash_window_s}s")
                        raise RuntimeError(
                            f"worker {r} failed rc={rc} after "
                            f"{self.max_restarts} restarts{window}")
                    respawn_at[r] = now + self._backoff.backoff_s(recent)
                time.sleep(poll_s)
        finally:
            for r, p in procs.items():
                if p.poll() is None:
                    p.kill()
        return dict(restarts=list(self.restarts),
                    preemptions=list(self.preemptions))
