"""Elastic local runner — failure detection closed into fault RECOVERY.

Ref: the reference only *detects* (HeartBeatMonitor warns on stalled
trainers, operators/distributed/heart_beat_monitor.h; PSLib workers sleep
through server restarts, fleet_wrapper.h:60) — dead trainers stay dead.
Here the detector drives supervision: a process supervisor relaunches
crashed workers, and workers recover through Trainer's checkpoint/resume
(state + step restored, seekable datasets continue mid-stream).

Single-host scope (process supervision); multi-host pods restart via
their cluster scheduler — the same worker-side resume path applies.
"""

import os
import subprocess
import sys
import time


class ElasticRunner:
    """Supervise N worker processes; restart any that die with a nonzero
    exit, up to max_restarts each. Workers are expected to be idempotent
    via checkpoint/resume (TrainerConfig.checkpoint_dir + resume)."""

    def __init__(self, nproc, script, script_args=(), max_restarts=3,
                 restart_delay_s=1.0, env_extra=None):
        self.nproc = nproc
        self.script = script
        self.script_args = list(script_args)
        self.max_restarts = max_restarts
        self.restart_delay_s = restart_delay_s
        self.env_extra = dict(env_extra or {})
        self.restarts = [0] * nproc

    def _spawn(self, rank):
        env = dict(os.environ)
        env.update(self.env_extra)
        env["PT_ELASTIC_RANK"] = str(rank)
        env["PT_ELASTIC_RESTART"] = str(self.restarts[rank])
        return subprocess.Popen(
            [sys.executable, self.script, *self.script_args], env=env)

    def run(self, timeout=600, poll_s=0.2):
        """Run until every worker exits 0. Raises RuntimeError when a
        worker exhausts its restart budget or the deadline passes."""
        procs = {r: self._spawn(r) for r in range(self.nproc)}
        done = set()
        deadline = time.monotonic() + timeout
        try:
            while len(done) < self.nproc:
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"elastic run timed out; completed={sorted(done)}")
                for r, p in list(procs.items()):
                    if r in done:
                        continue
                    rc = p.poll()
                    if rc is None:
                        continue
                    if rc == 0:
                        done.add(r)
                    else:
                        self.restarts[r] += 1
                        if self.restarts[r] > self.max_restarts:
                            raise RuntimeError(
                                f"worker {r} failed rc={rc} after "
                                f"{self.max_restarts} restarts")
                        time.sleep(self.restart_delay_s)
                        procs[r] = self._spawn(r)
                time.sleep(poll_s)
        finally:
            for r, p in procs.items():
                if p.poll() is None:
                    p.kill()
        return dict(restarts=list(self.restarts))
