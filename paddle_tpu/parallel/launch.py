"""Multi-host launcher + distributed runtime init.

Ref: /root/reference/python/paddle/distributed/launch.py (multi-proc-per-node
launcher exporting PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS, :78-81,159) and the gen_nccl_id gRPC bootstrap
(operators/distributed_ops/gen_nccl_id_op.cc).

TPU-first: `jax.distributed.initialize` + the JAX coordination service
replace both — one call wires every host into the global mesh over DCN; no
id broadcast, no per-trainer endpoint lists. The CLI here mirrors the
reference's `python -m paddle.distributed.launch` surface for multi-process
CPU/GPU simulation and multi-host TPU pods.

Usage:
  python -m paddle_tpu.parallel.launch --nproc 4 train.py  (local sim)
  # on TPU pods the platform sets the env; just call init_distributed().
"""

import argparse
import os
import re
import subprocess
import sys

import jax

from paddle_tpu.core import flags


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Initialize the multi-host runtime (replaces gen_nccl_id bootstrap).
    No-ops on single-process."""
    env = os.environ
    if coordinator_address is None:
        coordinator_address = env.get("PT_COORDINATOR")
    if coordinator_address is None:
        return False  # single process
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=int(num_processes or env.get("PT_NUM_PROCESSES", 1)),
        process_id=int(process_id or env.get("PT_PROCESS_ID", 0)))
    return True


def _gather_retryable(exc):
    """host_allgather's wait-for-peer predicate: an absent file is the
    normal not-published-yet state here (unlike remote I/O, where
    core/retry.py treats FileNotFoundError as an answer), and
    ValueError/EOFError are a peer's np.save caught mid-os.replace."""
    return isinstance(exc, (FileNotFoundError, ValueError, EOFError,
                            OSError))


def host_allgather(arr, rank, world, exchange_dir, tag, timeout=60.0,
                   generation=None, policy=None, ragged=False):
    """All-gather host numpy arrays across local processes via the shared
    filesystem — no XLA collectives, so it works on backends where
    multi-process computations are unimplemented (jax 0.4.x CPU, where
    multihost_utils.process_allgather raises inside the worker). Each
    rank atomically publishes its array (temp file + os.replace), then
    waits for the others under a core/retry.py RetryPolicy (jittered
    backoff, overall deadline = `timeout`; pass `policy` to override).
    `tag` must be unique per collective call site. Returns
    [world, *arr.shape], or a list of `world` per-rank arrays when
    `ragged=True` (for message-style exchanges — e.g. the fleet
    router's JSON command/response wire — where ranks legitimately
    publish different-length payloads that np.stack would reject).

    `generation` isolates incarnations of the SAME tag (the fleet
    router's respawned subprocess replicas restart their command
    sequence at 0): files are published as `{tag}.g{generation}_{rank}`
    and any file of this tag from an older generation is removed before
    publishing, so a respawned rank can never read a dead peer's stale
    payload as fresh."""
    import numpy as np

    from paddle_tpu.core.retry import RetryPolicy

    os.makedirs(exchange_dir, exist_ok=True)
    arr = np.asarray(arr)
    base = tag if generation is None else f"{tag}.g{int(generation)}"
    if generation is not None:
        stale = re.compile(rf"^{re.escape(tag)}\.g(\d+)_\d+\.npy$")
        for name in os.listdir(exchange_dir):
            m = stale.match(name)
            if m and int(m.group(1)) < int(generation):
                try:
                    os.remove(os.path.join(exchange_dir, name))
                except OSError:
                    pass           # the other rank's cleanup won the race
    tmp = os.path.join(exchange_dir, f".{base}_{rank}.tmp.npy")
    with open(tmp, "wb") as f:
        np.save(f, arr)
    os.replace(tmp, os.path.join(exchange_dir, f"{base}_{rank}.npy"))
    pol = policy or RetryPolicy(
        max_attempts=1_000_000_000, backoff_base_s=0.005,
        backoff_max_s=0.05, backoff_multiplier=1.5, deadline_s=timeout,
        retryable=_gather_retryable)
    out = []
    for r in range(world):
        path = os.path.join(exchange_dir, f"{base}_{r}.npy")

        def load_peer(p=path):
            return np.load(p)

        try:
            out.append(pol.call(load_peer))
        except Exception as e:
            if not _gather_retryable(e):
                raise
            raise TimeoutError(
                f"host_allgather({tag}): rank {r} did not publish "
                f"within {timeout}s") from e
    return out if ragged else np.stack(out)


def launch_local(nproc, script, script_args=(), base_port=12355,
                 env_extra=None):
    """Spawn nproc local processes wired into one JAX distributed job
    (ref: launch.py _start_procs). Used by multi-host simulation tests."""
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update({
            "PT_COORDINATOR": f"127.0.0.1:{base_port}",
            "PT_NUM_PROCESSES": str(nproc),
            "PT_PROCESS_ID": str(rank),
            "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        })
        env.update(env_extra or {})
        procs.append(subprocess.Popen(
            [sys.executable, script, *script_args], env=env))
    return procs


def wait_all(procs, timeout=600):
    """Wait for all ranks; raise if any failed (ref: launch.py watch loop —
    terminates the job when any proc dies)."""
    codes = []
    try:
        for p in procs:
            codes.append(p.wait(timeout=timeout))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(c != 0 for c in codes):
        raise RuntimeError(f"distributed job failed, exit codes: {codes}")
    return codes


def main():
    ap = argparse.ArgumentParser(description="paddle_tpu distributed launcher")
    ap.add_argument("--nproc", type=int, default=1)
    ap.add_argument("--port", type=int, default=12355)
    ap.add_argument("script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args()
    procs = launch_local(args.nproc, args.script, args.script_args, args.port)
    wait_all(procs)


if __name__ == "__main__":
    main()
