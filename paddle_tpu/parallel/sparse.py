"""Sparse-row gradients and beyond-HBM embedding tables — the
SelectedRows / PSLib successor.

Ref:
  * /root/reference/paddle/fluid/framework/selected_rows.h:1 — SelectedRows
    {rows, value} sparse row-slice tensor produced by embedding backward.
  * /root/reference/paddle/fluid/operators/optimizers/adam_op.h — every
    reference optimizer has a sparse branch applying updates only to touched
    rows (lazy-mode semantics for moment-carrying optimizers).
  * /root/reference/paddle/fluid/framework/fleet/fleet_wrapper.h:76
    PullSparseVarsSync / :110 PushSparseVarsWithLabelAsync — the PSLib
    pull/push flow serving tables larger than one machine's memory.

TPU-first redesign: XLA has no dynamic-shape SelectedRows, so the sparse
path is *static-size unique + segment-sum + row scatter*:

  1. ``unique_ids(ids, k)`` dedupes the step's ids into a fixed-size [k]
     buffer (k = ids.size bounds it) with an inverse map — the "rows" of
     SelectedRows, shape-stable under jit.
  2. The train step *pulls* those rows ([k, D], small), computes the loss
     through the pulled rows (so autodiff produces a [k, D] row-gradient,
     never a dense [V, D] table gradient), and *pushes* a row-wise optimizer
     update back with scatter. ``SparseTable`` keeps table + slots in HBM and
     does the whole cycle inside one jit.
  3. ``HostTable`` is the beyond-HBM tier: table + optimizer slots live in
     host RAM (numpy); per step only the touched rows cross PCIe, exactly
     PSLib's pull/push. An optional background prefetch thread overlaps the
     next batch's pull with the current step (async push/pull parity).
"""

import threading

import numpy as np

import jax
import jax.numpy as jnp


def unique_ids(ids, k=None):
    """Static-size unique: returns (uniq [k], inv (ids.shape), valid [k]).

    uniq is padded with uniq[0] (a real id, so gathers stay in-bounds);
    ``valid`` masks the padding. inv maps every original id position to its
    slot in uniq. k defaults to ids.size (worst case all-distinct).
    """
    flat = ids.reshape(-1)
    k = int(flat.size) if k is None else int(k)
    uniq, inv = jnp.unique(flat, size=k, fill_value=flat[0],
                           return_inverse=True)
    counts = jnp.zeros((k,), jnp.int32).at[inv].add(1)
    valid = counts > 0
    return uniq, inv.reshape(ids.shape), valid


def segment_rowsum(row_cotangents, inv, k):
    """Sum duplicate-id cotangents into unique rows ([*, D] -> [k, D]) —
    the SelectedRows duplicate-row merge (ref: math/selected_rows_functor.cc
    MergeAdd)."""
    flat = row_cotangents.reshape(-1, row_cotangents.shape[-1])
    return jnp.zeros((k, flat.shape[-1]), flat.dtype).at[
        inv.reshape(-1)].add(flat)


class SparseTable:
    """HBM-resident embedding table with sparse-row training.

    state = {"table": [V, D], "slots": {name: [V, ...]}} — a plain pytree, so
    it shards over an "ep" mesh axis with PartitionSpec(('ep', None)) and
    checkpoints like any param. The train cycle:

        rows, ctx = tbl.pull(state, ids)        # [k, D] touched rows
        ... loss uses tbl.embed(rows, ctx)       # differentiable wrt `rows`
        state = tbl.push(state, row_grad, ctx, lr)  # row-wise optimizer

    Only [k, D] tensors appear in the autodiff graph — the dense [V, D]
    gradient of the naive path never materializes (VERDICT: a 10Mx16 table
    no longer pays a 640MB dense grad per step).
    """

    def __init__(self, vocab_size, dim, optimizer=None, init_scale=0.01,
                 dtype=jnp.float32):
        from paddle_tpu.optimizer.optimizers import SGD
        self.vocab_size = vocab_size
        self.dim = dim
        self.opt = optimizer if optimizer is not None else SGD(0.01)
        self.init_scale = init_scale
        self.dtype = dtype

    def init(self, key):
        table = self.init_scale * jax.random.normal(
            key, (self.vocab_size, self.dim), self.dtype)
        slots = self.opt.slots(table)
        return {"table": table, "step": jnp.zeros((), jnp.int32),
                "slots": slots}

    def pull(self, state, ids, k=None):
        """Gather the step's unique rows. Returns (rows [k, D], ctx)."""
        uniq, inv, valid = unique_ids(ids, k)
        rows = jnp.take(state["table"], uniq, axis=0)
        return rows, {"uniq": uniq, "inv": inv, "valid": valid}

    @staticmethod
    def embed(rows, ctx):
        """Expand pulled unique rows back to per-position embeddings."""
        return jnp.take(rows, ctx["inv"], axis=0)

    def push(self, state, row_grad, ctx):
        """Apply the optimizer row-wise to the touched rows only (sparse /
        lazy-mode semantics, ref adam_op.h sparse branch)."""
        uniq, valid = ctx["uniq"], ctx["valid"]
        table, slots, step = state["table"], state["slots"], state["step"]
        p_rows = jnp.take(table, uniq, axis=0)
        s_rows = jax.tree_util.tree_map(
            lambda s: jnp.take(s, uniq, axis=0), slots)
        lr = self.opt.lr(step)
        new_rows, new_srows = self.opt._update_leaf(
            row_grad, p_rows, s_rows, lr, step)
        # Padding slots in uniq repeat a real id; route them out-of-bounds
        # and drop so a stale duplicate can never overwrite the real update.
        idx = jnp.where(valid, uniq, self.vocab_size)
        table = table.at[idx].set(new_rows.astype(table.dtype), mode="drop")
        slots = jax.tree_util.tree_map(
            lambda s, ns: s.at[idx].set(ns.astype(s.dtype), mode="drop"),
            slots, new_srows)
        return {"table": table, "step": step + 1, "slots": slots}


class HostTable:
    """Beyond-HBM tier: table + slots in host RAM, rows pulled to device per
    step and row-updates pushed back (PSLib parity; fleet_wrapper.h:76,:110).

    Not jittable end-to-end by design — the host hop IS the feature. Use
    ``prefetch`` to overlap the next batch's pull with the current step
    (async pull parity with AsyncCommunicator).
    """

    def __init__(self, vocab_size, dim, optimizer=None, init_scale=0.01,
                 seed=0, dtype=np.float32):
        from paddle_tpu.optimizer.optimizers import SGD
        self.vocab_size, self.dim = vocab_size, dim
        self.opt = optimizer if optimizer is not None else SGD(0.01)
        rng = np.random.RandomState(seed)
        self.table = (init_scale *
                      rng.standard_normal((vocab_size, dim))).astype(dtype)
        # honor the optimizer's slot initial values (e.g. Adagrad epsilon
        # accumulator) by probing one row and broadcasting it
        probe = self.opt.slots(jnp.zeros((1, dim), jnp.float32))
        self._slot_names = sorted(probe)
        self.slots = {n: np.broadcast_to(np.asarray(probe[n], dtype),
                                         (vocab_size, dim)).copy()
                      for n in self._slot_names}
        self.step = 0
        self._pool = {}
        # guards _pool AND table/slots: prefetch gathers on a background
        # thread while push writes rows in place
        self._lock = threading.Lock()

    def pull(self, ids):
        """Host gather of the unique rows for `ids` → device arrays."""
        flat = np.unique(np.asarray(ids).reshape(-1))
        with self._lock:
            host_rows = self.table[flat]
        return jnp.asarray(host_rows), flat

    def prefetch(self, ids, tag="next"):
        """Start an async pull; collect with `take_prefetched(tag)`.

        Safe against concurrent push(): pull's host gather and push's row
        writes serialize on the table lock, so prefetched rows are never
        torn mixes of pre-/post-update values (they may simply reflect the
        state before or after a concurrent push — async-SGD semantics, like
        the reference's AsyncCommunicator)."""
        def work():
            out = self.pull(ids)
            with self._lock:
                self._pool[tag] = out
        t = threading.Thread(target=work, daemon=True)
        t.start()
        return t

    def take_prefetched(self, tag="next"):
        with self._lock:
            return self._pool.pop(tag)

    def embed_ids(self, rows, uniq, ids):
        """Map pulled rows back to per-position embeddings (host inv map)."""
        inv = np.searchsorted(uniq, np.asarray(ids).reshape(-1))
        return jnp.take(rows, jnp.asarray(inv), axis=0).reshape(
            tuple(np.asarray(ids).shape) + (self.dim,))

    def push(self, uniq, row_grad):
        """Row-wise optimizer update applied in host memory."""
        g = np.asarray(row_grad)
        p = self.table[uniq]
        s = {n: self.slots[n][uniq] for n in self._slot_names}
        lr = float(self.opt.lr(jnp.asarray(self.step)))
        new_p, new_s = self.opt._update_leaf(
            jnp.asarray(g), jnp.asarray(p),
            {n: jnp.asarray(v) for n, v in s.items()}, lr,
            jnp.asarray(self.step))
        with self._lock:
            self.table[uniq] = np.asarray(new_p, dtype=self.table.dtype)
            for n in self._slot_names:
                self.slots[n][uniq] = np.asarray(new_s[n],
                                                 dtype=self.slots[n].dtype)
        self.step += 1

    def nbytes(self):
        return self.table.nbytes + sum(v.nbytes for v in self.slots.values())
