"""Sparse-row gradients and beyond-HBM embedding tables — the
SelectedRows / PSLib successor.

Ref:
  * /root/reference/paddle/fluid/framework/selected_rows.h:1 — SelectedRows
    {rows, value} sparse row-slice tensor produced by embedding backward.
  * /root/reference/paddle/fluid/operators/optimizers/adam_op.h — every
    reference optimizer has a sparse branch applying updates only to touched
    rows (lazy-mode semantics for moment-carrying optimizers).
  * /root/reference/paddle/fluid/framework/fleet/fleet_wrapper.h:76
    PullSparseVarsSync / :110 PushSparseVarsWithLabelAsync — the PSLib
    pull/push flow serving tables larger than one machine's memory.

TPU-first redesign: XLA has no dynamic-shape SelectedRows, so the sparse
path is *static-size unique + segment-sum + row scatter*:

  1. ``unique_ids(ids, k)`` dedupes the step's ids into a fixed-size [k]
     buffer (k = ids.size bounds it) with an inverse map — the "rows" of
     SelectedRows, shape-stable under jit.
  2. The train step *pulls* those rows ([k, D], small), computes the loss
     through the pulled rows (so autodiff produces a [k, D] row-gradient,
     never a dense [V, D] table gradient), and *pushes* a row-wise optimizer
     update back with scatter. ``SparseTable`` keeps table + slots in HBM and
     does the whole cycle inside one jit.
  3. ``HostTable`` is the beyond-HBM tier: table + optimizer slots live in
     host RAM (numpy); per step only the touched rows cross PCIe, exactly
     PSLib's pull/push. An optional background prefetch thread overlaps the
     next batch's pull with the current step (async push/pull parity).
"""

import threading

import numpy as np

import jax
import jax.numpy as jnp


def unique_ids(ids, k=None):
    """Static-size unique: returns (uniq [k], inv (ids.shape), valid [k]).

    uniq is padded with uniq[0] (a real id, so gathers stay in-bounds);
    ``valid`` masks the padding. inv maps every original id position to its
    slot in uniq. k defaults to ids.size (worst case all-distinct).
    """
    flat = ids.reshape(-1)
    k = int(flat.size) if k is None else int(k)
    uniq, inv = jnp.unique(flat, size=k, fill_value=flat[0],
                           return_inverse=True)
    counts = jnp.zeros((k,), jnp.int32).at[inv].add(1)
    valid = counts > 0
    return uniq, inv.reshape(ids.shape), valid


def segment_rowsum(row_cotangents, inv, k):
    """Sum duplicate-id cotangents into unique rows ([*, D] -> [k, D]) —
    the SelectedRows duplicate-row merge (ref: math/selected_rows_functor.cc
    MergeAdd)."""
    flat = row_cotangents.reshape(-1, row_cotangents.shape[-1])
    return jnp.zeros((k, flat.shape[-1]), flat.dtype).at[
        inv.reshape(-1)].add(flat)


def _probe_slots(opt, dim, dtype):
    """Probe the optimizer's slot initial values on one row (e.g. Adagrad's
    epsilon accumulator) so host tables can broadcast them."""
    probe = opt.slots(jnp.zeros((1, dim), jnp.float32))
    names = sorted(probe)
    init = {n: np.asarray(probe[n], dtype)[0] for n in names}
    return names, init


def _host_row_update(opt, step, rows_np, slots_np, grad):
    """One optimizer step over gathered host rows; returns (new_p, new_s)
    as numpy. Shared by every host-RAM tier (single source for the sparse
    row-update semantics)."""
    lr = float(opt.lr(jnp.asarray(step)))
    new_p, new_s = opt._update_leaf(
        jnp.asarray(np.asarray(grad)), jnp.asarray(rows_np),
        {n: jnp.asarray(v) for n, v in slots_np.items()}, lr,
        jnp.asarray(step))
    return np.asarray(new_p), {n: np.asarray(v) for n, v in new_s.items()}


def _embed_from_rows(rows, uniq, ids, dim):
    """Map pulled unique rows back to per-position embeddings (host inv)."""
    inv = np.searchsorted(uniq, np.asarray(ids).reshape(-1))
    return jnp.take(rows, jnp.asarray(inv), axis=0).reshape(
        tuple(np.asarray(ids).shape) + (dim,))


class SparseTable:
    """HBM-resident embedding table with sparse-row training.

    state = {"table": [V, D], "slots": {name: [V, ...]}} — a plain pytree, so
    it shards over an "ep" mesh axis with PartitionSpec(('ep', None)) and
    checkpoints like any param. The train cycle:

        rows, ctx = tbl.pull(state, ids)        # [k, D] touched rows
        ... loss uses tbl.embed(rows, ctx)       # differentiable wrt `rows`
        state = tbl.push(state, row_grad, ctx, lr)  # row-wise optimizer

    Only [k, D] tensors appear in the autodiff graph — the dense [V, D]
    gradient of the naive path never materializes (VERDICT: a 10Mx16 table
    no longer pays a 640MB dense grad per step).
    """

    def __init__(self, vocab_size, dim, optimizer=None, init_scale=0.01,
                 dtype=jnp.float32):
        from paddle_tpu.optimizer.optimizers import SGD
        self.vocab_size = vocab_size
        self.dim = dim
        self.opt = optimizer if optimizer is not None else SGD(0.01)
        self.init_scale = init_scale
        self.dtype = dtype

    def init(self, key):
        table = self.init_scale * jax.random.normal(
            key, (self.vocab_size, self.dim), self.dtype)
        slots = self.opt.slots(table)
        return {"table": table, "step": jnp.zeros((), jnp.int32),
                "slots": slots}

    def pull(self, state, ids, k=None):
        """Gather the step's unique rows. Returns (rows [k, D], ctx)."""
        uniq, inv, valid = unique_ids(ids, k)
        rows = jnp.take(state["table"], uniq, axis=0)
        return rows, {"uniq": uniq, "inv": inv, "valid": valid}

    @staticmethod
    def embed(rows, ctx):
        """Expand pulled unique rows back to per-position embeddings."""
        return jnp.take(rows, ctx["inv"], axis=0)

    def push(self, state, row_grad, ctx):
        """Apply the optimizer row-wise to the touched rows only (sparse /
        lazy-mode semantics, ref adam_op.h sparse branch)."""
        uniq, valid = ctx["uniq"], ctx["valid"]
        table, slots, step = state["table"], state["slots"], state["step"]
        p_rows = jnp.take(table, uniq, axis=0)
        s_rows = jax.tree_util.tree_map(
            lambda s: jnp.take(s, uniq, axis=0), slots)
        lr = self.opt.lr(step)
        new_rows, new_srows = self.opt._update_leaf(
            row_grad, p_rows, s_rows, lr, step)
        # Padding slots in uniq repeat a real id; route them out-of-bounds
        # and drop so a stale duplicate can never overwrite the real update.
        idx = jnp.where(valid, uniq, self.vocab_size)
        table = table.at[idx].set(new_rows.astype(table.dtype), mode="drop")
        slots = jax.tree_util.tree_map(
            lambda s, ns: s.at[idx].set(ns.astype(s.dtype), mode="drop"),
            slots, new_srows)
        return {"table": table, "step": step + 1, "slots": slots}


class HostTable:
    """Beyond-HBM tier: table + slots in host RAM, rows pulled to device per
    step and row-updates pushed back (PSLib parity; fleet_wrapper.h:76,:110).

    Not jittable end-to-end by design — the host hop IS the feature. Use
    ``prefetch`` to overlap the next batch's pull with the current step
    (async pull parity with AsyncCommunicator).
    """

    def __init__(self, vocab_size, dim, optimizer=None, init_scale=0.01,
                 seed=0, dtype=np.float32):
        from paddle_tpu.optimizer.optimizers import SGD
        self.vocab_size, self.dim = vocab_size, dim
        self.opt = optimizer if optimizer is not None else SGD(0.01)
        rng = np.random.RandomState(seed)
        self.table = (init_scale *
                      rng.standard_normal((vocab_size, dim))).astype(dtype)
        self._slot_names, slot_init = _probe_slots(self.opt, dim, dtype)
        self.slots = {n: np.broadcast_to(slot_init[n],
                                         (vocab_size, dim)).copy()
                      for n in self._slot_names}
        self.step = 0
        self._pool = {}
        # guards _pool AND table/slots: prefetch gathers on a background
        # thread while push writes rows in place
        self._lock = threading.Lock()

    def pull(self, ids):
        """Host gather of the unique rows for `ids` → device arrays."""
        flat = np.unique(np.asarray(ids).reshape(-1))
        with self._lock:
            host_rows = self.table[flat]
        return jnp.asarray(host_rows), flat

    def prefetch(self, ids, tag="next"):
        """Start an async pull; collect with `take_prefetched(tag)`.

        Safe against concurrent push(): pull's host gather and push's row
        writes serialize on the table lock, so prefetched rows are never
        torn mixes of pre-/post-update values (they may simply reflect the
        state before or after a concurrent push — async-SGD semantics, like
        the reference's AsyncCommunicator)."""
        def work():
            out = self.pull(ids)
            with self._lock:
                self._pool[tag] = out
        t = threading.Thread(target=work, daemon=True)
        t.start()
        return t

    def take_prefetched(self, tag="next"):
        with self._lock:
            return self._pool.pop(tag)

    def embed_ids(self, rows, uniq, ids):
        """Map pulled rows back to per-position embeddings (host inv map)."""
        return _embed_from_rows(rows, uniq, ids, self.dim)

    def push(self, uniq, row_grad):
        """Row-wise optimizer update applied in host memory."""
        p = self.table[uniq]
        slo = {n: self.slots[n][uniq] for n in self._slot_names}
        new_p, new_s = _host_row_update(self.opt, self.step, p, slo, row_grad)
        with self._lock:
            self.table[uniq] = new_p.astype(self.table.dtype)
            for n in self._slot_names:
                self.slots[n][uniq] = new_s[n].astype(self.slots[n].dtype)
        self.step += 1

    def nbytes(self):
        return self.table.nbytes + sum(v.nbytes for v in self.slots.values())


class FeatureTable:
    """PSLib-style *keyed* host table: arbitrary int64 feature signs (no
    bounded vocab), bounded resident capacity, and cold-row eviction.

    Ref: fleet_wrapper.h:76 pull flow + PSLib's DownpourSparseTable, whose
    entries are created on first touch and evicted by recency/frequency
    when the shard fills. Here: a host-RAM arena [capacity, D] plus an
    id->slot dict; eviction reinitializes the row on its next touch (the
    PSLib cold-feature semantics).

    evict: "lru" (least-recently-touched) or "lfu" (least-frequently).
    """

    def __init__(self, dim, capacity, optimizer=None, init_scale=0.01,
                 evict="lru", seed=0, dtype=np.float32):
        from paddle_tpu.optimizer.optimizers import SGD
        assert evict in ("lru", "lfu"), evict
        self.dim, self.capacity, self.evict = dim, int(capacity), evict
        self.opt = optimizer if optimizer is not None else SGD(0.01)
        self.init_scale = init_scale
        self._rng = np.random.RandomState(seed)
        self.arena = np.zeros((self.capacity, dim), dtype)
        self._slot_names, self._slot_init = _probe_slots(self.opt, dim, dtype)
        self.slots = {n: np.zeros((self.capacity, dim), dtype)
                      for n in self._slot_names}
        self._index = {}          # feature sign -> arena slot
        self._rindex = {}         # arena slot -> feature sign
        self._free = list(range(self.capacity - 1, -1, -1))
        self._clock = 0
        self._score = np.zeros((self.capacity,), np.int64)  # recency or freq
        self.step = 0
        self.evictions = 0
        self._lock = threading.Lock()

    def _touch(self, slot):
        self._clock += 1
        if self.evict == "lru":
            self._score[slot] = self._clock
        else:
            self._score[slot] += 1

    def _alloc(self, sign):
        if self._free:
            slot = self._free.pop()
        else:
            # evict the coldest resident row
            slot = int(np.argmin(self._score))
            old = self._rindex.pop(slot)
            del self._index[old]
            self.evictions += 1
        self._index[sign] = slot
        self._rindex[slot] = sign
        self.arena[slot] = (self.init_scale *
                            self._rng.standard_normal(self.dim))
        for n in self._slot_names:
            self.slots[n][slot] = self._slot_init[n]
        self._score[slot] = 0 if self.evict == "lfu" else self._clock
        return slot

    def pull(self, ids):
        """Unique host gather; creates rows on first touch. Returns
        (rows [k, D] device, uniq signs [k], ctx) — pass ctx to push()."""
        uniq = np.unique(np.asarray(ids).reshape(-1))
        with self._lock:
            slot_arr = np.empty((len(uniq),), np.int64)
            for i, sign in enumerate(uniq):
                s = self._index.get(int(sign))
                if s is None:
                    s = self._alloc(int(sign))
                self._touch(s)
                slot_arr[i] = s
            rows = self.arena[slot_arr]
        return jnp.asarray(rows), uniq, {"signs": uniq, "slots": slot_arr}

    def embed_ids(self, rows, uniq, ids):
        return _embed_from_rows(rows, uniq, ids, self.dim)

    def push(self, ctx, row_grad):
        """Row-wise optimizer update into the arena. Rows whose slot was
        reallocated to a DIFFERENT sign between pull and push (eviction
        under async prefetch) are dropped — checked by sign identity, the
        PSLib stale-update semantics."""
        slot_arr = np.asarray(ctx["slots"], np.int64)
        signs = np.asarray(ctx["signs"])
        g = np.asarray(row_grad)
        if slot_arr.size == 0:
            return
        with self._lock:
            live = np.array([self._rindex.get(int(sl)) == int(sg)
                             for sl, sg in zip(slot_arr, signs)], bool)
            if not live.any():
                self.step += 1
                return
            sl = slot_arr[live]
            p = self.arena[sl]
            slo = {n: self.slots[n][sl] for n in self._slot_names}
            new_p, new_s = _host_row_update(self.opt, self.step, p, slo,
                                            g[live])
            self.arena[sl] = new_p.astype(self.arena.dtype)
            for n in self._slot_names:
                self.slots[n][sl] = new_s[n].astype(self.slots[n].dtype)
            self.step += 1

    @property
    def resident(self):
        return len(self._index)


class ShardedHostTable:
    """Multi-host PSLib topology: each process owns the rows with
    ``sign % num_shards == shard_id`` in its own host RAM (ref:
    fleet_wrapper.h:55 — tables sharded across pserver machines;
    downpour_worker.cc pull/push flow).

    TPU-first redesign of the RPC pull: every process host-gathers the rows
    it owns into a zero-filled [k, D] buffer and the buffers are summed
    with one ``psum`` over the mesh axis — the parameter-server exchange as
    an XLA collective over ICI/DCN instead of brpc. Push needs no
    communication: row gradients are already replicated after the train
    step's psum, and each process updates only its owned rows.
    """

    def __init__(self, dim, capacity_per_shard, shard_id, num_shards,
                 optimizer=None, **kw):
        self.shard_id, self.num_shards = int(shard_id), int(num_shards)
        self.dim = dim
        self.local = FeatureTable(dim, capacity_per_shard,
                                  optimizer=optimizer, **kw)

    def owns(self, signs):
        return (np.asarray(signs) % self.num_shards) == self.shard_id

    def pull_local(self, uniq, return_ctx=False):
        """Host gather of the owned subset of `uniq` into a zero-filled
        [k, D] buffer (device). Sum the shards' buffers (psum over the mesh
        axis, or `sum_shards` in-process) to complete the pull. With
        return_ctx, also returns the ctx that push_local requires."""
        uniq = np.asarray(uniq).reshape(-1)
        mine = self.owns(uniq)
        buf = np.zeros((len(uniq), self.dim), self.local.arena.dtype)
        if mine.any():
            rows, _, lctx = self.local.pull(uniq[mine])
            buf[mine] = np.asarray(rows)
            ctx = {"local": lctx, "positions": np.where(mine)[0]}
        else:
            ctx = {"local": None, "positions": np.empty((0,), np.int64)}
        if return_ctx:
            return jnp.asarray(buf), ctx
        return jnp.asarray(buf)

    @staticmethod
    def sum_shards(buffers):
        """In-process stand-in for the cross-host psum (used by tests and
        single-process multi-shard serving)."""
        out = buffers[0]
        for b in buffers[1:]:
            out = out + b
        return out

    def push_local(self, row_grad, ctx):
        """Apply the (replicated) row-gradient to the owned rows only.
        ctx comes from ``pull_local(uniq, return_ctx=True)`` — pulls and
        pushes are explicitly paired (a hidden last-pull state would be
        silently clobbered by prefetch-style double pulls)."""
        if ctx["local"] is None:
            return
        g = np.asarray(row_grad)[ctx["positions"]]
        self.local.push(ctx["local"], g)
