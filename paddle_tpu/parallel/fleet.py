"""Fleet — the unified distributed-training facade.

Ref: /root/reference/python/paddle/fluid/incubate/fleet/base/fleet_base.py:38
(Fleet singleton: init(role_maker), distributed_optimizer(opt, strategy),
worker_index/num, barriers) and incubate/fleet/collective/__init__.py:94
(DistributedStrategy wrapping Build/ExecutionStrategy knobs: local_sgd,
use_hierarchical_allreduce, fusion sizes...).

TPU-first: the strategy names a mesh shape + gradient schedule instead of
graph-rewrite knobs; distributed_optimizer composes the functional wrappers
(GradientMerge / LocalSGD / GeoSGD / DGC / AMP) and `fleet.build_mesh()`
hands back the jax.sharding.Mesh the train step pjits over. Multi-host
bootstrap is jax.distributed (replacing gen_nccl_id + role makers reading
PADDLE_TRAINER_* env), but the same env vars are honored for launcher parity.
"""

import dataclasses
import os

import jax

from paddle_tpu.core.enforce import enforce
from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.parallel.communicator import GeoSGD, GradientMerge, LocalSGD


@dataclasses.dataclass
class DistributedStrategy:
    """Mesh shape + communication schedule (ref: fleet DistributedStrategy +
    DistributeTranspilerConfig in one place)."""
    dp: int = -1                 # data-parallel ways (-1: infer)
    fsdp: int = 1                # param-sharded data parallel
    tp: int = 1                  # tensor parallel
    pp: int = 1                  # pipeline stages
    pp_schedule: str = "gpipe"   # "gpipe" | "1f1b" | "interleaved"
    pp_chunks: int = 1           # virtual chunks/device (interleaved)
    sp: int = 1                  # sequence/context parallel
    ep: int = 1                  # embedding/expert shards
    amp: bool = False            # bf16 mixed precision
    recompute: bool = False      # activation checkpointing wrapper
    gradient_merge_steps: int = 1
    local_sgd_steps: int = 0     # >0: LocalSGD with this sync period
    geo_sgd_steps: int = 0       # >0: Geo-SGD delta sync period
    dc_asgd_steps: int = 0       # >0: DC-ASGD with this pull period
    dc_asgd_lambda: float = 1.0  # delay-compensation strength
    dc_asgd_lr: float = 0.0      # server lr (0 -> optimizer's lr attr)
    dgc: bool = False            # top-k compressed grads
    dgc_sparsity: float = 0.99

    def mesh_axes(self):
        axes = {}
        for name in ("dp", "fsdp", "tp", "pp", "sp", "ep"):
            size = getattr(self, name)
            if size == -1 or size > 1:
                axes[name] = size
        return axes or {"dp": -1}

    @classmethod
    def from_plan(cls, plan):
        """The strategy equivalent of an autoplan MeshPlan: mesh axes
        from the winning factorization, pipeline schedule + microbatch
        count from the plan's choice."""
        pp = plan.axes.get("pp", 1)
        return cls(dp=plan.axes.get("dp", 1),
                   tp=plan.axes.get("tp", 1), pp=pp,
                   pp_schedule=plan.schedule if pp > 1 else "gpipe",
                   pp_chunks=1)

    def pipeline_kwargs(self):
        """kwargs for parallel.pipeline.make_pipeline_train_step matching
        this strategy's pipeline schedule (ref: PipelineOptimizer config +
        section_worker concurrency knobs). An EXPLICIT dp > 1 with a tick
        schedule composes the dp x pp hybrid (dp_axis='dp', which shards
        the per-microbatch batch dim — a contract change the inferred
        dp = -1 default must not silently opt into). gpipe ignores dp
        here: its pipeline step has no dp composition path, so pick a
        tick schedule for the hybrid."""
        kw = {"schedule": self.pp_schedule, "num_chunks": self.pp_chunks}
        if self.pp_schedule in ("1f1b", "interleaved") and self.dp > 1:
            kw["dp_axis"] = "dp"
        return kw


class Fleet:
    """Process-level facade (singleton `fleet`, like the reference)."""

    def __init__(self):
        self._initialized = False
        self._strategy = None
        self._mesh = None
        self._barrier_gen = 0
        self._auto_plan = None   # cached autoplan MeshPlan ("auto")

    # -- role / topology (ref: role_maker.py) --
    def init(self, coordinator_address=None, num_processes=None,
             process_id=None):
        """Single-host: no-op. Multi-host: jax.distributed bootstrap; honors
        PADDLE_TRAINER_* envs for launcher parity (launch.py:78-81)."""
        if coordinator_address is None:
            coordinator_address = os.environ.get("PADDLE_COORDINATOR")
        if num_processes is None and "PADDLE_TRAINERS_NUM" in os.environ:
            num_processes = int(os.environ["PADDLE_TRAINERS_NUM"])
        if process_id is None and "PADDLE_TRAINER_ID" in os.environ:
            process_id = int(os.environ["PADDLE_TRAINER_ID"])
        if coordinator_address is not None:
            jax.distributed.initialize(coordinator_address, num_processes,
                                       process_id)
        self._initialized = True
        return self

    @property
    def worker_index(self):
        return jax.process_index()

    @property
    def worker_num(self):
        return jax.process_count()

    def is_first_worker(self):
        return self.worker_index == 0

    # -- auto-parallelism (parallel/autoplan) --
    def auto_plan(self, model_cfg=None, batch=None, seq=None, spec=None,
                  topology=None, devices=None, allow_pp=True, **kw):
        """Run the autoplan cost-model search and cache the winning
        MeshPlan as this fleet's ``strategy="auto"`` resolution.

        Pass a model config (+ batch/seq) or a prebuilt
        autoplan.ModelSpec; the device budget defaults to the live
        ``jax.devices()`` while `topology` (name or Topology) supplies
        per-chip characteristics."""
        from paddle_tpu.parallel import autoplan as ap
        if spec is None:
            enforce(model_cfg is not None and batch and seq,
                    "fleet.auto_plan needs model_cfg + batch + seq "
                    "(or a prebuilt spec=ModelSpec(...))")
            spec = ap.ModelSpec.from_config(model_cfg, batch=batch,
                                            seq=seq)
        n = devices if devices is not None else len(jax.devices())
        self._auto_plan = ap.plan(spec, topology=topology, devices=n,
                                  allow_pp=allow_pp, **kw)
        return self._auto_plan

    @property
    def mesh_plan(self):
        """The cached autoplan MeshPlan (None until auto_plan runs)."""
        return self._auto_plan

    def _resolve_strategy(self, strategy):
        """Map strategy='auto' (or the auto_mesh flag with no explicit
        strategy) onto the cached MeshPlan's DistributedStrategy."""
        if strategy is None:
            from paddle_tpu.core.flags import get_flag
            if get_flag("auto_mesh") and self._auto_plan is not None:
                strategy = "auto"
        if strategy == "auto":
            enforce(self._auto_plan is not None,
                    "strategy='auto' requires a prior "
                    "fleet.auto_plan(model_cfg, batch=..., seq=...) — "
                    "the planner must see the model and topology before "
                    "it can choose a mesh")
            return DistributedStrategy.from_plan(self._auto_plan)
        return strategy

    # -- mesh (ref: ParallelExecutor places / nccl rings) --
    def build_mesh(self, strategy=None, devices=None):
        strategy = self._resolve_strategy(strategy)
        strategy = strategy or self._strategy or DistributedStrategy()
        self._mesh = mesh_lib.make_mesh(strategy.mesh_axes(), devices)
        self._strategy = strategy
        return self._mesh

    @property
    def mesh(self):
        return self._mesh

    # -- optimizer composition (ref: fleet_base distributed_optimizer) --
    def distributed_optimizer(self, optimizer, strategy=None):
        """Compose the strategy's schedule wrappers around an Optimizer.

        Returns an object with init/apply_gradients/minimize (GradientMerge,
        plain) or init/step (LocalSGD/GeoSGD — divergent replicas, run under
        shard_map). strategy="auto" resolves through the cached
        fleet.auto_plan(...) MeshPlan."""
        strategy = self._resolve_strategy(strategy)
        strategy = strategy or self._strategy or DistributedStrategy()
        self._strategy = strategy
        enforce(sum(bool(x) for x in (strategy.local_sgd_steps,
                                      strategy.geo_sgd_steps,
                                      strategy.dc_asgd_steps)) <= 1,
                "local_sgd_steps / geo_sgd_steps / dc_asgd_steps are "
                "mutually exclusive")
        if strategy.dgc:
            from paddle_tpu.optimizer.wrappers import DGCMomentum
            enforce(isinstance(optimizer, DGCMomentum),
                    "strategy.dgc=True requires a DGCMomentum optimizer "
                    "(its sparse allreduce IS the communication schedule)")
        # composition, innermost out: base -> GradientMerge (application) ->
        # AMP -> Recompute (gradient computation) -> LocalSGD/GeoSGD
        # (replica schedule); grad-computation wrappers delegate downward so
        # every legal combination actually takes effect.
        if strategy.gradient_merge_steps > 1:
            optimizer = GradientMerge(optimizer, strategy.gradient_merge_steps)
        if strategy.amp:
            from paddle_tpu import amp
            optimizer = amp.decorate(optimizer, amp.bf16_policy())
        if strategy.recompute:
            from paddle_tpu.optimizer.wrappers import RecomputeOptimizer
            optimizer = RecomputeOptimizer(optimizer)
        if strategy.local_sgd_steps:
            return LocalSGD(optimizer, strategy.local_sgd_steps)
        if strategy.geo_sgd_steps:
            return GeoSGD(optimizer, strategy.geo_sgd_steps)
        if strategy.dc_asgd_steps:
            from paddle_tpu.optimizer.optimizers import SGD
            from paddle_tpu.parallel.communicator import DCASGD
            # DC-ASGD's server update IS plain SGD (the reference DCAsgd
            # is built on SGD) — silently replacing a different optimizer
            # or a decaying schedule would degrade training with no sign
            enforce(isinstance(optimizer, SGD) or strategy.dc_asgd_lr,
                    "dc_asgd_steps replaces the optimizer with the "
                    "DC-ASGD server rule (plain SGD, fixed lr — ref "
                    "distribute_transpiler dc_asgd mode). Pass an SGD "
                    "optimizer, or set strategy.dc_asgd_lr explicitly "
                    "to acknowledge the fixed server lr")
            lr = strategy.dc_asgd_lr
            if not lr:  # optimizer.lr is a schedule; sample its step-0 value
                sched = getattr(optimizer, "lr", None)
                lr = float(sched(0)) if callable(sched) else 0.01
            return DCASGD(lr, strategy.dc_asgd_steps,
                          lambda_=strategy.dc_asgd_lambda)
        return optimizer

    # -- convenience: one-call data-parallel trainer --
    def data_parallel(self, optimizer, loss_fn, strategy=None, devices=None):
        from paddle_tpu.parallel.api import DataParallel
        m = self.build_mesh(strategy, devices)
        enforce(not (self._strategy.local_sgd_steps
                     or self._strategy.geo_sgd_steps),
                "LocalSGD/GeoSGD need divergent per-group replicas (run "
                "their .step under shard_map with stack_replicas); they "
                "cannot ride the replicated-param DataParallel path")
        opt = self.distributed_optimizer(optimizer, self._strategy)
        return DataParallel(m, opt, loss_fn)

    def barrier(self, directory=None, tag="fleet", timeout_s=300.0):
        """Worker barrier (ref: fleet_base barrier_worker). In-process
        single-host: no-op; cross-process: file barrier on a shared dir.

        The generation counter is derived from this worker's own marker
        files in the shared directory, not in-memory state: a worker that
        restarts mid-job resumes at the generation its peers are waiting on
        instead of resetting to 1 and deadlocking every later barrier."""
        if directory is None or self.worker_num == 1:
            return
        import os
        import re
        from paddle_tpu.parallel.heartbeat import barrier_with_timeout
        if self._barrier_gen == 0:
            # first barrier after (re)start: recover the generation from our
            # own marker files; later calls just increment the cached value
            # (no per-sync directory scan)
            pat = re.compile(re.escape(tag) + r"-(\d+)\." +
                             re.escape(str(self.worker_index)) + r"$")
            if os.path.isdir(directory):
                for name in os.listdir(directory):
                    m = pat.match(name)
                    if m:
                        self._barrier_gen = max(self._barrier_gen,
                                                int(m.group(1)))
        self._barrier_gen += 1
        gen = self._barrier_gen
        barrier_with_timeout(directory, self.worker_index, self.worker_num,
                             timeout_s=timeout_s, tag=f"{tag}-{gen}")


fleet = Fleet()
