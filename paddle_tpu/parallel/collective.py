"""Collective communication ops.

Ref: /root/reference/paddle/fluid/operators/collective/ — c_allreduce_{sum,
max,min,prod} (c_allreduce_op.h), c_allgather, c_reducescatter, c_broadcast,
c_sync_*_stream, c_comm_init / c_gen_nccl_id — NCCL-ring kernels bootstrapped
over gRPC.

TPU-first: these are jax.lax collectives (psum/pmean/all_gather/ppermute/
psum_scatter) valid inside shard_map/pjit over a Mesh axis. XLA schedules
them onto ICI neighbors (no rings to build, no unique-id bootstrap — the JAX
distributed runtime's coordination service replaces gen_nccl_id). The
reference's stream-sync ops (c_sync_calc_stream) have no equivalent: XLA's
dataflow ordering subsumes them.
"""

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce(x, axis_name, op="sum"):
    """ref: operators/collective/c_allreduce_op.h"""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "prod":
        return jax.numpy.prod(
            lax.all_gather(x, axis_name, axis=0, tiled=False), axis=0)
    raise ValueError(op)


def all_gather(x, axis_name, axis=0, tiled=True):
    """ref: operators/collective/c_allgather_op.h"""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    """ref: operators/collective/c_reducescatter_op.h"""
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=tiled)


def broadcast(x, axis_name, root=0):
    """ref: operators/collective/c_broadcast_op.h — everyone takes root's
    value."""
    idx = lax.axis_index(axis_name)
    masked = jax.numpy.where(idx == root, x, jax.numpy.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute(x, axis_name, perm):
    """Ring shift primitive (used by ring attention / pipeline)."""
    return lax.ppermute(x, axis_name, perm)


def ring_shift(x, axis_name, shift=1):
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    """Ulysses-style resharding primitive."""
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.axis_size(axis_name)


def compressed_psum(x, axis_name, compress="bf16"):
    """Bandwidth-compressed cross-replica sum (the EQuARX direction —
    quantized allreduce in XLA, arXiv:2506.17615 — expressed with stock
    collectives; complements the DGC top-k path in `parallel/dgc.py`).

    compress:
      "bf16"  sum in bfloat16 — halves collective bytes vs f32; error
              ~1e-2 relative (gradient allreduce tolerates it; this is
              the standard mixed-precision gradient exchange).
      "int8"  symmetric per-tensor quantization against the global
              max-abs (pmax), summed in int32. NOTE: the int32 psum means
              stock XLA moves 4 bytes/elem on the wire — true int8 wire
              traffic needs EQuARX-style collective internals; this
              variant exists for SEMANTIC parity (bounded-error
              compressed exchange) and for backends that lower small-int
              collectives natively.
      None/"none"  exact f32 psum.
    """
    if compress in (None, "none"):
        return lax.psum(x, axis_name)
    if compress == "bf16":
        return lax.psum(x.astype(jnp.bfloat16), axis_name).astype(x.dtype)
    if compress == "int8":
        scale = lax.pmax(jnp.max(jnp.abs(x)), axis_name)
        scale = jnp.maximum(scale, 1e-30)
        q = jnp.clip(jnp.round(x / scale * 127.0), -127, 127).astype(
            jnp.int8)
        s = lax.psum(q.astype(jnp.int32), axis_name)
        return (s.astype(x.dtype) / 127.0) * scale
    from paddle_tpu.core.enforce import EnforceError
    raise EnforceError(f"compressed_psum: unknown compress={compress!r} "
                       "(bf16 | int8 | none)")
