"""Sharded embedding tables — the parameter-server successor.

Ref: the reference's large-sparse story: remote embedding lookups against
parameter servers (/root/reference/paddle/fluid/operators/distributed_ops/
distributed_lookup_table_op.cc, transpiler param slicing
distribute_transpiler.py:137-173) and PSLib sparse tables
(framework/fleet/fleet_wrapper.h:76 PullSparseVarsSync).

TPU-first: the table shards across a mesh axis ("ep" — mirrors pserver
blocks); lookup = shard_index remap (ref: operators/shard_index_op.cc) +
local gather + psum over the axis. Gradients flow through the same path
reversed (scatter-add locally, psum implicit in autodiff of psum). No RPC,
no separate server processes: ICI is the fabric. Host-offload tiers for
beyond-HBM tables are a planned extension (orbax/jax host offload).
"""

import jax
import jax.numpy as jnp
from jax import lax


def sharded_embedding_lookup(ids, local_table, axis_name, vocab_size):
    """Inside shard_map: local_table [V/N, D] shard of the global table; ids
    are global [B, T] or [B]. Returns dense embeddings, psum-combined."""
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    # ceil division so trailing ids still land in the last shard (matches
    # shard_index, ref operators/shard_index_op.cc)
    shard_size = -(-vocab_size // n)
    local = ids - me * shard_size
    in_shard = (local >= 0) & (local < shard_size)
    safe = jnp.clip(local, 0, shard_size - 1)
    out = jnp.take(local_table, safe, axis=0)
    out = out * in_shard[..., None].astype(out.dtype)
    return lax.psum(out, axis_name)


class ShardedEmbedding:
    """Table + optimizer-state sharding plan over the "ep" axis.

    API mirrors the reference's distributed lookup-table flow:
      init_table(key)      -> per-shard table param (use with shard_map/pjit)
      lookup(ids, table)   -> embeddings (inside shard_map)
    """

    def __init__(self, vocab_size, dim, axis_name="ep", init_scale=0.01):
        self.vocab_size = vocab_size
        self.dim = dim
        self.axis_name = axis_name
        self.init_scale = init_scale

    def global_shape(self):
        return (self.vocab_size, self.dim)

    def init_table(self, key):
        return self.init_scale * jax.random.normal(
            key, (self.vocab_size, self.dim))

    def partition_spec(self):
        from jax.sharding import PartitionSpec as P
        return P(self.axis_name, None)

    def lookup(self, ids, local_table):
        return sharded_embedding_lookup(ids, local_table, self.axis_name,
                                        self.vocab_size)
