"""High-level distribution API — the ParallelExecutor / transpiler successor.

Ref: /root/reference/paddle/fluid/framework/parallel_executor.cc:393 (graph
replication + allreduce insertion) and python transpiler
(distribute_transpiler.py): the reference *rewrites programs* to distribute
them. TPU-first, distribution is **sharding annotation**: the same jitted
train step runs on any mesh; jax.sharding + GSPMD insert collectives.

`DataParallel` = the reference's ParallelExecutor allreduce mode.
`fsdp_sharding` = param sharding (no reference equivalent; modern).
`shard_batch` = per-device batch splitting (ref: feed splitting in
executor.py _split_data).
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.autoplan import layouts
from paddle_tpu.parallel.mesh import DP, FSDP, TP


def shard_batch(mesh, batch, axis=DP):
    """Place host batch sharded along the data axis (ref: executor.py feed
    split across places)."""
    def place(x):
        spec = P(axis) if hasattr(x, "ndim") and x.ndim >= 1 else P()
        return jax.device_put(x, NamedSharding(mesh, spec))
    return jax.tree_util.tree_map(place, batch)


def replicate(mesh, tree):
    """Broadcast params to all devices (ref: parallel_executor.cc:630
    BCastParamsToDevices)."""
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)


def fsdp_sharding(mesh, tree, axis=FSDP, min_size=2 ** 12):
    """Shard each param's largest divisible dim over `axis` (ZeRO-3 style).
    Small params stay replicated."""
    size = mesh.shape[axis]

    def spec_for(x):
        if x.ndim == 0 or x.size < min_size:
            return P()
        # choose the largest dim divisible by axis size
        cands = [(d, i) for i, d in enumerate(x.shape) if d % size == 0]
        if not cands:
            return P()
        _, dim = max(cands)
        spec = [None] * x.ndim
        spec[dim] = axis
        return P(*spec)

    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh, spec_for(x))), tree)


def tp_lm_specs(tree, tp=TP, min_size=2 ** 11):
    """Megatron-flavored tensor-parallel PartitionSpecs for the
    transformer LM families (GPT/BERT/ERNIE/Transformer):

      * token-embedding tables (`tok_emb`/`src_emb`/`tgt_emb` weight,
        the [V, H] "vh" layout) shard their VOCAB dim -> P(tp, None),
        so the tied-embedding fused cross-entropy (ops/fused.py
        fused_xent vocab_axis=) runs per shard with no weight gather;
      * the NMT output projection (`out_proj` weight, [H, V] "hv")
        shards its vocab dim -> P(None, tp);
      * vocab-length biases (`mlm_bias`) follow the table -> P(tp);
      * remaining large 2-D weights (FFN/attention) column-shard
        -> P(None, tp); everything else replicates.

    Returns a pytree of PartitionSpec mirroring `tree`. The rules
    themselves live in parallel/autoplan/layouts.py (lm_layout) — one
    source of truth shared with the DistributionPlanner emission layer.
    """

    def spec(path, x):
        names = [str(getattr(p, "key", getattr(p, "name", p)))
                 for p in path]
        t, _ = layouts.lm_layout(names, tuple(x.shape), tp=tp,
                                 min_size=min_size)
        return P(*t) if any(a is not None for a in t) else P()

    return jax.tree_util.tree_map_with_path(spec, tree)


def tp_lm_sharding(mesh, tree, tp=TP, min_size=2 ** 11):
    """device_put `tree` onto `mesh` with tp_lm_specs — skipping any leaf
    whose named dim is not divisible by the tp axis size (replicated
    instead), so tiny demo configs never trap on divisibility."""
    size = mesh.shape[tp]

    def place(path, x):
        names = [str(getattr(p, "key", getattr(p, "name", p)))
                 for p in path]
        t, _ = layouts.lm_layout(names, tuple(x.shape), tp=tp,
                                 min_size=min_size, tp_size=size)
        s = P(*t) if any(a is not None for a in t) else P()
        return jax.device_put(x, NamedSharding(mesh, s))

    return jax.tree_util.tree_map_with_path(place, tree)


def infer_vocab_axis(arr, dim):
    """Mesh-axis name partitioning `dim` of a CONCRETE array's
    NamedSharding, else None (tracers, replicated dims, non-named
    shardings). The eager-mode half of fused_xent's sharding
    auto-detection."""
    try:
        spec = tuple(arr.sharding.spec)
    except Exception:
        return None
    if dim >= len(spec):
        return None
    entry = spec[dim]
    if isinstance(entry, (tuple, list)):
        return entry[0] if entry else None
    return entry


class DataParallel:
    """Single-controller data-parallel trainer (ref: ParallelExecutor +
    CompiledProgram.with_data_parallel, compiler.py:138).

    Wraps a per-example train step; gradients average over the mesh's data
    axis automatically because the loss mean spans the global batch under
    pjit — XLA inserts the all-reduce (replacing
    ir/multi_devices_graph_pass AllReduceOpHandle insertion) and fuses/
    combines gradient all-reduces (replacing fuse_all_reduce_op_pass).
    """

    def __init__(self, mesh, optimizer, loss_fn, donate=True):
        self.mesh = mesh
        self.optimizer = optimizer
        self.loss_fn = loss_fn

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def _step(params, opt_state, batch):
            loss, params, opt_state, aux = optimizer.minimize(
                loss_fn, params, opt_state, batch)
            return params, opt_state, loss, aux

        self._step = _step

    def init(self, params):
        # jnp.copy first: the train step donates its inputs, and device_put
        # can zero-copy alias its source (even with may_alias=False on CPU),
        # so donation would free the caller's original arrays
        put = lambda x: jax.device_put(  # noqa: E731
            jnp.copy(x), NamedSharding(self.mesh, P()))
        params = jax.tree_util.tree_map(put, params)
        return params, jax.tree_util.tree_map(
            put, self.optimizer.init(params))

    def step(self, params, opt_state, batch):
        batch = shard_batch(self.mesh, batch)
        return self._step(params, opt_state, batch)


def local_sgd_sync(params, axis_name):
    """Local-SGD periodic model averaging (ref:
    transpiler/collective.py:269 LocalSGD — broadcast-averaged params every
    k steps instead of per-step allreduce)."""
    return jax.tree_util.tree_map(
        lambda p: jax.lax.pmean(p, axis_name), params)
