"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up re-design of the capabilities of PaddlePaddle Fluid 1.5.x
(reference: /root/reference) for TPU hardware: JAX/XLA/Pallas for the compute
path, `jax.sharding` meshes + XLA collectives over ICI/DCN for distribution,
and a functional, compiler-friendly programming model instead of a hand-built
C++ SSA-graph runtime.

Layer map (mirrors reference SURVEY.md §1, re-architected TPU-first):
  core/       platform + framework core: dtypes, flags, enforce, registry,
              captured Program IR           (ref: paddle/fluid/platform, framework)
  ops/        operator library on XLA + Pallas kernels
                                            (ref: paddle/fluid/operators ~480 ops)
  nn/         Layer/Module API (dygraph parity)
                                            (ref: python/paddle/fluid/dygraph)
  optimizer/  optimizer suite + LR schedules + clip + regularizers
                                            (ref: python/paddle/fluid/optimizer.py)
  amp         mixed-precision policies      (ref: contrib/mixed_precision)
  parallel/   mesh/sharding, DP/TP/PP/SP, collectives, sharded embeddings
                                            (ref: ParallelExecutor + transpiler + fleet)
  data/       data loaders w/ device prefetch
                                            (ref: reader.py, data_feed.cc)
  io/         checkpointing + inference export
                                            (ref: io.py save/load_persistables)
  models/     flagship model zoo (ResNet, BERT, Transformer, DeepFM, ...)
  static/     Program/Executor compatibility layer
                                            (ref: framework.py Program, executor.py)
  observability/ metrics registry + RunLog + trace spans + step telemetry
                                            (ref: platform/profiler.h, tools/timeline.py)
"""

__version__ = "0.1.0"

import jax as _jax

if not hasattr(_jax.lax, "axis_size"):
    # jax < 0.4.38 compat: psum of a Python literal folds statically to
    # the mapped axis size — the pre-axis_size idiom
    _jax.lax.axis_size = lambda axis_name: _jax.lax.psum(1, axis_name)

from paddle_tpu.core import enforce, flags
from paddle_tpu.core.dtype import (
    bfloat16,
    bool_,
    float16,
    float32,
    float64,
    int8,
    int16,
    int32,
    int64,
    uint8,
)
from paddle_tpu import ops
from paddle_tpu import nn
from paddle_tpu import optimizer
from paddle_tpu import amp
from paddle_tpu import distributions
from paddle_tpu import parallel
from paddle_tpu import data
from paddle_tpu import io
from paddle_tpu import static
from paddle_tpu import models
from paddle_tpu import serving
from paddle_tpu import metrics
from paddle_tpu import quant
from paddle_tpu import slim
from paddle_tpu import profiler
from paddle_tpu import observability
from paddle_tpu import initializer
from paddle_tpu.core.random import seed
