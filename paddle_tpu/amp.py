"""Automatic mixed precision.

Ref: /root/reference/python/paddle/fluid/contrib/mixed_precision/ —
decorator.py:216 `decorate(optimizer, ...)` (OptimizerWithMixedPrecision),
fp16_lists.py (white/black op lists), fp16_utils.py (static + dynamic loss
scaling).

TPU-first: the native low-precision type is **bfloat16** — same exponent
range as fp32, so no loss scaling is required (the reference's dynamic loss
scaler exists because of fp16's narrow range; we keep it for fp16 parity).
A Policy maps pytrees between storage/compute dtypes; master weights stay
fp32 in the optimizer, compute runs bf16 through the MXU.
"""

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class Policy:
    """Param storage / compute / output dtypes (≈ fp16 white/black lists at
    whole-model granularity, the idiomatic XLA formulation)."""

    param_dtype: object = jnp.float32
    compute_dtype: object = jnp.bfloat16
    output_dtype: object = jnp.float32

    def cast_to_compute(self, tree):
        return _cast_floating(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        return _cast_floating(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return _cast_floating(tree, self.output_dtype)


def _cast_floating(tree, dtype):
    """Cast floating arrays; pass python scalars / int arrays through."""
    def leaf(x):
        if not hasattr(x, "dtype"):
            return x
        return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x
    return jax.tree_util.tree_map(leaf, tree)


def bf16_policy():
    return Policy(jnp.float32, jnp.bfloat16, jnp.float32)


def fp16_policy():
    return Policy(jnp.float32, jnp.float16, jnp.float32)


class LossScaler:
    """Dynamic loss scaling (ref: fp16_utils.py update_loss_scaling —
    init_loss_scaling 2**15, incr_every_n_steps, decr_every_n_nan_or_inf)."""

    def __init__(self, init_scale=2.0 ** 15, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, incr_ratio=2.0, decr_ratio=0.5,
                 dynamic=True):
        self.init_scale = init_scale
        self.incr_every = incr_every_n_steps
        self.decr_every = decr_every_n_nan_or_inf
        self.incr_ratio = incr_ratio
        self.decr_ratio = decr_ratio
        self.dynamic = dynamic

    def init(self):
        return {"scale": jnp.asarray(self.init_scale, jnp.float32),
                "good_steps": jnp.zeros((), jnp.int32),
                "bad_steps": jnp.zeros((), jnp.int32),
                # cumulative skipped-update count; ScalerObserver publishes
                # host-side deltas as amp.skipped_steps
                "skipped": jnp.zeros((), jnp.int32)}

    def scale_loss(self, loss, state):
        return loss * state["scale"]

    def unscale(self, grads, state):
        inv = 1.0 / state["scale"]
        return jax.tree_util.tree_map(lambda g: g * inv, grads)

    def check_finite(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        finite = jnp.all(jnp.array(
            [jnp.all(jnp.isfinite(g)) for g in leaves]))
        return finite

    def update(self, state, grads_finite):
        # skip accounting runs even with static scaling (old states that
        # predate the leaf default to 0, so restores stay compatible)
        skipped = (state.get("skipped", jnp.zeros((), jnp.int32))
                   + jnp.where(grads_finite, 0, 1))
        if not self.dynamic:
            return {**state, "skipped": skipped}
        good = jnp.where(grads_finite, state["good_steps"] + 1, 0)
        bad = jnp.where(grads_finite, 0, state["bad_steps"] + 1)
        scale = state["scale"]
        scale = jnp.where(good >= self.incr_every, scale * self.incr_ratio,
                          scale)
        good = jnp.where(good >= self.incr_every, 0, good)
        scale = jnp.where(bad >= self.decr_every, scale * self.decr_ratio,
                          scale)
        bad = jnp.where(bad >= self.decr_every, 0, bad)
        scale = jnp.clip(scale, 1.0, 2.0 ** 24)
        return {"scale": scale, "good_steps": good, "bad_steps": bad,
                "skipped": skipped}


class ScalerObserver:
    """Host-side bridge from a LossScaler state to the metrics registry:
    the amp.loss_scale gauge and the amp.skipped_steps counter.

    Feed `publish()` host values only — the training guardian hands it
    the trailing-fetched scaler state, so publishing adds no device
    sync. The in-state skip count is cumulative; the observer publishes
    deltas and ignores backward jumps (a guardian rollback rewinds the
    state's count, but the counter is monotonic)."""

    def __init__(self, registry=None):
        # lazy import: amp itself stays importable without observability
        from paddle_tpu.observability import metrics as _metrics
        from paddle_tpu.observability.catalog import help_for as _help
        self._reg = registry if registry is not None else _metrics.registry()
        self._help = _help
        self._last_skipped = None

    def publish(self, scaler_state):
        if not scaler_state:
            return
        scale = scaler_state.get("scale")
        if scale is not None:
            self._reg.gauge("amp.loss_scale",
                            self._help("amp.loss_scale")).set(float(scale))
        skipped = scaler_state.get("skipped")
        if skipped is not None:
            cur = int(skipped)
            if self._last_skipped is None:
                # first sight of a resumed state: adopt, don't re-count
                self._last_skipped = cur
            elif cur > self._last_skipped:
                self._reg.counter(
                    "amp.skipped_steps",
                    self._help("amp.skipped_steps")).inc(
                        cur - self._last_skipped)
                self._last_skipped = cur


def decorate(optimizer, policy=None, scaler=None):
    """ref: decorator.py:216 decorate() — wraps an optimizer so minimize()
    runs forward in compute dtype, keeps fp32 master weights, and (for fp16)
    applies dynamic loss scaling with skipped-on-overflow updates."""
    policy = policy or bf16_policy()
    use_scaler = scaler is not None or policy.compute_dtype == jnp.float16
    scaler = scaler or LossScaler()

    class MixedPrecisionOptimizer:
        def __init__(self):
            self.inner = optimizer
            self.policy = policy
            self.scaler = scaler

        def init(self, params):
            st = {"inner": self.inner.init(params)}
            if use_scaler:
                st["scaler"] = self.scaler.init()
            return st

        def minimize(self, loss_fn, params, state, *args, **kwargs):
            def cast_loss(p, *a, **kw):
                pc = self.policy.cast_to_compute(p)
                # inputs follow the compute dtype (lax convs/dots require
                # matching dtypes; mirrors the reference's cast-insertion at
                # fp16 boundaries, fp16_utils.py). Aux (e.g. BN running
                # stats) is cast back to param dtype for storage.
                ac = self.policy.cast_to_compute(a)
                loss, aux = loss_fn(pc, *ac, **kw)
                loss = loss.astype(jnp.float32)
                aux = self.policy.cast_to_param(aux)
                if use_scaler:
                    loss = self.scaler.scale_loss(loss, state["scaler"])
                return loss, aux

            (loss, aux), grads = jax.value_and_grad(
                cast_loss, has_aux=True)(params, *args, **kwargs)
            grads = self.policy.cast_to_param(grads)
            if use_scaler:
                grads = self.scaler.unscale(grads, state["scaler"])
                finite = self.scaler.check_finite(grads)
                new_params, new_inner = self.inner.apply_gradients(
                    params, grads, state["inner"])
                # skip update on overflow (ref: fp16_utils update skipping)
                new_params = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(finite, n, o), new_params, params)
                new_inner = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(finite, n, o), new_inner,
                    state["inner"])
                new_scaler = self.scaler.update(state["scaler"], finite)
                loss = loss / state["scaler"]["scale"]
                return loss, new_params, {"inner": new_inner,
                                          "scaler": new_scaler}, aux
            new_params, new_inner = self.inner.apply_gradients(
                params, grads, state["inner"])
            return loss, new_params, {"inner": new_inner}, aux

        def apply_gradients(self, params, grads, state):
            new_params, new_inner = self.inner.apply_gradients(
                params, grads, state["inner"])
            new_state = dict(state)
            new_state["inner"] = new_inner
            return new_params, new_state

    return MixedPrecisionOptimizer()
