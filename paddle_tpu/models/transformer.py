"""Transformer (encoder-decoder) for NMT — WMT en-de "big"/"base" configs.

Ref: BASELINE.md "Transformer big WMT en-de (Fluid
neural_machine_translation)" and the reference's transformer test fixture
(/root/reference/python/paddle/fluid/tests/unittests/dist_transformer.py —
the Fluid-era layers implementation). Rebuilt with first-class attention ops
and lax.scan beam-search decoding (ops/rnn.py beam_search_decode).
"""

import dataclasses

import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.ops import activations as A
from paddle_tpu.ops import loss as L


@dataclasses.dataclass
class TransformerConfig:
    src_vocab: int = 32000
    tgt_vocab: int = 32000
    d_model: int = 512
    num_heads: int = 8
    ffn_dim: int = 2048
    enc_layers: int = 6
    dec_layers: int = 6
    dropout: float = 0.1
    max_len: int = 256

    @staticmethod
    def base():
        return TransformerConfig()

    @staticmethod
    def big():
        return TransformerConfig(d_model=1024, num_heads=16, ffn_dim=4096)

    @staticmethod
    def tiny():
        return TransformerConfig(src_vocab=1000, tgt_vocab=1000, d_model=64,
                                 num_heads=4, ffn_dim=128, enc_layers=2,
                                 dec_layers=2, max_len=32)


def positional_encoding(max_len, d_model):
    pos = jnp.arange(max_len)[:, None].astype(jnp.float32)
    i = jnp.arange(d_model // 2)[None, :].astype(jnp.float32)
    angle = pos / jnp.power(10000.0, 2 * i / d_model)
    pe = jnp.concatenate([jnp.sin(angle), jnp.cos(angle)], axis=-1)
    return pe


class EncoderLayer(nn.Module):
    def __init__(self, cfg):
        super().__init__()
        self.attn = nn.MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                          dropout=cfg.dropout)
        self.ln1 = nn.LayerNorm(cfg.d_model)
        self.fc1 = nn.Linear(cfg.d_model, cfg.ffn_dim)
        self.fc2 = nn.Linear(cfg.ffn_dim, cfg.d_model)
        self.ln2 = nn.LayerNorm(cfg.d_model)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x, mask=None):
        # residual=: fused add+LN (one HBM pass, Pallas kernel on TPU)
        x = self.ln1(self.drop(self.attn(x, mask=mask)), residual=x)
        x = self.ln2(self.drop(self.fc2(A.relu(self.fc1(x)))), residual=x)
        return x


class DecoderLayer(nn.Module):
    def __init__(self, cfg):
        super().__init__()
        self.self_attn = nn.MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                               dropout=cfg.dropout)
        self.cross_attn = nn.MultiHeadAttention(cfg.d_model, cfg.num_heads,
                                                dropout=cfg.dropout)
        self.ln1 = nn.LayerNorm(cfg.d_model)
        self.ln2 = nn.LayerNorm(cfg.d_model)
        self.ln3 = nn.LayerNorm(cfg.d_model)
        self.fc1 = nn.Linear(cfg.d_model, cfg.ffn_dim)
        self.fc2 = nn.Linear(cfg.ffn_dim, cfg.d_model)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x, memory, self_mask=None, cross_mask=None):
        x = self.ln1(self.drop(self.self_attn(x, causal=True,
                                              mask=self_mask)), residual=x)
        x = self.ln2(self.drop(self.cross_attn(x, kv=memory,
                                               mask=cross_mask)),
                     residual=x)
        x = self.ln3(self.drop(self.fc2(A.relu(self.fc1(x)))), residual=x)
        return x


class Transformer(nn.Module):
    def __init__(self, cfg: TransformerConfig):
        super().__init__()
        self.cfg = cfg
        self.src_emb = nn.Embedding(cfg.src_vocab, cfg.d_model)
        self.tgt_emb = nn.Embedding(cfg.tgt_vocab, cfg.d_model)
        self.enc_layers = [EncoderLayer(cfg) for _ in range(cfg.enc_layers)]
        self.dec_layers = [DecoderLayer(cfg) for _ in range(cfg.dec_layers)]
        self.out_proj = nn.Linear(cfg.d_model, cfg.tgt_vocab, bias=False)
        self.drop = nn.Dropout(cfg.dropout)

    def encode(self, src, src_mask=None):
        pe = positional_encoding(src.shape[1], self.cfg.d_model)
        x = self.src_emb(src) * (self.cfg.d_model ** 0.5) + pe[None]
        x = self.drop(x)
        mask = src_mask[:, None, None, :] if src_mask is not None else None
        for layer in self.enc_layers:
            x = layer(x, mask=mask)
        return x

    def decode_hidden(self, tgt, memory, src_mask=None):
        """Decoder stack output [B, T, D] before the vocab projection (the
        fused loss consumes this directly)."""
        pe = positional_encoding(tgt.shape[1], self.cfg.d_model)
        x = self.tgt_emb(tgt) * (self.cfg.d_model ** 0.5) + pe[None]
        x = self.drop(x)
        cross = src_mask[:, None, None, :] if src_mask is not None else None
        for layer in self.dec_layers:
            x = layer(x, memory, cross_mask=cross)
        return x

    def decode(self, tgt, memory, src_mask=None):
        return self.out_proj(self.decode_hidden(tgt, memory, src_mask))

    def forward(self, src, tgt, src_mask=None):
        memory = self.encode(src, src_mask)
        return self.decode(tgt, memory, src_mask)

    def loss(self, src, tgt_in, tgt_out, src_mask=None, pad_id=0,
             label_smoothing=0.1, vocab_axis=None, batch_axis=None,
             mesh=None, mesh_plan=None):
        """Label-smoothed NMT loss as an apply() entry point. Default path
        fuses the vocab projection into the chunked cross-entropy — no
        [B, T, V] logits and no same-shape one_hot soft labels (the two
        HBM sinks of the reference recipe). PT_FUSED_XENT=0 restores
        forward() + nmt_loss.

        vocab_axis/batch_axis: mesh axis names when out_proj is
        vocab-partitioned (P(None, tp), the hv layout) and the batch
        dp-sharded under GSPMD — the fused CE then runs per vocab shard
        with pmax/psum combines instead of gathering the projection.
        mesh_plan: an autoplan MeshPlan — fills the three kwargs above
        from the planned mesh (explicit values win)."""
        from paddle_tpu.ops.fused import fused_xent, fused_xent_enabled
        if mesh_plan is not None:
            vocab_axis, batch_axis, mesh = mesh_plan.resolve_loss_axes(
                vocab_axis, batch_axis, mesh)
        memory = self.encode(src, src_mask)
        h = self.decode_hidden(tgt_in, memory, src_mask)
        if not fused_xent_enabled() or self.out_proj.has_p("weight_q"):
            return nmt_loss(self.out_proj(h), tgt_out, pad_id,
                            label_smoothing)
        ce = fused_xent(h, self.out_proj.p("weight"), tgt_out,
                        weight_layout="hv", label_smoothing=label_smoothing,
                        vocab_axis=vocab_axis, batch_axis=batch_axis,
                        mesh=mesh)
        valid = (tgt_out != pad_id).astype(jnp.float32)
        return jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1.0)


def nmt_loss(logits, labels, pad_id=0, label_smoothing=0.1):
    """Label-smoothed CE ignoring pads (ref: the reference transformer recipe
    uses label_smooth + softmax_with_cross_entropy soft labels). Parity
    reference for Transformer.loss's fused path (PT_FUSED_XENT gates)."""
    vocab = logits.shape[-1]
    valid = (labels != pad_id).astype(jnp.float32)
    import jax
    smooth_pos = 1.0 - label_smoothing
    smooth_neg = label_smoothing / (vocab - 1)
    onehot = jax.nn.one_hot(labels, vocab) * (smooth_pos - smooth_neg) \
        + smooth_neg
    loss = L.softmax_with_cross_entropy(logits, onehot, soft_label=True)[..., 0]
    return jnp.sum(loss * valid) / jnp.maximum(jnp.sum(valid), 1.0)
