"""ResNet family (ResNet-18/34/50/101/152).

Ref: the reference ships ResNet as a *model recipe* over fluid.layers
(/root/reference/python/paddle/fluid/tests/unittests/dist_se_resnext.py and
tests/book image_classification — conv_bn_layer + bottleneck patterns).
BASELINE.md flagship: ResNet-50 ImageNet throughput.

TPU-first: NCHW inputs accepted but compute can run bf16 via amp.Policy;
XLA's layout assignment handles the HWCN internals. BN state functional.
"""

import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.ops import nn as F


class ConvBN(nn.Module):
    def __init__(self, cin, cout, k, stride=1, act="relu", groups=1):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups, bias=False,
                              weight_init=I.msra())
        self.bn = nn.BatchNorm(cout, act=act)

    def forward(self, x):
        return self.bn(self.conv(x))


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = ConvBN(cin, cout, 3, stride)
        self.conv2 = ConvBN(cout, cout, 3, act=None)
        self.short = None
        if stride != 1 or cin != cout:
            self.short = ConvBN(cin, cout, 1, stride, act=None)

    def forward(self, x):
        out = self.conv2(self.conv1(x))
        sc = self.short(x) if self.short is not None else x
        return jnp.maximum(out + sc, 0)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * self.expansion
        self.conv1 = ConvBN(cin, width, 1)
        self.conv2 = ConvBN(width, width, 3, stride)
        self.conv3 = ConvBN(width, cout, 1, act=None)
        self.short = None
        if stride != 1 or cin != cout:
            self.short = ConvBN(cin, cout, 1, stride, act=None)

    def forward(self, x):
        out = self.conv3(self.conv2(self.conv1(x)))
        sc = self.short(x) if self.short is not None else x
        return jnp.maximum(out + sc, 0)


_CONFIGS = {
    18: (BasicBlock, [2, 2, 2, 2]),
    34: (BasicBlock, [3, 4, 6, 3]),
    50: (Bottleneck, [3, 4, 6, 3]),
    101: (Bottleneck, [3, 4, 23, 3]),
    152: (Bottleneck, [3, 8, 36, 3]),
}


class ResNet(nn.Module):
    def __init__(self, depth=50, num_classes=1000, small_input=False):
        super().__init__()
        block, layers = _CONFIGS[depth]
        self.small_input = small_input
        if small_input:  # CIFAR-style stem (ref: tests/book resnet_cifar10)
            self.stem = ConvBN(3, 64, 3)
        else:
            self.stem = ConvBN(3, 64, 7, stride=2)
        stages = []
        cin = 64
        for i, n in enumerate(layers):
            width = 64 * (2 ** i)
            blocks = []
            for j in range(n):
                stride = 2 if (j == 0 and i > 0) else 1
                blocks.append(block(cin, width, stride))
                cin = width * block.expansion
            stages.append(nn.Sequential(blocks))
        self.stages = stages  # becomes ModuleList
        self.fc = nn.Linear(cin, num_classes,
                            weight_init=I.uniform(-0.01, 0.01))

    def forward(self, x):
        x = self.stem(x)
        if not self.small_input:
            x = F.pool2d(x, 3, "max", 2, padding=1)
        for stage in self.stages:
            x = stage(x)
        x = F.pool2d(x, pool_type="avg", global_pooling=True)
        return self.fc(x.reshape(x.shape[0], -1))


def resnet50(num_classes=1000, **kw):
    return ResNet(50, num_classes, **kw)


def resnet18(num_classes=1000, **kw):
    return ResNet(18, num_classes, **kw)
