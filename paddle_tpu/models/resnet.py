"""ResNet family (ResNet-18/34/50/101/152).

Ref: the reference ships ResNet as a *model recipe* over fluid.layers
(/root/reference/python/paddle/fluid/tests/unittests/dist_se_resnext.py and
tests/book image_classification — conv_bn_layer + bottleneck patterns).
BASELINE.md flagship: ResNet-50 ImageNet throughput.

TPU-first: NCHW inputs accepted but compute can run bf16 via amp.Policy;
XLA's layout assignment handles the HWCN internals. BN state functional.
"""

import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.core.flags import get_flag
from paddle_tpu.ops import nn as F


def _space_to_depth_nhwc(x, b=2):
    """[N,H,W,C] -> [N,H/b,W/b,b*b*C]; channel order (di, dj, c)."""
    n, h, w, c = x.shape
    if h % b or w % b:
        raise ValueError(
            f"PT_FLAGS_resnet_s2d_stem requires H and W divisible by {b}; "
            f"got {h}x{w}. Use the default 7x7 stem for odd input sizes.")
    x = x.reshape(n, h // b, b, w // b, b, c).transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // b, w // b, b * b * c)


def _stem_s2d_weights(w):
    """Rewrite the 7x7/s2 stem kernel [7,7,cin,cout] (HWIO) into the exact
    4x4/s1 kernel over space-to-depth(2) input, [4,4,4*cin,cout].

    The 7-tap/stride-2/pad-3 window [2o-3, 2o+3] is zero-padded on the
    top/left to 8 taps covering [2o-4, 2o+3] = s2d rows o-2..o+1, i.e. a
    4-tap stride-1 conv on the halved grid with padding (2, 1). This is the
    standard TPU ResNet stem transform: a C=3 NHWC conv wastes almost the
    whole (8,128) register tile on channel padding; C=12 at half the
    spatial size quarters the padded-lane traffic. Numerically exact
    (pure index rewrite, no approximation)."""
    k, _, cin, cout = w.shape
    assert k == 7, "s2d stem transform expects the 7x7 ImageNet stem"
    w8 = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
    ws = w8.reshape(4, 2, 4, 2, cin, cout).transpose(0, 2, 1, 3, 4, 5)
    return ws.reshape(4, 4, 4 * cin, cout)


class ConvBN(nn.Module):
    def __init__(self, cin, cout, k, stride=1, act="relu", groups=1,
                 data_format="NCHW"):
        super().__init__()
        self.conv = nn.Conv2D(cin, cout, k, stride=stride,
                              padding=(k - 1) // 2, groups=groups, bias=False,
                              weight_init=I.msra(), data_format=data_format)
        self.bn = nn.BatchNorm(cout, act=act, data_format=data_format)

    def forward(self, x):
        return self.bn(self.conv(x))


class BasicBlock(nn.Module):
    expansion = 1

    def __init__(self, cin, cout, stride=1, data_format="NCHW"):
        super().__init__()
        self.conv1 = ConvBN(cin, cout, 3, stride, data_format=data_format)
        self.conv2 = ConvBN(cout, cout, 3, act=None, data_format=data_format)
        self.short = None
        if stride != 1 or cin != cout:
            self.short = ConvBN(cin, cout, 1, stride, act=None,
                                data_format=data_format)

    def forward(self, x):
        out = self.conv2(self.conv1(x))
        sc = self.short(x) if self.short is not None else x
        return jnp.maximum(out + sc, 0)


class Bottleneck(nn.Module):
    expansion = 4

    def __init__(self, cin, width, stride=1, data_format="NCHW"):
        super().__init__()
        cout = width * self.expansion
        self.conv1 = ConvBN(cin, width, 1, data_format=data_format)
        self.conv2 = ConvBN(width, width, 3, stride, data_format=data_format)
        self.conv3 = ConvBN(width, cout, 1, act=None, data_format=data_format)
        self.short = None
        if stride != 1 or cin != cout:
            self.short = ConvBN(cin, cout, 1, stride, act=None,
                                data_format=data_format)

    def forward(self, x):
        out = self.conv3(self.conv2(self.conv1(x)))
        sc = self.short(x) if self.short is not None else x
        return jnp.maximum(out + sc, 0)


_CONFIGS = {
    18: (BasicBlock, [2, 2, 2, 2]),
    34: (BasicBlock, [3, 4, 6, 3]),
    50: (Bottleneck, [3, 4, 6, 3]),
    101: (Bottleneck, [3, 4, 23, 3]),
    152: (Bottleneck, [3, 8, 36, 3]),
}


class ResNet(nn.Module):
    """TPU-first default is channels-last (data_format='NHWC'): convs run
    ~3x faster than NCHW on TPU (measured; see nn.Conv2D docstring). Inputs
    are still accepted as NCHW [B,3,H,W] per the reference convention and
    transposed once at the stem — one cheap transpose per step vs per-conv
    layout churn."""

    def __init__(self, depth=50, num_classes=1000, small_input=False,
                 data_format="NHWC", input_layout="NCHW"):
        super().__init__()
        block, layers = _CONFIGS[depth]
        self.small_input = small_input
        self.data_format = data_format
        # input_layout: layout of the *incoming* batch. Default NCHW per the
        # reference convention (one transpose at the stem); a TPU-first input
        # pipeline should feed NHWC directly and skip that per-step copy.
        self.input_layout = input_layout
        df = data_format
        if small_input:  # CIFAR-style stem (ref: tests/book resnet_cifar10)
            self.stem = ConvBN(3, 64, 3, data_format=df)
        else:
            self.stem = ConvBN(3, 64, 7, stride=2, data_format=df)
        stages = []
        cin = 64
        for i, n in enumerate(layers):
            width = 64 * (2 ** i)
            blocks = []
            for j in range(n):
                stride = 2 if (j == 0 and i > 0) else 1
                blocks.append(block(cin, width, stride, data_format=df))
                cin = width * block.expansion
            stages.append(nn.Sequential(blocks))
        self.stages = stages  # becomes ModuleList
        self.fc = nn.Linear(cin, num_classes,
                            weight_init=I.uniform(-0.01, 0.01))

    def forward(self, x):
        if self.data_format == "NHWC" and self.input_layout == "NCHW":
            x = jnp.transpose(x, (0, 2, 3, 1))  # NCHW input -> NHWC compute
        if (not self.small_input and self.data_format == "NHWC"
                and get_flag("resnet_s2d_stem")):
            w = _stem_s2d_weights(self.stem.conv.p("weight"))
            # through F.conv2d so the backward uses the same conv_custom_vjp
            # path as the 7x7 form — the s2d A/B on silicon must isolate the
            # layout rewrite, not switch VJPs at the same time
            x = F.conv2d(_space_to_depth_nhwc(x), w.astype(x.dtype),
                         padding=((2, 1), (2, 1)), data_format="NHWC")
            x = self.stem.bn(x)
        else:
            x = self.stem(x)
        if not self.small_input:
            x = F.pool2d(x, 3, "max", 2, padding=1,
                         data_format=self.data_format)
        for stage in self.stages:
            x = stage(x)
        x = F.pool2d(x, pool_type="avg", global_pooling=True,
                     data_format=self.data_format)
        return self.fc(x.reshape(x.shape[0], -1))


def resnet50(num_classes=1000, **kw):
    return ResNet(50, num_classes, **kw)


def resnet18(num_classes=1000, **kw):
    return ResNet(18, num_classes, **kw)
