"""ERNIE 1.0 — knowledge-enhanced BERT pretraining.

Ref: BASELINE.md capability target "ERNIE 1.0". ERNIE 1.0 (Baidu, 2019 —
contemporary with the reference's Fluid BERT recipes) keeps the BERT
transformer backbone and changes the *pretraining masking strategy*:
instead of masking only independent word pieces, whole PHRASES and named
ENTITIES are masked as units (basic-level / phrase-level / entity-level
masking), forcing the model to recover knowledge spans from context. It
also trains on dialogue data with a sentence-pair (DLM/NSP-style) head.

TPU-first: the backbone reuses BertForPretraining unchanged (same MXU
path); the ERNIE-ness lives in `knowledge_mask`, a host-side batch
transform that masks whole spans, and in the config (Chinese vocab,
ERNIE-base dimensions). This mirrors how the original implementation
shipped: same net, different data pipeline.
"""

import dataclasses

import numpy as np

from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                    pretrain_loss)


@dataclasses.dataclass
class ErnieConfig(BertConfig):
    """ERNIE 1.0 base: BERT-base dims over an 18k Chinese vocab."""
    vocab_size: int = 18000

    @staticmethod
    def base():
        return ErnieConfig()

    @staticmethod
    def tiny():
        return ErnieConfig(vocab_size=512, hidden_size=64, num_layers=2,
                           num_heads=4, intermediate_size=128,
                           max_position=64)


class ErnieForPretraining(BertForPretraining):
    """Same heads as BERT (MLM over spans + sentence-pair); the knowledge
    masking happens in the data pipeline (knowledge_mask). The step-fusion
    perf surface rides along through the shared backbone: cfg.scan_layers /
    cfg.remat (scan-over-layers encoder) and the fused .loss() entry point
    (chunked vocab cross-entropy, PT_FUSED_XENT) — including the
    vocab-sharded GSPMD path (.loss(vocab_axis="tp", batch_axis="dp")
    with the tied table P(tp, None), inherited from
    BertForPretraining.loss)."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__(cfg)


ernie_pretrain_loss = pretrain_loss


def knowledge_mask(ids, spans, mask_id, vocab_size, mask_prob=0.15,
                   seed=0, pad_id=0):
    """Span-level knowledge masking (host-side batch transform).

    ids:   [B, T] int token ids
    spans: per example, a list of (start, end) half-open intervals marking
           phrase/entity units (from a host tokenizer/NER); positions not
           covered by any span are treated as single-token (basic) units.
    Units are selected with probability ~mask_prob; a selected unit is
    masked AS A WHOLE — 80% [MASK], 10% random id, 10% unchanged (BERT's
    replacement distribution applied per unit, ERNIE's unit granularity).

    Returns (masked_ids, mlm_labels, mlm_weights) ready for
    pretrain_loss: labels hold the original ids, weights are 1.0 on masked
    positions.
    """
    ids = np.asarray(ids)
    B, T = ids.shape
    rng = np.random.RandomState(seed)
    masked = ids.copy()
    weights = np.zeros((B, T), np.float32)
    for b in range(B):
        covered = np.zeros(T, bool)
        units = []
        for s, e in spans[b] if b < len(spans) else []:
            s, e = max(0, int(s)), min(T, int(e))
            if e > s:
                units.append((s, e))
                covered[s:e] = True
        for t in range(T):
            if not covered[t] and ids[b, t] != pad_id:
                units.append((t, t + 1))
        for s, e in units:
            if rng.random_sample() >= mask_prob:
                continue
            weights[b, s:e] = 1.0
            r = rng.random_sample()
            if r < 0.8:
                masked[b, s:e] = mask_id
            elif r < 0.9:
                masked[b, s:e] = rng.randint(0, vocab_size, e - s)
            # else: keep original (10%)
    return masked, ids, weights
