"""CTR models: DeepFM and Wide&Deep — the sparse-embedding flagship path.

Ref: BASELINE.md "DeepFM / Wide&Deep CTR (sparse embedding + pserver
distributed path)" and the reference's CTR fixture
(/root/reference/python/paddle/fluid/tests/unittests/dist_ctr.py — embedding
+ fc over sparse slots trained against pservers).

TPU-first: embedding tables shard over the "ep" mesh axis via
parallel/embedding.py (the pserver-shard successor) or run dense on one
chip; the model code is identical either way.
"""

import dataclasses

import jax
import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.core.enforce import EnforceError
from paddle_tpu.ops import loss as L


@dataclasses.dataclass
class CTRConfig:
    num_sparse_fields: int = 26
    num_dense_fields: int = 13
    vocab_size: int = 10000       # per-field hash size
    embed_dim: int = 16
    hidden: tuple = (400, 400, 400)

    @staticmethod
    def tiny():
        return CTRConfig(num_sparse_fields=4, num_dense_fields=3,
                         vocab_size=100, embed_dim=8, hidden=(32, 16))


class DeepFM(nn.Module):
    """FM (1st+2nd order) + DNN over shared embeddings.

    With sparse_tables=True the embedding tables are NOT model params: they
    live in parallel/sparse.py SparseTable/HostTable objects and the model is
    driven through ``forward_from_emb`` with pre-pulled embeddings — the
    PSLib pull/push flow (ref fleet_wrapper.h:76) where only touched rows
    enter the autodiff graph. See make_sparse_deepfm_train_step.
    """

    def __init__(self, cfg: CTRConfig, sparse_tables=False):
        super().__init__()
        self.cfg = cfg
        self.sparse_tables = sparse_tables
        # one shared table across fields; ids offset per field by caller or
        # hashed into one space (reference dist_ctr uses per-slot tables;
        # single offset table shards better on TPU)
        if not sparse_tables:
            self.embed = nn.Embedding(cfg.vocab_size * cfg.num_sparse_fields,
                                      cfg.embed_dim,
                                      weight_init=I.normal(0, 0.01))
            self.fm_linear = nn.Embedding(
                cfg.vocab_size * cfg.num_sparse_fields,
                1, weight_init=I.zeros())
        self.dense_linear = nn.Linear(cfg.num_dense_fields, 1)
        dnn_in = cfg.num_sparse_fields * cfg.embed_dim + cfg.num_dense_fields
        layers = []
        for h in cfg.hidden:
            layers.append(nn.Linear(dnn_in, h, act="relu"))
            dnn_in = h
        self.dnn = nn.Sequential(layers)
        self.dnn_out = nn.Linear(dnn_in, 1)

    def _offset_ids(self, sparse_ids):
        offsets = jnp.arange(self.cfg.num_sparse_fields) * self.cfg.vocab_size
        return sparse_ids + offsets[None, :]

    def forward(self, dense, sparse_ids):
        """dense [B, D_dense]; sparse_ids [B, F] per-field ids."""
        if self.sparse_tables:
            raise EnforceError(
                "DeepFM(sparse_tables=True) has no in-model embedding "
                "tables; drive it via apply(..., method='forward_from_emb') "
                "with rows pulled from SparseTable/HostTable (see "
                "make_sparse_deepfm_train_step)")
        ids = self._offset_ids(sparse_ids)
        return self.forward_from_emb(dense, self.embed(ids),
                                     self.fm_linear(ids))

    def forward_from_emb(self, dense, emb, first_order):
        """Head over pre-pulled embeddings: emb [B, F, K], first_order
        [B, F, 1]. Sparse-table entry point (apply with
        method='forward_from_emb')."""
        # FM first order
        first = jnp.sum(first_order, axis=(1, 2), keepdims=False)
        first = first[:, None] + self.dense_linear(dense)
        # FM second order: 0.5 * ((sum v)^2 - sum v^2)
        sum_v = jnp.sum(emb, axis=1)
        sum_sq = jnp.sum(jnp.square(emb), axis=1)
        second = 0.5 * jnp.sum(jnp.square(sum_v) - sum_sq, axis=1,
                               keepdims=True)
        # DNN
        flat = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1), dense], axis=1)
        deep = self.dnn_out(self.dnn(flat))
        return first + second + deep               # logits [B, 1]


class WideAndDeep(nn.Module):
    """ref: wide_deep CTR pattern (linear wide part + DNN deep part)."""

    def __init__(self, cfg: CTRConfig):
        super().__init__()
        self.cfg = cfg
        self.wide = nn.Embedding(cfg.vocab_size * cfg.num_sparse_fields, 1,
                                 weight_init=I.zeros())
        self.wide_dense = nn.Linear(cfg.num_dense_fields, 1)
        self.embed = nn.Embedding(cfg.vocab_size * cfg.num_sparse_fields,
                                  cfg.embed_dim,
                                  weight_init=I.normal(0, 0.01))
        dnn_in = cfg.num_sparse_fields * cfg.embed_dim + cfg.num_dense_fields
        layers = []
        for h in cfg.hidden:
            layers.append(nn.Linear(dnn_in, h, act="relu"))
            dnn_in = h
        self.dnn = nn.Sequential(layers)
        self.dnn_out = nn.Linear(dnn_in, 1)

    def forward(self, dense, sparse_ids):
        offsets = jnp.arange(self.cfg.num_sparse_fields) * self.cfg.vocab_size
        ids = sparse_ids + offsets[None, :]
        wide = jnp.sum(self.wide(ids), axis=(1, 2))[:, None] \
            + self.wide_dense(dense)
        emb = self.embed(ids).reshape(ids.shape[0], -1)
        deep = self.dnn_out(self.dnn(jnp.concatenate([emb, dense], 1)))
        return wide + deep


def ctr_loss(logits, labels):
    """Sigmoid CE (ref: dist_ctr.py uses cross_entropy over softmax; modern
    CTR uses logistic loss)."""
    return jnp.mean(L.sigmoid_cross_entropy_with_logits(logits, labels))


def make_sparse_deepfm_train_step(model, opt, embed_tbl, linear_tbl):
    """Sparse-row DeepFM training (ref: the reference CTR path — DownpourWorker
    pulls sparse rows, trains, pushes row grads; fleet_wrapper.h:76,:110,
    selected_rows.h sparse embedding gradients).

    model: DeepFM(cfg, sparse_tables=True); embed_tbl/linear_tbl:
    parallel.sparse.SparseTable for the [V*F, K] and [V*F, 1] tables. The
    returned step is fully jittable: only the batch's unique rows enter the
    autodiff graph, never a dense [V, D] gradient.

        step(params, opt_state, emb_st, lin_st, dense, sparse_ids, labels)
          -> (loss, params, opt_state, emb_st, lin_st)
    """
    cfg = model.cfg

    def step(params, opt_state, emb_st, lin_st, dense, sparse_ids, labels):
        offsets = jnp.arange(cfg.num_sparse_fields) * cfg.vocab_size
        ids = sparse_ids + offsets[None, :]
        erows, ectx = embed_tbl.pull(emb_st, ids)
        lrows, lctx = linear_tbl.pull(lin_st, ids)

        def loss_fn(p, erows, lrows):
            emb = embed_tbl.embed(erows, ectx)          # [B, F, K]
            first = linear_tbl.embed(lrows, lctx)       # [B, F, 1]
            logits = model.apply({"params": p, "state": {}}, dense, emb,
                                 first, method="forward_from_emb")
            return ctr_loss(logits, labels)

        loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
            params, erows, lrows)
        params, opt_state = opt.apply_gradients(params, grads[0], opt_state)
        emb_st = embed_tbl.push(emb_st, grads[1], ectx)
        lin_st = linear_tbl.push(lin_st, grads[2], lctx)
        return loss, params, opt_state, emb_st, lin_st

    return step
