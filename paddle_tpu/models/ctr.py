"""CTR models: DeepFM and Wide&Deep — the sparse-embedding flagship path.

Ref: BASELINE.md "DeepFM / Wide&Deep CTR (sparse embedding + pserver
distributed path)" and the reference's CTR fixture
(/root/reference/python/paddle/fluid/tests/unittests/dist_ctr.py — embedding
+ fc over sparse slots trained against pservers).

TPU-first: embedding tables shard over the "ep" mesh axis via
parallel/embedding.py (the pserver-shard successor) or run dense on one
chip; the model code is identical either way.
"""

import dataclasses

import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.ops import loss as L


@dataclasses.dataclass
class CTRConfig:
    num_sparse_fields: int = 26
    num_dense_fields: int = 13
    vocab_size: int = 10000       # per-field hash size
    embed_dim: int = 16
    hidden: tuple = (400, 400, 400)

    @staticmethod
    def tiny():
        return CTRConfig(num_sparse_fields=4, num_dense_fields=3,
                         vocab_size=100, embed_dim=8, hidden=(32, 16))


class DeepFM(nn.Module):
    """FM (1st+2nd order) + DNN over shared embeddings."""

    def __init__(self, cfg: CTRConfig):
        super().__init__()
        self.cfg = cfg
        # one shared table across fields; ids offset per field by caller or
        # hashed into one space (reference dist_ctr uses per-slot tables;
        # single offset table shards better on TPU)
        self.embed = nn.Embedding(cfg.vocab_size * cfg.num_sparse_fields,
                                  cfg.embed_dim,
                                  weight_init=I.normal(0, 0.01))
        self.fm_linear = nn.Embedding(cfg.vocab_size * cfg.num_sparse_fields,
                                      1, weight_init=I.zeros())
        self.dense_linear = nn.Linear(cfg.num_dense_fields, 1)
        dnn_in = cfg.num_sparse_fields * cfg.embed_dim + cfg.num_dense_fields
        layers = []
        for h in cfg.hidden:
            layers.append(nn.Linear(dnn_in, h, act="relu"))
            dnn_in = h
        self.dnn = nn.Sequential(layers)
        self.dnn_out = nn.Linear(dnn_in, 1)

    def _offset_ids(self, sparse_ids):
        offsets = jnp.arange(self.cfg.num_sparse_fields) * self.cfg.vocab_size
        return sparse_ids + offsets[None, :]

    def forward(self, dense, sparse_ids):
        """dense [B, D_dense]; sparse_ids [B, F] per-field ids."""
        ids = self._offset_ids(sparse_ids)
        emb = self.embed(ids)                      # [B, F, K]
        # FM first order
        first = jnp.sum(self.fm_linear(ids), axis=(1, 2), keepdims=False)
        first = first[:, None] + self.dense_linear(dense)
        # FM second order: 0.5 * ((sum v)^2 - sum v^2)
        sum_v = jnp.sum(emb, axis=1)
        sum_sq = jnp.sum(jnp.square(emb), axis=1)
        second = 0.5 * jnp.sum(jnp.square(sum_v) - sum_sq, axis=1,
                               keepdims=True)
        # DNN
        flat = jnp.concatenate(
            [emb.reshape(emb.shape[0], -1), dense], axis=1)
        deep = self.dnn_out(self.dnn(flat))
        return first + second + deep               # logits [B, 1]


class WideAndDeep(nn.Module):
    """ref: wide_deep CTR pattern (linear wide part + DNN deep part)."""

    def __init__(self, cfg: CTRConfig):
        super().__init__()
        self.cfg = cfg
        self.wide = nn.Embedding(cfg.vocab_size * cfg.num_sparse_fields, 1,
                                 weight_init=I.zeros())
        self.wide_dense = nn.Linear(cfg.num_dense_fields, 1)
        self.embed = nn.Embedding(cfg.vocab_size * cfg.num_sparse_fields,
                                  cfg.embed_dim,
                                  weight_init=I.normal(0, 0.01))
        dnn_in = cfg.num_sparse_fields * cfg.embed_dim + cfg.num_dense_fields
        layers = []
        for h in cfg.hidden:
            layers.append(nn.Linear(dnn_in, h, act="relu"))
            dnn_in = h
        self.dnn = nn.Sequential(layers)
        self.dnn_out = nn.Linear(dnn_in, 1)

    def forward(self, dense, sparse_ids):
        offsets = jnp.arange(self.cfg.num_sparse_fields) * self.cfg.vocab_size
        ids = sparse_ids + offsets[None, :]
        wide = jnp.sum(self.wide(ids), axis=(1, 2))[:, None] \
            + self.wide_dense(dense)
        emb = self.embed(ids).reshape(ids.shape[0], -1)
        deep = self.dnn_out(self.dnn(jnp.concatenate([emb, dense], 1)))
        return wide + deep


def ctr_loss(logits, labels):
    """Sigmoid CE (ref: dist_ctr.py uses cross_entropy over softmax; modern
    CTR uses logistic loss)."""
    return jnp.mean(L.sigmoid_cross_entropy_with_logits(logits, labels))
