"""Model zoo — the reference's flagship configs (BASELINE.md).

  resnet       ResNet-18/50/101 (ImageNet/CIFAR)   ref: dist_se_resnext.py, book
  bert         BERT-base/large pretraining          ref: PaddleNLP Fluid bert
  transformer  WMT en-de base/big NMT               ref: dist_transformer.py
  ctr          DeepFM / Wide&Deep CTR               ref: dist_ctr.py
  word2vec     N-gram LM + skip-gram NCE            ref: book test_word2vec.py
  mnist        smoke-test models                    ref: book recognize_digits
"""

from paddle_tpu.models import (bert, ctr, ernie, gpt, mnist, recommender, resnet, sentiment, seq2seq,
                               tagging, transformer, vision_cls, word2vec)
from paddle_tpu.models.resnet import ResNet, resnet18, resnet50
from paddle_tpu.models.seq2seq import AttentionSeq2Seq, Seq2SeqConfig, nmt_loss
from paddle_tpu.models.tagging import BiLstmCrfTagger, TaggerConfig
from paddle_tpu.models.recommender import RecommenderNet, RecConfig, rating_loss
from paddle_tpu.models.vision_cls import VGG, SEResNeXt, se_resnext50, vgg16
from paddle_tpu.models.bert import BertConfig, BertEncoder, BertForPretraining
from paddle_tpu.models.transformer import Transformer, TransformerConfig
from paddle_tpu.models.ctr import CTRConfig, DeepFM, WideAndDeep
from paddle_tpu.models.gpt import GPT, GPTConfig, GPTDecoder
from paddle_tpu.models.word2vec import SkipGramNCE, Word2Vec
from paddle_tpu.models.mnist import (MLP, ConvNet, LinearRegression,
                                     SoftmaxRegression)
