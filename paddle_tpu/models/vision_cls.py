"""VGG + SE-ResNeXt image classifiers.

Ref: /root/reference/python/paddle/fluid/tests/book/test_image_classification.py
(vgg16_bn_drop for CIFAR) and unittests/dist_se_resnext.py /
test_parallel_executor_seresnext.py (SE-ResNeXt-50: grouped 3x3 bottleneck +
squeeze-and-excitation gate) — the reference's multi-device regression models.
"""

import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.models.resnet import ConvBN
from paddle_tpu.ops import nn as F


class VGG(nn.Module):
    """Configurable VGG with BN (the book's vgg16_bn_drop shape)."""

    CFGS = {
        11: (1, 1, 2, 2, 2),
        13: (2, 2, 2, 2, 2),
        16: (2, 2, 3, 3, 3),
        19: (2, 2, 4, 4, 4),
    }

    def __init__(self, depth=16, num_classes=10, in_channels=3, dropout=0.5):
        super().__init__()
        widths = (64, 128, 256, 512, 512)
        blocks = []
        cin = in_channels
        for reps, w in zip(self.CFGS[depth], widths):
            for _ in range(reps):
                blocks.append(ConvBN(cin, w, 3))
                cin = w
        self.blocks = blocks
        self.stage_reps = self.CFGS[depth]
        self.drop = nn.Dropout(dropout)
        self.fc1 = nn.Linear(512, 512, act="relu")
        self.fc2 = nn.Linear(512, 512, act="relu")
        self.head = nn.Linear(512, num_classes)

    def forward(self, x):
        i = 0
        for reps in self.stage_reps:
            for _ in range(reps):
                x = self.blocks[i](x)
                i += 1
            x = F.pool2d(x, 2, pool_type="max", stride=2)
        x = jnp.mean(x, axis=(2, 3))          # global pool to [B, 512]
        x = self.drop(self.fc1(x))
        x = self.drop(self.fc2(x))
        return self.head(x)


def vgg16(num_classes=10, **kw):
    return VGG(16, num_classes, **kw)


class SEBlock(nn.Module):
    """Squeeze-and-excitation channel gate (dist_se_resnext.py
    squeeze_excitation)."""

    def __init__(self, channels, reduction=16):
        super().__init__()
        mid = max(channels // reduction, 4)
        self.fc1 = nn.Linear(channels, mid, act="relu")
        self.fc2 = nn.Linear(mid, channels, act="sigmoid")

    def forward(self, x):
        s = jnp.mean(x, axis=(2, 3))          # [B,C]
        s = self.fc2(self.fc1(s))
        return x * s[:, :, None, None]


class SEBottleneck(nn.Module):
    expansion = 2

    def __init__(self, cin, width, cardinality=32, stride=1, reduction=16):
        super().__init__()
        cout = width * self.expansion
        self.conv1 = ConvBN(cin, width, 1)
        self.conv2 = ConvBN(width, width, 3, stride, groups=cardinality)
        self.conv3 = ConvBN(width, cout, 1, act=None)
        self.se = SEBlock(cout, reduction)
        self.short = None
        if stride != 1 or cin != cout:
            self.short = ConvBN(cin, cout, 1, stride, act=None)

    def forward(self, x):
        out = self.se(self.conv3(self.conv2(self.conv1(x))))
        sc = self.short(x) if self.short is not None else x
        return jnp.maximum(out + sc, 0)


class SEResNeXt(nn.Module):
    """SE-ResNeXt-50 (32x4d family), the reference's parallel-executor
    regression model."""

    def __init__(self, layers=(3, 4, 6, 3), cardinality=32, num_classes=1000,
                 in_channels=3):
        super().__init__()
        self.stem = ConvBN(in_channels, 64, 7, stride=2)
        widths = (128, 256, 512, 1024)
        blocks = []
        cin = 64
        for si, (reps, w) in enumerate(zip(layers, widths)):
            for bi in range(reps):
                stride = 2 if (bi == 0 and si > 0) else 1
                blocks.append(SEBottleneck(cin, w, cardinality, stride))
                cin = w * SEBottleneck.expansion
        self.blocks = blocks
        self.head = nn.Linear(cin, num_classes,
                              weight_init=I.uniform(-0.001, 0.001))

    def forward(self, x):
        x = self.stem(x)
        x = F.pool2d(x, 3, pool_type="max", stride=2, padding=1)
        for b in self.blocks:
            x = b(x)
        x = jnp.mean(x, axis=(2, 3))
        return self.head(x)


def se_resnext50(num_classes=1000, **kw):
    return SEResNeXt((3, 4, 6, 3), num_classes=num_classes, **kw)
