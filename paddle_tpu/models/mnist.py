"""MNIST models — the minimal end-to-end fixtures.

Ref: /root/reference/python/paddle/fluid/tests/book/test_recognize_digits.py
(softmax_regression, multilayer_perceptron, convolutional_neural_network —
the reference's e2e smoke models) and nets.py simple_img_conv_pool.
"""

from paddle_tpu import nn
from paddle_tpu.ops import nn as F


class SoftmaxRegression(nn.Module):
    def __init__(self, num_classes=10, in_dim=784):
        super().__init__()
        self.fc = nn.Linear(in_dim, num_classes)

    def forward(self, x):
        return self.fc(x.reshape(x.shape[0], -1))


class MLP(nn.Module):
    """ref: multilayer_perceptron in test_recognize_digits.py"""

    def __init__(self, num_classes=10, in_dim=784):
        super().__init__()
        self.fc1 = nn.Linear(in_dim, 128, act="relu")
        self.fc2 = nn.Linear(128, 64, act="relu")
        self.fc3 = nn.Linear(64, num_classes)

    def forward(self, x):
        return self.fc3(self.fc2(self.fc1(x.reshape(x.shape[0], -1))))


class ConvNet(nn.Module):
    """ref: convolutional_neural_network / simple_img_conv_pool"""

    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2D(1, 20, 5, act="relu")
        self.conv2 = nn.Conv2D(20, 50, 5, act="relu")
        self.fc = nn.Linear(50 * 4 * 4, num_classes)

    def forward(self, x):
        x = F.pool2d(self.conv1(x), 2, "max", 2)
        x = F.pool2d(self.conv2(x), 2, "max", 2)
        return self.fc(x.reshape(x.shape[0], -1))


class LinearRegression(nn.Module):
    """The fit_a_line book model (ref: tests/book/test_fit_a_line.py):
    single fc, square-error cost."""

    def __init__(self, in_features=13):
        super().__init__()
        self.fc = nn.Linear(in_features, 1)

    def forward(self, x):
        return self.fc(x)[..., 0]
