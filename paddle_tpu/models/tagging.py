"""BiLSTM-CRF sequence tagger — the label_semantic_roles book model.

Ref: /root/reference/python/paddle/fluid/tests/book/test_label_semantic_roles.py
(word+predicate+context embeddings -> stacked bidirectional LSTM chain ->
linear_chain_crf cost, crf_decoding inference) and layers/nn.py lstm/embedding.

TPU-first: padded [B,T] batches + lengths (no LoD); CRF loss/decode are the
lax.scan ops in ops/crf.py.
"""

import dataclasses

import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.ops import crf as C


@dataclasses.dataclass
class TaggerConfig:
    vocab_size: int = 4096
    num_tags: int = 16
    embed_dim: int = 32
    hidden: int = 64
    num_lstm_layers: int = 2      # ref uses depth 8 stacked bi-LSTM
    num_extra_features: int = 0   # e.g. predicate/context marks (SRL)
    dropout: float = 0.0

    @staticmethod
    def tiny():
        return TaggerConfig(vocab_size=64, num_tags=5, embed_dim=8, hidden=16,
                            num_lstm_layers=1)


class BiLstmCrfTagger(nn.Module):
    def __init__(self, cfg: TaggerConfig):
        super().__init__()
        self.cfg = cfg
        self.embed = nn.Embedding(cfg.vocab_size, cfg.embed_dim)
        if cfg.num_extra_features:
            self.extra_embeds = [
                nn.Embedding(cfg.vocab_size, cfg.embed_dim)
                for _ in range(cfg.num_extra_features)]
        in_dim = cfg.embed_dim * (1 + cfg.num_extra_features)
        self.lstm = nn.LSTM(in_dim, cfg.hidden,
                            num_layers=cfg.num_lstm_layers, bidirectional=True)
        self.emission = nn.Linear(cfg.hidden * 2, cfg.num_tags)
        # CRF transition params, reference layout [K+2, K]
        self.param("transition", (cfg.num_tags + 2, cfg.num_tags),
                   I.uniform(-0.1, 0.1))
        self.dropout = nn.Dropout(cfg.dropout)

    def emissions(self, token_ids, lengths, extra_ids=None):
        emb = self.embed(token_ids)
        if self.cfg.num_extra_features:
            feats = [emb] + [e(extra_ids[..., i])
                             for i, e in enumerate(self.extra_embeds)]
            emb = jnp.concatenate(feats, axis=-1)
        emb = self.dropout(emb)
        out, _ = self.lstm(emb, lengths=lengths)
        return self.emission(out)                          # [B,T,K]

    def forward(self, token_ids, lengths, labels=None, extra_ids=None):
        """With labels: mean CRF negative log-likelihood (training cost).
        Without: Viterbi-decoded tag paths [B,T]."""
        em = self.emissions(token_ids, lengths, extra_ids)
        if labels is not None:
            nll = C.linear_chain_crf(em, self.p("transition"), labels, lengths)
            return jnp.mean(nll)
        return C.crf_decoding(em, self.p("transition"), lengths)
