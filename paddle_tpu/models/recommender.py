"""Recommender system — the recommender_system book model (MovieLens-style).

Ref: /root/reference/python/paddle/fluid/tests/book/test_recommender_system.py:
user tower (user id + gender + age + job embeddings -> fc) and movie tower
(movie id embedding + category/title sequence pooling -> fc), combined by
cosine similarity, trained with square error against the rating.
"""

import dataclasses

import jax.numpy as jnp

from paddle_tpu import nn


@dataclasses.dataclass
class RecConfig:
    num_users: int = 256
    num_genders: int = 2
    num_ages: int = 8
    num_jobs: int = 32
    num_movies: int = 512
    num_categories: int = 32
    title_vocab: int = 1024
    embed_dim: int = 32
    fc_dim: int = 64

    @staticmethod
    def tiny():
        return RecConfig(num_users=16, num_movies=32, num_categories=8,
                         title_vocab=64, embed_dim=8, fc_dim=16)


class RecommenderNet(nn.Module):
    """Twin-tower rating regressor: scaled cosine(usr, movie) * 5."""

    def __init__(self, cfg: RecConfig):
        super().__init__()
        self.cfg = cfg
        E, F = cfg.embed_dim, cfg.fc_dim
        self.usr_emb = nn.Embedding(cfg.num_users, E)
        self.gender_emb = nn.Embedding(cfg.num_genders, E // 2)
        self.age_emb = nn.Embedding(cfg.num_ages, E // 2)
        self.job_emb = nn.Embedding(cfg.num_jobs, E // 2)
        self.usr_fc = nn.Linear(E + 3 * (E // 2), F, act="tanh")
        self.mov_emb = nn.Embedding(cfg.num_movies, E)
        self.cat_emb = nn.Embedding(cfg.num_categories, E // 2)
        self.title_emb = nn.Embedding(cfg.title_vocab, E)
        self.mov_fc = nn.Linear(E + E // 2 + E, F, act="tanh")

    def forward(self, usr_id, gender, age, job, mov_id, categories,
                cat_mask, title_ids, title_mask):
        """categories/title_ids: [B, L] padded multi-hot sequences with
        0/1 masks (the reference pools LoD sequences; here masked mean/sum)."""
        u = jnp.concatenate([
            self.usr_emb(usr_id), self.gender_emb(gender),
            self.age_emb(age), self.job_emb(job)], axis=-1)
        u = self.usr_fc(u)

        cat = jnp.sum(self.cat_emb(categories) * cat_mask[..., None], 1) / \
            jnp.maximum(jnp.sum(cat_mask, 1, keepdims=True), 1.0)
        title = jnp.max(
            self.title_emb(title_ids) * title_mask[..., None] +
            (title_mask[..., None] - 1.0) * 1e9, axis=1)   # masked max pool
        # rows with an empty title sequence fall back to zeros instead of -1e9
        has_title = jnp.sum(title_mask, 1, keepdims=True) > 0
        title = jnp.where(has_title, title, 0.0)
        m = jnp.concatenate([self.mov_emb(mov_id), cat, title], axis=-1)
        m = self.mov_fc(m)

        cos = jnp.sum(u * m, -1) / jnp.maximum(
            jnp.linalg.norm(u, axis=-1) * jnp.linalg.norm(m, axis=-1), 1e-8)
        return 5.0 * cos                                    # rating scale


def rating_loss(pred, rating):
    return jnp.mean((pred - rating) ** 2)
