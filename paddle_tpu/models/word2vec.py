"""word2vec (skip-gram with negative sampling / NCE).

Ref: /root/reference/python/paddle/fluid/tests/book/test_word2vec.py and
unittests/dist_word2vec.py — the reference's book model uses a small
N-gram LM with shared embeddings; dist variant trains embeddings against
pservers. Here: N-gram LM forward + NCE loss path (ops/loss.py nce_loss).
"""

import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.ops import loss as L


class Word2Vec(nn.Module):
    """N-gram LM: concat(context embeddings) → fc → softmax over vocab
    (ref: test_word2vec.py network)."""

    def __init__(self, vocab_size=2048, embed_dim=32, context=4,
                 hidden=256):
        super().__init__()
        self.vocab_size = vocab_size
        self.embed = nn.Embedding(vocab_size, embed_dim,
                                  weight_init=I.uniform(-0.5 / embed_dim,
                                                        0.5 / embed_dim))
        self.fc1 = nn.Linear(context * embed_dim, hidden, act="sigmoid")
        self.fc2 = nn.Linear(hidden, vocab_size)

    def forward(self, context_ids):
        """context_ids [B, C] -> logits [B, V]."""
        e = self.embed(context_ids)
        flat = e.reshape(e.shape[0], -1)
        return self.fc2(self.fc1(flat))


def lm_loss(logits, labels):
    return jnp.mean(L.softmax_with_cross_entropy(logits, labels))


class SkipGramNCE(nn.Module):
    """Skip-gram trained with NCE (ref: nce usage in fluid layers)."""

    def __init__(self, vocab_size=2048, embed_dim=64, num_neg=16):
        super().__init__()
        self.vocab_size = vocab_size
        self.num_neg = num_neg
        self.embed = nn.Embedding(vocab_size, embed_dim,
                                  weight_init=I.uniform(-0.05, 0.05))
        self.param("nce_weight", (vocab_size, embed_dim), I.normal(0, 0.01))
        self.param("nce_bias", (vocab_size,), I.zeros())

    def forward(self, center_ids, target_ids):
        h = self.embed(center_ids)  # [B, D]
        return L.nce_loss(self.rng("nce"), h, target_ids,
                          self.p("nce_weight"), self.p("nce_bias"),
                          self.vocab_size, self.num_neg)
