"""Decoder-only causal LM (GPT-style) — the long-context flagship.

Ref: no decoder-only LM exists in the reference (2019-era; its language
models are word2vec + the NMT transformer, tests/book). This family exists
because the brief's long-context requirement (BASELINE.json north star)
needs a first-class consumer: causal flash attention on one chip,
ring/Ulysses sequence parallelism across chips.

Design: pre-norm transformer decoder; attention runs
  * `flash_attention(causal=True)` (Pallas, O(T) memory) on a single chip
  * `ring_flash_attention` over the `sp` mesh axis when `seq_axis` is set
    (call inside shard_map with the sequence dim sharded)
"""

import dataclasses

import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.ops import activations as A
from paddle_tpu.ops import loss as L


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 2048
    dropout: float = 0.1
    use_flash: bool = True
    seq_axis: str = None       # mesh axis name for ring sequence parallelism

    @staticmethod
    def small():
        return GPTConfig()

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                         num_heads=4, intermediate_size=128,
                         max_position=128)


class GPTBlock(nn.Module):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        # the shared fused-MHA layer (one implementation across BERT /
        # Transformer / GPT); the ring sequence-parallel branch is selected
        # per-call via seq_axis
        self.attn = nn.MultiHeadAttention(cfg.hidden_size, cfg.num_heads,
                                          dropout=cfg.dropout,
                                          use_flash=cfg.use_flash)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x):
        # pre-norm residual blocks (GPT-2 style)
        x = x + self.drop(self.attn(self.ln1(x), causal=True,
                                    seq_axis=self.cfg.seq_axis))
        x = x + self.drop(self.fc2(A.gelu(self.fc1(self.ln2(x)))))
        return x


class GPT(nn.Module):
    """Causal LM: returns next-token logits [B, T, V] (weight-tied head)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.tok_emb = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.pos_emb = nn.Embedding(cfg.max_position, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        self.blocks = [GPTBlock(cfg) for _ in range(cfg.num_layers)]
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def forward(self, input_ids, pos_offset=0):
        b, t = input_ids.shape
        if self.cfg.seq_axis is not None:
            # under shard_map the leading tokens of this shard sit at
            # global position rank * t_local
            from jax import lax
            pos_offset = pos_offset + lax.axis_index(
                self.cfg.seq_axis) * t
        pos = pos_offset + jnp.arange(t)[None, :]
        x = self.drop(self.tok_emb(input_ids) + self.pos_emb(pos))
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        return x @ self.tok_emb.p("weight").T


def lm_loss(logits, labels, pad_id=None):
    """Shifted next-token cross entropy; optionally ignores pad positions."""
    lp = logits[:, :-1]
    tgt = labels[:, 1:]
    ce = L.softmax_with_cross_entropy(lp, tgt[..., None])[..., 0]
    if pad_id is not None:
        valid = (tgt != pad_id).astype(ce.dtype)
        return jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1.0)
    return jnp.mean(ce)
