"""Decoder-only causal LM (GPT-style) — the long-context flagship.

Ref: no decoder-only LM exists in the reference (2019-era; its language
models are word2vec + the NMT transformer, tests/book). This family exists
because the brief's long-context requirement (BASELINE.json north star)
needs a first-class consumer: causal flash attention on one chip,
ring/Ulysses sequence parallelism across chips.

Design: pre-norm transformer decoder; attention runs
  * `flash_attention(causal=True)` (Pallas, O(T) memory) on a single chip
  * `ring_flash_attention` over the `sp` mesh axis when `seq_axis` is set
    (call inside shard_map with the sequence dim sharded)
"""

import dataclasses

import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.ops import activations as A
from paddle_tpu.ops import loss as L


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 2048
    dropout: float = 0.1
    use_flash: bool = True
    seq_axis: str = None       # mesh axis name for ring sequence parallelism
    moe_experts: int = 0       # >0: MoE FFN with this many experts
    moe_k: int = 2
    moe_ep_axis: str = None    # mesh axis for expert parallelism
    scan_layers: bool = False  # stack block params + lax.scan over layers
    remat: str = None          # nothing|dots_saveable|full (None -> flag)

    @staticmethod
    def small():
        return GPTConfig()

    @staticmethod
    def tiny():
        return GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                         num_heads=4, intermediate_size=128,
                         max_position=128)


class GPTBlock(nn.Module):
    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        # the shared fused-MHA layer (one implementation across BERT /
        # Transformer / GPT); the ring sequence-parallel branch is selected
        # per-call via seq_axis
        self.attn = nn.MultiHeadAttention(cfg.hidden_size, cfg.num_heads,
                                          dropout=cfg.dropout,
                                          use_flash=cfg.use_flash)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        if cfg.moe_experts:
            from paddle_tpu.nn.moe import MoE
            self.mlp = MoE(cfg.hidden_size, cfg.intermediate_size,
                           cfg.moe_experts, k=cfg.moe_k,
                           ep_axis=cfg.moe_ep_axis)
        else:
            self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
            self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def _ffn(self, x):
        if self.cfg.moe_experts:
            return self.mlp(x)
        return nn.fused_ffn(self.fc1, self.fc2, x)

    def forward(self, x):
        # pre-norm residual blocks (GPT-2 style)
        x = x + self.drop(self.attn(self.ln1(x), causal=True,
                                    seq_axis=self.cfg.seq_axis))
        x = x + self.drop(self._ffn(self.ln2(x)))
        return x

    def decode_step(self, x, cache, pos):
        """Incremental twin of forward: same pre-norm residual structure,
        attention through the KV cache (dropout is inference-off)."""
        h, cache = self.attn.decode_step(self.ln1(x), cache, pos)
        x = x + h
        x = x + self._ffn(self.ln2(x))
        return x, cache

    def prefill(self, x, cache, start=0):
        """Batched cache fill over the whole prompt (inference, no
        dropout): one causal forward instead of T decode_steps."""
        h, cache = self.attn.prefill(self.ln1(x), cache, start)
        x = x + h
        x = x + self._ffn(self.ln2(x))
        return x, cache

    def paged_decode_step(self, x, pool, page_table, att_lengths,
                          write_pages, write_offsets):
        """Incremental twin of forward against the paged serving cache
        (same pre-norm residual structure as decode_step)."""
        h, pool = self.attn.paged_decode_step(
            self.ln1(x), pool, page_table, att_lengths, write_pages,
            write_offsets)
        x = x + h
        x = x + self._ffn(self.ln2(x))
        return x, pool

    def paged_prefill(self, x, pool, page_ids, offsets):
        """Batched prompt fill into this block's page pool."""
        h, pool = self.attn.paged_prefill(self.ln1(x), pool, page_ids,
                                          offsets)
        x = x + h
        x = x + self._ffn(self.ln2(x))
        return x, pool

    def paged_prefill_chunk(self, x, pool, page_ids, offsets, page_rows,
                            q_pos, chunked):
        """Chunked prompt fill (continuation chunks attend the slot's
        whole cached prefix — see MultiHeadAttention.paged_prefill_chunk)."""
        h, pool = self.attn.paged_prefill_chunk(
            self.ln1(x), pool, page_ids, offsets, page_rows, q_pos,
            chunked)
        x = x + h
        x = x + self._ffn(self.ln2(x))
        return x, pool


class GPT(nn.Module):
    """Causal LM: returns next-token logits [B, T, V] (weight-tied head)."""

    def __init__(self, cfg: GPTConfig):
        super().__init__()
        self.cfg = cfg
        self.tok_emb = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.pos_emb = nn.Embedding(cfg.max_position, cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        if cfg.scan_layers:
            self.blocks = nn.ScanLayers(GPTBlock(cfg), cfg.num_layers,
                                        remat=cfg.remat,
                                        needs_rng=cfg.dropout > 0)
        else:
            self.blocks = [GPTBlock(cfg) for _ in range(cfg.num_layers)]
        self.ln_f = nn.LayerNorm(cfg.hidden_size)

    def hidden(self, input_ids, pos_offset=0):
        """Final post-LN hidden states [B, T, H] (the vocab head is applied
        by forward, or fused into the loss by .loss)."""
        b, t = input_ids.shape
        if self.cfg.seq_axis is not None:
            # under shard_map the leading tokens of this shard sit at
            # global position rank * t_local
            from jax import lax
            pos_offset = pos_offset + lax.axis_index(
                self.cfg.seq_axis) * t
        pos = pos_offset + jnp.arange(t)[None, :]
        x = self.drop(self.tok_emb(input_ids) + self.pos_emb(pos))
        if self.cfg.scan_layers:
            x = self.blocks(x)
        else:
            for blk in self.blocks:
                x = blk(x)
        return self.ln_f(x)

    def forward(self, input_ids, pos_offset=0):
        return nn.tied_vocab_head(self.tok_emb,
                                  self.hidden(input_ids, pos_offset))

    def loss(self, input_ids, labels=None, pad_id=None, vocab_axis=None,
             batch_axis=None, mesh=None, mesh_plan=None):
        """Shifted next-token CE as an apply() entry point
        (``model.apply(vars, ids, method="loss")``). Default path: the
        chunked fused cross-entropy against the tied embedding table —
        no [B, T, V] logits. PT_FUSED_XENT=0 restores the
        logits-then-lm_loss reference composition.

        vocab_axis/batch_axis: mesh axis names when the tied embedding is
        vocab-partitioned (P(tp, None)) and the batch dp-sharded under
        GSPMD — the fused CE then runs per vocab shard with pmax/psum
        combines instead of gathering the table (ops/fused.py).
        mesh_plan: an autoplan MeshPlan — fills the three kwargs above
        from the planned mesh (explicit values win)."""
        from paddle_tpu.ops.fused import fused_xent, fused_xent_enabled
        if mesh_plan is not None:
            vocab_axis, batch_axis, mesh = mesh_plan.resolve_loss_axes(
                vocab_axis, batch_axis, mesh)
        if labels is None:
            labels = input_ids
        h = self.hidden(input_ids)
        if not fused_xent_enabled() or self.tok_emb.has_p("weight_q"):
            return lm_loss(nn.tied_vocab_head(self.tok_emb, h), labels,
                           pad_id)
        ce = fused_xent(h[:, :-1], self.tok_emb.p("weight"), labels[:, 1:],
                        vocab_axis=vocab_axis, batch_axis=batch_axis,
                        mesh=mesh)
        if pad_id is not None:
            valid = (labels[:, 1:] != pad_id).astype(ce.dtype)
            return jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1.0)
        return jnp.mean(ce)


def lm_loss(logits, labels, pad_id=None):
    """Shifted next-token cross entropy; optionally ignores pad positions.
    Parity reference for GPT.loss's fused path (PT_FUSED_XENT gates)."""
    lp = logits[:, :-1]
    tgt = labels[:, 1:]
    ce = L.softmax_with_cross_entropy(lp, tgt[..., None])[..., 0]
    if pad_id is not None:
        valid = (tgt != pad_id).astype(ce.dtype)
        return jnp.sum(ce * valid) / jnp.maximum(jnp.sum(valid), 1.0)
    return jnp.mean(ce)


def _gpt_decode_step(model, token, caches, pos):
    """One incremental forward through all blocks with KV caches.
    token: [B, 1] int32 (lookup_table's Paddle trailing-1 squeeze is
    undone with an explicit reshape)."""
    b = token.shape[0]
    e = model.cfg.hidden_size
    x = (model.tok_emb(token)
         + model.pos_emb(jnp.full(token.shape, pos, jnp.int32))
         ).reshape(b, 1, e)
    new_caches = []
    for blk, cache in zip(model.blocks, caches):
        x, cache = blk.decode_step(x, cache, pos)
        new_caches.append(cache)
    x = model.ln_f(x)
    return nn.tied_vocab_head(model.tok_emb, x), new_caches


class GPTDecoder(GPT):
    """GPT + incremental decoding: KV caches make each generated token an
    O(1)-projection step (no full-sequence recompute). No reference
    counterpart — Fluid's decoders re-ran the network per step via the
    beam_search op loop."""

    def __init__(self, cfg: GPTConfig):
        from paddle_tpu.core.enforce import enforce
        enforce(not cfg.scan_layers,
                "GPTDecoder steps per-layer KV caches and needs unrolled "
                "blocks (scan_layers=False); train params saved from a "
                "scan model convert via io.checkpoint.unstack_layer_tree")
        super().__init__(cfg)

    def init_caches(self, batch, max_len, dtype=jnp.float32):
        from paddle_tpu.core.enforce import enforce
        enforce(self.cfg.seq_axis is None,
                "GPTDecoder decoding needs an unsharded sequence "
                "(seq_axis must be None); gather the sequence before "
                "decoding")
        return [blk.attn.init_cache(batch, max_len, dtype)
                for blk in self.blocks]

    def decode_step(self, token, caches, pos):
        """token: [B, 1] int32; pos: scalar. -> (logits [B, 1, V], caches)."""
        return _gpt_decode_step(self, token, caches, pos)

    # --- paged serving cache (slot/page-pool layout; ops/attention.py) ---

    def init_paged_caches(self, num_pages, page_size, dtype=jnp.float32,
                          kv_dtype=None):
        """Per-layer page pools for the serving engine. Unlike
        init_caches, capacity is pages (shared across slots), not a
        padded [B, Tmax] rectangle per request. kv_dtype=int8 stores
        quantized values with per-row scales (ops/attention.py)."""
        from paddle_tpu.core.enforce import enforce
        enforce(self.cfg.seq_axis is None,
                "paged decoding needs an unsharded sequence")
        return [blk.attn.init_page_pool(num_pages, page_size, dtype,
                                        kv_dtype=kv_dtype)
                for blk in self.blocks]

    def paged_decode_step(self, tokens, caches, page_table, lengths,
                          active):
        """One serve-step forward for all slots. tokens: [S] int32 (the
        pending token per slot, sits at position `lengths`); page_table:
        [S, Pmax] int32 (in-range everywhere); lengths: [S] tokens
        already in the cache; active: [S] bool. The new token's K/V lands
        at page_table[s, lengths//ps] offset lengths%ps (dropped for
        inactive slots); attention covers lengths+1 tokens.
        -> (logits [S, V], new_caches)."""
        s = tokens.shape[0]
        num_pages, _, page_size, _ = caches[0]["k"].shape
        write_pages = page_table[jnp.arange(s), lengths // page_size]
        write_pages = jnp.where(active, write_pages, num_pages)  # drop
        write_offsets = lengths % page_size
        att_lengths = lengths + active.astype(lengths.dtype)
        pos = jnp.minimum(lengths, self.cfg.max_position - 1)
        x = (self.tok_emb(tokens[:, None])
             + self.pos_emb(pos[:, None])
             ).reshape(s, 1, self.cfg.hidden_size)
        new_caches = []
        for blk, pool in zip(self.blocks, caches):
            x, pool = blk.paged_decode_step(x, pool, page_table,
                                            att_lengths, write_pages,
                                            write_offsets)
            new_caches.append(pool)
        x = self.ln_f(x)
        return nn.tied_vocab_head(self.tok_emb, x)[:, 0], new_caches

    def paged_prefill(self, prompt, lengths, caches, page_rows):
        """Admission prefill: one causal forward over the padded prompt
        batch writes each request's K/V into its pages. prompt: [B, Lp]
        int32 (padded; Lp fixed so admission never retraces); lengths:
        [B] true prompt lengths; page_rows: [B, Pmax] int32. Pad
        positions route to the out-of-range drop page. Returns (logits
        of each request's LAST real token [B, V], new_caches).

        The single-chunk (starts = 0) case of paged_prefill_chunk, kept
        as the stable entry point — per-request jnp.where selection makes
        a first chunk numerically identical to the pre-chunking path."""
        b = prompt.shape[0]
        return self.paged_prefill_chunk(
            prompt, jnp.zeros((b,), jnp.int32), lengths, caches,
            page_rows)

    def paged_prefill_chunk(self, prompt, starts, chunk_lengths, caches,
                            page_rows, write_floor=None):
        """Chunked admission prefill: the fixed [B, Lp] window holds
        tokens at ABSOLUTE positions starts[b] .. starts[b] +
        chunk_lengths[b] - 1 of each request, so a prompt longer than Lp
        is admitted as ceil(len / Lp) calls of one trace. First chunks
        (starts == 0) take the in-chunk causal path bit-exactly;
        continuation chunks re-attend the slot's whole cached prefix
        through its page table. write_floor ([B] int32, optional): K/V
        writes below that absolute position are dropped — the serving
        engine's prefix-cache hits map shared read-only pages there, so
        their content must not be rewritten (it is bit-identical anyway;
        dropping the write is what keeps the pages shareable). Returns
        (logits of each request's LAST chunk token [B, V], new_caches)."""
        x, new_caches = self._paged_chunk_hidden(
            prompt, starts, chunk_lengths, caches, page_rows, write_floor)
        last = jnp.take_along_axis(
            x, jnp.maximum(chunk_lengths - 1, 0)[:, None, None], axis=1)
        return nn.tied_vocab_head(self.tok_emb, last)[:, 0], new_caches

    def _paged_chunk_hidden(self, prompt, starts, chunk_lengths, caches,
                            page_rows, write_floor=None):
        """Shared body of paged_prefill_chunk / paged_verify_chunk: run
        the fixed [B, Lp] window through every block's gathered-prefix
        chunk attention and return the FULL post-ln_f hidden states
        [B, Lp, H] plus the updated pools."""
        b, lp = prompt.shape
        num_pages, _, page_size, _ = caches[0]["k"].shape
        p_max = page_rows.shape[1]
        rel = jnp.arange(lp)
        pos = starts[:, None] + rel[None, :]                    # [B, Lp]
        in_chunk = rel[None, :] < chunk_lengths[:, None]
        page_ids = jnp.take_along_axis(
            page_rows, jnp.minimum(pos // page_size, p_max - 1), axis=1)
        page_ids = jnp.where(in_chunk, page_ids, num_pages)
        if write_floor is not None:
            page_ids = jnp.where(pos >= write_floor[:, None], page_ids,
                                 num_pages)
        offsets = pos % page_size
        emb_pos = jnp.minimum(pos, self.cfg.max_position - 1)
        x = self.tok_emb(prompt) + self.pos_emb(emb_pos)
        chunked = starts > 0
        new_caches = []
        for blk, pool in zip(self.blocks, caches):
            x, pool = blk.paged_prefill_chunk(x, pool, page_ids, offsets,
                                              page_rows, pos, chunked)
            new_caches.append(pool)
        return self.ln_f(x), new_caches

    def paged_verify_chunk(self, window, starts, win_lengths, caches,
                           page_rows):
        """Speculative-decoding verify: score EVERY position of a
        [B, W] token window sitting at absolute positions starts[b] ..
        starts[b] + win_lengths[b] - 1 against the paged cache, through
        the same gathered-prefix chunk-attention path chunked prefill
        uses (starts >= 1 for any live slot, so every window re-attends
        the slot's whole cached prefix plus itself causally). K/V for
        the window tokens is written into the slot's pages as a side
        effect — rejection rollback is the caller's length edit; stale
        rows past the accepted prefix are simply overwritten later.
        Returns (hidden [B, W, H], new_caches); the caller applies
        verify_head per position, keeping sampling temporaries at
        [B, V] — never a dense [B, W, V] lattice."""
        return self._paged_chunk_hidden(window, starts, win_lengths,
                                        caches, page_rows)

    def verify_head(self, hidden_row):
        """Vocab logits for ONE window position's hidden states
        [B, H] -> [B, V] (the weight-tied head, applied per position by
        the speculative verify step)."""
        return nn.tied_vocab_head(self.tok_emb, hidden_row[:, None])[:, 0]

    def generate(self, prompt, max_new, temperature=0.0, key=None,
                 cache_dtype=jnp.float32):
        """Greedy (temperature=0) or sampled generation. prompt: [B, Tp].
        Returns [B, Tp + max_new] (prompt prefix included).

        cache_dtype: KV-cache storage dtype. At serving batch sizes the
        padded cache reads dominate per-token HBM traffic (each decode
        step streams the whole [B, H, Tmax, hd] x 2 x layers cache), so
        bf16 halves the decode bandwidth bill for ~3 decimal digits on
        stored keys/values."""
        from jax import lax

        from paddle_tpu.core.enforce import enforce
        enforce(temperature <= 0.0 or key is not None,
                "sampled generation (temperature > 0) requires a PRNG key")
        b, tp = prompt.shape
        total = tp + max_new
        assert total <= self.cfg.max_position, (total,
                                                self.cfg.max_position)
        caches = self.init_caches(b, total, dtype=cache_dtype)

        # batched prefill: ONE causal forward over the whole prompt fills
        # every layer's cache (vs Tp sequential decode_steps — the
        # prefill/decode split every serving stack uses)
        x = (self.tok_emb(prompt)
             + self.pos_emb(jnp.arange(tp)[None, :]))
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, cache = blk.prefill(x, cache, start=0)
            new_caches.append(cache)
        caches = new_caches
        last_logits = nn.tied_vocab_head(self.tok_emb,
                                         self.ln_f(x[:, -1:, :]))

        def sample(logits, k):
            if temperature <= 0.0:
                return jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
            return jax.random.categorical(
                k, logits[:, 0] / temperature, -1).astype(jnp.int32)

        keys = (jax.random.split(key, max_new) if key is not None
                else jnp.zeros((max_new, 2), jnp.uint32))

        def step(carry, inp):
            caches, last_logits = carry
            t, k = inp
            tok = sample(last_logits, k)[:, None]        # [B, 1]
            logits, caches = _gpt_decode_step(self, tok, caches, tp + t)
            return (caches, logits), tok[:, 0]

        (_, _), new_toks = lax.scan(
            step, (caches, last_logits), (jnp.arange(max_new), keys))
        return jnp.concatenate([prompt, new_toks.T.astype(prompt.dtype)], 1)
