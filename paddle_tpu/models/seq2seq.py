"""Attention seq2seq NMT — the machine_translation book model.

Ref: /root/reference/python/paddle/fluid/tests/book/test_machine_translation.py
(encoder-decoder GRU with attention + beam-search decode built from
DynamicRNN / layers.attention primitives) and unittests/dist_transformer.py
for the bigger NMT config.

TPU-first: teacher-forced training forward is one batched scan (no
DynamicRNN graph surgery); decoding reuses ops.rnn.beam_search_decode's
static-shape beam search. Decode entry points run via
`model.apply(variables, ..., method="greedy_decode")`.
"""

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.ops import rnn as R


@dataclasses.dataclass
class Seq2SeqConfig:
    src_vocab: int = 1024
    tgt_vocab: int = 1024
    embed_dim: int = 64
    hidden: int = 128
    bidirectional_encoder: bool = True
    dropout: float = 0.0

    @staticmethod
    def tiny():
        return Seq2SeqConfig(src_vocab=64, tgt_vocab=64, embed_dim=16,
                             hidden=32)


class AttentionSeq2Seq(nn.Module):
    """GRU encoder-decoder with additive (Bahdanau) attention."""

    def __init__(self, cfg: Seq2SeqConfig):
        super().__init__()
        self.cfg = cfg
        H = cfg.hidden
        self.src_embed = nn.Embedding(cfg.src_vocab, cfg.embed_dim)
        self.tgt_embed = nn.Embedding(cfg.tgt_vocab, cfg.embed_dim)
        self.encoder = nn.GRU(cfg.embed_dim, H,
                              bidirectional=cfg.bidirectional_encoder)
        enc_out = H * (2 if cfg.bidirectional_encoder else 1)
        self.enc_proj = nn.Linear(enc_out, H)
        # decoder GRU cell params (manual cell: attention feeds each step)
        self.param("dec_w_ih", (cfg.embed_dim + enc_out, 3 * H))
        self.param("dec_w_hh", (H, 3 * H))
        self.param("dec_b", (3 * H,), I.zeros())
        # additive attention
        self.param("att_q", (H, H))
        self.param("att_k", (enc_out, H))
        self.param("att_v", (H, 1))
        self.out_proj = nn.Linear(H, cfg.tgt_vocab)
        self.dropout = nn.Dropout(cfg.dropout)

    def encode(self, src_ids, src_lengths):
        """Returns (enc_out [B,S,E], att_keys [B,S,H], mask [B,S], h0 [B,H])."""
        emb = self.src_embed(src_ids)
        enc_out, _ = self.encoder(emb, lengths=src_lengths)
        S = src_ids.shape[1]
        mask = jnp.arange(S)[None, :] < src_lengths[:, None]
        h0 = jnp.tanh(self.enc_proj(
            jnp.sum(enc_out * mask[..., None], 1) /
            jnp.maximum(src_lengths[:, None], 1)))
        att_keys = enc_out @ self.p("att_k")   # hoisted: loop-invariant
        return enc_out, att_keys, mask, h0

    def _attend(self, h, enc_out, att_keys, enc_mask):
        """h [B,H] -> context [B,E], weights [B,S]."""
        q = h @ self.p("att_q")                            # [B,H]
        e = (jnp.tanh(q[:, None, :] + att_keys) @ self.p("att_v"))[..., 0]
        e = jnp.where(enc_mask, e, -1e9)
        w = jax.nn.softmax(e, axis=-1)
        ctx = jnp.einsum("bs,bse->be", w, enc_out)
        return ctx, w

    def _dec_step(self, h, y_emb, enc_out, att_keys, enc_mask):
        ctx, _ = self._attend(h, enc_out, att_keys, enc_mask)
        x = jnp.concatenate([y_emb, ctx], axis=-1)
        return R.gru_cell(x, h, self.p("dec_w_ih"), self.p("dec_w_hh"),
                          self.p("dec_b"))

    def forward(self, src_ids, src_lengths, tgt_ids):
        """Teacher-forced training: tgt_ids [B,T] (BOS-prefixed); returns
        logits [B,T,V] predicting tgt_ids shifted left."""
        enc_out, att_keys, mask, h0 = self.encode(src_ids, src_lengths)
        y = self.dropout(self.tgt_embed(tgt_ids))          # [B,T,E]

        def step(h, y_t):
            h = self._dec_step(h, y_t, enc_out, att_keys, mask)
            return h, h

        _, hs = lax.scan(step, h0, jnp.moveaxis(y, 1, 0))
        hs = jnp.moveaxis(hs, 0, 1)                        # [B,T,H]
        return self.out_proj(hs)

    def greedy_decode(self, src_ids, src_lengths, bos_id, eos_id, max_len):
        """Greedy decode -> [B, max_len] token ids. Run via
        apply(variables, ..., method="greedy_decode")."""
        enc_out, att_keys, mask, h0 = self.encode(src_ids, src_lengths)

        def step(carry, _):
            h, tok, done = carry
            y = self.tgt_embed(tok)
            h = self._dec_step(h, y, enc_out, att_keys, mask)
            logits = self.out_proj(h)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
            return (h, nxt, done), nxt

        B = src_ids.shape[0]
        tok0 = jnp.full((B,), bos_id, jnp.int32)
        done0 = jnp.zeros((B,), bool)
        _, toks = lax.scan(step, (h0, tok0, done0), None, length=max_len)
        return jnp.moveaxis(toks, 0, 1)

    def beam_decode(self, src_ids, src_lengths, bos_id, eos_id, beam_size,
                    max_len):
        """Beam-search decode (ref: beam_search_op path in the book model).
        Returns (sequences [B, K, max_len], scores [B, K]). Run via
        apply(variables, ..., method="beam_decode")."""
        B = src_ids.shape[0]
        V = self.cfg.tgt_vocab
        K = beam_size
        enc_out, att_keys, mask, h0 = self.encode(src_ids, src_lengths)
        enc_k = jnp.repeat(enc_out, K, axis=0)
        keys_k = jnp.repeat(att_keys, K, axis=0)
        mask_k = jnp.repeat(mask, K, axis=0)
        h_k = jnp.repeat(h0, K, axis=0)

        def log_probs_fn(tokens, h):
            y = self.tgt_embed(tokens)
            h = self._dec_step(h, y, enc_k, keys_k, mask_k)
            return jax.nn.log_softmax(self.out_proj(h), -1), h

        return R.beam_search_decode(log_probs_fn, h_k, bos_id, eos_id,
                                    beam_size, max_len, B, V)


def nmt_loss(logits, labels, lengths):
    """Masked cross-entropy; labels [B,T] are the gold next tokens."""
    T = labels.shape[1]
    mask = (jnp.arange(T)[None, :] < lengths[:, None]).astype(logits.dtype)
    logp = jax.nn.log_softmax(logits, -1)
    nll = -jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
