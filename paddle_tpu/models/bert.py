"""BERT — transformer encoder for masked-LM pretraining.

Ref: BASELINE.md flagship "BERT-base pretraining (PaddleNLP Fluid bert/
recipe)". The reference frames it over fluid.layers (multi_head_attention in
layers/nn.py + ERNIE-style recipes); here it's a first-class model with
flash-attention, bf16 policy support, and mesh-shardable params.

Sharding plan (parallel/api.py + models/sharding.py): embeddings and FFN
weights shard over "tp"; sequence dim over "sp" with ring attention for
long-context.
"""

import dataclasses

import jax
import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.ops import activations as A
from paddle_tpu.ops import loss as L


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    use_flash: bool = True
    scan_layers: bool = False  # stack layer params + lax.scan over layers
    remat: str = None          # nothing|dots_saveable|full (None -> flag)

    @staticmethod
    def base():
        return BertConfig()

    @staticmethod
    def tiny():
        return BertConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                          num_heads=4, intermediate_size=128,
                          max_position=128)

    @staticmethod
    def large():
        return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                          intermediate_size=4096)


class TransformerLayer(nn.Module):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.attn = nn.MultiHeadAttention(cfg.hidden_size, cfg.num_heads,
                                          dropout=cfg.dropout,
                                          use_flash=cfg.use_flash)
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.fc1 = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
        self.fc2 = nn.Linear(cfg.intermediate_size, cfg.hidden_size)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)

    def forward(self, x, mask=None):
        h = self.attn(x, mask=mask)
        x = self.ln1(self.drop(h), residual=x)   # fused add+LN
        h = nn.fused_ffn(self.fc1, self.fc2, x)
        x = self.ln2(self.drop(h), residual=x)
        return x


class BertEncoder(nn.Module):
    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.tok_emb = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.pos_emb = nn.Embedding(cfg.max_position, cfg.hidden_size)
        self.seg_emb = nn.Embedding(cfg.type_vocab_size, cfg.hidden_size)
        self.emb_ln = nn.LayerNorm(cfg.hidden_size)
        self.drop = nn.Dropout(cfg.dropout)
        if cfg.scan_layers:
            self.layers = nn.ScanLayers(TransformerLayer(cfg),
                                        cfg.num_layers, remat=cfg.remat,
                                        needs_rng=cfg.dropout > 0)
        else:
            self.layers = [TransformerLayer(cfg)
                           for _ in range(cfg.num_layers)]

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        b, t = input_ids.shape
        pos = jnp.arange(t)[None, :]
        x = self.tok_emb(input_ids) + self.pos_emb(pos)
        if token_type_ids is not None:
            x = x + self.seg_emb(token_type_ids)
        x = self.drop(self.emb_ln(x))
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :]  # [B,1,1,T]
        if self.cfg.scan_layers:
            x = self.layers(x, mask=mask)
        else:
            for layer in self.layers:
                x = layer(x, mask=mask)
        return x


class BertForPretraining(nn.Module):
    """MLM + NSP heads (ref: the Fluid BERT recipe's create_model)."""

    def __init__(self, cfg: BertConfig):
        super().__init__()
        self.cfg = cfg
        self.encoder = BertEncoder(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size,
                                       act="gelu")
        self.mlm_ln = nn.LayerNorm(cfg.hidden_size)
        self.param("mlm_bias", (cfg.vocab_size,), I.zeros())
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size, act="tanh")
        self.nsp = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                mask_positions=None):
        """mask_positions [B, M] (int): gather only the masked positions
        before the MLM transform + vocab projection, the way the reference
        recipe gathers mask_pos before its fc — at 15% masking this skips
        ~85% of the head's [*, H]x[H, V] MXU work and its backward. Returned
        mlm_logits are then [B, M, V] (align labels/weights to the same
        positions). None keeps the full [B, T, V] head."""
        h = self.encoder(input_ids, token_type_ids, attention_mask)
        hm = h if mask_positions is None else jnp.take_along_axis(
            h, mask_positions[..., None], axis=1)
        mlm_h = self.mlm_ln(self.mlm_transform(hm))
        # weight tying with token embedding (standard BERT); int8-table
        # aware (nn.tied_vocab_head) for weight-only serving
        mlm_logits = (nn.tied_vocab_head(self.encoder.tok_emb, mlm_h)
                      + self.p("mlm_bias"))
        pooled = self.pooler(h[:, 0])
        nsp_logits = self.nsp(pooled)
        return mlm_logits, nsp_logits

    def loss(self, input_ids, mlm_labels, nsp_labels, mlm_mask,
             token_type_ids=None, attention_mask=None, mask_positions=None,
             vocab_axis=None, batch_axis=None, mesh=None, mesh_plan=None):
        """MLM + NSP pretraining loss as an apply() entry point. Default
        path fuses the MLM vocab projection into the chunked cross-entropy
        (no [B, M, V] logits, no tied-head matmul output in HBM);
        PT_FUSED_XENT=0 restores forward() + pretrain_loss.

        vocab_axis/batch_axis: mesh axis names when the tied embedding
        (and mlm_bias) are vocab-partitioned and the batch dp-sharded
        under GSPMD — the fused CE then runs per vocab shard with
        pmax/psum combines instead of gathering the table. mesh_plan: an
        autoplan MeshPlan — fills the three kwargs above from the
        planned mesh (explicit values win)."""
        from paddle_tpu.ops.fused import fused_xent, fused_xent_enabled
        if mesh_plan is not None:
            vocab_axis, batch_axis, mesh = mesh_plan.resolve_loss_axes(
                vocab_axis, batch_axis, mesh)
        if (not fused_xent_enabled()
                or self.encoder.tok_emb.has_p("weight_q")):
            mlm_logits, nsp_logits = self.forward(
                input_ids, token_type_ids, attention_mask, mask_positions)
            return pretrain_loss(mlm_logits, nsp_logits, mlm_labels,
                                 nsp_labels, mlm_mask)
        h = self.encoder(input_ids, token_type_ids, attention_mask)
        hm = h if mask_positions is None else jnp.take_along_axis(
            h, mask_positions[..., None], axis=1)
        mlm_h = self.mlm_ln(self.mlm_transform(hm))
        ce = fused_xent(mlm_h, self.encoder.tok_emb.p("weight"),
                        mlm_labels, bias=self.p("mlm_bias"),
                        vocab_axis=vocab_axis, batch_axis=batch_axis,
                        mesh=mesh)
        mlm = (jnp.sum(ce * mlm_mask)
               / jnp.maximum(jnp.sum(mlm_mask), 1))
        nsp_logits = self.nsp(self.pooler(h[:, 0]))
        nsp = jnp.mean(L.softmax_with_cross_entropy(nsp_logits,
                                                    nsp_labels[..., None]))
        return mlm + nsp


def pretrain_loss(mlm_logits, nsp_logits, mlm_labels, nsp_labels,
                  mlm_mask):
    """Masked-LM + NSP loss. mlm_mask: 1.0 at masked positions. Parity
    reference for BertForPretraining.loss's fused path."""
    mlm = L.softmax_with_cross_entropy(mlm_logits, mlm_labels[..., None])
    mlm = jnp.sum(mlm[..., 0] * mlm_mask) / jnp.maximum(jnp.sum(mlm_mask), 1)
    nsp = jnp.mean(L.softmax_with_cross_entropy(nsp_logits,
                                                nsp_labels[..., None]))
    return mlm + nsp
