"""Sentiment classification — the book's understand_sentiment fixtures.

Ref: /root/reference/python/paddle/fluid/tests/book/
test_understand_sentiment.py — three recipes over IMDB: convolution_net
(text-CNN via sequence_conv_pool), stacked_lstm_net, and a dynamic-RNN
variant. TPU-first: padded [B, T] batches + length masks instead of LoD;
the conv net projects centered context windows (sequence_conv's window
convention over dense batches), the LSTM net stacks masked-scan LSTMs.
"""

import dataclasses

import jax.numpy as jnp

from paddle_tpu import initializer as I
from paddle_tpu import nn
from paddle_tpu.ops import loss as L
from paddle_tpu.ops import rnn as R


@dataclasses.dataclass
class SentimentConfig:
    vocab_size: int = 5149        # imdb word dict size in the book fixture
    embed_dim: int = 128
    hidden: int = 128
    num_classes: int = 2
    window: int = 3

    @staticmethod
    def tiny():
        return SentimentConfig(vocab_size=200, embed_dim=16, hidden=16)


class TextCNNSentiment(nn.Module):
    """convolution_net (ref test_understand_sentiment.py:36): embedding →
    two context-window conv+pool branches → softmax head."""

    def __init__(self, cfg: SentimentConfig):
        super().__init__()
        self.cfg = cfg
        self.embed = nn.Embedding(cfg.vocab_size, cfg.embed_dim,
                                  weight_init=I.normal(0, 0.1))
        # window-conv = Linear over the concatenated context window
        self.conv3 = nn.Linear(3 * cfg.embed_dim, cfg.hidden, act="tanh")
        self.conv4 = nn.Linear(4 * cfg.embed_dim, cfg.hidden, act="tanh")
        self.fc = nn.Linear(2 * cfg.hidden, cfg.num_classes)

    def _window_pool(self, emb, mask, width, proj):
        """Centered width-token window projection then max-pool over time
        (same window convention as ops/sequence.sequence_conv with
        context_start=-(width-1)//2; reimplemented over padded [B,T,D]
        because this model is a dense-batch recipe, not a RaggedBatch op)."""
        B, T, D = emb.shape
        start = -((width - 1) // 2)
        cols = []
        for k in range(width):
            off = start + k
            shifted = jnp.roll(emb, -off, axis=1)
            pos = jnp.arange(T) + off
            ok = (pos >= 0)[None, :] & (pos < T)[None, :]
            cols.append(jnp.where(ok[..., None], shifted, 0.0))
        win = jnp.concatenate(cols, axis=-1)         # [B, T, width*D]
        h = proj(win)
        neg = jnp.asarray(jnp.finfo(h.dtype).min, h.dtype)
        h = jnp.where(mask[..., None], h, neg)
        return jnp.max(h, axis=1)

    def forward(self, ids, lengths=None):
        B, T = ids.shape
        if lengths is None:
            lengths = jnp.full((B,), T, jnp.int32)
        mask = jnp.arange(T)[None, :] < lengths[:, None]
        emb = self.embed(ids) * mask[..., None]
        a = self._window_pool(emb, mask, 3, self.conv3)
        b = self._window_pool(emb, mask, 4, self.conv4)
        return self.fc(jnp.concatenate([a, b], axis=-1))


class _DirLSTM(nn.Module):
    """One LSTM stack with a fixed scan direction (the book's `is_reverse`
    flag on dynamic_lstm)."""

    def __init__(self, input_size, hidden_size, reverse=False,
                 dtype=jnp.float32):
        super().__init__()
        self.hidden_size = hidden_size
        self.reverse = reverse
        self.param("w_ih", (input_size, 4 * hidden_size), I.xavier(), dtype)
        self.param("w_hh", (hidden_size, 4 * hidden_size), I.xavier(), dtype)
        self.param("b", (4 * hidden_size,), I.zeros(), dtype)

    def forward(self, x, lengths=None):
        b = x.shape[0]
        h0 = jnp.zeros((b, self.hidden_size), x.dtype)
        c0 = jnp.zeros((b, self.hidden_size), x.dtype)
        outs, _ = R.lstm(x, h0, c0, self.p("w_ih"), self.p("w_hh"),
                         self.p("b"), lengths=lengths, reverse=self.reverse)
        return outs


class StackedLSTMSentiment(nn.Module):
    """stacked_lstm_net (ref test_understand_sentiment.py:62): embedding →
    stacked (fc + lstm) layers with alternating direction → max-pool head."""

    def __init__(self, cfg: SentimentConfig, stacked_num=3):
        super().__init__()
        self.cfg = cfg
        self.stacked_num = stacked_num
        self.embed = nn.Embedding(cfg.vocab_size, cfg.embed_dim,
                                  weight_init=I.normal(0, 0.1))
        self.fcs = [nn.Linear(cfg.embed_dim if i == 0 else cfg.hidden,
                              cfg.hidden) for i in range(stacked_num)]
        self.lstms = [_DirLSTM(cfg.hidden, cfg.hidden, reverse=bool(i % 2))
                      for i in range(stacked_num)]
        self.out = nn.Linear(2 * cfg.hidden, cfg.num_classes)

    def forward(self, ids, lengths=None):
        B, T = ids.shape
        if lengths is None:
            lengths = jnp.full((B,), T, jnp.int32)
        mask = jnp.arange(T)[None, :] < lengths[:, None]
        h = self.embed(ids) * mask[..., None]
        for i in range(self.stacked_num):
            f = self.fcs[i](h)
            # alternate scan direction per stack (the book's inverse flag)
            h = self.lstms[i](f, lengths=lengths)
        neg = jnp.asarray(jnp.finfo(h.dtype).min, h.dtype)
        masked_h = jnp.where(mask[..., None], h, neg)
        pooled_h = jnp.max(masked_h, axis=1)
        masked_f = jnp.where(mask[..., None], f, neg)
        pooled_f = jnp.max(masked_f, axis=1)
        return self.out(jnp.concatenate([pooled_h, pooled_f], axis=-1))


def sentiment_loss(logits, labels):
    return jnp.mean(L.softmax_with_cross_entropy(logits, labels))
