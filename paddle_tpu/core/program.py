"""Captured Program IR.

Ref: /root/reference/paddle/fluid/framework/framework.proto:212 (ProgramDesc →
BlockDesc → OpDesc/VarDesc) and python/paddle/fluid/framework.py:3459
(Program). The reference builds programs *op-by-op* through Python API calls,
serializes them as protobuf, and interprets them with a C++ Executor
(executor.cc:403 op loop).

TPU-first redesign: a Program is a **traced JAX function** — tracing replaces
the op-by-op graph builder, a jaxpr replaces BlockDesc, and StableHLO is the
serialized interchange format (the ProgramDesc equivalent; consumed by the C++
serving runtime in csrc/). XLA replaces the op-loop interpreter: the whole
program compiles to one executable, fused and scheduled by the compiler instead
of by hand (details/*.cc SSA executors).
"""

import jax
import jax.numpy as jnp


class Program:
    """A captured computation: python callable + trace artifacts.

    ``capture`` traces ``fn`` with example args into a ClosedJaxpr (the
    in-memory IR) and can lower to StableHLO text/bytes for serialization —
    the counterpart of ProgramDesc serialize/parse (framework.py:3459
    Program.to_string / parse_from_string).
    """

    def __init__(self, fn, jaxpr=None, example_args=None, name="program"):
        self.fn = fn
        self.jaxpr = jaxpr
        self.example_args = example_args
        self.name = name
        self._compiled = None

    @staticmethod
    def capture(fn, *example_args, name="program", **example_kwargs):
        closed = jax.make_jaxpr(lambda *a: fn(*a, **example_kwargs))(*example_args)
        return Program(fn, jaxpr=closed, example_args=example_args, name=name)

    # --- introspection (OpDesc-level view of the captured graph) ---
    def ops(self):
        """List primitive op names in program order (ref: BlockDesc.ops)."""
        if self.jaxpr is None:
            raise ValueError("Program not captured; call Program.capture")
        return [str(eqn.primitive) for eqn in self.jaxpr.jaxpr.eqns]

    def num_ops(self):
        return len(self.ops())

    def input_avals(self):
        return [v.aval for v in self.jaxpr.jaxpr.invars]

    def output_avals(self):
        return [v.aval for v in self.jaxpr.jaxpr.outvars]

    # --- lowering / serialization (ProgramDesc proto equivalent) ---
    def lower(self, *args, **kwargs):
        args = args or self.example_args
        return jax.jit(self.fn).lower(*args, **kwargs)

    def to_stablehlo(self, *args):
        """StableHLO text — the serialized-IR interchange format."""
        return self.lower(*args).as_text(dialect="stablehlo")

    def compile(self, *args, donate_argnums=()):
        if self._compiled is None:
            self._compiled = jax.jit(self.fn, donate_argnums=donate_argnums)
        return self._compiled

    def __call__(self, *args, **kwargs):
        return self.compile()(*args, **kwargs)


def flop_estimate(fn, *example_args):
    """Static FLOP estimate from XLA cost analysis (used by bench/MFU math)."""
    lowered = jax.jit(fn).lower(*example_args)
    compiled = lowered.compile()
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0))
    except Exception:
        return 0.0
