"""Op registry.

Ref: /root/reference/paddle/fluid/framework/op_registry.h:199
(REGISTER_OPERATOR) and op_info.h:93 (OpInfoMap singleton). The reference
needs a registry to map serialized OpDesc names to kernels per
(place, dtype, layout, library). On TPU, XLA owns kernel selection; the
registry's remaining job is *serializability*: captured Programs name ops, and
the loader must resolve names back to callables (see core/program.py and
io/inference.py). It also powers introspection (`list_ops`) for parity audits
against the reference's ~480 op surface.
"""

import functools


class OpRegistry:
    """Name → callable registry with per-op metadata."""

    def __init__(self):
        self._ops = {}

    def register(self, name, fn=None, **meta):
        if fn is None:
            return functools.partial(self.register, name, **meta)
        if name in self._ops:
            raise KeyError(f"Op '{name}' already registered")
        self._ops[name] = (fn, meta)
        return fn

    def get(self, name):
        if name not in self._ops:
            raise KeyError(f"Op '{name}' is not registered")
        return self._ops[name][0]

    def meta(self, name):
        return self._ops[name][1]

    def __contains__(self, name):
        return name in self._ops

    def list_ops(self):
        return sorted(self._ops)


GLOBAL_OP_REGISTRY = OpRegistry()


def register_op(name, **meta):
    """Decorator: register a function as a named framework op.

    Usage::

        @register_op("softmax")
        def softmax(x, axis=-1): ...
    """
    return GLOBAL_OP_REGISTRY.register(name, **meta)
