"""Canonical dtypes.

Ref: /root/reference/paddle/fluid/framework/framework.proto:105 (VarType.Type
enumerates BOOL/INT16/INT32/INT64/FP16/FP32/FP64/UINT8/INT8) and
platform/float16.h. On TPU the preferred low-precision type is bfloat16
(MXU-native); float16 is kept for API parity.
"""

import jax.numpy as jnp
import numpy as np

bool_ = jnp.bool_
int8 = jnp.int8
uint8 = jnp.uint8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64

_STR_TO_DTYPE = {
    "bool": bool_,
    "int8": int8,
    "uint8": uint8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "fp32": float32,
    "float64": float64,
    "fp64": float64,
}


def convert_dtype(dtype):
    """Normalize a string/numpy/jax dtype spec to a jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower()
        if key not in _STR_TO_DTYPE:
            raise TypeError(f"Unsupported dtype string: {dtype!r}")
        return _STR_TO_DTYPE[key]
    return jnp.dtype(dtype).type


def is_floating(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


def finfo(dtype):
    return jnp.finfo(dtype)


def iinfo(dtype):
    return jnp.iinfo(dtype)


def numpy_dtype(dtype):
    return np.dtype(jnp.dtype(convert_dtype(dtype)))
