"""Core: platform + framework layer.

TPU-native replacement for the reference's `paddle/fluid/platform` and
`paddle/fluid/framework` C++ core. PJRT (via JAX) owns device contexts,
allocation, streams, and kernel dispatch — what the reference hand-built
(device_context.h, allocator_facade.h, operator.cc kernel choice) the XLA
runtime provides. What remains framework-level lives here:

  dtype.py     canonical dtypes (ref: framework.proto VarType)
  enforce.py   error-checking macros (ref: platform/enforce.h PADDLE_ENFORCE)
  flags.py     global config flags (ref: platform/flags.cc)
  registry.py  op registry keyed by name (ref: framework/op_registry.h)
  program.py   captured Program IR via jax tracing (ref: framework.proto ProgramDesc)
  random.py    global seed management
  ragged.py    ragged/variable-length batching (ref: lod_tensor.h LoD)
  retry.py     retry/backoff policy for remote I/O (no reference
               counterpart — the reference propagated one-shot failures)
"""

from paddle_tpu.core import dtype, enforce, flags, random, retry
from paddle_tpu.core.registry import OpRegistry, register_op
from paddle_tpu.core.retry import RetryPolicy, retrying
