"""Error checking — the PADDLE_ENFORCE family, Python-native.

Ref: /root/reference/paddle/fluid/platform/enforce.h:286 (PADDLE_ENFORCE,
PADDLE_ENFORCE_EQ/NE/GT/GE/LT/LE/NOT_NULL with demangled stack traces).
Python tracebacks already carry the stack; we add structured error types and
shape/dtype-specific checks used throughout the op library.
"""


class EnforceError(RuntimeError):
    """Framework invariant violation (ref: platform::EnforceNotMet)."""


def enforce(cond, msg="", *args):
    if not cond:
        raise EnforceError(msg % args if args else str(msg))


def enforce_eq(a, b, msg=""):
    if a != b:
        raise EnforceError(f"Expected {a!r} == {b!r}. {msg}")


def enforce_ne(a, b, msg=""):
    if a == b:
        raise EnforceError(f"Expected {a!r} != {b!r}. {msg}")


def enforce_gt(a, b, msg=""):
    if not a > b:
        raise EnforceError(f"Expected {a!r} > {b!r}. {msg}")


def enforce_ge(a, b, msg=""):
    if not a >= b:
        raise EnforceError(f"Expected {a!r} >= {b!r}. {msg}")


def enforce_lt(a, b, msg=""):
    if not a < b:
        raise EnforceError(f"Expected {a!r} < {b!r}. {msg}")


def enforce_le(a, b, msg=""):
    if not a <= b:
        raise EnforceError(f"Expected {a!r} <= {b!r}. {msg}")


def enforce_not_none(x, name="value"):
    if x is None:
        raise EnforceError(f"{name} must not be None")
    return x


def enforce_rank(x, rank, name="tensor"):
    if x.ndim != rank:
        raise EnforceError(f"{name} must have rank {rank}, got shape {x.shape}")
    return x


def enforce_shape_match(a, b, msg=""):
    if tuple(a.shape) != tuple(b.shape):
        raise EnforceError(f"Shape mismatch: {a.shape} vs {b.shape}. {msg}")
