"""Error checking — the PADDLE_ENFORCE family, Python-native.

Ref: /root/reference/paddle/fluid/platform/enforce.h:286 (PADDLE_ENFORCE,
PADDLE_ENFORCE_EQ/NE/GT/GE/LT/LE/NOT_NULL with demangled stack traces).
Python tracebacks already carry the stack; we add structured error types and
shape/dtype-specific checks used throughout the op library.
"""


class EnforceError(RuntimeError):
    """Framework invariant violation (ref: platform::EnforceNotMet)."""


def enforce(cond, msg="", *args):
    if not cond:
        raise EnforceError(msg % args if args else str(msg))


def enforce_eq(a, b, msg=""):
    if a != b:
        raise EnforceError(f"Expected {a!r} == {b!r}. {msg}")


def enforce_ne(a, b, msg=""):
    if a == b:
        raise EnforceError(f"Expected {a!r} != {b!r}. {msg}")


def enforce_gt(a, b, msg=""):
    if not a > b:
        raise EnforceError(f"Expected {a!r} > {b!r}. {msg}")


def enforce_ge(a, b, msg=""):
    if not a >= b:
        raise EnforceError(f"Expected {a!r} >= {b!r}. {msg}")


def enforce_lt(a, b, msg=""):
    if not a < b:
        raise EnforceError(f"Expected {a!r} < {b!r}. {msg}")


def enforce_le(a, b, msg=""):
    if not a <= b:
        raise EnforceError(f"Expected {a!r} <= {b!r}. {msg}")


def enforce_not_none(x, name="value"):
    if x is None:
        raise EnforceError(f"{name} must not be None")
    return x


def enforce_rank(x, rank, name="tensor"):
    if x.ndim != rank:
        raise EnforceError(f"{name} must have rank {rank}, got shape {x.shape}")
    return x


def enforce_shape_match(a, b, msg=""):
    if tuple(a.shape) != tuple(b.shape):
        raise EnforceError(f"Shape mismatch: {a.shape} vs {b.shape}. {msg}")


def check_numerics(tree, label="tensors"):
    """Host-side NaN/Inf validation of a pytree of arrays.

    Ref: /root/reference/paddle/fluid/platform/flags.cc:44
    (FLAGS_check_nan_inf validates every op output at the executor level).
    TPU-first: device code can't raise (and the tunneled PJRT platform has no
    host callbacks), so the check runs on fetched host values — call it on
    step outputs / fetched vars. Raises EnforceError naming the bad leaves.
    """
    import jax
    import numpy as np

    bad = []
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        if arr.dtype.kind == "f" and not np.all(np.isfinite(arr)):
            n_nan = int(np.isnan(arr).sum())
            n_inf = int(np.isinf(arr).sum())
            bad.append(f"{jax.tree_util.keystr(path)} "
                       f"(nan={n_nan}, inf={n_inf})")
    if bad:
        raise EnforceError(
            f"check_nan_inf: non-finite values in {label}: " + ", ".join(bad))
    return tree
