"""Global seed / PRNG key management.

JAX uses explicit functional PRNG keys; the reference uses global generator
state (paddle/fluid/framework via Place-level generators, python
fluid.default_startup_program().random_seed). We provide a tiny global
key-stream for imperative convenience while keeping all library code
explicit-key underneath.
"""

import threading

import jax

_state = threading.local()


def _ensure():
    if not hasattr(_state, "key"):
        _state.key = jax.random.key(0)


def seed(s):
    """Set the global seed (ref: fluid.Program.random_seed)."""
    _state.key = jax.random.key(s)


def get_state():
    """JSON-serializable snapshot of the global key stream (a list of
    ints, or None before any seeding). Checkpoint meta carries it so a
    resumed run continues the exact key sequence (bit-exact resume)."""
    if not hasattr(_state, "key"):
        return None
    data = jax.random.key_data(_state.key)
    return [int(x) for x in jax.numpy.ravel(data)]


def set_state(data):
    """Restore a get_state() snapshot into the global key stream; None
    (never-seeded snapshot) is a no-op."""
    if data is None:
        return
    arr = jax.numpy.asarray(data, dtype=jax.numpy.uint32)
    _state.key = jax.random.wrap_key_data(arr)


def next_key(n=None):
    """Split the global key-stream; returns one key or a list of n keys."""
    _ensure()
    if n is None:
        _state.key, sub = jax.random.split(_state.key)
        return sub
    keys = jax.random.split(_state.key, n + 1)
    _state.key = keys[0]
    return list(keys[1:])
