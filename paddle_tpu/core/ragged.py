"""Ragged / variable-length sequence batching — the LoDTensor equivalent.

Ref: /root/reference/paddle/fluid/framework/lod_tensor.h:52 — the reference
batches variable-length sequences by concatenating them along dim0 and
carrying `LoD` (level-of-detail) offset tables; 24 `sequence_ops/` kernels
consume those offsets (ref: paddle/fluid/operators/sequence_ops/).

TPU-first redesign: XLA wants static shapes, so raggedness is represented as
  * ``RaggedBatch``: flat values `[total_len, ...]` + int32 `row_lengths`
    (== LoD level-1 deltas) — host-side container;
  * on device, either **dense padded + mask** (`to_padded`) for MXU-heavy ops,
    or **segment-ids** (`segment_ids`) for jax.ops.segment_* reductions.
Length-bucketing (`bucket_boundaries`) bounds the number of compiled shapes,
replacing the reference's truly-dynamic LoD at a small padding cost.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.enforce import enforce


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class RaggedBatch:
    """Concatenated sequences + per-row lengths (LoD level 1).

    values:      [total_len, ...] flat concatenation of sequences
    row_lengths: [batch] int32 sequence lengths (sum == total_len)
    """

    values: jax.Array
    row_lengths: jax.Array

    def tree_flatten(self):
        return (self.values, self.row_lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def nrows(self):
        return self.row_lengths.shape[0]

    def offsets(self):
        """LoD-style offsets [batch+1] (ref lod_tensor.h LoD vector)."""
        return jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), jnp.cumsum(self.row_lengths)]
        ).astype(jnp.int32)

    def segment_ids(self):
        """[total_len] row index per element — for segment_sum/max pooling."""
        return jnp.repeat(
            jnp.arange(self.nrows, dtype=jnp.int32),
            self.row_lengths,
            total_repeat_length=self.values.shape[0],
        )

    def to_padded(self, max_len=None, pad_value=0):
        """Densify to [batch, max_len, ...] plus a bool mask [batch, max_len].

        Static max_len keeps shapes compile-friendly; defaults to total_len
        bound (callers training on TPU should pass a bucketed max_len).
        """
        max_len = int(max_len) if max_len is not None else int(self.values.shape[0])
        b = self.nrows
        offs = self.offsets()[:-1]  # [b]
        idx = offs[:, None] + jnp.arange(max_len)[None, :]  # [b, max_len]
        valid = jnp.arange(max_len)[None, :] < self.row_lengths[:, None]
        idx = jnp.clip(idx, 0, self.values.shape[0] - 1)
        dense = self.values[idx]
        if pad_value != 0:
            shape = valid.shape + (1,) * (dense.ndim - 2)
            dense = jnp.where(valid.reshape(shape), dense, pad_value)
        else:
            shape = valid.shape + (1,) * (dense.ndim - 2)
            dense = dense * valid.reshape(shape).astype(dense.dtype)
        return dense, valid

    @staticmethod
    def from_list(seqs, dtype=None):
        """Host-side construction from a list of numpy arrays / lists."""
        arrs = [np.asarray(s, dtype=dtype) for s in seqs]
        enforce(len(arrs) > 0, "empty ragged batch")
        values = np.concatenate(arrs, axis=0)
        lengths = np.array([a.shape[0] for a in arrs], np.int32)
        return RaggedBatch(jnp.asarray(values), jnp.asarray(lengths))

    @staticmethod
    def from_padded(dense, lengths):
        """Inverse of to_padded: gather valid positions to a flat buffer.

        Host-side (concrete lengths) — under jit keep the padded+mask form
        instead; true raggedness needs a concrete total length.
        """
        b, m = dense.shape[:2]
        lengths = jnp.asarray(lengths, jnp.int32)
        valid = jnp.arange(m)[None, :] < lengths[:, None]
        flat = dense.reshape((b * m,) + dense.shape[2:])
        order = jnp.argsort(~valid.reshape(-1), stable=True)
        total = int(jnp.sum(lengths))
        return RaggedBatch(flat[order][:total], lengths)


def bucket_boundaries(max_len, num_buckets=8):
    """Geometric length buckets to bound compiled-shape count (replaces the
    reference's fully dynamic LoD shapes)."""
    bounds = []
    b = max(8, max_len // (2 ** (num_buckets - 1)))
    while b < max_len:
        bounds.append(b)
        b *= 2
    bounds.append(max_len)
    return bounds


def bucket_for(length, boundaries):
    for b in boundaries:
        if length <= b:
            return b
    return boundaries[-1]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class NestedRagged:
    """Multi-level LoD (ref lod_tensor.h:52 `LoD = vector<Vector<size_t>>`).

    The reference nests sequences-of-sequences (e.g. documents → sentences →
    words, label_semantic_roles-style models): LoD level 0 groups rows of
    level 1, whose deltas measure the innermost values. Here each level is a
    RaggedBatch-style lengths vector:

      values:  [total_innermost, ...] flat concatenation
      lengths: tuple of int32 vectors, outermost first;
               lengths[-1] measures rows of `values`, and lengths[k]
               measures entries of lengths[k+1].

    Example (2 docs; doc0 = 2 sentences of 3,1 words; doc1 = 1 of 2):
      lengths = ([2, 1], [3, 1, 2]), values.shape[0] == 6.
    """

    values: jax.Array
    lengths: tuple

    def tree_flatten(self):
        return (self.values,) + tuple(self.lengths), len(self.lengths)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], tuple(children[1:]))

    @property
    def num_levels(self):
        return len(self.lengths)

    def check(self):
        for k in range(self.num_levels - 1):
            enforce(int(jnp.sum(self.lengths[k]))
                    == int(self.lengths[k + 1].shape[0]),
                    "level %d lengths must sum to level %d row count",
                    k, k + 1)
        enforce(int(jnp.sum(self.lengths[-1])) == int(self.values.shape[0]),
                "innermost lengths must sum to the value count")
        return self

    def level(self, k):
        """RaggedBatch view of level k's rows over the next level's items.

        level(num_levels-1) is the innermost view whose values are the real
        data; outer levels return lengths-over-lengths views (offsets, as
        in the reference's multi-level LoD table)."""
        if k == self.num_levels - 1:
            return RaggedBatch(self.values, self.lengths[k])
        return RaggedBatch(self.lengths[k + 1], self.lengths[k])

    def flatten_outer(self):
        """Drop the outermost level (ref: LoD slicing one level down):
        sentences stop being grouped by document."""
        enforce(self.num_levels >= 2, "need >= 2 levels to flatten")
        return NestedRagged(self.values, tuple(self.lengths[1:]))

    def outer_segment_ids(self):
        """[total_innermost] outermost-group id per value element — one
        jnp.repeat chain down the levels (for segment reductions over the
        outermost grouping, e.g. per-document pooling)."""
        ids = jnp.arange(self.lengths[0].shape[0], dtype=jnp.int32)
        for k in range(self.num_levels):
            total = (int(self.lengths[k + 1].shape[0])
                     if k + 1 < self.num_levels
                     else int(self.values.shape[0]))
            ids = jnp.repeat(ids, self.lengths[k],
                             total_repeat_length=total)
        return ids

    @staticmethod
    def from_parts(values, lengths):
        """Direct construction: values [total, ...] + per-level lengths
        (outermost first). Use for feature-valued innermost data."""
        return NestedRagged(
            jnp.asarray(values),
            tuple(jnp.asarray(v, jnp.int32) for v in lengths)).check()

    @staticmethod
    def from_nested_list(nested, dtype=None):
        """Host construction from nested python lists of scalars
        (outermost first), e.g. docs -> sentences -> word ids. For
        feature-valued leaves use from_parts."""
        lengths_per_level = []
        layer = list(nested)
        while layer and isinstance(layer[0], (list, tuple, np.ndarray)):
            lengths_per_level.append(
                np.asarray([len(x) for x in layer], np.int32))
            layer = [y for x in layer for y in x]
        enforce(lengths_per_level, "from_nested_list needs nested lists")
        return NestedRagged(
            jnp.asarray(np.asarray(layer, dtype=dtype)),
            tuple(jnp.asarray(v) for v in lengths_per_level)).check()
