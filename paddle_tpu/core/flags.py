"""Global framework flags.

Ref: /root/reference/paddle/fluid/platform/flags.cc:33-451 — the reference
defines ~40 process-level gflags (allocator_strategy, eager_delete_tensor_gb,
check_nan_inf, cudnn knobs, communicator tuning) exported to Python via
pybind.cc:1355. Here flags are a plain validated registry; env vars prefixed
``PT_FLAGS_`` override defaults at import time (mirrors how the reference reads
FLAGS_* from environment in __bootstrap__).

XLA-level tuning goes through XLA_FLAGS / jax.config — not duplicated here.
"""

import os

_FLAGS = {}
_DEFS = {}


def define_flag(name, default, help_str=""):
    _DEFS[name] = (type(default) if default is not None else str, help_str)
    env = os.environ.get("PT_FLAGS_" + name)
    if env is not None:
        ty = _DEFS[name][0]
        if ty is bool:
            _FLAGS[name] = env.lower() in ("1", "true", "yes")
        else:
            _FLAGS[name] = ty(env)
    else:
        _FLAGS[name] = default


def get_flag(name):
    if name not in _FLAGS:
        raise KeyError(f"Unknown flag: {name}")
    return _FLAGS[name]


def _coerce(ty, v):
    if v is None or isinstance(v, ty):
        return v
    if ty is bool and isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    return ty(v)


def set_flags(flags_dict):
    for k, v in flags_dict.items():
        if k not in _FLAGS:
            raise KeyError(f"Unknown flag: {k}")
        _FLAGS[k] = _coerce(_DEFS[k][0], v)


def all_flags():
    return dict(_FLAGS)


# --- framework flags (counterparts cited to reference flags.cc) ---
# ref flags.cc:44 FLAGS_check_nan_inf — validate op outputs for NaN/Inf
define_flag("check_nan_inf", False, "Check outputs of every op for NaN/Inf.")
# ref flags.cc:308 allocator_strategy — PJRT owns allocation on TPU; kept for
# host-staging arena selection
define_flag("host_pinned_staging", True, "Use pinned host staging buffers.")
# default compute dtype for AMP-less training
define_flag("default_dtype", "float32", "Default floating point dtype.")
# matmul precision on TPU MXU: 'default' | 'high' | 'highest'
define_flag("matmul_precision", "default", "jax.lax matmul precision.")
# conv2d fast backward (physically-transposed dgrad kernels, ~3x on TPU).
# custom_vjp does not support forward-mode autodiff — disable for jvp/hessian
define_flag("conv_custom_vjp", True,
            "Use the TPU-fast custom conv backward (no jvp support).")
define_flag("resnet_s2d_stem", False,
            "Compute the ResNet 7x7/s2 stem as an exact 4x4/s1 conv over "
            "space-to-depth(2) input (NHWC only). Avoids the C=3 lane-"
            "padding traffic on TPU; flip after silicon measurement.")
# run Pallas kernels through the interpreter — engages the kernels even
# off-TPU (CPU testing of kernel logic)
define_flag("pallas_interpret", False,
            "Run Pallas kernels in interpreter mode (CPU testing).")
define_flag("flash_block_q", 512,
            "Flash attention query-block size (tools/flash_tune.py sweeps).")
define_flag("flash_block_k", 512,
            "Flash attention key-block size (tools/flash_tune.py sweeps).")
# escape hatch for the Pallas fused layer_norm (ADVICE r1: gate the kernel)
define_flag("use_pallas_layer_norm", True,
            "Route layer_norm through the Pallas TPU kernel; False forces "
            "the XLA twin.")
# step-fusion: chunked softmax-cross-entropy over the vocab axis (never
# materializes [batch, seq, vocab] logits or one-hot targets). The env
# spelling PT_FUSED_XENT is also honored (see ops/fused.py).
define_flag("fused_xent", True,
            "Route model .loss() train paths through the chunked/fused "
            "softmax-cross-entropy; False restores the reference "
            "logits-then-loss composition.")
define_flag("xent_chunk", 8192,
            "Vocab-axis tile size for the fused cross-entropy (rows x chunk "
            "logits are the largest temporary on the loss path).")
define_flag("use_pallas_xent", True,
            "Use the Pallas forward-stats kernel for the fused cross-"
            "entropy on TPU; False forces the chunked XLA formulation.")
# fused-xent backward: Pallas dh + dw/db kernels recomputing chunk
# probabilities from the saved logsumexp (flash-attn-2 style) vs the
# chunked-XLA recompute
define_flag("use_pallas_xent_bwd", True,
            "Use the Pallas backward kernels for the fused cross-entropy "
            "on TPU; False falls back to the chunked XLA recompute.")
# scan-over-layers remat policy for transformer encoders (models pass
# cfg.remat to override per-model): nothing | dots_saveable | full
define_flag("remat_policy", "nothing",
            "Gradient checkpointing policy for scan-over-layers encoder "
            "blocks: 'nothing' (save all), 'dots_saveable' (save matmul "
            "outputs, recompute elementwise), 'full' (recompute the whole "
            "block).")
# flash-attention backward: Pallas dq/dkv kernels (flash-attn-2 style) vs
# the recompute-based chunked-XLA fallback
define_flag("flash_pallas_bwd", True,
            "Use the Pallas flash-attention backward kernels; False falls "
            "back to recompute via the chunked XLA formulation.")
# serving fast path — paged KV cache decode attention (ops/attention.py
# paged_decode_attention; kernel in ops/pallas/decode_attention.py). The
# XLA escape hatch gathers live pages densely and masks by length — the
# parity reference, but it materializes a [slots, Tmax]-scale score
# temporary the kernel never does.
define_flag("use_pallas_decode", True,
            "Use the Pallas paged decode-attention kernel on TPU; False "
            "falls back to the XLA gather-and-mask formulation.")
define_flag("serve_page_size", 16,
            "Tokens per KV-cache page in the serving engine (multiples of "
            "8; 128 fills a TPU lane tile exactly).")
define_flag("serve_slots", 4,
            "Concurrent decode slots in the serving engine (the fixed "
            "batch dimension of the jitted serve step).")
# serving resilience (serving/engine.py): bounded admission, chunked
# prefill, and crash-isolated step recovery — degraded conditions produce
# degraded service (rejected/shed/recovered requests), never lost ones
define_flag("serve_queue_limit", 0,
            "Max queued (not yet admitted) requests in the serving "
            "engine; submissions beyond it are REJECTED with a terminal "
            "status and a retriable hint. 0 = unbounded.")
define_flag("serve_default_deadline_s", 0.0,
            "Default per-request deadline (seconds from submit) applied "
            "when submit() passes none; queued requests past their "
            "deadline are shed. 0 = no default deadline.")
define_flag("serve_step_retries", 3,
            "Consecutive failed serve steps (prefill or decode) the "
            "engine recovers from — quarantine pools, re-admit in-flight "
            "requests recompute-style — before giving up and re-raising.")
define_flag("serve_chunked_prefill", True,
            "Admit prompts longer than prefill_len in fixed-shape "
            "prefill_len chunks (one prefill trace, page tables grown "
            "per chunk); False restores the long-prompt rejection.")
# prefix caching + per-request sampling (serving/engine.py +
# serving/prefix_cache.py): shared prompt prefixes map to refcounted
# read-only KV pages (prefill skipped for the hit), copy-on-write on
# divergence; sampling knobs ride per-slot traced arrays in the ONE
# decode trace
define_flag("serve_prefix_cache", True,
            "Cache full prompt pages by rolling content hash and map "
            "shared prefixes read-only into new slots (prefill skipped "
            "for the matched tokens, copy-on-write on divergence); "
            "False prefills every prompt privately.")
define_flag("serve_prefix_pages", 0,
            "Max refcount-zero (idle) pages the prefix cache retains "
            "for future hits; beyond it, least-recently-released idle "
            "entries are evicted eagerly. 0 = bounded only by the pool "
            "(idle pages are reclaimed on demand).")
define_flag("serve_top_k", 0,
            "Default per-request top-k for sampled decoding (keep the k "
            "highest logits; 0 = no top-k cut). Per-request submit() "
            "values override; greedy requests (temperature 0) ignore it.")
define_flag("serve_top_p", 0.0,
            "Default per-request nucleus (top-p) mass for sampled "
            "decoding; 0 = no nucleus cut. Per-request submit() values "
            "override; greedy requests (temperature 0) ignore it.")
define_flag("serve_kv_dtype", "",
            "Paged KV pool storage dtype for the serving engine: "
            "'int8' stores quantized values with per-row scales beside "
            "each page (roughly halving KV bytes vs bf16, 4x vs f32 — "
            "doubled servable context), dequantized inside the fused "
            "decode kernel and the XLA fallback alike. '' or 'f32' "
            "keeps the unquantized pool (ServeConfig.cache_dtype).")
# speculative decoding (serving/engine.py): a draft model proposes
# serve_spec_k tokens per active slot each round and ONE batched verify
# step scores every position against the paged KV cache — more than one
# emitted token per target-model step at high acceptance, token-exact
# with the plain path by construction (the emitted tokens are always the
# target's own per-position samples)
define_flag("serve_draft", False,
            "Enable speculative decoding in the serving engine: the "
            "draft model (ServeConfig.draft_spec, or the target model "
            "itself when none is configured — self-draft) proposes "
            "serve_spec_k tokens per slot per round and one jitted "
            "verify step scores all of them; accepted prefixes emit "
            "multiple tokens per target step, rejection rolls back via "
            "a host-side length edit.")
define_flag("serve_spec_k", 3,
            "Draft tokens proposed per active slot per speculative "
            "round (the verify window is spec_k + 1 positions); only "
            "read when serve_draft is on.")
# fleet serving (serving/fleet.py): a router in front of N ServingEngine
# replicas — least-loaded dispatch, heartbeat liveness, failover replay
# of in-flight requests, bounded respawn, graceful drain
define_flag("serve_replicas", 1,
            "Engine replicas owned by the fleet router (FleetConfig "
            "fields left unset resolve from the fleet_* flags).")
define_flag("fleet_heartbeat_s", 1.0,
            "Fleet router heartbeat timeout per replica, in seconds: a "
            "replica whose ping is older than this is marked stalled "
            "(no new dispatch); silent past heartbeat_dead_factor x "
            "this, it is declared dead and failed over.")
define_flag("fleet_respawn_budget", 3,
            "Consecutive failures (crash, heartbeat death, failed "
            "respawn) the fleet router tolerates per replica before it "
            "stops respawning that replica and leaves it dead.")
define_flag("fleet_drain_timeout_s", 120.0,
            "Wall-clock budget for FleetRouter.drain() to retire every "
            "accepted request while quiescing replicas one at a time; "
            "0 = unbounded.")
define_flag("fleet_canary_weight", 0.1,
            "Fraction of fresh fleet traffic routed to the canary "
            "version while one is deployed (deploy(..., canary=True)); "
            "in [0, 1]. A request never switches versions mid-stream.")
define_flag("fleet_autoscale_min", 1,
            "Floor on live replicas the fleet autoscaler may drain down "
            "to (never below 1).")
define_flag("fleet_autoscale_max", 0,
            "Ceiling on live replicas the fleet autoscaler may spawn up "
            "to; 0 disables autoscaling entirely.")
define_flag("fleet_prefill_replicas", 0,
            "Prefill/decode disaggregation: carve the first N fleet "
            "replicas out as dedicated prefill replicas (role "
            "'prefill'); the rest serve decode. Prefill-heavy requests "
            "(prompt longer than the engine's prefill_len) run their "
            "chunked prefill plus first token on a prefill replica, "
            "then hand off token-exactly to a decode replica via the "
            "adopt() replay path. 0 = every replica mixed-mode.")
define_flag("fleet_scale_cooldown_s", 5.0,
            "Minimum seconds between fleet autoscaling actions (spawn "
            "or drain-then-retire), so one load spike produces one "
            "deliberate step, not a thrash.")
define_flag("fleet_deploy_verify", 1,
            "Verify a deployed checkpoint against its crc32 integrity "
            "manifest before any replica is touched (FleetRouter."
            "deploy); a corrupt manifest aborts the rollout with the "
            "fleet still serving the old version. 0 skips verification.")
# profiler
define_flag("profiler_dir", "/tmp/paddle_tpu_trace", "Profiler trace dir.")
# data loader
define_flag("reader_queue_size", 2, "Device prefetch depth for DataLoader.")
# distributed
define_flag("dist_heartbeat_interval_s", 10.0, "Heartbeat interval (DCN).")
define_flag("dist_heartbeat_timeout_s", 300.0, "Peer failure timeout.")
# fault tolerance — remote I/O retry (core/retry.py RetryPolicy; the ONE
# retry implementation: io/fs.py remote primitives, checkpoint mirroring,
# and ElasticRunner restart pacing all resolve these defaults)
define_flag("retry_max_attempts", 4,
            "Max attempts (1 = no retry) for remote I/O operations.")
define_flag("retry_backoff_base_s", 0.05,
            "Initial retry backoff in seconds (grows per attempt).")
define_flag("retry_backoff_max_s", 2.0,
            "Cap on a single retry backoff sleep, in seconds.")
define_flag("retry_backoff_multiplier", 2.0,
            "Backoff growth factor between attempts.")
define_flag("retry_jitter", 0.25,
            "Backoff jitter fraction in [0, 1]: each sleep is scaled by a "
            "uniform factor in [1-j, 1+j] to decorrelate retry storms.")
define_flag("retry_deadline_s", 60.0,
            "Overall deadline for one retried operation (<= 0 = none): "
            "give up rather than start a sleep that would cross it.")
# observability (observability/): Trainer step telemetry defaults —
# TelemetryConfig fields left None resolve from these, so a run can be
# instrumented with env vars alone (PT_FLAGS_telemetry=1
# PT_FLAGS_telemetry_run_log=/runs/x/run.jsonl python train.py)
define_flag("telemetry", False,
            "Default-enable Trainer step telemetry (TelemetryConfig "
            "fields left unset resolve from the telemetry_* flags).")
define_flag("telemetry_run_log", "",
            "Default RunLog JSONL path for step telemetry ('' keeps "
            "records in memory only).")
define_flag("telemetry_every_n", 1,
            "Emit a step telemetry record every N steps.")
# live observability plane (observability/exporter.py + watchdog.py):
# a stdlib HTTP server scraping the whole metrics registry in Prometheus
# text exposition, plus serving SLO targets and the anomaly watchdog
define_flag("metrics_port", 0,
            "Serve /metrics (Prometheus text exposition of the metrics "
            "registry) and /healthz on this port while a Trainer or "
            "ServingEngine runs; 0 disables the exporter.")
define_flag("slo_ttft_s", 0.0,
            "Serving SLO: max time-to-first-token in seconds; retired "
            "requests above it count serve.slo_violations{kind=ttft} and "
            "lower serve.goodput. 0 = unbounded.")
define_flag("slo_token_latency_s", 0.0,
            "Serving SLO: max mean per-token decode latency in seconds; "
            "violations count serve.slo_violations{kind=token_latency}. "
            "0 = unbounded.")
define_flag("watchdog", False,
            "Default-enable the runtime anomaly watchdog (slow-step, "
            "ingest-stall, steady-state-retrace, goodput-collapse "
            "detection) in the Trainer and serving loops.")
define_flag("watchdog_window", 64,
            "Rolling window (steps) for the watchdog's step-time median.")
define_flag("watchdog_slow_factor", 3.0,
            "A step slower than slow_factor x the rolling median latches "
            "a slow_step anomaly.")
define_flag("watchdog_stall_s", 1.0,
            "Per-step ingest-channel wait above this latches an "
            "ingest_stall anomaly.")
define_flag("watchdog_goodput_min", 0.5,
            "serve.goodput below this (after enough retired requests) "
            "latches a goodput_collapse anomaly.")
# distributed tracing + flight recorder (observability/trace.py +
# flight.py): fleet-durable trace contexts and the anomaly-triggered
# evidence bundle
define_flag("trace_fleet", True,
            "Mint durable fleet-wide trace contexts at FleetRouter."
            "submit() and carry them across dispatch/failover hops so "
            "one trace id covers a request's whole life; off falls back "
            "to engine-run-scoped ids.")
define_flag("flight_ring", 256,
            "Per-process flight-recorder ring size (recent trace events "
            "+ metric deltas kept in memory for anomaly bundles); 0 "
            "disables recording.")
define_flag("flight_profile_s", 0.0,
            "Seconds of jax.profiler XPlane capture to include in a "
            "flight bundle (0 skips the capture — dumps stay instant).")
define_flag("flight_dir", "/tmp/paddle_tpu_flight",
            "Directory flight-recorder bundles are dumped into (one "
            "timestamped subdir per dump).")
# training guardian (static/guardian.py): in-trace non-finite
# containment, host-side loss-spike detection, and the skip -> re-read ->
# rollback mitigation ladder (GuardianConfig fields left unset resolve
# from these)
define_flag("trainer_rollback_budget", 3,
            "Consecutive checkpoint rollbacks the training guardian may "
            "perform without an intervening healthy checkpoint before it "
            "gives up and re-raises (TrainingDiverged), mirroring "
            "serve_step_retries exhaustion semantics.")
define_flag("trainer_spike_factor", 10.0,
            "A finite loss above spike_factor x the rolling median of "
            "recent healthy losses latches a loss_spike anomaly and "
            "advances the guardian's mitigation ladder.")
define_flag("trainer_ingest_fail_fast", True,
            "Abort the Trainer step loop as soon as an ingest reader "
            "thread dies (the error still raises with full context); "
            "False drains the surviving readers first and raises at "
            "end of stream.")
# checkpoint integrity (io/checkpoint.py): per-leaf crc32 manifests
# written beside each step and checked on restore
define_flag("checkpoint_verify", True,
            "Verify restored checkpoint leaves against the step's crc32 "
            "manifest; a corrupt leaf degrades to a clean mirror re-fetch "
            "or the previous committed step instead of loading garbage.")
# fault tolerance — checkpoint mirroring (io/checkpoint.py): False = a
# mirror push that still fails after retries is logged and queued for the
# next save (training continues on the durable local copy); True = raise
# into the train loop (pre-fault-tolerance behavior)
define_flag("strict_mirror", False,
            "Fail training when a checkpoint remote-mirror push fails "
            "after retries, instead of degrading to queue-and-continue.")
# auto-parallelism (parallel/autoplan): cost-model-driven mesh planning —
# model + topology in, dp x tp x pp mesh + shardings out
define_flag("auto_mesh", False,
            "Treat an unset strategy as strategy='auto' in "
            "fleet.build_mesh / fleet.distributed_optimizer: resolve the "
            "mesh through the autoplan cost-model search (requires a "
            "prior fleet.auto_plan(...) or uses its cached plan).")
define_flag("autoplan_topology", "",
            "Topology preset the autoplan search prices against (e.g. "
            "cpu4, v5e-8, 2xv5e-16); '' auto-detects from jax.devices().")
define_flag("autoplan_hbm_fraction", 0.9,
            "Fraction of per-chip HBM the planner may budget; candidates "
            "whose memory estimate exceeds it are pruned with a recorded "
            "reason.")
define_flag("quant_allreduce", "auto",
            "Data-parallel gradient all-reduce strategy: 'auto' lets the "
            "autoplan cost model choose between the f32 psum and the "
            "chunked int8 quantize->psum->dequant collective per "
            "topology (quantized wins on DCN-bandwidth dp axes, loses "
            "on ICI); 'on' forces quantized, 'off' forces f32.")
define_flag("quant_allreduce_chunk", 65536,
            "Chunk size (elements) of the quantized all-reduce: each "
            "chunk carries one shared f32 scale, so smaller chunks "
            "track gradient dynamic range tighter at 4/chunk bytes of "
            "scale overhead on the wire.")
# Pallas tile autotuner (ops/pallas/autotune.py): sweep candidate block
# sizes on first eager contact with a (kernel, shape, chip) triple, cache
# winners, and feed measured achieved-flops/s into the autoplan cost model
define_flag("autotune", False,
            "Autotune Pallas kernel tile sizes: sweep candidate block "
            "shapes on first eager contact with a (kernel, shape, chip) "
            "triple and reuse the cached winner afterwards; False keeps "
            "the static defaults.")
define_flag("autotune_cache", "/tmp/paddle_tpu_autotune.json",
            "JSON cache file for autotuned tile winners (and the measured "
            "per-tile times the autoplan cost model consumes).")
# fused MLP/GLU block (ops/pallas/mlp.py) — the first kernel built on the
# shared primitive core; used by the GPT/BERT feed-forward
define_flag("use_pallas_mlp", True,
            "Route the transformer feed-forward through the fused Pallas "
            "MLP kernel (never materializes the [rows, intermediate] "
            "activation in HBM); False keeps the unfused XLA composition.")
