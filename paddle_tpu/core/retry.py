"""Retry/backoff policy — THE single retry mechanism of the framework.

Ref: the reference had none — fs.cc shells out to `hadoop fs` once and
propagates whatever the shell returns; checkpoint_notify_op.cc fires one
RPC per pserver and PSLib workers just sleep through restarts
(fleet_wrapper.h:60). Production object stores and preemptible pods make
every remote I/O edge a transient-failure surface, so retry semantics are
centralized here: exponential backoff + full jitter + an overall deadline
+ a retryable-exception predicate, all flag-configurable (core/flags.py
``retry_*``). Consumers (io/fs.py remote primitives, checkpoint
mirroring, ElasticRunner restart pacing) never hand-roll sleep loops —
they construct a `RetryPolicy` (or take `default_policy()`) so chaos
tests can tune one knob set and reason about one behavior.

    from paddle_tpu.core.retry import RetryPolicy, retrying

    policy = RetryPolicy(max_attempts=5, deadline_s=30.0)
    data = policy.call(read_remote_blob, url)

    @retrying()                      # defaults from flags, read per call
    def push(blob): ...
"""

import random as _random
import time

from paddle_tpu.core import flags as F
from paddle_tpu.observability import metrics as _metrics


def _op_name(fn):
    return getattr(fn, "__name__", None) or type(fn).__name__


def default_retryable(exc):
    """Transient-looking I/O failures retry; semantic misses never do.

    FileNotFoundError & friends are answers, not hiccups — retrying them
    only turns a clear error into a slow one (and breaks callers that
    branch on existence)."""
    if isinstance(exc, (FileNotFoundError, NotADirectoryError,
                        IsADirectoryError, PermissionError)):
        return False
    return isinstance(exc, (OSError, ConnectionError, TimeoutError))


class RetryPolicy:
    """Exponential backoff + jitter + deadline around a callable.

    Unset parameters resolve from the ``retry_*`` flags at construction,
    so per-run tuning (PT_FLAGS_retry_max_attempts=1 to fail fast in a
    debug session) needs no code changes. `sleep`/`rng`/`clock` are
    injectable for deterministic tests.
    """

    def __init__(self, max_attempts=None, backoff_base_s=None,
                 backoff_max_s=None, backoff_multiplier=None, jitter=None,
                 deadline_s=None, retryable=None, sleep=None, rng=None,
                 clock=None, on_retry=None):
        def _f(v, name):
            return F.get_flag(name) if v is None else v
        self.max_attempts = max(1, int(_f(max_attempts,
                                          "retry_max_attempts")))
        self.backoff_base_s = float(_f(backoff_base_s,
                                       "retry_backoff_base_s"))
        self.backoff_max_s = float(_f(backoff_max_s, "retry_backoff_max_s"))
        self.backoff_multiplier = float(_f(backoff_multiplier,
                                           "retry_backoff_multiplier"))
        self.jitter = float(_f(jitter, "retry_jitter"))
        self.deadline_s = float(_f(deadline_s, "retry_deadline_s"))
        self.retryable = retryable or default_retryable
        self.on_retry = on_retry          # (attempt, exc, sleep_s) -> None
        self._sleep = sleep or time.sleep
        self._rng = rng or _random
        self._clock = clock or time.monotonic

    def backoff_s(self, attempt):
        """Sleep before retry number `attempt` (1-based failure count)."""
        b = min(self.backoff_max_s,
                self.backoff_base_s
                * self.backoff_multiplier ** max(0, attempt - 1))
        if self.jitter:
            b *= 1.0 + self.jitter * (2.0 * self._rng.random() - 1.0)
        return max(0.0, b)

    def call(self, fn, *args, **kwargs):
        """Run fn(*args, **kwargs), retrying retryable failures. The last
        exception is re-raised as itself (not wrapped) so upstream
        except-clauses keep working.

        Every retryable failure increments `retry.attempts{op=...}` in
        the metrics registry, and exhaustion (attempts or deadline)
        increments `retry.giveups{op=...}` — a run report can say how
        flaky the remote edges were without log archaeology."""
        start = self._clock()
        failures = 0
        op = _op_name(fn)
        while True:
            try:
                return fn(*args, **kwargs)
            except Exception as e:
                failures += 1
                if not self.retryable(e):
                    raise
                _metrics.counter("retry.attempts").inc(op=op)
                if failures >= self.max_attempts:
                    _metrics.counter("retry.giveups").inc(op=op)
                    raise
                delay = self.backoff_s(failures)
                if (self.deadline_s > 0
                        and self._clock() - start + delay > self.deadline_s):
                    _metrics.counter("retry.giveups").inc(op=op)
                    raise
                if self.on_retry is not None:
                    self.on_retry(failures, e, delay)
                self._sleep(delay)

    def wrap(self, fn):
        """Decorator form of `call` (bound to this policy instance)."""
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)
        return wrapped


class RetryBudget:
    """Consecutive-failure budget over a RetryPolicy, for loop-shaped
    consumers (the serving engine's step recovery) where one logical
    operation spans many calls: `failure()` counts a failure, sleeps the
    policy's backoff, and re-raises once the policy's attempt budget is
    spent; `success()` resets the streak. Shares the policy's
    `retry.attempts`/`retry.giveups` metric accounting."""

    def __init__(self, policy, op):
        self.policy = policy
        self.op = op
        self.failures = 0

    def success(self):
        self.failures = 0

    def failure(self, exc):
        """Record one failure: sleep the backoff and return the streak
        length, or re-raise `exc` once max_attempts is reached."""
        self.failures += 1
        _metrics.counter("retry.attempts").inc(op=self.op)
        if self.failures >= self.policy.max_attempts:
            _metrics.counter("retry.giveups").inc(op=self.op)
            raise exc
        self.policy._sleep(self.policy.backoff_s(self.failures))
        return self.failures


def default_policy(**overrides):
    """A policy from the current ``retry_*`` flags (fresh each call so
    `set_flags` between operations takes effect)."""
    return RetryPolicy(**overrides)


def retrying(policy=None, **policy_kwargs):
    """Decorator: `@retrying()` retries with flag defaults resolved at
    each call; `@retrying(policy)` pins an explicit policy."""
    import functools

    def deco(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            p = policy or RetryPolicy(**policy_kwargs)
            return p.call(fn, *args, **kwargs)
        return wrapped
    return deco
