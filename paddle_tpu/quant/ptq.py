"""Post-training quantization + freeze/export.

Ref: /root/reference/python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:587 QuantizationFreezePass (fold fake-quant into real
int8 weights + dequant), :846 area ConvertToInt8Pass, and
mkldnn_post_training_strategy.py (calibration-based PTQ); also
contrib/quantize/quantize_transpiler.py (program-rewrite flavour).

TPU-first pipeline:
    qmodel = qat.quantize_model(model, cfg)          # swap layers
    variables = qat.upgrade_variables(qmodel, variables, key)
    variables = ptq.calibrate(qmodel, variables, batches)   # act scales
    variables = ptq.freeze(qmodel, variables)        # bake weight quant
    int8_tree = ptq.export_int8(qmodel, variables)   # serving payload
"""

import jax
import jax.numpy as jnp

from paddle_tpu.quant import ops as Q
from paddle_tpu.quant.qat import (QuantConfig, QuantizedConv2D,
                                  QuantizedLinear)


def _quantized_leaves(model, path=()):
    """Yield (path, module) for every quantized layer in the tree,
    including the root itself (quantize_model may swap the root)."""
    if path == () and isinstance(model, (QuantizedLinear, QuantizedConv2D)):
        yield (), model
    for name, child in model._children.items():
        p = path + (name,)
        if isinstance(child, (QuantizedLinear, QuantizedConv2D)):
            yield p, child
        yield from _quantized_leaves(child, p)


def calibrate(qmodel, variables, batches, apply_kwargs=None):
    """Run calibration forwards so moving-average activation scales settle.

    Ref: mkldnn_post_training_strategy.py — the reference feeds a calibration
    dataset and collects per-tensor scales; here the quantizer state IS the
    scale store. Runs in `calibrating` mode: Dropout/BatchNorm keep their
    eval behavior (no noise, running stats untouched) while quantizer scale
    states update; apply always returns (out, new_state) in this mode.
    """
    apply_kwargs = apply_kwargs or {}
    for batch in batches:
        args = batch if isinstance(batch, (list, tuple)) else (batch,)
        _, new_state = qmodel.apply(variables, *args, calibrating=True,
                                    **apply_kwargs)
        variables = {"params": variables["params"], "state": new_state}
    return variables


def freeze(qmodel, variables):
    """Bake weight fake-quantization into the stored float weights so eval
    no longer re-quantizes stochastically-trained values.

    Ref: quantization_pass.py:628 QuantizationFreezePass.apply.

    Functional: returns a new variables tree; the input is not mutated.
    """
    def set_path(node, path, fn):
        node = dict(node)
        if len(path) == 1:
            node[path[0]] = fn(node[path[0]])
        else:
            node[path[0]] = set_path(node[path[0]], path[1:], fn)
        return node

    params = variables["params"]
    for path, mod in _quantized_leaves(qmodel):
        cfg = mod.quant_cfg
        axis = (mod.CHANNEL_AXIS
                if cfg.weight_quantize_type == "channel_wise_abs_max"
                else None)

        def bake(leaf, axis=axis, bits=cfg.weight_bits):
            leaf = dict(leaf)
            if "weight" in leaf:
                w = leaf["weight"]
                scale = Q.abs_max_scale(w, axis)
                leaf["weight"] = Q.dequantize_from_int(
                    Q.quantize_to_int(w, scale, bits, axis),
                    scale, bits, axis).astype(w.dtype)
            return leaf

        try:
            params = bake(params) if path == () else \
                set_path(params, path, bake)
        except KeyError:
            continue
    return {"params": params, "state": variables.get("state", {})}


def export_int8(qmodel, variables):
    """Produce the serving payload: int8 weights + scales per quantized
    layer, plus activation scales (ref: ConvertToInt8Pass + the scale
    outputs the freeze pass leaves for the inference engine)."""
    out = {}
    params, state = variables["params"], variables.get("state", {})
    for path, mod in _quantized_leaves(qmodel):
        node, snode = params, state
        for k in path:
            node = node.get(k, {}) if isinstance(node, dict) else {}
        for k in path + ("input_quant",):
            snode = snode.get(k, {}) if isinstance(snode, dict) else {}
        if "weight" not in node:
            continue
        cfg = mod.quant_cfg
        axis = (mod.CHANNEL_AXIS
                if cfg.weight_quantize_type == "channel_wise_abs_max"
                else None)
        w = node["weight"]
        scale = Q.abs_max_scale(w, axis)
        entry = {
            "weight_int8": Q.quantize_to_int(w, scale, cfg.weight_bits, axis),
            "weight_scale": scale,
            "weight_bits": cfg.weight_bits,
            "channel_axis": axis,
        }
        if "bias" in node:
            entry["bias"] = node["bias"]
        if isinstance(snode, dict) and "scale" in snode:
            entry["act_scale"] = snode["scale"]
            entry["act_bits"] = cfg.activation_bits
        out["/".join(path)] = entry
    return out


def int8_linear(x, entry):
    """Reference int8 serving kernel: dequantized-weight matmul. On TPU the
    int8 weights ride HBM at 1/4 bandwidth and dequant fuses into the matmul
    prologue (XLA handles the convert); true int8 MXU matmul arrives with
    AQT-style lowering later."""
    w = Q.dequantize_from_int(entry["weight_int8"], entry["weight_scale"],
                              entry["weight_bits"], entry["channel_axis"])
    y = jnp.asarray(x) @ w
    if "bias" in entry:
        y = y + entry["bias"]
    return y
