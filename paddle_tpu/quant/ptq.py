"""Post-training quantization + freeze/export.

Ref: /root/reference/python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:587 QuantizationFreezePass (fold fake-quant into real
int8 weights + dequant), :846 area ConvertToInt8Pass, and
mkldnn_post_training_strategy.py (calibration-based PTQ); also
contrib/quantize/quantize_transpiler.py (program-rewrite flavour).

TPU-first pipeline:
    qmodel = qat.quantize_model(model, cfg)          # swap layers
    variables = qat.upgrade_variables(qmodel, variables, key)
    variables = ptq.calibrate(qmodel, variables, batches)   # act scales
    variables = ptq.freeze(qmodel, variables)        # bake weight quant
    int8_tree = ptq.export_int8(qmodel, variables)   # serving payload
"""

import jax
import jax.numpy as jnp

from paddle_tpu.quant import ops as Q
from paddle_tpu.quant.qat import (QuantConfig, QuantizedConv2D,
                                  QuantizedLinear)


def _quantized_leaves(model, path=()):
    """Yield (path, module) for every quantized layer in the tree,
    including the root itself (quantize_model may swap the root)."""
    if path == () and isinstance(model, (QuantizedLinear, QuantizedConv2D)):
        yield (), model
    for name, child in model._children.items():
        p = path + (name,)
        if isinstance(child, (QuantizedLinear, QuantizedConv2D)):
            yield p, child
        yield from _quantized_leaves(child, p)


def calibrate(qmodel, variables, batches, apply_kwargs=None):
    """Run calibration forwards so moving-average activation scales settle.

    Ref: mkldnn_post_training_strategy.py — the reference feeds a calibration
    dataset and collects per-tensor scales; here the quantizer state IS the
    scale store. Runs in `calibrating` mode: Dropout/BatchNorm keep their
    eval behavior (no noise, running stats untouched) while quantizer scale
    states update; apply always returns (out, new_state) in this mode.
    """
    apply_kwargs = apply_kwargs or {}
    for batch in batches:
        args = batch if isinstance(batch, (list, tuple)) else (batch,)
        _, new_state = qmodel.apply(variables, *args, calibrating=True,
                                    **apply_kwargs)
        variables = {"params": variables["params"], "state": new_state}
    return variables


def freeze(qmodel, variables):
    """Bake weight fake-quantization into the stored float weights so eval
    no longer re-quantizes stochastically-trained values.

    Ref: quantization_pass.py:628 QuantizationFreezePass.apply.

    Functional: returns a new variables tree; the input is not mutated.
    """
    def set_path(node, path, fn):
        node = dict(node)
        if len(path) == 1:
            node[path[0]] = fn(node[path[0]])
        else:
            node[path[0]] = set_path(node[path[0]], path[1:], fn)
        return node

    params = variables["params"]
    for path, mod in _quantized_leaves(qmodel):
        cfg = mod.quant_cfg
        axis = (mod.CHANNEL_AXIS
                if cfg.weight_quantize_type == "channel_wise_abs_max"
                else None)

        def bake(leaf, axis=axis, bits=cfg.weight_bits):
            leaf = dict(leaf)
            if "weight" in leaf:
                w = leaf["weight"]
                scale = Q.abs_max_scale(w, axis)
                leaf["weight"] = Q.dequantize_from_int(
                    Q.quantize_to_int(w, scale, bits, axis),
                    scale, bits, axis).astype(w.dtype)
            return leaf

        try:
            params = bake(params) if path == () else \
                set_path(params, path, bake)
        except KeyError:
            continue
    return {"params": params, "state": variables.get("state", {})}


def export_int8(qmodel, variables):
    """Produce the serving payload: int8 weights + scales per quantized
    layer, plus activation scales (ref: ConvertToInt8Pass + the scale
    outputs the freeze pass leaves for the inference engine)."""
    out = {}
    params, state = variables["params"], variables.get("state", {})
    for path, mod in _quantized_leaves(qmodel):
        node, snode = params, state
        for k in path:
            node = node.get(k, {}) if isinstance(node, dict) else {}
        for k in path + ("input_quant",):
            snode = snode.get(k, {}) if isinstance(snode, dict) else {}
        if "weight" not in node:
            continue
        cfg = mod.quant_cfg
        axis = (mod.CHANNEL_AXIS
                if cfg.weight_quantize_type == "channel_wise_abs_max"
                else None)
        w = node["weight"]
        scale = Q.abs_max_scale(w, axis)
        entry = {
            "weight_int8": Q.quantize_to_int(w, scale, cfg.weight_bits, axis),
            "weight_scale": scale,
            "weight_bits": cfg.weight_bits,
            "channel_axis": axis,
        }
        if "bias" in node:
            entry["bias"] = node["bias"]
        if isinstance(snode, dict) and "scale" in snode:
            entry["act_scale"] = snode["scale"]
            entry["act_bits"] = cfg.activation_bits
        out["/".join(path)] = entry
    return out


def int8_linear(x, entry):
    """Reference int8 serving kernel: dequantized-weight matmul. On TPU the
    int8 weights ride HBM at 1/4 bandwidth and dequant fuses into the matmul
    prologue (XLA handles the convert); true int8 MXU matmul arrives with
    AQT-style lowering later."""
    w = Q.dequantize_from_int(entry["weight_int8"], entry["weight_scale"],
                              entry["weight_bits"], entry["channel_axis"])
    y = jnp.asarray(x) @ w
    if "bias" in entry:
        y = y + entry["bias"]
    return y


def save_int8_inference_model(path, qmodel, variables, example_args,
                              apply_kwargs=None, float_model=None):
    """Export an int8 serving artifact for the C++ predictor.

    Ref: the reference's int8 serve path — QuantizationFreezePass +
    ConvertToInt8Pass write int8 weights into the inference ProgramDesc
    (slim/quantization/quantization_pass.py:628,:764) consumed by the C++
    engine. Here: quantized layers' weights are stored as REAL int8 tensors
    in params.bin (4x smaller, 1/4 HBM bandwidth at serve time); the
    exported program dequantizes them inline, which XLA fuses into the
    consuming matmul/conv prologue. Non-quantized params stay float.

    Serve-time compute runs the FLOAT architecture over the dequantized
    weights (pass `float_model`, the unquantized twin of qmodel): this
    matches freeze()'s numerics exactly. Running qmodel itself would
    re-fake-quantize the already-dequantized weights with re-derived
    scales — a second, different rounding. Without float_model, qmodel is
    used (with that caveat).

    Returns the artifact path (same layout as io.save_inference_model, so
    csrc/predictor serves it unchanged).
    """
    from paddle_tpu.io.inference import save_inference_model

    serve_model = float_model if float_model is not None else qmodel

    entries = export_int8(qmodel, variables)
    params = variables["params"]
    state = variables.get("state", {})
    apply_kwargs = dict(apply_kwargs or {})

    # split: int8 payload + float remainder (quantized weights removed)
    def strip(node, path=()):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            p = path + (k,)
            if "/".join(p[:-1]) in entries and k == "weight":
                continue  # replaced by int8 payload
            out[k] = strip(v, p)
        return out

    mixed = {
        "float": strip(params),
        "int8": {name: {"w": e["weight_int8"], "s": e["weight_scale"]}
                 for name, e in entries.items()},
    }
    meta = {name: {"bits": e["weight_bits"], "axis": e["channel_axis"]}
            for name, e in entries.items()}

    def rebuild(mixed_params):
        params = jax.tree_util.tree_map(lambda x: x, mixed_params["float"])
        for name, payload in mixed_params["int8"].items():
            keys = (tuple(name.split("/")) if name else ()) + ("weight",)
            w = Q.dequantize_from_int(payload["w"], payload["s"],
                                      meta[name]["bits"],
                                      meta[name]["axis"])
            node = params
            for k in keys[:-1]:
                node = node[k]
            node[keys[-1]] = w
        return params

    def fwd(mixed_params, *inputs):
        p = rebuild(mixed_params)
        # full state either way: the float model reads what it needs (BN
        # stats) and ignores the quantizer subtrees
        return serve_model.apply({"params": p, "state": state},
                                 *inputs, **apply_kwargs)

    return save_inference_model(path, fwd, example_args, mixed)
