"""Quantization-aware training — the QuantizationTransformPass successor.

Ref: /root/reference/python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:58 QuantizationTransformPass — rewrites the graph,
inserting fake quant/dequant before every quantizable op (conv2d, mul/fc...),
with configurable weight/activation quantize types and bit widths.

TPU-first: instead of graph surgery, `quantize_model` swaps Linear/Conv2D
modules in the layer tree for quantized subclasses that fake-quant their
weights and input activations in forward. Scales for the moving-average
activation quantizer live in the module state tree (the functional analogue
of the reference's scale Variables) and update during training forwards.
"""

import dataclasses

import jax
import jax.numpy as jnp

from paddle_tpu.core.enforce import enforce
from paddle_tpu.nn import layers as L
from paddle_tpu.nn.module import Module
from paddle_tpu.quant import ops as Q

WEIGHT_QUANT_TYPES = ("abs_max", "channel_wise_abs_max")
ACT_QUANT_TYPES = ("abs_max", "moving_average_abs_max", "range_abs_max")


@dataclasses.dataclass
class QuantConfig:
    """Ref: QuantizationTransformPass ctor args (quantization_pass.py:59-146):
    weight_bits, activation_bits, activation_quantize_type,
    weight_quantize_type, window_size, moving_rate."""
    weight_bits: int = 8
    activation_bits: int = 8
    weight_quantize_type: str = "channel_wise_abs_max"
    activation_quantize_type: str = "moving_average_abs_max"
    moving_rate: float = 0.9
    window_size: int = 10000

    def __post_init__(self):
        enforce(self.weight_quantize_type in WEIGHT_QUANT_TYPES,
                "unknown weight_quantize_type %s", self.weight_quantize_type)
        enforce(self.activation_quantize_type in ACT_QUANT_TYPES,
                "unknown activation_quantize_type %s",
                self.activation_quantize_type)


class _ActQuant(Module):
    """Input-activation fake quantizer with stateful scale."""

    def __init__(self, cfg: QuantConfig):
        super().__init__()
        self.cfg = cfg
        if cfg.activation_quantize_type != "abs_max":
            self.state("scale", (), lambda k, s, d: jnp.ones(s, d))
            self.state("step", (), lambda k, s, d: jnp.zeros(s, d),
                       dtype=jnp.int32)

    def forward(self, x):
        cfg = self.cfg
        if cfg.activation_quantize_type == "abs_max":
            return Q.fake_quant_abs_max(x, cfg.activation_bits)
        prev = self.s("scale")
        if self.training or self.calibrating:
            step = self.s("step")
            if cfg.activation_quantize_type == "moving_average_abs_max":
                # seed from the first observed batch instead of the 1.0 init
                scale = jnp.where(
                    step == 0, Q.abs_max_scale(x),
                    Q.moving_average_scale(prev, x, cfg.moving_rate))
            else:  # range_abs_max
                scale = Q.range_abs_max_scale(prev, x, step, cfg.window_size)
            self.update_state("scale", scale)
            self.update_state("step", self.s("step") + 1)
        else:
            scale = prev
        return Q.fake_quant_dequant(x, jax.lax.stop_gradient(scale),
                                    cfg.activation_bits)


def _quant_weight(w, cfg: QuantConfig, channel_axis):
    if cfg.weight_quantize_type == "channel_wise_abs_max":
        return Q.fake_quant_abs_max(w, cfg.weight_bits, channel_axis)
    return Q.fake_quant_abs_max(w, cfg.weight_bits)


def _clone_as_quantized(cls, m, cfg):
    """Rebuild a float layer as its quantized subclass: same attribute dict,
    same param/state/child specs, plus an input-activation quantizer."""
    q = cls.__new__(cls)
    Module.__init__(q)
    q.__dict__.update({k: v for k, v in m.__dict__.items()
                       if k not in ("_params", "_state", "_children")})
    q._params.update(m._params)
    q._state.update(m._state)
    q._children.update(m._children)
    q.quant_cfg = cfg
    q.input_quant = _ActQuant(cfg)
    return q


class QuantizedLinear(L.Linear):
    """Linear with fake-quantized weight + input (ref: 'mul'/'fc' in
    _quantizable_op_type, quantization_pass.py:58 area).

    Weight layout (in, out) → channel axis 1 (per-output-channel, matching
    the reference's channel-wise scheme on the output dim).
    """
    CHANNEL_AXIS = 1

    @classmethod
    def from_float(cls, m: L.Linear, cfg: QuantConfig):
        return _clone_as_quantized(cls, m, cfg)

    def forward(self, x):
        w = _quant_weight(self.p("weight"), self.quant_cfg, self.CHANNEL_AXIS)
        x = self.input_quant(x)
        y = x @ w
        if self.has_bias:
            y = y + self.p("bias")
        return L._act(self.act, y)


class QuantizedConv2D(L.Conv2D):
    """Conv2D with fake-quantized weight + input; weight layout (O,I,H,W) →
    channel axis 0 (ref: _insert_channel_quant_op quantizes conv filters
    per output channel, quantization_pass.py:485)."""
    CHANNEL_AXIS = 0

    @classmethod
    def from_float(cls, m: L.Conv2D, cfg: QuantConfig):
        return _clone_as_quantized(cls, m, cfg)

    def forward(self, x):
        from paddle_tpu.ops import nn as opsnn
        w = _quant_weight(self.p("weight"), self.quant_cfg, self.CHANNEL_AXIS)
        x = self.input_quant(x)
        y = opsnn.conv2d(x, w, self.p("bias") if self.has_bias else None,
                         self.stride, self.padding, self.dilation,
                         self.groups)
        return L._act(self.act, y)


_SWAP = {L.Conv2D: QuantizedConv2D, L.Linear: QuantizedLinear}


def quantize_model(model: Module, config: QuantConfig = None) -> Module:
    """Swap quantizable layers for quantized versions, in place on the layer
    tree (the layer tree is a spec, not trained state — parameters live in
    the variables pytree, whose param structure this preserves; it only adds
    `input_quant` state entries).

    Ref: QuantizationTransformPass.apply (quantization_pass.py:147).
    """
    config = config or QuantConfig()
    root_cls = _SWAP.get(type(model))
    if root_cls is not None:
        return root_cls.from_float(model, config)
    for name, child in list(model._children.items()):
        cls = _SWAP.get(type(child))
        if cls is not None:
            qchild = cls.from_float(child, config)
            model._children[name] = qchild
            if getattr(model, name, None) is child:
                object.__setattr__(model, name, qchild)
            items = getattr(model, "_items", None)
            if items is not None:
                for i, it in enumerate(items):
                    if it is child:
                        items[i] = qchild
        else:
            quantize_model(child, config)
    return model


def upgrade_variables(qmodel: Module, variables, key):
    """Merge trained float variables into a freshly-inited quantized tree
    (adds the new quantizer state entries, keeps every trained value)."""
    fresh = qmodel.init(key)

    def merge(old, new):
        if isinstance(new, dict):
            return {k: merge(old.get(k), new[k]) if isinstance(old, dict)
                    else new[k] for k in new}
        return new if old is None else old

    return {"params": merge(variables.get("params", {}), fresh["params"]),
            "state": merge(variables.get("state", {}), fresh["state"])}
