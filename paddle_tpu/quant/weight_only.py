"""Weight-only int8 serving transform.

Ref: the reference's int8 serve pipeline (slim/quantization/
quantization_pass.py:628 QuantizationFreezePass + :764 ConvertToInt8Pass)
rewrites the inference ProgramDesc so conv/mul read real int8 weights.
TPU-first form: a *params-pytree transform* — every nn.Linear kernel and
nn.Embedding table is replaced in place by

    {"weight_q": int8, "weight_scale": f32[channels]}

and the layers consume them directly (nn/layers.py Linear/Embedding, the
GPT tied head): the int8 tensor stays resident in HBM and feeds a
mixed-dtype `lax.dot_general` (or a gathered-row dequant for lookups),
so weight HBM traffic drops 2x vs bf16 / 4x vs f32 — the lever for
weight-bandwidth-bound serving (KV-cache decode reads every parameter
once per token; see bench.py gpt_decode).

Scale axes are chosen so the dequant is algebraically EXACT on the
consuming contraction (no fake-quant round trip at serve time):
  * Linear [in, out]  -> per-out-column scale: x@(q*s) == (x@q)*s
  * MultiHeadAttention wq/wk/wv/wo [E, E] -> per-out-column (same rule;
    the decode_step projections consume them int8-resident, the full
    forward dequantizes once per call)
  * Embedding [vocab, dim] -> per-row scale: works for both the lookup
    (rows[ids]*s[ids]) and the weight-tied head (x@(q*s[:,None]).T ==
    (x@q.T)*s[None,:]) — one table serves both consumers.

Quantization error is the usual symmetric-int8 rounding on the weights
only (activations stay bf16/f32); per-channel abs-max keeps it ~1e-2
relative, the same contract as the reference's channel_wise_abs_max.
"""

import jax.numpy as jnp

from paddle_tpu.nn import layers as L
from paddle_tpu.quant import ops as Q

__all__ = ["quantize_weights_int8"]


def _q8(w, axis):
    scale = Q.abs_max_scale(w, axis)
    q = Q.quantize_to_int(w, scale, 8, axis)
    # stored scale is the DEQUANT step (abs_max / 127): w ~= q * s, so the
    # consuming layers multiply by s alone. Scale keeps the ORIGINAL
    # weight dtype — it defines the dequantized output dtype, and a bf16
    # model must not silently upcast its activation path to f32 (scale
    # rounding in bf16 is far below the int8 step it multiplies).
    return q, (scale / Q.qrange(8)).astype(w.dtype)


def _module_paths(model, path=()):
    yield path, model
    for name, child in model._children.items():
        yield from _module_paths(child, path + (name,))


def quantize_weights_int8(model, params, include_embeddings=True,
                          min_size=4096):
    """Return a new params pytree with every Linear kernel (and, when
    include_embeddings, every Embedding table) replaced by int8 payload
    {"weight_q", "weight_scale"}. Leaves smaller than min_size elements
    stay float (their bandwidth does not matter and tiny layers lose the
    most accuracy). Biases, norms, and everything else pass through
    untouched. The returned tree serves directly through model.apply —
    no architecture changes, no recompile of the float path."""
    # per-module map: param name -> quantization channel axis. Exact types
    # only: subclasses (FC, QuantizedLinear) override forward() with
    # p("weight") reads that do not understand the int8 layout.
    targets = {}
    for path, mod in _module_paths(model):
        if type(mod) is L.Linear:
            targets[path] = {"weight": 1}   # [in, out] -> per-out-column
        elif type(mod) is L.MultiHeadAttention:
            # the four projection kernels [E, E] — a third of a
            # transformer block's weight bytes, read every decode step
            targets[path] = {f"w{n}": 1 for n in ("q", "k", "v", "o")}
        elif include_embeddings and type(mod) is L.Embedding:
            targets[path] = {"weight": 0}   # [vocab, dim] -> per-row

    def walk(node, path=()):
        if not isinstance(node, dict):
            return node
        out = {}
        for k, v in node.items():
            p = path + (k,)
            axis = targets.get(path, {}).get(k)
            if (axis is not None and hasattr(v, "size")
                    and v.size >= min_size and getattr(v, "ndim", 0) == 2):
                q, s = _q8(v, axis)
                out[f"{k}_q"] = q
                out[f"{k}_scale"] = s
            else:
                out[k] = walk(v, p)
        return out

    return walk(params)
