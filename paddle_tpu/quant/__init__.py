"""Quantization — QAT + PTQ + int8 export (the contrib/slim successor).

Ref: /root/reference/python/paddle/fluid/contrib/slim/quantization/ and
contrib/quantize/quantize_transpiler.py.
"""

from paddle_tpu.quant import ops, ptq, qat
from paddle_tpu.quant.ops import (abs_max_scale, dequantize_from_int,
                                  fake_quant_abs_max, fake_quant_dequant,
                                  moving_average_scale, quantize_to_int,
                                  range_abs_max_scale)
from paddle_tpu.quant.ptq import (calibrate, export_int8, freeze,
                                  int8_linear,
                                  save_int8_inference_model)
from paddle_tpu.quant.qat import (QuantConfig, QuantizedConv2D,
                                  QuantizedLinear, quantize_model,
                                  upgrade_variables)
from paddle_tpu.quant.weight_only import quantize_weights_int8

__all__ = [
    "ops", "ptq", "qat", "quantize_weights_int8", "QuantConfig", "QuantizedConv2D", "QuantizedLinear",
    "quantize_model", "upgrade_variables", "calibrate", "export_int8",
    "freeze", "int8_linear", "save_int8_inference_model",
    "fake_quant_abs_max", "fake_quant_dequant",
    "abs_max_scale", "moving_average_scale", "range_abs_max_scale",
    "quantize_to_int", "dequantize_from_int",
]
