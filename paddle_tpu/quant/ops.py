"""Fake-quantization primitives with straight-through gradients.

Ref: /root/reference/python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py — the reference inserts `fake_quantize_abs_max`,
`fake_quantize_range_abs_max`, `fake_quantize_moving_average_abs_max` and
`fake_channel_wise_quantize_abs_max` graph ops before quantizable ops
(:284-513) and pairs them with dequant ops (:515-566); backward is the
straight-through estimator (gradient flows to the float input, :207
_transform_backward).

TPU-first: fake quant/dequant is a single fused elementwise op under one
`jax.custom_vjp` — XLA fuses it into the surrounding matmul/conv epilogue,
so QAT costs ~nothing extra on the MXU. Scales are explicit values (pytree
state), not graph variables.
"""

import functools

import jax
import jax.numpy as jnp


def qrange(bits):
    """Symmetric signed range: [-bound, bound] with bound = 2^(bits-1) - 1."""
    return float(2 ** (bits - 1) - 1)


def abs_max_scale(x, channel_axis=None):
    """Per-tensor (or per-channel) abs-max scale.

    Ref: quantization_pass.py:297 _insert_quant_abs_max_op (per-tensor) and
    :485 _insert_channel_quant_op (per-output-channel for conv weights).
    """
    x = jnp.asarray(x)
    if channel_axis is None:
        return jnp.max(jnp.abs(x))
    axes = tuple(i for i in range(x.ndim) if i != channel_axis)
    return jnp.max(jnp.abs(x), axis=axes)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fake_quant_dequant(x, scale, bits=8, channel_axis=None):
    """Simulate int quantization: round(x / step) * step, clipped to range.

    Straight-through backward: dy passes to x where |x| <= scale, else 0
    (the saturating-STE used by the reference's fake_quantize grad kernels).
    """
    y, _ = _fqdq_fwd(x, scale, bits, channel_axis)
    return y


def _broadcast_scale(scale, x, channel_axis):
    scale = jnp.asarray(scale)
    if channel_axis is None or scale.ndim == 0:
        return scale
    shape = [1] * x.ndim
    shape[channel_axis] = scale.shape[0]
    return scale.reshape(shape)


def _fqdq_fwd(x, scale, bits, channel_axis):
    bound = qrange(bits)
    scale = jnp.asarray(scale)
    s = _broadcast_scale(scale, x, channel_axis)
    s = jnp.maximum(s, 1e-8)
    step = s / bound
    q = jnp.clip(jnp.round(x / step), -bound, bound)
    y = q * step
    mask = (jnp.abs(x) <= s).astype(x.dtype)
    return y, (mask, scale)


def _fqdq_bwd(bits, channel_axis, res, dy):
    mask, scale = res
    return dy * mask, jnp.zeros_like(scale)  # no gradient to the scale


fake_quant_dequant.defvjp(_fqdq_fwd, _fqdq_bwd)


def fake_quant_abs_max(x, bits=8, channel_axis=None):
    """Dynamic abs-max fake quant (scale recomputed from the live tensor).

    Ref: quantization_pass.py:297 — 'abs_max' quantize type.
    """
    scale = jax.lax.stop_gradient(abs_max_scale(x, channel_axis))
    return fake_quant_dequant(x, scale, bits, channel_axis)


def moving_average_scale(prev_scale, x, rate=0.9):
    """state' = rate*state + (1-rate)*abs_max(x); returns the new scale.

    Ref: quantization_pass.py:398 _insert_quant_moving_average_abs_max_op
    (accum/state variables with moving_rate, default 0.9).
    """
    cur = abs_max_scale(x)
    return rate * prev_scale + (1.0 - rate) * cur


def range_abs_max_scale(prev_scale, x, step, window_size=10000):
    """Windowed running max: reset at window boundaries, else running max.

    Ref: quantization_pass.py:327 _insert_quant_range_abs_max_op
    (window_size attr, scales buffer; here collapsed to the effective
    running-max-within-window recurrence).
    """
    cur = abs_max_scale(x)
    at_boundary = (step % window_size) == 0
    return jnp.where(at_boundary, cur, jnp.maximum(prev_scale, cur))


def quantize_to_int(x, scale, bits=8, channel_axis=None):
    """Real quantization to integers (for freeze/export, not training).

    Ref: quantization_pass.py:628 QuantizationFreezePass.apply — weights
    are converted to round(w / step) int8 at freeze time.
    """
    bound = qrange(bits)
    s = _broadcast_scale(jnp.maximum(jnp.asarray(scale), 1e-8), x,
                         channel_axis)
    q = jnp.clip(jnp.round(x * (bound / s)), -bound, bound)
    return q.astype(jnp.int8 if bits <= 8 else jnp.int32)


def dequantize_from_int(q, scale, bits=8, channel_axis=None):
    """Inverse of quantize_to_int (ref: :515 _insert_dequant_op)."""
    bound = qrange(bits)
    q = jnp.asarray(q).astype(jnp.float32)
    s = _broadcast_scale(jnp.asarray(scale), q, channel_axis)
    return q * (s / bound)
