"""Knowledge distillation losses.

Ref: /root/reference/python/paddle/fluid/contrib/slim/distillation/
distiller.py — L2Distiller (:25, mean-square between student/teacher
feature maps), FSPDistiller (:108, L2 between FSP matrices of layer pairs,
_fsp_matrix :191), SoftLabelDistiller (:195, cross entropy between
temperature-softened teacher and student logits).

TPU-first: the reference implements these as graph-merge passes over two
ProgramDescs; here teacher and student are plain functions, so a distiller
is a loss term — compose into the student's loss_fn and jit the whole
thing (teacher forward under stop_gradient).
"""

import jax
import jax.numpy as jnp

from paddle_tpu.ops.nn import fsp_matrix


def l2_loss(student_feat, teacher_feat, weight=1.0):
    """ref distiller.py L2Distiller: mean((s - t)^2) * weight."""
    t = jax.lax.stop_gradient(teacher_feat)
    return weight * jnp.mean(jnp.square(student_feat - t))


def fsp_loss(student_pair, teacher_pair, weight=1.0):
    """ref distiller.py FSPDistiller: L2 between the FSP matrices of a
    (near, far) feature-map pair from each net. Each pair: ([B,C1,H,W],
    [B,C2,H,W])."""
    s = fsp_matrix(*student_pair)
    t = jax.lax.stop_gradient(fsp_matrix(*teacher_pair))
    return weight * jnp.mean(jnp.square(s - t))


def soft_label_loss(student_logits, teacher_logits, student_temperature=1.0,
                    teacher_temperature=1.0, weight=1.0):
    """ref distiller.py SoftLabelDistiller: cross entropy of softened
    teacher probabilities vs softened student log-probs."""
    t = jax.nn.softmax(
        jax.lax.stop_gradient(teacher_logits) / teacher_temperature, axis=-1)
    logp = jax.nn.log_softmax(student_logits / student_temperature, axis=-1)
    return weight * jnp.mean(-jnp.sum(t * logp, axis=-1))


class Distiller:
    """Weighted combination of distillation terms + the task loss
    (ref distillation_strategy.py composing distiller passes)."""

    def __init__(self, terms):
        """terms: list of zero-arg-composable (fn, weight) where fn takes
        (student_out, teacher_out) dicts and returns a scalar."""
        self.terms = list(terms)

    def loss(self, student_out, teacher_out):
        total = jnp.zeros(())
        for fn, weight in self.terms:
            total = total + weight * fn(student_out, teacher_out)
        return total
