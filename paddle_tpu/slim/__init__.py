"""Model compression (slim): pruning, distillation, search.

Ref: /root/reference/python/paddle/fluid/contrib/slim/ — quantization
(already in paddle_tpu/quant/), prune/ (Pruner/StructurePruner +
Uniform/Sensitive strategies), distillation/ (L2/FSP/SoftLabel distillers),
nas/+searcher/ (LightNAS over an SAController).

TPU-first notes: pruning during training keeps *static shapes* by zero-mask
("lazy") pruning — masks fuse into the jitted step and the MXU sees dense
tiles; physical shrinking ("remove") is an export-time transform. The
distillers are plain loss terms composed into the student's loss function
(no graph-surgery passes needed — the captured program IS the graph).
"""

from paddle_tpu.slim.distill import (Distiller, fsp_loss, l2_loss,
                                     soft_label_loss)
from paddle_tpu.slim.nas import (ControllerServer, LightNAS, SAController,
                                 SearchAgent, SearchSpace,
                                 distributed_search)
from paddle_tpu.slim.prune import (MaskedOptimizer, StructurePruner,
                                   prune_tree, sensitive_prune,
                                   sensitive_prune_ratios, sensitivity)
