"""Architecture search: simulated-annealing controller + LightNAS loop.

Ref: /root/reference/python/paddle/fluid/contrib/slim/searcher/controller.py
(SAController :59 — accept better rewards always, worse ones with
exp(dr/T) probability, geometric temperature decay, single random token
mutation per step) and nas/light_nas_strategy.py (LightNASStrategy — search
a token space where each token vector describes an architecture, reward =
metric under a latency/flops constraint).

TPU-first: the reference runs the controller behind a socket server for
distributed search; here the controller is in-process and the trial
evaluator is any callable (typically: build model from tokens, short-train
jitted, return metric). A constrain_func can reject candidates (e.g. FLOPs
budget) before paying for evaluation, exactly like the reference.
"""

import math

import numpy as np

from paddle_tpu.core.enforce import enforce


class SearchSpace:
    """Token-vector search space (ref nas/search_space.py): subclass or
    construct with range_table + init_tokens + a tokens->model builder."""

    def __init__(self, range_table, init_tokens):
        enforce(len(range_table) == len(init_tokens),
                "range_table and init_tokens must align")
        self.range_table = list(range_table)
        self.init_tokens = list(init_tokens)


class SAController:
    """Simulated-annealing evolutionary controller (ref controller.py:59)."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=0):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._reward = -float("inf")
        self._tokens = None
        self._max_reward = -float("inf")
        self._best_tokens = None
        self._iter = 0
        self._rng = np.random.RandomState(seed)
        self._constrain_func = None

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0

    def update(self, tokens, reward):
        """Metropolis accept (ref controller.py:105)."""
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        dr = reward - self._reward
        if dr > 0 or self._rng.random_sample() <= math.exp(
                dr / max(temperature, 1e-9)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self):
        """Mutate one random position (ref controller.py:127); retries
        through constrain_func when set."""
        mutable = [i for i, r in enumerate(self._range_table) if r > 1]
        enforce(mutable, "search space has no mutable positions "
                         "(all range_table entries are 1)")
        for _ in range(256):
            new_tokens = list(self._tokens)
            index = mutable[int(len(mutable) * self._rng.random_sample())]
            new_tokens[index] = (
                new_tokens[index]
                + self._rng.randint(self._range_table[index] - 1) + 1
            ) % self._range_table[index]
            if self._constrain_func is None or self._constrain_func(
                    new_tokens):
                return new_tokens
        return list(self._tokens)

    @property
    def best(self):
        return self._best_tokens, self._max_reward


class LightNAS:
    """In-process LightNAS loop (ref nas/light_nas_strategy.py minus the
    controller server): search the space with SA, evaluating candidates
    with a user trial function."""

    def __init__(self, space: SearchSpace, eval_fn, constrain_func=None,
                 controller=None):
        self.space = space
        self.eval_fn = eval_fn
        self.controller = controller or SAController()
        self.controller.reset(space.range_table, space.init_tokens,
                              constrain_func)

    def search(self, steps=20):
        """Run `steps` trials; returns (best_tokens, best_reward)."""
        tokens = list(self.space.init_tokens)
        reward = float(self.eval_fn(tokens))
        self.controller.update(tokens, reward)
        for _ in range(steps - 1):
            tokens = self.controller.next_tokens()
            reward = float(self.eval_fn(tokens))
            self.controller.update(tokens, reward)
        return self.controller.best


class ControllerServer:
    """Socket-served controller for DISTRIBUTED search (ref
    nas/controller_server.py + search_agent.py: N agents each train a
    candidate and report rewards to one central SA controller).

    Protocol (original design, line-delimited JSON over TCP):
      agent -> {"op": "next"}                      -> {"tokens": [...]}
      agent -> {"op": "update", "tokens": [...],
                "reward": r}                       -> {"ok": true,
                                                       "steps_left": n}
      agent -> {"op": "best"}                      -> {"tokens": [...],
                                                       "reward": r}
    The controller state is guarded by a lock, so any number of agents can
    pull candidates and push rewards concurrently (the reference's
    max_client_num concurrency)."""

    def __init__(self, controller, search_steps=None, address=("", 0)):
        import socket
        import threading
        self._controller = controller
        self._steps_left = [search_steps if search_steps is not None
                            else -1]
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(address)
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        self._closed = False
        self._thread = None

    def start(self):
        import threading
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self.port

    def _serve(self):
        import json
        import threading

        def handle(conn):
            f = conn.makefile("rw")
            try:
                for line in f:
                    req = json.loads(line)
                    with self._lock:
                        if req["op"] == "next":
                            if self._steps_left[0] == 0:
                                # budget exhausted (ref controller_server's
                                # search_steps): stop handing out candidates
                                resp = {"tokens": None, "done": True}
                            else:
                                resp = {"tokens":
                                        self._controller.next_tokens()}
                        elif req["op"] == "update":
                            self._controller.update(req["tokens"],
                                                    float(req["reward"]))
                            if self._steps_left[0] > 0:
                                self._steps_left[0] -= 1
                            resp = {"ok": True,
                                    "steps_left": self._steps_left[0]}
                        elif req["op"] == "best":
                            t, r = self._controller.best
                            resp = {"tokens": t, "reward": r}
                        else:
                            resp = {"error": f"unknown op {req['op']}"}
                    f.write(json.dumps(resp) + "\n")
                    f.flush()
            except (ValueError, OSError):
                pass
            finally:
                conn.close()

        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=handle, args=(conn,),
                             daemon=True).start()

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class SearchAgent:
    """Client side of the distributed search (ref nas/search_agent.py):
    pull a candidate, evaluate it locally, report the reward."""

    def __init__(self, host, port):
        self._addr = (host, port)

    def _rpc(self, req):
        import json
        import socket
        with socket.create_connection(self._addr, timeout=60) as s:
            f = s.makefile("rw")
            f.write(json.dumps(req) + "\n")
            f.flush()
            return json.loads(f.readline())

    def next_tokens(self):
        return self._rpc({"op": "next"})["tokens"]

    def update(self, tokens, reward):
        return self._rpc({"op": "update", "tokens": list(tokens),
                          "reward": float(reward)})

    def best(self):
        r = self._rpc({"op": "best"})
        return r["tokens"], r["reward"]

    def run(self, eval_fn, steps):
        """Evaluate up to `steps` candidates against the shared controller;
        stops early when the server's search budget is exhausted."""
        for _ in range(steps):
            tokens = self.next_tokens()
            if tokens is None:          # server budget exhausted
                return
            self.update(tokens, float(eval_fn(tokens)))


def distributed_search(space, eval_fn, num_agents=2, steps_per_agent=10,
                       constrain_func=None, controller=None):
    """Multi-agent search against one ControllerServer (in-process agents;
    point real SearchAgents at server.port for multi-host). Returns
    (best_tokens, best_reward)."""
    import threading
    ctrl = controller or SAController()
    ctrl.reset(space.range_table, space.init_tokens, constrain_func)
    # seed the controller with the init point so next_tokens mutates it
    ctrl.update(list(space.init_tokens), float(eval_fn(space.init_tokens)))
    server = ControllerServer(ctrl)
    server.start()
    agents = [SearchAgent("127.0.0.1", server.port)
              for _ in range(num_agents)]
    errors = []

    def run_agent(a):
        try:
            a.run(eval_fn, steps_per_agent)
        except BaseException as e:      # surfaced after join — a crashed
            errors.append(e)            # search must not look successful

    threads = [threading.Thread(target=run_agent, args=(a,))
               for a in agents]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    best = agents[0].best()
    server.close()
    if errors:
        raise RuntimeError(
            f"{len(errors)} search agent(s) failed") from errors[0]
    return best
