"""Architecture search: simulated-annealing controller + LightNAS loop.

Ref: /root/reference/python/paddle/fluid/contrib/slim/searcher/controller.py
(SAController :59 — accept better rewards always, worse ones with
exp(dr/T) probability, geometric temperature decay, single random token
mutation per step) and nas/light_nas_strategy.py (LightNASStrategy — search
a token space where each token vector describes an architecture, reward =
metric under a latency/flops constraint).

TPU-first: the reference runs the controller behind a socket server for
distributed search; here the controller is in-process and the trial
evaluator is any callable (typically: build model from tokens, short-train
jitted, return metric). A constrain_func can reject candidates (e.g. FLOPs
budget) before paying for evaluation, exactly like the reference.
"""

import math

import numpy as np

from paddle_tpu.core.enforce import enforce


class SearchSpace:
    """Token-vector search space (ref nas/search_space.py): subclass or
    construct with range_table + init_tokens + a tokens->model builder."""

    def __init__(self, range_table, init_tokens):
        enforce(len(range_table) == len(init_tokens),
                "range_table and init_tokens must align")
        self.range_table = list(range_table)
        self.init_tokens = list(init_tokens)


class SAController:
    """Simulated-annealing evolutionary controller (ref controller.py:59)."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=0):
        self._range_table = range_table
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._reward = -float("inf")
        self._tokens = None
        self._max_reward = -float("inf")
        self._best_tokens = None
        self._iter = 0
        self._rng = np.random.RandomState(seed)
        self._constrain_func = None

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0

    def update(self, tokens, reward):
        """Metropolis accept (ref controller.py:105)."""
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        dr = reward - self._reward
        if dr > 0 or self._rng.random_sample() <= math.exp(
                dr / max(temperature, 1e-9)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self):
        """Mutate one random position (ref controller.py:127); retries
        through constrain_func when set."""
        mutable = [i for i, r in enumerate(self._range_table) if r > 1]
        enforce(mutable, "search space has no mutable positions "
                         "(all range_table entries are 1)")
        for _ in range(256):
            new_tokens = list(self._tokens)
            index = mutable[int(len(mutable) * self._rng.random_sample())]
            new_tokens[index] = (
                new_tokens[index]
                + self._rng.randint(self._range_table[index] - 1) + 1
            ) % self._range_table[index]
            if self._constrain_func is None or self._constrain_func(
                    new_tokens):
                return new_tokens
        return list(self._tokens)

    @property
    def best(self):
        return self._best_tokens, self._max_reward


class LightNAS:
    """In-process LightNAS loop (ref nas/light_nas_strategy.py minus the
    controller server): search the space with SA, evaluating candidates
    with a user trial function."""

    def __init__(self, space: SearchSpace, eval_fn, constrain_func=None,
                 controller=None):
        self.space = space
        self.eval_fn = eval_fn
        self.controller = controller or SAController()
        self.controller.reset(space.range_table, space.init_tokens,
                              constrain_func)

    def search(self, steps=20):
        """Run `steps` trials; returns (best_tokens, best_reward)."""
        tokens = list(self.space.init_tokens)
        reward = float(self.eval_fn(tokens))
        self.controller.update(tokens, reward)
        for _ in range(steps - 1):
            tokens = self.controller.next_tokens()
            reward = float(self.eval_fn(tokens))
            self.controller.update(tokens, reward)
        return self.controller.best
