"""Pruning — structured channel pruning + masked training.

Ref: /root/reference/python/paddle/fluid/contrib/slim/prune/pruner.py
(StructurePruner.cal_pruned_idx :55 l1_norm group sort, prune_tensor :81
lazy/remove modes) and prune_strategy.py (UniformPruneStrategy :563,
SensitivePruneStrategy — per-param sensitivity then ratio assignment).

TPU-first: "lazy" pruning (zero masks) is the training-time mode — shapes
stay static so one compiled step serves the whole schedule, and masks fold
into the jitted update (MaskedOptimizer re-applies them after each step,
replacing the reference's scope surgery). "remove" mode physically shrinks
tensors (numpy, host) for export.
"""

import re

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.core.enforce import enforce


class StructurePruner:
    """Group (channel) pruner (ref pruner.py:34).

    pruning_axis: {param-name-or-'*': axis}
    criterions:   {param-name-or-'*': 'l1_norm' | 'l2_norm'}
    """

    def __init__(self, pruning_axis=None, criterions=None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def _lookup(self, table, name):
        return table[name] if name in table else table["*"]

    def cal_pruned_idx(self, name, param, ratio, axis=None):
        """Indexes of the weakest groups on `axis` (ref pruner.py:55)."""
        criterion = self._lookup(self.criterions, name)
        if axis is None:
            axis = self._lookup(self.pruning_axis, name)
        param = np.asarray(param)
        prune_num = int(round(param.shape[axis] * ratio))
        reduce_dims = tuple(i for i in range(param.ndim) if i != axis)
        if criterion == "l1_norm":
            scores = np.sum(np.abs(param), axis=reduce_dims)
        elif criterion == "l2_norm":
            scores = np.sqrt(np.sum(np.square(param), axis=reduce_dims))
        else:
            raise ValueError(f"unknown criterion {criterion!r}")
        return scores.argsort()[:prune_num]

    def prune_tensor(self, tensor, pruned_idx, pruned_axis, lazy=False):
        """lazy=True zeroes the groups (static shape); False removes them
        (ref pruner.py:81)."""
        tensor = np.asarray(tensor)
        mask = np.zeros(tensor.shape[pruned_axis], bool)
        mask[np.asarray(pruned_idx, int)] = True
        if lazy:
            keep = ~mask
            shape = [1] * tensor.ndim
            shape[pruned_axis] = tensor.shape[pruned_axis]
            return tensor * keep.reshape(shape)
        return np.take(tensor, np.where(~mask)[0], axis=pruned_axis)

    def mask_for(self, name, param, ratio, axis=None):
        """Boolean keep-mask broadcastable over `param` (True = keep)."""
        if axis is None:
            axis = self._lookup(self.pruning_axis, name)
        idx = self.cal_pruned_idx(name, param, ratio, axis)
        m = np.ones(np.asarray(param).shape[axis], bool)
        m[idx] = False
        shape = [1] * np.asarray(param).ndim
        shape[axis] = m.shape[0]
        return jnp.asarray(m.reshape(shape))


def _iter_params(params, pattern):
    rx = re.compile(pattern)
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if rx.search(name):
            yield path, name, leaf


def prune_tree(params, ratio, pattern=r"conv.*weight", pruner=None,
               lazy=True):
    """Prune every param matching `pattern` by `ratio` (ref
    UniformPruneStrategy). Returns (new_params, masks {name: keep-mask}).
    lazy=True zero-masks in place (shapes unchanged, TPU mode)."""
    pruner = pruner or StructurePruner()
    masks = {}
    flat = dict(jax.tree_util.tree_leaves_with_path(params))
    for path, name, leaf in _iter_params(params, pattern):
        mask = pruner.mask_for(name, leaf, ratio)
        masks[name] = mask
        enforce(lazy, "prune_tree: only lazy (mask) mode operates on "
                      "pytrees; use pruner.prune_tensor for removal")
        flat[path] = jnp.asarray(leaf) * mask.astype(leaf.dtype)
    new_params = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), [flat[p] for p, _ in
                                               jax.tree_util.tree_leaves_with_path(params)])
    return new_params, masks


def apply_masks(params, masks):
    """Re-zero masked groups (after an optimizer step)."""
    flat = jax.tree_util.tree_leaves_with_path(params)
    out = []
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if name in masks:
            leaf = leaf * masks[name].astype(leaf.dtype)
        out.append(leaf)
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(params), out)


class MaskedOptimizer:
    """Optimizer wrapper keeping pruned groups at zero through training
    (the reference retrains pruned models by zeroing in the scope each
    step; here the mask application fuses into the jitted update)."""

    def __init__(self, inner, masks):
        self.inner = inner
        self.masks = masks

    def init(self, params):
        return self.inner.init(params)

    def apply_gradients(self, params, grads, state):
        params, state = self.inner.apply_gradients(params, grads, state)
        return apply_masks(params, self.masks), state

    def minimize(self, loss_fn, params, state, *args, **kwargs):
        loss, params, state, aux = self.inner.minimize(
            loss_fn, params, state, *args, **kwargs)
        return loss, apply_masks(params, self.masks), state, aux


def sensitivity(eval_fn, params, pattern=r"conv.*weight",
                ratios=(0.1, 0.3, 0.5), pruner=None):
    """Per-param pruning sensitivity (ref SensitivePruneStrategy):
    eval_fn(params) -> scalar metric (higher is better); returns
    {name: {ratio: metric_loss_fraction}}."""
    pruner = pruner or StructurePruner()
    base = float(eval_fn(params))
    out = {}
    for path, name, leaf in _iter_params(params, pattern):
        out[name] = {}
        for ratio in ratios:
            # anchored exact-name pattern: prune ONLY this param (a bare
            # substring would co-prune e.g. 'conv1/weight_norm')
            pruned, _ = prune_tree(params, ratio,
                                   pattern="^" + re.escape(name) + "$",
                                   pruner=pruner)
            m = float(eval_fn(pruned))
            out[name][float(ratio)] = (base - m) / (abs(base) + 1e-12)
    return out


def sensitive_prune_ratios(sens, max_loss=0.05):
    """Per-layer ratios from sensitivity curves (ref
    SensitivePruneStrategy._get_best_ratios): for each param pick the
    LARGEST ratio reachable before the curve first exceeds `max_loss`
    (0.0 when even the smallest ratio exceeds it). The scan stops at the
    first violation — sensitivity curves are not always monotone, and a
    later in-budget ratio past an observed degradation spike is not
    trustworthy (matches the reference strategy's monotone assumption)."""
    out = {}
    for name, curve in sens.items():
        best = 0.0
        for ratio in sorted(curve):
            if curve[ratio] > max_loss:
                break
            best = ratio
        out[name] = best
    return out


def sensitive_prune(eval_fn, params, pattern=r"conv.*weight",
                    ratios=(0.1, 0.3, 0.5), max_loss=0.05, pruner=None):
    """Sensitivity-driven structured pruning end-to-end (ref
    prune_strategy.py SensitivePruneStrategy): measure curves, pick
    per-layer ratios under the degradation budget, prune each layer at its
    own ratio. Returns (pruned_params, masks, chosen_ratios)."""
    pruner = pruner or StructurePruner()
    sens = sensitivity(eval_fn, params, pattern=pattern, ratios=ratios,
                       pruner=pruner)
    chosen = sensitive_prune_ratios(sens, max_loss=max_loss)
    pruned, masks = params, {}
    for path, name, leaf in _iter_params(params, pattern):
        r = chosen.get(name, 0.0)
        if r <= 0.0:
            continue
        pruned, m = prune_tree(pruned, r,
                               pattern="^" + re.escape(name) + "$",
                               pruner=pruner)
        masks.update(m)
    return pruned, masks, chosen
