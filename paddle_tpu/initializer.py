"""Parameter initializers.

Ref: /root/reference/python/paddle/fluid/initializer.py (Constant, Uniform,
Normal, TruncatedNormal, Xavier, MSRA, Bilinear, NumpyArrayInitializer).
Each initializer is `fn(key, shape, dtype) -> array` — explicit PRNG keys
(TPU counter-based RNG, reproducible under pjit).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtype import convert_dtype


def _fans(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels OIHW: receptive = prod(spatial)
    receptive = int(np.prod(shape[2:]))
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


def constant(value=0.0):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, value, convert_dtype(dtype))
    return init


def zeros():
    return constant(0.0)


def ones():
    return constant(1.0)


def uniform(low=-1.0, high=1.0):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, convert_dtype(dtype), low, high)
    return init


def normal(loc=0.0, scale=1.0):
    def init(key, shape, dtype=jnp.float32):
        return loc + scale * jax.random.normal(key, shape, convert_dtype(dtype))
    return init


def truncated_normal(loc=0.0, scale=1.0):
    def init(key, shape, dtype=jnp.float32):
        return loc + scale * jax.random.truncated_normal(
            key, -2.0, 2.0, shape, convert_dtype(dtype))
    return init


def xavier(uniform_=True, fan_in=None, fan_out=None):
    """ref: initializer.py XavierInitializer"""
    def init(key, shape, dtype=jnp.float32):
        fi, fo = _fans(shape)
        fi = fan_in if fan_in is not None else fi
        fo = fan_out if fan_out is not None else fo
        if uniform_:
            limit = math.sqrt(6.0 / (fi + fo))
            return jax.random.uniform(key, shape, convert_dtype(dtype),
                                      -limit, limit)
        std = math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, convert_dtype(dtype))
    return init


def msra(uniform_=False, fan_in=None):
    """Kaiming/He (ref: initializer.py MSRAInitializer)."""
    def init(key, shape, dtype=jnp.float32):
        fi, _ = _fans(shape)
        fi = fan_in if fan_in is not None else fi
        if uniform_:
            limit = math.sqrt(6.0 / fi)
            return jax.random.uniform(key, shape, convert_dtype(dtype),
                                      -limit, limit)
        std = math.sqrt(2.0 / fi)
        return std * jax.random.normal(key, shape, convert_dtype(dtype))
    return init


def bilinear():
    """Bilinear upsampling kernel for conv_transpose (ref: initializer.py
    BilinearInitializer)."""
    def init(key, shape, dtype=jnp.float32):
        # shape: [C, C', kh, kw]
        kh, kw = shape[-2], shape[-1]
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        c_h = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        c_w = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = np.ogrid[:kh, :kw]
        filt = ((1 - abs(og[0] / f_h - c_h)) * (1 - abs(og[1] / f_w - c_w)))
        w = np.zeros(shape, np.float32)
        for i in range(min(shape[0], shape[1])):
            w[i, i] = filt
        return jnp.asarray(w, convert_dtype(dtype))
    return init


def numpy_array(arr):
    def init(key, shape, dtype=jnp.float32):
        a = jnp.asarray(arr, convert_dtype(dtype))
        assert tuple(a.shape) == tuple(shape), (a.shape, shape)
        return a
    return init
