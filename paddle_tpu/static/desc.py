"""Op-level ProgramDesc: serializable op sequences executed via the registry.

Ref: /root/reference/paddle/fluid/framework/framework.proto:212 (ProgramDesc →
BlockDesc → OpDesc {type, inputs, outputs, attrs}) and framework.py:3459
Program.to_string / parse_from_string. The reference serializes programs as
protobuf op lists and re-instantiates each op through OpRegistry
(op_registry.h:199); the Executor then interprets the list (executor.cc:438).

TPU-first: the *compiled* interchange format is StableHLO/jax.export
(io/inference.py) — that is what serving consumes. This module is the
op-level twin for the cases the reference used ProgramDesc text for:
building programs from descriptions (no Python closures), textual
round-trips, and program surgery. `build_fn` resolves each OpDesc.type
through GLOBAL_OP_REGISTRY — the registry's loader role — and returns a
plain traceable function, so a parsed program jits/grads/shards like any
other (XLA remains the interpreter; there is no op-by-op runtime loop).
"""

import dataclasses
import json

from paddle_tpu.core.enforce import EnforceError, enforce
from paddle_tpu.core.registry import GLOBAL_OP_REGISTRY


@dataclasses.dataclass
class OpDesc:
    """One op invocation (ref framework.proto:43 OpDesc)."""
    type: str
    inputs: list          # var names, positional
    outputs: list         # var names bound to (tupled) results
    attrs: dict = dataclasses.field(default_factory=dict)

    def to_dict(self):
        return {"type": self.type, "inputs": list(self.inputs),
                "outputs": list(self.outputs), "attrs": dict(self.attrs)}

    @staticmethod
    def from_dict(d):
        return OpDesc(d["type"], list(d["inputs"]), list(d["outputs"]),
                      dict(d.get("attrs", {})))


@dataclasses.dataclass
class ProgramDesc:
    """A feed→ops→fetch block (ref framework.proto:174 BlockDesc).

    feeds:   input var names in positional order
    ops:     OpDesc list, executed in order over a name→value environment
    fetches: output var names
    """
    feeds: list
    ops: list
    fetches: list

    def append_op(self, type_, inputs, outputs, **attrs):
        enforce(type_ in GLOBAL_OP_REGISTRY,
                "op '%s' is not registered", type_)
        self.ops.append(OpDesc(type_, list(inputs), list(outputs), attrs))
        return self

    # --- serialization (to_string / parse_from_string parity) ---
    def to_json(self):
        return json.dumps({
            "version": 1,
            "feeds": list(self.feeds),
            "fetches": list(self.fetches),
            "ops": [op.to_dict() for op in self.ops],
        }, indent=2)

    @staticmethod
    def parse_from_string(text):
        d = json.loads(text)
        enforce(d.get("version") == 1, "unsupported ProgramDesc version")
        return ProgramDesc(list(d["feeds"]),
                           [OpDesc.from_dict(o) for o in d["ops"]],
                           list(d["fetches"]))

    # --- the registry consumer: desc -> traceable function ---
    def build_fn(self):
        """Resolve ops through the registry into fn(*feeds) -> {fetch: val}.

        Missing ops raise EnforceError naming the op. The returned function
        is pure and traceable — jit/grad/pjit compose."""
        resolved = []
        for op in self.ops:
            if op.type not in GLOBAL_OP_REGISTRY:
                raise EnforceError(
                    f"ProgramDesc op '{op.type}' is not in the op registry")
            resolved.append((GLOBAL_OP_REGISTRY.get(op.type), op))

        def fn(*args):
            enforce(len(args) == len(self.feeds),
                    "expected %d feeds, got %d", len(self.feeds), len(args))
            env = dict(zip(self.feeds, args))
            for impl, op in resolved:
                try:
                    ins = [env[n] for n in op.inputs]
                except KeyError as e:
                    raise EnforceError(
                        f"op '{op.type}' reads undefined var {e}") from e
                out = impl(*ins, **op.attrs)
                if len(op.outputs) == 1:
                    env[op.outputs[0]] = out
                else:
                    enforce(len(out) == len(op.outputs),
                            "op '%s' produced %d outputs, desc names %d",
                            op.type, len(out), len(op.outputs))
                    for name, val in zip(op.outputs, out):
                        env[name] = val
            missing = [n for n in self.fetches if n not in env]
            enforce(not missing, "fetch vars never produced: %s", missing)
            return {n: env[n] for n in self.fetches}

        return fn

    def to_static_program(self, name="main"):
        """Adapter into static.Executor (feed-dict API)."""
        from paddle_tpu.static.program import StaticProgram
        fn = self.build_fn()
        return StaticProgram(
            lambda **feeds: fn(*[feeds[n] for n in self.feeds]),
            self.feeds, self.fetches, name=name)


def program_desc(feeds, fetches):
    return ProgramDesc(list(feeds), [], list(fetches))
