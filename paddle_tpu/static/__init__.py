"""Static-graph compatibility layer: Program/Executor surface.

Ref: /root/reference/python/paddle/fluid/framework.py (Program :3459,
default_main_program guards :4503) and executor.py (Executor.run :672 with
feed/fetch). The reference builds graphs op-by-op into ProgramDesc; here a
"static program" is a traced python function, and Executor.run matches the
feed/fetch calling convention on top of jax.jit.
"""

from paddle_tpu.static.program import (
    Executor,
    StaticProgram,
    program_from_fn,
)
from paddle_tpu.static.desc import OpDesc, ProgramDesc, program_desc
from paddle_tpu.static.guardian import (GuardianConfig, TrainGuardian,
                                        TrainingDiverged)
from paddle_tpu.static.trainer import (PREEMPTED_EXIT_CODE, Preempted,
                                       Trainer, TrainerConfig,
                                       train_from_dataset)
from paddle_tpu.core.program import Program, flop_estimate
