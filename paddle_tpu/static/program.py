"""Program/Executor compatibility API.

Ref: /root/reference/python/paddle/fluid/executor.py:672 Executor.run(
program, feed={name: array}, fetch_list=[names]) — the reference injects
feed/fetch ops into block 0 (executor.py:233,271) and interprets; here the
program is a function of named inputs, jitted once per shape signature
(the program-cache equivalent of executor.py:355 _get_program_cache).
"""

import jax

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.program import Program


class StaticProgram:
    """A named-input program: fn(**feeds) -> {name: output}."""

    def __init__(self, fn, input_names, output_names, name="main"):
        self.fn = fn
        self.input_names = list(input_names)
        self.output_names = list(output_names)
        self.name = name

    def capture(self, example_feed):
        args = [example_feed[n] for n in self.input_names]
        return Program.capture(lambda *a: self.fn(**dict(
            zip(self.input_names, a))), *args, name=self.name)


def program_from_fn(fn, input_names, output_names, name="main"):
    return StaticProgram(fn, input_names, output_names, name)


class Executor:
    """ref: executor.py Executor — jit-compiled program cache keyed by
    (program, shapes/dtypes)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program: StaticProgram, feed=None, fetch_list=None):
        feed = feed or {}
        enforce(set(program.input_names) <= set(feed),
                "missing feeds: %s",
                set(program.input_names) - set(feed))
        key = (id(program),
               tuple((n, tuple(jax.numpy.shape(feed[n])),
                      str(jax.numpy.asarray(feed[n]).dtype))
                     for n in program.input_names))
        if key not in self._cache:
            self._cache[key] = jax.jit(
                lambda *a: program.fn(**dict(zip(program.input_names, a))))
        outs = self._cache[key](*[feed[n] for n in program.input_names])
        if fetch_list is None:
            return outs
        if isinstance(outs, dict):
            return [outs[n] for n in fetch_list]
        return outs
