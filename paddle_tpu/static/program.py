"""Program/Executor compatibility API.

Ref: /root/reference/python/paddle/fluid/executor.py:672 Executor.run(
program, feed={name: array}, fetch_list=[names]) — the reference injects
feed/fetch ops into block 0 (executor.py:233,271) and interprets; here the
program is a function of named inputs, jitted once per shape signature
(the program-cache equivalent of executor.py:355 _get_program_cache).
"""

import jax

from paddle_tpu.core.enforce import enforce
from paddle_tpu.core.program import Program


class StaticProgram:
    """A named-input program: fn(**feeds) -> {name: output}."""

    def __init__(self, fn, input_names, output_names, name="main"):
        self.fn = fn
        self.input_names = list(input_names)
        self.output_names = list(output_names)
        self.name = name

    def capture(self, example_feed):
        args = [example_feed[n] for n in self.input_names]
        return Program.capture(lambda *a: self.fn(**dict(
            zip(self.input_names, a))), *args, name=self.name)


def program_from_fn(fn, input_names, output_names, name="main"):
    return StaticProgram(fn, input_names, output_names, name)


class Executor:
    """ref: executor.py Executor — jit-compiled program cache keyed by
    (program, shapes/dtypes)."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program: StaticProgram, feed=None, fetch_list=None):
        feed = feed or {}
        enforce(set(program.input_names) <= set(feed),
                "missing feeds: %s",
                set(program.input_names) - set(feed))
        key = (id(program),
               tuple((n, tuple(jax.numpy.shape(feed[n])),
                      str(jax.numpy.asarray(feed[n]).dtype))
                     for n in program.input_names))
        if key not in self._cache:
            self._cache[key] = jax.jit(
                lambda *a: program.fn(**dict(zip(program.input_names, a))))
        outs = self._cache[key](*[feed[n] for n in program.input_names])
        from paddle_tpu.core.flags import get_flag
        if get_flag("check_nan_inf"):
            # ref flags.cc:44 — validate executor outputs (host-side; the
            # fetched values are the op-output surface on TPU).
            from paddle_tpu.core.enforce import check_numerics
            check_numerics(outs, f"outputs of program '{program.name}'")
        if fetch_list is None:
            return outs
        if not isinstance(outs, dict):
            # Align positional outputs with the program's declared output
            # names so fetch_list selects by name, matching the reference's
            # fetch semantics (executor.py:271 fetch-op injection).
            seq = outs if isinstance(outs, (list, tuple)) else (outs,)
            enforce(len(seq) == len(program.output_names),
                    "program returned %d outputs but declares %d names",
                    len(seq), len(program.output_names))
            outs = dict(zip(program.output_names, seq))
        missing = [n for n in fetch_list if n not in outs]
        enforce(not missing, "unknown fetch names: %s", missing)
        return [outs[n] for n in fetch_list]

    def train_from_dataset(self, train_step, state, dataset, config=None,
                           sparse_tables=None, batch_size=None):
        """Threaded-ingestion training loop (ref executor.py:1107
        train_from_dataset → TrainerFactory → DeviceWorker threads); see
        static/trainer.py for the TPU-first design."""
        from paddle_tpu.static.trainer import train_from_dataset as _tfd
        return _tfd(train_step, state, dataset, config=config,
                    sparse_tables=sparse_tables, batch_size=batch_size)
