"""Training guardian — numerical fault containment for long runs.

Ref: the reference framework's production trainers shipped checkpoint
notify RPCs and pserver recovery, but a NaN loss, a poisoned batch, or a
corrupted checkpoint silently wrecked the run — fault handling stopped
at process death. The serving stack here already self-heals (retry
budgets, quarantine + replay, fleet failover); this module gives the
Trainer the same anomaly -> mitigate -> rollback machinery, built on
the identical primitives (core/retry.py RetryBudget, the watchdog latch,
the chaos fault points):

  in-trace containment   wrap_step() gates the parameter/optimizer
                         update on isfinite(loss) & isfinite(global
                         update norm): a non-finite step applies NOTHING
                         (jnp.where picks every old buffer, so state
                         stays bit-identical) and the `applied` flag
                         rides the step's outputs — counted host-side
                         from the trailing fetch, zero new sync.
  loss-spike detector    observe_step() keeps a rolling window of
                         healthy losses; a finite loss above
                         spike_factor x the rolling median latches a
                         loss_spike anomaly (watchdog-style: once per
                         episode, re-armed by a healthy step).
  mitigation ladder      consecutive anomalous steps escalate:
                         1 tolerate/skip -> 2 re-read the batch ->
                         3+ roll back to the last good checkpoint and
                         replay the data stream to the same cursor.
                         Rollbacks are bounded by a RetryBudget
                         (trainer_rollback_budget flag); exhaustion
                         re-raises TrainingDiverged into the train loop,
                         exactly like serve_step_retries exhaustion.

Trailing-fetch discipline (PR-4): observe_step(step, ...) parks the
device scalars and processes the tuple parked at step-1, which finished
long ago — jax.device_get returns without stalling the in-flight step.
The hot-path-sync lint analyzes this module from the Trainer.train root;
the flush-spy test (tests/test_guardian.py) proves the discipline at
runtime.

Everything observable flows through the shared plumbing: counters
(trainer.nonfinite_skips / loss_spikes / rollbacks), the watchdog
(loss_spike anomalies), the RunLog ("guardian" records that
tools/run_report.py --train-health reconstructs), and amp.ScalerObserver
(amp.loss_scale / amp.skipped_steps from the scaler state riding the
train state tree).
"""

import collections
import dataclasses
import math

import jax
import jax.numpy as jnp

from paddle_tpu.observability import metrics as _metrics
from paddle_tpu.observability.catalog import help_for as _help
from paddle_tpu.testing.chaos import fault_point


class TrainingDiverged(RuntimeError):
    """The mitigation ladder exhausted its rollback budget: the run is
    re-diverging faster than checkpoint rollbacks can heal it."""


@dataclasses.dataclass
class GuardianConfig:
    """None fields resolve from the trainer_* flags, so a run can arm
    the guardian with env vars alone (PT_FLAGS_trainer_spike_factor=5)."""

    spike_factor: float = None   # None -> flag trainer_spike_factor
    spike_window: int = 64       # rolling-median window (healthy losses)
    min_samples: int = 8         # median needs this many healthy losses
    rollback_budget: int = None  # None -> flag trainer_rollback_budget
    check_update_norm: bool = True  # gate on the global update norm too
    # optional selector: train state -> amp.LossScaler state dict, e.g.
    # lambda st: st["opt"]["scaler"]; enables the amp.* metrics bridge
    scaler_state_fn: object = None

    def resolve(self):
        from paddle_tpu.core import flags as F
        c = dataclasses.replace(self)
        if c.spike_factor is None:
            c.spike_factor = float(F.get_flag("trainer_spike_factor"))
        if c.rollback_budget is None:
            c.rollback_budget = int(F.get_flag("trainer_rollback_budget"))
        c.spike_window = max(2, int(c.spike_window))
        c.min_samples = max(2, int(c.min_samples))
        return c


class TrainGuardian:
    """One instance per training run. The Trainer wraps its step through
    `wrap_step`, feeds `observe_step` once per completed step, and acts
    on the returned mitigation ("reread" / "rollback" / None)."""

    def __init__(self, config=None):
        from paddle_tpu.core.retry import RetryBudget, RetryPolicy
        self.cfg = (config or GuardianConfig()).resolve()
        # consecutive-rollback accountant: success() on a healthy
        # checkpoint resets it; exhaustion re-raises TrainingDiverged
        self._budget = RetryBudget(
            RetryPolicy(max_attempts=self.cfg.rollback_budget + 1),
            "trainer.rollback")
        self._run_log = None
        self._wd = None
        self._scaler = None
        self._window = collections.deque(maxlen=self.cfg.spike_window)
        self._pending = None        # (step, loss, applied, scaler) devrefs
        self._spike_latched = False
        self.episode = 0            # consecutive anomalous steps
        self.episode_start = None   # step of the episode's first anomaly
        self.skips = 0              # non-finite skip-applies seen
        self.spikes = 0             # loss-spike episodes latched
        self.rollbacks = 0          # rollbacks performed

    def attach(self, run_log=None, watchdog=None, registry=None):
        """Wire the run's observability plane (the Trainer calls this
        once telemetry/watchdog exist)."""
        self._run_log = run_log
        self._wd = watchdog
        if self.cfg.scaler_state_fn is not None:
            from paddle_tpu.amp import ScalerObserver
            self._scaler = ScalerObserver(registry=registry)
        return self

    # -- in-trace containment ----------------------------------------------
    def wrap_step(self, step_fn):
        """Wrap a (state, *batch) -> (loss, new_state) step so the update
        only applies when loss AND the global update norm are finite.

        The wrapper is jitted; a user step that is itself jitted simply
        inlines (nested jit), and the returned callable keeps the
        _cache_size probe so Watchdog.watch_jit still sees retraces. On a
        healthy step jnp.where(True, new, old) selects every new buffer
        unchanged, so arming the guardian does not perturb a healthy
        run's trajectory — the bit-exact-resume reference runs share one
        config."""
        check_norm = self.cfg.check_update_norm

        def guarded(state, *batch):
            loss, new_state = step_fn(state, *batch)
            ok = jnp.isfinite(loss)
            if check_norm:
                sq = [jnp.sum(jnp.square((n - o).astype(jnp.float32)))
                      for n, o in zip(jax.tree_util.tree_leaves(new_state),
                                      jax.tree_util.tree_leaves(state))
                      if (hasattr(n, "dtype")
                          and jnp.issubdtype(n.dtype, jnp.inexact))]
                if sq:
                    ok = ok & jnp.isfinite(sum(sq))

            def gate(n, o):
                return jnp.where(ok, n, o) if hasattr(n, "dtype") else n

            gated = jax.tree_util.tree_map(gate, new_state, state)
            return loss, gated, ok

        return jax.jit(guarded)

    # -- per-step host logic (trailing) ------------------------------------
    def observe_step(self, step, loss, applied, state):
        """Park this step's device scalars and process the tuple parked
        one step ago (trailing-fetch: those values are a full step old,
        so the fetch cannot stall the in-flight step). Returns the
        mitigation for the PROCESSED step: None (healthy or tolerate),
        "reread", or "rollback"."""
        prev = self._pending
        scaler = (self.cfg.scaler_state_fn(state)
                  if self.cfg.scaler_state_fn is not None else None)
        self._pending = (int(step), loss, applied, scaler)
        if prev is None:
            return None
        return self._process(*prev)

    def flush_trailing(self):
        """Drain the last parked step at end of run (its mitigation, if
        any, is moot — there is no next step to act on)."""
        prev, self._pending = self._pending, None
        if prev is not None:
            self._process(*prev)

    def _process(self, step, loss, applied, scaler):
        host = jax.device_get((loss, applied))  # graft-lint: disable=hot-path-sync (trailing fetch: these scalars are >= one full step old, so device_get returns without stalling the in-flight step)
        if scaler is not None and self._scaler is not None:
            self._scaler.publish(jax.device_get(scaler))  # graft-lint: disable=hot-path-sync (trailing fetch: scaler state parked at the previous step is already retired)
        return self._classify(step, float(host[0]), bool(host[1]))

    def _classify(self, step, loss_v, applied_v):
        """Pure-host anomaly triage for one processed step (unit-testable
        without device values)."""
        if not applied_v:
            self.skips += 1
            _metrics.counter("trainer.nonfinite_skips",
                             _help("trainer.nonfinite_skips")).inc()
            kind = "nonfinite"
        elif self._is_spike(loss_v):
            if not self._spike_latched:
                self._spike_latched = True
                self.spikes += 1
                _metrics.counter("trainer.loss_spikes",
                                 _help("trainer.loss_spikes")).inc()
                if self._wd is not None:
                    self._wd.alert("loss_spike", step, loss=loss_v,
                                   median=self._median())
            kind = "spike"
        else:
            if math.isfinite(loss_v):
                self._window.append(loss_v)
            if self.episode:
                self.episode = 0
                self.episode_start = None
            if self._spike_latched:
                self._spike_latched = False
                if self._wd is not None:
                    self._wd.resolve("loss_spike")
            return None
        self.episode += 1
        if self.episode == 1:
            self.episode_start = step
        action = (None if self.episode == 1
                  else "reread" if self.episode == 2 else "rollback")
        self._log({"guardian": kind, "step": step, "loss": loss_v,
                   "episode": self.episode, "action": action or "skip"})
        return action

    def _median(self):
        if len(self._window) < self.cfg.min_samples:
            return None
        vals = sorted(self._window)
        n = len(vals)
        mid = n // 2
        return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])

    def _is_spike(self, loss_v):
        if not math.isfinite(loss_v):
            return True     # a non-finite loss whose update still applied
        med = self._median()
        return (med is not None and med > 0
                and loss_v > self.cfg.spike_factor * med)

    # -- mitigation ladder: rollback ---------------------------------------
    @property
    def rollback_bound(self):
        """Newest checkpoint step that is safe to roll back to: strictly
        before the episode's first anomalous step (that step's update may
        already be poisoned — a boundary save at it would re-diverge)."""
        if self.episode_start is None:
            return None
        return int(self.episode_start) - 1

    def begin_rollback(self, at_step, **detail):
        """Charge one rollback against the budget. Raises
        TrainingDiverged through RetryBudget exhaustion semantics
        (retry.attempts / retry.giveups {op=trainer.rollback}) when
        budget+1 consecutive rollbacks happen without an intervening
        healthy checkpoint."""
        exc = TrainingDiverged(
            f"loss diverged at step {at_step} and the rollback budget "
            f"({self.cfg.rollback_budget}) is exhausted")
        self._budget.failure(exc)
        fault_point("trainer.rollback")
        self.rollbacks += 1
        _metrics.counter("trainer.rollbacks",
                         _help("trainer.rollbacks")).inc()
        self._log({"guardian": "rollback", "step": int(at_step), **detail})
        # the episode ends here; the pre-anomaly window stays valid (the
        # replay re-walks the same healthy trajectory), and KEEPING it is
        # what lets a persistent divergence re-trip the detector instead
        # of poisoning a fresh median with its own spikes
        self.episode = 0
        self.episode_start = None
        self._pending = None

    def note_rollback_done(self, restored_step):
        self._log({"guardian": "rollback_done",
                   "restored_step": int(restored_step)})

    def note_checkpoint(self, step):
        """A checkpoint landed while healthy: training has durably
        progressed, so the consecutive-rollback streak resets."""
        if self.healthy():
            self._budget.success()

    def healthy(self):
        """No open anomaly episode — interval checkpoint saves are gated
        on this so the newest checkpoint is always a good one."""
        return self.episode == 0

    # -- bit-exact resume --------------------------------------------------
    def state_dict(self):
        """JSON-serializable detector state carried in checkpoint meta,
        covering every step processed before the save (the step saved AT
        is still parked; it re-parks identically after resume)."""
        return {"skips": self.skips, "spikes": self.spikes,
                "rollbacks": self.rollbacks,
                "window": [float(x) for x in self._window]}

    def load_state(self, sd):
        if not sd:
            return
        self.skips = int(sd.get("skips", 0))
        self.spikes = int(sd.get("spikes", 0))
        self.rollbacks = int(sd.get("rollbacks", 0))
        self._window.clear()
        self._window.extend(float(x) for x in sd.get("window", []))

    def _log(self, record):
        if self._run_log is not None:
            self._run_log.write(record)
